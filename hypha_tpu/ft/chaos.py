"""Deterministic fault injection for tests and benchmarks.

Real failures are timing accidents; tests need them on a schedule. A
:class:`ChaosController` watches the same per-round metrics stream the
orchestrator's MetricsBridge sees and fires scripted actions at exact round
boundaries, against the in-process :class:`~hypha_tpu.worker.runtime.
WorkerNode` objects a test or ``bench.py --chaos`` holds:

  * ``kill``       — stop the worker node outright (lease renewals start
    failing, its delta never arrives: the canonical DiLoCo dropout);
  * ``delay``      — add ``delay_s`` to every outbound push (a straggler:
    its delta arrives but may miss the round deadline and be dropped as
    stale);
  * ``partition``  — fail every outbound push *and* request from the worker
    (uplink loss: the worker computes but cannot report; the φ detector is
    the only thing that can see this one);
  * ``kill-ps``      — stop the PARAMETER SERVER's worker node mid-round
    (the durable-PS recovery scenario, ft.durable: the harness restarts
    the node and the journal + generation handshake resume the round);
  * ``partition-ps`` — for ``delay_s`` seconds, drop every push between
    the PS and the workers (both directions): workers must park and
    re-push with backoff (aio.retry), and the PS journal must dedup the
    copies whose first attempt actually landed;
  * ``kill-scheduler`` — stop the SCHEDULER's node mid-round (the durable
    control-plane recovery scenario, ft.durable DurableScheduler: the
    harness restarts the scheduler under the same peer id, which replays
    its journal and re-adopts the live executions in place);
  * ``partition-scheduler`` — for ``delay_s`` seconds, fail every request
    and push from the fleet TOWARD the scheduler (uplink loss: workers'
    Status/UpdateReceived and the PS's Updated park in aio.retry; quorate
    rounds keep closing; the scheduler's own renewals still flow, so no
    lease lapses), then heal.

Degrade modes (net-new, ROADMAP item 4 — heterogeneity is a steady state,
not an event, so these default to ``at_round=0`` and fire on attach):

  * ``slow-worker:<x>`` / ``slow-worker:<peer>:<x>`` — a slow-CPU worker:
    every per-batch Status round-trip is stretched so each inner batch
    takes ~``x``× its natural wall-clock (the training thread blocks on
    the Status response between batches, so the slowdown is real to every
    observer: the scheduler's timing stats, the round deadline, the
    worker itself);
  * ``bw-cap:<peer>:<mbps>`` — cap the peer's LINK at ``mbps``: every
    push from the peer (delta uploads) and to the peer (update
    broadcasts) is streamed through a chunk-throttled source, so the
    RECEIVER measures the cap mid-transfer — exactly what the parameter
    server's LinkTable (ft.adaptive) keys its per-link codec choice on;
  * ``jitter:<peer>:<s>`` — add deterministic pseudo-random delay in
    ``[0, s]`` to every push touching the peer (seeded per target, so a
    re-run sees the identical delay sequence).

Specs compose: ``bench.py --chaos kill-worker:2,bw-cap:w1:10`` runs both
(:func:`parse_chaos_specs`).

Trigger semantics: action ``at_round=r`` fires the first time a METRICS
event for round ``r-1`` is observed — i.e. while round ``r`` is running —
so "kill worker X mid-round r" is reproducible to the batch. ``at_round=0``
fires on attach (before the job's first batch).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable

from .. import aio

__all__ = [
    "ChaosAction",
    "ChaosController",
    "parse_chaos_spec",
    "parse_chaos_specs",
]

log = logging.getLogger("hypha.ft.chaos")

_KINDS = (
    "kill", "delay", "partition", "kill-ps", "partition-ps",
    "kill-scheduler", "partition-scheduler",
    "slow", "bw-cap", "jitter",
)

# Kinds that model a steady condition rather than an event: they attach
# immediately unless the spec pins a round.
_DEGRADE_KINDS = ("slow", "bw-cap", "jitter")

# Throttled-push chunk: small enough that a capped toy-scale delta still
# spreads over several sleeps (the receiver must SEE the cap mid-stream).
_THROTTLE_CHUNK = 16 * 1024


@dataclass(slots=True)
class ChaosAction:
    kind: str  # one of _KINDS
    target: str  # worker peer id
    at_round: int = 1
    delay_s: float = 0.0  # kind == "delay" | "partition-ps" | "jitter"
    factor: float = 1.0  # kind == "slow": per-batch wall-clock multiplier
    rate_bps: float = 0.0  # kind == "bw-cap": link cap in BITS/second
    fired_at: float | None = None  # monotonic time the action ran

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(f"unknown chaos kind {self.kind!r}")
        if self.at_round < 0:
            raise ValueError("at_round must be >= 0")
        if self.kind == "slow" and self.factor < 1.0:
            raise ValueError("slow-worker factor must be >= 1.0")
        if self.kind == "bw-cap" and self.rate_bps <= 0:
            raise ValueError("bw-cap rate must be positive")


def _is_number(token: str) -> bool:
    try:
        float(token)
    except ValueError:
        return False
    return True


def parse_chaos_spec(spec: str, target: str) -> ChaosAction:
    """Parse ONE CLI chaos spec into an action.

    ``target`` is the harness's default victim; specs that name a peer
    inline (``bw-cap:w1:10``, ``slow-worker:w2:4``) override it. Numeric
    second fields keep their historical meaning (round for the event
    kinds, factor/rate for the degrade kinds).
    """
    parts = spec.split(":")
    head = parts[0]
    if head in ("kill-worker", "kill"):
        kind = "kill"
    elif head in ("delay-worker", "delay"):
        kind = "delay"
    elif head in ("partition-worker", "partition"):
        kind = "partition"
    elif head in (
        "kill-ps", "partition-ps", "kill-scheduler", "partition-scheduler"
    ):
        kind = head
    elif head in ("slow-worker", "slow"):
        kind = "slow"
    elif head == "bw-cap":
        kind = "bw-cap"
    elif head in ("jitter", "jitter-link"):
        kind = "jitter"
    else:
        raise ValueError(f"unknown chaos spec {spec!r}")
    args = parts[1:]
    if kind in _DEGRADE_KINDS:
        # Optional inline peer first (bw-cap REQUIRES one — a bandwidth cap
        # on "the default victim" is too easy to point at the wrong link).
        if args and not _is_number(args[0]):
            target = args[0]
            args = args[1:]
        elif kind == "bw-cap":
            raise ValueError(f"bw-cap needs a peer: bw-cap:<peer>:<mbps> ({spec!r})")
        if kind == "slow":
            factor = float(args[0]) if args else 4.0
            at_round = int(args[1]) if len(args) > 1 else 0
            return ChaosAction(
                kind=kind, target=target, at_round=at_round, factor=factor
            )
        if kind == "bw-cap":
            if not args:
                raise ValueError(f"bw-cap needs a rate: bw-cap:<peer>:<mbps> ({spec!r})")
            rate_bps = float(args[0]) * 1e6
            at_round = int(args[1]) if len(args) > 1 else 0
            return ChaosAction(
                kind=kind, target=target, at_round=at_round, rate_bps=rate_bps
            )
        delay_s = float(args[0]) if args else 0.25
        at_round = int(args[1]) if len(args) > 1 else 0
        return ChaosAction(
            kind=kind, target=target, at_round=at_round, delay_s=delay_s
        )
    at_round = int(args[0]) if args else 1
    default_delay = 3.0 if kind in ("partition-ps", "partition-scheduler") else 1.0
    delay_s = float(args[1]) if len(args) > 1 else default_delay
    return ChaosAction(kind=kind, target=target, at_round=at_round, delay_s=delay_s)


def parse_chaos_specs(spec: str, target: str) -> list[ChaosAction]:
    """Parse a comma-composed CLI chaos spec (``kill-worker:2,bw-cap:w1:10``)
    into the action list — one scenario can now mix an event with steady
    degrade conditions instead of exactly one action per run."""
    actions = [
        parse_chaos_spec(part.strip(), target)
        for part in spec.split(",")
        if part.strip()
    ]
    if not actions:
        raise ValueError(f"empty chaos spec {spec!r}")
    return actions


class ChaosController:
    """Runs scripted :class:`ChaosAction`s against in-process worker nodes.

    ``workers`` maps peer id → WorkerNode (anything with ``.stop()`` and
    ``.node``). Wire :meth:`metrics_hook` into the orchestrator's metrics
    connector so round completions drive the schedule.
    """

    def __init__(
        self,
        actions: list[ChaosAction],
        workers: dict[str, Any],
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.actions = list(actions)
        self.workers = dict(workers)
        self._clock = clock
        self._tasks: set[asyncio.Task] = set()
        self.fired: list[ChaosAction] = []
        for action in self.actions:
            if action.at_round == 0:
                self._fire(action)

    # ---------------------------------------------------------------- hooks
    def metrics_hook(
        self, inner: Callable[[str, int, dict], None] | None = None
    ) -> Callable[[str, int, dict], None]:
        """A metrics callback for CallbackConnector; chains to ``inner``."""

        def on_metrics(peer: str, round_num: int, metrics: dict) -> None:
            self.on_round_metrics(round_num)
            if inner is not None:
                inner(peer, round_num, metrics)

        return on_metrics

    def on_round_metrics(self, round_num: int) -> None:
        """A worker reported metrics for ``round_num`` (end of that round)."""
        for action in self.actions:
            if action.fired_at is None and action.at_round <= round_num + 1:
                self._fire(action)

    # ---------------------------------------------------------------- firing
    def _fire(self, action: ChaosAction) -> None:
        action.fired_at = self._clock()
        self.fired.append(action)
        # Flight-recorder breadcrumb: a stalled round's forensics must show
        # WHEN the injected fault fired, next to the retries/drops it caused.
        from ..telemetry.flight import FLIGHT

        FLIGHT.record(
            f"chaos.{action.kind}", node=action.target,
            target=action.target, at_round=action.at_round,
            delay_s=action.delay_s, factor=action.factor,
            rate_bps=action.rate_bps,
        )
        worker = self.workers.get(action.target)
        if worker is None:
            log.warning("chaos: no worker %r to %s", action.target, action.kind)
            return
        log.info("chaos: %s %s (round trigger %d)", action.kind, action.target, action.at_round)
        if action.kind in ("kill", "kill-ps", "kill-scheduler"):
            aio.spawn(
                self._kill(worker), tasks=self._tasks, what="chaos kill", logger=log
            )
        elif action.kind == "delay":
            self._wrap_push_delay(worker.node, action.delay_s)
        elif action.kind == "partition":
            self._partition(worker.node)
        elif action.kind == "partition-ps":
            self._partition_ps(action.target, action.delay_s)
        elif action.kind == "partition-scheduler":
            self._partition_scheduler(action.target, action.delay_s)
        elif action.kind == "slow":
            self._wrap_slow_cpu(worker.node, action.factor)
        elif action.kind == "bw-cap":
            self._wrap_bw_cap(action.target, action.rate_bps)
        elif action.kind == "jitter":
            self._wrap_jitter(action.target, action.delay_s)

    @staticmethod
    async def _kill(worker: Any) -> None:
        """Crash semantics: sever the NODE first (instant network death —
        in-flight deltas and heartbeats stop mid-round), then reap the
        worker's local state in the background. A graceful worker.stop()
        alone lets the training thread finish shipping the current round's
        delta, which is a shutdown, not a failure."""
        node_stop = getattr(getattr(worker, "node", None), "stop", None)
        try:
            if callable(node_stop):
                await node_stop()
            await worker.stop()
        except Exception as e:
            # CancelledError propagates: a cancelled kill task must end
            # cancelled, not swallow its own teardown signal.
            log.warning("chaos kill: stop raised %s", e)

    @staticmethod
    def _wrap_push_delay(node: Any, delay_s: float) -> None:
        orig_push = node.push

        async def delayed_push(peer_id: str, resource: Any, source) -> int:
            await asyncio.sleep(delay_s)
            return await orig_push(peer_id, resource, source)

        node.push = delayed_push

    # ------------------------------------------------------- degrade wraps

    @staticmethod
    def _wrap_slow_cpu(node: Any, factor: float) -> None:
        """A slow-CPU worker: stretch every per-batch Status round-trip.

        The training thread synchronously awaits each Status response
        between batches, so sleeping ``(factor - 1) × compute`` in the
        request path makes every inner batch take ~``factor``× its
        natural wall-clock — real to the scheduler's timing statistics,
        the PS round deadline, and the worker alike. The compute estimate
        is the gap since we released the PREVIOUS Status (excluding our
        own injected sleeps, so the slowdown is a stable multiplier
        instead of compounding geometrically)."""
        from ..messages import PROTOCOL_PROGRESS, ProgressKind

        orig_request = node.request
        state = {"last": None}

        async def slow_request(peer_id: str, protocol: str, msg: Any, **kw) -> Any:
            if protocol == PROTOCOL_PROGRESS:
                if getattr(msg, "kind", None) != ProgressKind.STATUS:
                    # Round boundary (update / metrics / update-received):
                    # the gap to the NEXT status is broadcast wait, not
                    # compute — stretching it would model a slow NETWORK
                    # (and make the φ detector see huge one-off stalls),
                    # not a slow CPU. Drop the baseline instead.
                    state["last"] = None
                    return await orig_request(peer_id, protocol, msg, **kw)
                now = time.monotonic()
                last = state["last"]
                if last is not None and now > last:
                    await asyncio.sleep((factor - 1.0) * (now - last))
                result = await orig_request(peer_id, protocol, msg, **kw)
                state["last"] = time.monotonic()
                return result
            return await orig_request(peer_id, protocol, msg, **kw)

        node.request = slow_request

    @staticmethod
    def _throttled_source(source, rate_bps: float):
        """Wrap a push source (bytes | file path) in an async iterator that
        trickles chunks at ``rate_bps`` BITS/second — the receiver sees
        the cap DURING the transfer (its save_to measures it), not as an
        up-front delay it cannot attribute to the link."""

        async def gen():
            if isinstance(source, (bytes, bytearray, memoryview)):
                data = bytes(source)
                for i in range(0, max(len(data), 1), _THROTTLE_CHUNK):
                    chunk = data[i : i + _THROTTLE_CHUNK]
                    await asyncio.sleep(len(chunk) * 8.0 / rate_bps)
                    if chunk:
                        yield chunk
                return
            f = await asyncio.to_thread(open, source, "rb")
            try:
                while True:
                    chunk = await asyncio.to_thread(f.read, _THROTTLE_CHUNK)
                    if not chunk:
                        break
                    await asyncio.sleep(len(chunk) * 8.0 / rate_bps)
                    yield chunk
            finally:
                await asyncio.to_thread(f.close)

        return gen()

    def _maybe_throttled(self, source, rate_bps: float):
        """Throttle byte/file sources; pass anything already streaming
        (an async iterator — e.g. a previously wrapped source) through."""
        if isinstance(source, (bytes, bytearray, memoryview, str, Path)):
            return self._throttled_source(source, rate_bps)
        return source

    def _wrap_bw_cap(self, target: str, rate_bps: float) -> None:
        """Cap every push AND pull payload on the target's LINK (both
        directions): its own uploads (delta pushes) and served pulls
        (a capped DATA NODE's slice streams), plus pushes/pull payloads
        toward it from every other node the controller holds (update
        broadcasts, catch-ups, slices it pulls)."""
        for name, worker in self.workers.items():
            node = getattr(worker, "node", None)
            if node is None:
                continue
            handler = getattr(node, "_pull_handler", None)
            if handler is not None:
                if name == target:

                    async def capped_pull(
                        peer: str, resource: Any, _h=handler
                    ):
                        return self._maybe_throttled(
                            await _h(peer, resource), rate_bps
                        )

                else:

                    async def capped_pull(
                        peer: str, resource: Any, _h=handler
                    ):
                        source = await _h(peer, resource)
                        if peer != target:
                            return source
                        return self._maybe_throttled(source, rate_bps)

                node.on_pull(capped_pull)
            orig_push = node.push

            if name == target:

                async def capped_push(
                    peer_id: str, resource: Any, source, _orig=orig_push
                ) -> int:
                    return await _orig(
                        peer_id, resource,
                        self._throttled_source(source, rate_bps),
                    )

            else:

                async def capped_push(
                    peer_id: str, resource: Any, source, _orig=orig_push
                ) -> int:
                    if peer_id != target:
                        return await _orig(peer_id, resource, source)
                    return await _orig(
                        peer_id, resource,
                        self._throttled_source(source, rate_bps),
                    )

            node.push = capped_push

    def _wrap_jitter(self, target: str, max_delay_s: float) -> None:
        """Deterministic pseudo-random delay in [0, max_delay_s] on every
        push touching the target's link — seeded per target, so a re-run
        sees the identical delay sequence."""
        rng = random.Random(f"hypha-chaos-jitter:{target}:{max_delay_s}")

        for name, worker in self.workers.items():
            node = getattr(worker, "node", None)
            if node is None:
                continue
            orig_push = node.push
            mine = name == target

            async def jittery_push(
                peer_id: str, resource: Any, source,
                _orig=orig_push, _mine=mine,
            ) -> int:
                if _mine or peer_id == target:
                    await asyncio.sleep(rng.uniform(0.0, max_delay_s))
                return await _orig(peer_id, resource, source)

            node.push = jittery_push

    def _partition_ps(self, ps_peer: str, duration_s: float) -> None:
        """Sever the data plane between ``ps_peer`` and every other worker
        for ``duration_s`` seconds, then heal. Workers' pushes toward the
        PS (and the PS's broadcasts out) fail with RequestError — the
        exact shape a mid-restart PS presents — so the client retry path
        (aio.retry in the connectors) is what keeps the round alive."""
        from ..network.node import RequestError

        undo: list[tuple[Any, Any]] = []
        for name, worker in self.workers.items():
            node = getattr(worker, "node", None)
            if node is None:
                continue
            orig_push = node.push
            if name == ps_peer:

                async def ps_push(peer_id: str, resource: Any, source) -> int:
                    raise RequestError(
                        "chaos partition-ps: broadcast push dropped"
                    )

                node.push = ps_push
            else:

                async def worker_push(
                    peer_id: str, resource: Any, source, _orig=orig_push
                ) -> int:
                    if peer_id == ps_peer:
                        raise RequestError(
                            f"chaos partition-ps: push to {ps_peer} dropped"
                        )
                    return await _orig(peer_id, resource, source)

                node.push = worker_push
            undo.append((node, orig_push))

        async def heal() -> None:
            await asyncio.sleep(duration_s)
            for node, orig_push in undo:
                node.push = orig_push
            log.info("chaos: partition-ps around %s healed", ps_peer)

        aio.spawn(heal(), tasks=self._tasks, what="chaos heal", logger=log)

    def _partition_scheduler(self, sched_peer: str, duration_s: float) -> None:
        """Sever the fleet's UPLINK to the scheduler for ``duration_s``
        seconds, then heal. Every other node's requests (Status,
        UpdateReceived, Updated, JobStatus) and pushes toward the
        scheduler fail with RequestError — the exact shape a dead/restart-
        ing scheduler presents — so the park-in-aio.retry paths (bridge
        status sends, the PS's resilient Updated notify) are what keep the
        job alive. The scheduler's own outbound renewals are untouched:
        this models uplink loss, not the full crash (``kill-scheduler``
        covers that one)."""
        from ..network.node import RequestError

        undo: list[tuple[Any, Any, Any]] = []
        for name, worker in self.workers.items():
            if name == sched_peer:
                continue
            node = getattr(worker, "node", None)
            if node is None:
                continue
            orig_push = node.push
            orig_request = node.request

            async def cut_push(
                peer_id: str, resource: Any, source, _orig=orig_push
            ) -> int:
                if peer_id == sched_peer:
                    raise RequestError(
                        f"chaos partition-scheduler: push to {sched_peer} dropped"
                    )
                return await _orig(peer_id, resource, source)

            async def cut_request(
                peer_id: str, protocol: str, msg: Any,
                _orig=orig_request, **kw,
            ) -> Any:
                if peer_id == sched_peer:
                    raise RequestError(
                        f"chaos partition-scheduler: request to {sched_peer} dropped"
                    )
                return await _orig(peer_id, protocol, msg, **kw)

            node.push = cut_push
            node.request = cut_request
            undo.append((node, orig_push, orig_request))

        async def heal() -> None:
            await asyncio.sleep(duration_s)
            for node, orig_push, orig_request in undo:
                node.push = orig_push
                node.request = orig_request
            log.info(
                "chaos: partition-scheduler around %s healed", sched_peer
            )

        aio.spawn(heal(), tasks=self._tasks, what="chaos heal", logger=log)

    @staticmethod
    def _partition(node: Any) -> None:
        from ..network.node import RequestError

        async def dead_push(peer_id: str, resource: Any, source) -> int:
            raise RequestError(f"chaos partition: push to {peer_id} dropped")

        async def dead_request(peer_id: str, protocol: str, msg: Any, **kw) -> Any:
            raise RequestError(f"chaos partition: request to {peer_id} dropped")

        node.push = dead_push
        node.request = dead_request

    # --------------------------------------------------------------- queries
    def fired_at(self, target: str) -> float | None:
        for action in self.fired:
            if action.target == target:
                return action.fired_at
        return None

    async def drain(self) -> None:
        """Wait for in-flight kill tasks (test teardown hygiene)."""
        if self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)
