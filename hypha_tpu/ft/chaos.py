"""Deterministic fault injection for tests and benchmarks.

Real failures are timing accidents; tests need them on a schedule. A
:class:`ChaosController` watches the same per-round metrics stream the
orchestrator's MetricsBridge sees and fires scripted actions at exact round
boundaries, against the in-process :class:`~hypha_tpu.worker.runtime.
WorkerNode` objects a test or ``bench.py --chaos`` holds:

  * ``kill``       — stop the worker node outright (lease renewals start
    failing, its delta never arrives: the canonical DiLoCo dropout);
  * ``delay``      — add ``delay_s`` to every outbound push (a straggler:
    its delta arrives but may miss the round deadline and be dropped as
    stale);
  * ``partition``  — fail every outbound push *and* request from the worker
    (uplink loss: the worker computes but cannot report; the φ detector is
    the only thing that can see this one);
  * ``kill-ps``      — stop the PARAMETER SERVER's worker node mid-round
    (the durable-PS recovery scenario, ft.durable: the harness restarts
    the node and the journal + generation handshake resume the round);
  * ``partition-ps`` — for ``delay_s`` seconds, drop every push between
    the PS and the workers (both directions): workers must park and
    re-push with backoff (aio.retry), and the PS journal must dedup the
    copies whose first attempt actually landed.

Trigger semantics: action ``at_round=r`` fires the first time a METRICS
event for round ``r-1`` is observed — i.e. while round ``r`` is running —
so "kill worker X mid-round r" is reproducible to the batch. ``at_round=0``
fires on attach (before the job's first batch).
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from .. import aio

__all__ = ["ChaosAction", "ChaosController", "parse_chaos_spec"]

log = logging.getLogger("hypha.ft.chaos")

_KINDS = ("kill", "delay", "partition", "kill-ps", "partition-ps")


@dataclass(slots=True)
class ChaosAction:
    kind: str  # "kill" | "delay" | "partition"
    target: str  # worker peer id
    at_round: int = 1
    delay_s: float = 0.0  # kind == "delay"
    fired_at: float | None = None  # monotonic time the action ran

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(f"unknown chaos kind {self.kind!r}")
        if self.at_round < 0:
            raise ValueError("at_round must be >= 0")


def parse_chaos_spec(spec: str, target: str) -> ChaosAction:
    """Parse a CLI chaos spec like ``kill-worker:1`` or ``delay-worker:2:0.5``
    into an action against ``target``."""
    parts = spec.split(":")
    head = parts[0]
    if head in ("kill-worker", "kill"):
        kind = "kill"
    elif head in ("delay-worker", "delay"):
        kind = "delay"
    elif head in ("partition-worker", "partition"):
        kind = "partition"
    elif head in ("kill-ps", "partition-ps"):
        kind = head
    else:
        raise ValueError(f"unknown chaos spec {spec!r}")
    at_round = int(parts[1]) if len(parts) > 1 else 1
    default_delay = 3.0 if kind == "partition-ps" else 1.0
    delay_s = float(parts[2]) if len(parts) > 2 else default_delay
    return ChaosAction(kind=kind, target=target, at_round=at_round, delay_s=delay_s)


class ChaosController:
    """Runs scripted :class:`ChaosAction`s against in-process worker nodes.

    ``workers`` maps peer id → WorkerNode (anything with ``.stop()`` and
    ``.node``). Wire :meth:`metrics_hook` into the orchestrator's metrics
    connector so round completions drive the schedule.
    """

    def __init__(
        self,
        actions: list[ChaosAction],
        workers: dict[str, Any],
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.actions = list(actions)
        self.workers = dict(workers)
        self._clock = clock
        self._tasks: set[asyncio.Task] = set()
        self.fired: list[ChaosAction] = []
        for action in self.actions:
            if action.at_round == 0:
                self._fire(action)

    # ---------------------------------------------------------------- hooks
    def metrics_hook(
        self, inner: Callable[[str, int, dict], None] | None = None
    ) -> Callable[[str, int, dict], None]:
        """A metrics callback for CallbackConnector; chains to ``inner``."""

        def on_metrics(peer: str, round_num: int, metrics: dict) -> None:
            self.on_round_metrics(round_num)
            if inner is not None:
                inner(peer, round_num, metrics)

        return on_metrics

    def on_round_metrics(self, round_num: int) -> None:
        """A worker reported metrics for ``round_num`` (end of that round)."""
        for action in self.actions:
            if action.fired_at is None and action.at_round <= round_num + 1:
                self._fire(action)

    # ---------------------------------------------------------------- firing
    def _fire(self, action: ChaosAction) -> None:
        action.fired_at = self._clock()
        self.fired.append(action)
        worker = self.workers.get(action.target)
        if worker is None:
            log.warning("chaos: no worker %r to %s", action.target, action.kind)
            return
        log.info("chaos: %s %s (round trigger %d)", action.kind, action.target, action.at_round)
        if action.kind in ("kill", "kill-ps"):
            aio.spawn(
                self._kill(worker), tasks=self._tasks, what="chaos kill", logger=log
            )
        elif action.kind == "delay":
            self._wrap_push_delay(worker.node, action.delay_s)
        elif action.kind == "partition":
            self._partition(worker.node)
        elif action.kind == "partition-ps":
            self._partition_ps(action.target, action.delay_s)

    @staticmethod
    async def _kill(worker: Any) -> None:
        """Crash semantics: sever the NODE first (instant network death —
        in-flight deltas and heartbeats stop mid-round), then reap the
        worker's local state in the background. A graceful worker.stop()
        alone lets the training thread finish shipping the current round's
        delta, which is a shutdown, not a failure."""
        node_stop = getattr(getattr(worker, "node", None), "stop", None)
        try:
            if callable(node_stop):
                await node_stop()
            await worker.stop()
        except Exception as e:
            # CancelledError propagates: a cancelled kill task must end
            # cancelled, not swallow its own teardown signal.
            log.warning("chaos kill: stop raised %s", e)

    @staticmethod
    def _wrap_push_delay(node: Any, delay_s: float) -> None:
        orig_push = node.push

        async def delayed_push(peer_id: str, resource: Any, source) -> int:
            await asyncio.sleep(delay_s)
            return await orig_push(peer_id, resource, source)

        node.push = delayed_push

    def _partition_ps(self, ps_peer: str, duration_s: float) -> None:
        """Sever the data plane between ``ps_peer`` and every other worker
        for ``duration_s`` seconds, then heal. Workers' pushes toward the
        PS (and the PS's broadcasts out) fail with RequestError — the
        exact shape a mid-restart PS presents — so the client retry path
        (aio.retry in the connectors) is what keeps the round alive."""
        from ..network.node import RequestError

        undo: list[tuple[Any, Any]] = []
        for name, worker in self.workers.items():
            node = getattr(worker, "node", None)
            if node is None:
                continue
            orig_push = node.push
            if name == ps_peer:

                async def ps_push(peer_id: str, resource: Any, source) -> int:
                    raise RequestError(
                        "chaos partition-ps: broadcast push dropped"
                    )

                node.push = ps_push
            else:

                async def worker_push(
                    peer_id: str, resource: Any, source, _orig=orig_push
                ) -> int:
                    if peer_id == ps_peer:
                        raise RequestError(
                            f"chaos partition-ps: push to {ps_peer} dropped"
                        )
                    return await _orig(peer_id, resource, source)

                node.push = worker_push
            undo.append((node, orig_push))

        async def heal() -> None:
            await asyncio.sleep(duration_s)
            for node, orig_push in undo:
                node.push = orig_push
            log.info("chaos: partition-ps around %s healed", ps_peer)

        aio.spawn(heal(), tasks=self._tasks, what="chaos heal", logger=log)

    @staticmethod
    def _partition(node: Any) -> None:
        from ..network.node import RequestError

        async def dead_push(peer_id: str, resource: Any, source) -> int:
            raise RequestError(f"chaos partition: push to {peer_id} dropped")

        async def dead_request(peer_id: str, protocol: str, msg: Any, **kw) -> Any:
            raise RequestError(f"chaos partition: request to {peer_id} dropped")

        node.push = dead_push
        node.request = dead_request

    # --------------------------------------------------------------- queries
    def fired_at(self, target: str) -> float | None:
        for action in self.fired:
            if action.target == target:
                return action.fired_at
        return None

    async def drain(self) -> None:
        """Wait for in-flight kill tasks (test teardown hygiene)."""
        if self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)
