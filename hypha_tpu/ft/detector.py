"""Phi-accrual failure detection over heartbeat arrivals.

Hayashibara et al., "The φ Accrual Failure Detector" (2004): instead of a
boolean alive/dead verdict, expose a continuous suspicion level

    φ(t) = -log10( P_later(t - t_last) )

where ``P_later`` is the probability that a heartbeat arrives later than the
current silence, under a normal distribution fitted to the observed
inter-arrival history. φ grows without bound while a peer is silent and
drops back to ~0 the moment a heartbeat lands (re-heal), so a threshold
crossing is a *tunable* trade between detection latency and false positives
— exactly what an unreliable permissioned swarm needs on top of the hard
lease-renewal signal (worker/lease_manager.py): renewals are seconds apart,
per-batch ``Status`` progress events are tens of milliseconds apart, and the
detector consumes both streams without caring which is which.

Pure logic with an injectable clock for deterministic tests.
"""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Callable

__all__ = ["PhiAccrualDetector", "PHI_THRESHOLD_DEFAULT"]

# Cassandra's production default is 8 (~a 1-in-10^8 chance the peer is
# actually alive); we keep the same order of magnitude.
PHI_THRESHOLD_DEFAULT = 8.0

# Floor on the fitted standard deviation: a perfectly regular heartbeat
# (simulated clocks, in-process tests) would otherwise make φ a step
# function that fires on the first microsecond of jitter.
_MIN_STD_S = 0.05

# NOTE: a peer that dies with fewer than ``min_samples`` recorded intervals
# is never suspected by φ (phi() returns 0.0 below the warm-up gate, by
# design — see PhiAccrualDetector.min_samples).  Early death is caught by
# the lease-renewal failure path instead, which needs no distribution.


def _phi_of_z(z: float) -> float:
    """φ as a function of the standardized silence z (the exact formula
    :meth:`PhiAccrualDetector.phi` evaluates, including its underflow
    fallback) — strictly monotone increasing."""
    p_later = 0.5 * math.erfc(z)
    if p_later <= 0.0:
        return z * z / math.log(10.0)
    return -math.log10(p_later)


def _solve_z(threshold: float) -> float:
    """The z where φ crosses ``threshold``, by bisection (φ is monotone;
    one solve per detector, reused for every peer's suspect_at)."""
    lo, hi = -10.0, 10.0
    while _phi_of_z(hi) < threshold:
        hi *= 2.0
        if hi > 1e6:  # pathological threshold; fall back to "always check"
            return float("-inf")
    for _ in range(200):
        mid = (lo + hi) / 2.0
        if _phi_of_z(mid) < threshold:
            lo = mid
        else:
            hi = mid
    return hi


class _PeerHistory:
    __slots__ = ("intervals", "last", "_sum", "_sum_sq", "suspect_at")

    def __init__(self, now: float, window: int) -> None:
        self.intervals: deque[float] = deque(maxlen=window)
        self.last = now
        self._sum = 0.0
        self._sum_sq = 0.0
        # Earliest clock() at which φ can reach the detector's threshold
        # (solved in closed form from the fitted distribution at each
        # heartbeat). Until then suspicion checks are ONE float compare —
        # the poll loop's per-tick cost stops scaling with erfc calls at
        # fleet size (ISSUE 14).
        self.suspect_at = float("inf")

    def record(self, now: float) -> None:
        interval = max(now - self.last, 0.0)
        self.last = now
        if len(self.intervals) == self.intervals.maxlen:
            old = self.intervals[0]
            self._sum -= old
            self._sum_sq -= old * old
        self.intervals.append(interval)
        self._sum += interval
        self._sum_sq += interval * interval

    def mean_std(self) -> tuple[float, float]:
        # Only reached past the min_samples warm-up gate, so n >= 1 always.
        n = len(self.intervals)
        mean = self._sum / n
        var = max(self._sum_sq / n - mean * mean, 0.0)
        return mean, max(math.sqrt(var), _MIN_STD_S)


class PhiAccrualDetector:
    """Per-peer suspicion levels from heartbeat inter-arrival statistics."""

    def __init__(
        self,
        threshold: float = PHI_THRESHOLD_DEFAULT,
        window: int = 128,
        min_samples: int = 3,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if threshold <= 0:
            raise ValueError("phi threshold must be positive")
        self.threshold = threshold
        self.window = window
        # Warm-up gate: with fewer than this many observed intervals there
        # is no distribution worth trusting — a worker's first batches can
        # be separated by a multi-second jit compile, and suspecting the
        # whole fleet at startup helps nobody.
        self.min_samples = min_samples
        self._clock = clock
        self._peers: dict[str, _PeerHistory] = {}
        # z* with φ(z*) == threshold (φ is strictly monotone in z): the
        # crossing elapsed is mean + z*·√2·std, giving every peer a
        # closed-form suspect_at timestamp per heartbeat.
        self._z_threshold = _solve_z(threshold)

    # -- feeding -------------------------------------------------------------
    def heartbeat(self, peer: str) -> None:
        """Any liveness signal: Status progress, lease renewal, metrics."""
        now = self._clock()
        hist = self._peers.get(peer)
        if hist is None:
            self._peers[peer] = _PeerHistory(now, self.window)
        else:
            hist.record(now)
            if len(hist.intervals) >= self.min_samples:
                mean, std = hist.mean_std()
                hist.suspect_at = (
                    now + mean + self._z_threshold * std * math.sqrt(2.0)
                )

    def remove(self, peer: str) -> None:
        self._peers.pop(peer, None)

    def peers(self) -> list[str]:
        return list(self._peers)

    # -- querying ------------------------------------------------------------
    def phi(self, peer: str) -> float:
        """Current suspicion level; 0.0 for unknown peers (benefit of the
        doubt until they have spoken at least once)."""
        hist = self._peers.get(peer)
        if hist is None or len(hist.intervals) < self.min_samples:
            return 0.0
        elapsed = self._clock() - hist.last
        if elapsed <= 0:
            return 0.0
        mean, std = hist.mean_std()
        # P(heartbeat later than `elapsed`) under N(mean, std).
        z = (elapsed - mean) / (std * math.sqrt(2.0))
        p_later = 0.5 * math.erfc(z)
        if p_later <= 0.0:
            # erfc underflowed: far past any plausible arrival. Use the
            # asymptotic tail so φ keeps growing monotonically instead of
            # saturating at an arbitrary cap.
            return z * z / math.log(10.0)
        return -math.log10(p_later)

    def suspected(self, peer: str) -> bool:
        # Fast negative (the overwhelming case): before suspect_at the
        # fitted φ cannot have crossed the threshold — one float compare
        # instead of an erfc per peer per poll tick. The exact φ check
        # stays the verdict past the horizon (and for short histories,
        # whose suspect_at is still +inf).
        hist = self._peers.get(peer)
        if hist is None or self._clock() < hist.suspect_at:
            return False
        return self.phi(peer) >= self.threshold

    def suspicion_levels(self) -> dict[str, float]:
        """Snapshot of φ for every known peer (telemetry / orchestrator)."""
        return {peer: self.phi(peer) for peer in self._peers}
