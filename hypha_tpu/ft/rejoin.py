"""Worker rejoin: catch a replacement up to the current global weights.

Protocol. The parameter server never holds full weights — it sees only
pseudo-gradients and emits update tensors — but every worker initializes
θ₀ deterministically from the job's model seed. So "current global weights"
factor as

    θ_r = θ₀ + Σ_{k<r} update_k

and the PS *can* cheaply maintain the running sum Σ update_k (one
param-sized f32 tree, accumulated each round). A rejoining worker:

  1. is dispatched a train job with ``rejoin=True`` (same job-unique
     updates/results tags as the original workers);
  2. initializes θ₀ from the seed like everyone else;
  3. blocks on its results stream until a push whose header carries
     ``catchup: True`` arrives — the PS's cumulative update Σ_{k<r},
     stamped with the authoritative next round number ``r`` and the
     membership epoch;
  4. merges it (θ ← θ₀ + Σ), re-anchors, sets ``round_num = r`` and enters
     the normal inner loop — contributing to round ``r`` like any other
     member, no whole-job restart anywhere.

The PS serves catch-ups only at consistent points (between collecting and
the next round's first broadcast), so a rejoiner can never observe a
regular round update before its catch-up; :func:`await_catchup` still skips
stray non-catch-up events defensively, because their content is *included*
in any later cumulative sum.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Callable, Iterable, Iterator

import numpy as np
from safetensors.numpy import save_file

from ..compress import read_delta

__all__ = ["CatchupBuffer", "await_catchup", "CATCHUP_KEY"]

# Header key marking a results-stream push as a rejoin catch-up.
CATCHUP_KEY = "catchup"


class CatchupBuffer:
    """The parameter server's running Σ of broadcast updates (f32, host).

    Kept in memory between rounds; written to a SafeTensors file on demand
    when a rejoiner needs it. Empty until the first outer step — a worker
    joining during round 0 receives an empty catch-up (nothing to merge,
    θ₀ already is the global state).

    Fragment-aware (hypha_tpu.stream): a streaming job's rounds each carry
    ONE fragment's update, and the pipelined parameter server may fold
    them as their broadcasts complete — not necessarily in global round
    order. That is still exact: every tensor belongs to exactly one
    fragment, so as long as each FRAGMENT's updates accumulate in its own
    round order (they do — a fragment closes sequentially every F rounds),
    each leaf's f32 additions happen in the same order a worker's merges
    did, and θ₀ + Σ reproduces worker params bit-for-bit regardless of
    how the fragments interleaved. ``fragment_rounds`` tracks the per-
    fragment fold counts so tests (and the catch-up metadata) can assert
    the interleaving never skipped a fragment round.
    """

    def __init__(self) -> None:
        self._cum: dict[str, np.ndarray] = {}
        self.rounds = 0  # outer updates accumulated so far
        # fragment_id -> updates folded for it (None = unfragmented jobs).
        self.fragment_rounds: dict[int | None, int] = {}
        self._written: tuple[int, str] | None = None  # (rounds, path) cache

    def accumulate(
        self, update_path: Path | str, fragment_id: int | None = None
    ) -> None:
        """Fold one round's update file into the running sum.

        Decode-aware (hypha_tpu.compress.read_delta): a quantized (HQD1)
        or bf16 broadcast accumulates at its DECODED values — what every
        worker actually merged — so θ₀ + Σ reproduces their params
        exactly regardless of wire codec.
        """
        self.accumulate_tree(read_delta(update_path), fragment_id=fragment_id)

    def accumulate_tree(
        self, update: dict, fragment_id: int | None = None
    ) -> None:
        """Fold one round's already-decoded update tree into the sum (the
        PS's broadcast encode returns exactly this tree — re-reading the
        parameter-sized wire file would be pure waste). ``fragment_id``
        names the fragment a streaming round synced; leaves of other
        fragments are untouched by construction (the update only carries
        the due fragment's tensors)."""
        for key, value in update.items():
            arr = np.asarray(value, np.float32)
            prev = self._cum.get(key)
            if prev is None:
                self._cum[key] = arr.copy()
            elif prev.shape != arr.shape:
                raise ValueError(
                    f"catchup {key!r}: update shape {arr.shape} != {prev.shape}"
                )
            else:
                prev += arr
        self.rounds += 1
        self.fragment_rounds[fragment_id] = (
            self.fragment_rounds.get(fragment_id, 0) + 1
        )

    def state(self) -> tuple[dict[str, np.ndarray], int, dict[int | None, int]]:
        """(cumulative sum, rounds, fragment_rounds) for ft.durable's
        outer-state checkpoint — a recovered PS must serve rejoiners the
        same Σ its predecessor held."""
        return dict(self._cum), self.rounds, dict(self.fragment_rounds)

    def restore(
        self,
        cum: dict[str, np.ndarray],
        rounds: int,
        fragment_rounds: dict[int | None, int],
    ) -> None:
        self._cum = {k: np.asarray(v, np.float32).copy() for k, v in cum.items()}
        self.rounds = int(rounds)
        self.fragment_rounds = dict(fragment_rounds)
        self._written = None

    def write(self, path: Path | str) -> Path:
        """Materialize the sum for a catch-up push (atomic via temp name).

        Idempotent per accumulation state: the sum only changes in
        :meth:`accumulate`, so re-serializing the parameter-sized file for
        every pending rejoiner / retry tick would be pure waste.
        """
        path = Path(path)
        if self._written == (self.rounds, str(path)) and path.is_file():
            return path
        tmp = path.with_suffix(".tmp")
        save_file(self._cum, str(tmp))
        tmp.replace(path)
        self._written = (self.rounds, str(path))
        return path

    def is_empty(self) -> bool:
        return not self._cum


def await_catchup(
    events: Iterator[dict[str, Any]],
    on_skip: Callable[[dict[str, Any]], None] | None = None,
) -> dict[str, Any]:
    """Consume bridge receive events until the catch-up arrives.

    Returns the catch-up event (its ``meta`` carries ``round`` and
    ``epoch``). Non-catch-up events that race in first are handed to
    ``on_skip`` (e.g. to unlink the file) and dropped — safe because any
    round update a rejoiner could see here is already folded into the
    cumulative sum it is waiting for.
    """
    for event in events:
        meta = event.get("meta") or {}
        if meta.get(CATCHUP_KEY):
            return event
        if on_skip is not None:
            on_skip(event)
    raise RuntimeError("results stream ended before the rejoin catch-up arrived")


def merge_catchup_arrays(
    params: dict[str, np.ndarray], cum: dict[str, np.ndarray]
) -> dict[str, np.ndarray]:
    """Host-side θ₀ + Σ merge for non-JAX callers (tests, tools); the
    executor's hot path uses the jitted tree op (executor.diloco)."""
    merged = dict(params)
    for key, value in cum.items():
        if key not in merged:
            raise KeyError(f"catchup tensor {key!r} not in params")
        base = merged[key]
        merged[key] = (base.astype(np.float32) + value).astype(base.dtype)
    return merged


def sum_updates(paths: Iterable[Path | str]) -> dict[str, np.ndarray]:
    """Σ over a list of update files (utility mirror of CatchupBuffer)."""
    buf = CatchupBuffer()
    for p in paths:
        buf.accumulate(p)
    return dict(buf._cum)
