"""Fault tolerance for DiLoCo rounds: elastic round membership.

The seed blocks each outer round on exactly ``num_workers`` deltas and
recovers from any worker failure by restarting the whole job. This package
replaces that with graceful degradation — DiLoCo's outer average is well
defined over whichever replicas actually reported:

  detector.py   — φ-accrual failure detector over heartbeats/lease renewals
  membership.py — epoch-numbered RoundMembership + the FT wire vocabulary
  rejoin.py     — catch-up protocol (θ_r = θ₀ + Σ updates) for replacements
  durable.py    — parameter-server round journal + outer-state checkpoint:
                  a PS crash resumes the interrupted round (generation ids
                  + client retry make re-sent deltas idempotent)
  adaptive.py   — WAN-adaptive outer rounds: straggler-adaptive per-worker
                  inner steps (EWMA round-trip history) + per-link codec
                  selection from a measured-bandwidth table
  chaos.py      — deterministic fault injection for tests and bench.py
                  (kill / delay / partition events + steady degrade modes:
                  slow-CPU workers, per-link bandwidth caps, jitter)

See docs/fault_tolerance.md for the full protocol description.
"""

from .adaptive import Ewma, LinkTable, StragglerController
from .chaos import (
    ChaosAction,
    ChaosController,
    parse_chaos_spec,
    parse_chaos_specs,
)
from .detector import PHI_THRESHOLD_DEFAULT, PhiAccrualDetector
from .durable import GENERATION_KEY, DurablePS, DurableScheduler, RoundJournal
from .membership import (
    PROTOCOL_FT,
    FTConfig,
    MembershipUpdate,
    MembershipView,
    RoundMembership,
    quorum_size,
)
from .rejoin import CATCHUP_KEY, CatchupBuffer, await_catchup

__all__ = [
    "PHI_THRESHOLD_DEFAULT",
    "PhiAccrualDetector",
    "PROTOCOL_FT",
    "FTConfig",
    "MembershipUpdate",
    "MembershipView",
    "RoundMembership",
    "quorum_size",
    "CATCHUP_KEY",
    "GENERATION_KEY",
    "CatchupBuffer",
    "DurablePS",
    "DurableScheduler",
    "RoundJournal",
    "await_catchup",
    "ChaosAction",
    "ChaosController",
    "parse_chaos_spec",
    "parse_chaos_specs",
    "Ewma",
    "LinkTable",
    "StragglerController",
]
