"""Dev PKI generator CLI — ``python -m hypha_tpu.certutil``.

Parity with the reference's ``hypha-certutil`` binary
(reference: crates/certutil/src/main.rs:20-87): generates the three-tier
Ed25519 hierarchy (root CA → org CA → node certs with SANs) plus CRLs.

    python -m hypha_tpu.certutil root --out pki/
    python -m hypha_tpu.certutil org  --out pki/ --name my-org
    python -m hypha_tpu.certutil node --out pki/ --org my-org --name worker-1 \
        --san localhost --san 10.0.0.5
    python -m hypha_tpu.certutil revoke --out pki/ --org my-org --cert pki/worker-1.crt
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from . import certs


def _cmd_root(args) -> int:
    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    cert, key = certs.generate_root_ca(args.name, days=args.days)
    (out / "root.crt").write_bytes(cert)
    key_path = out / "root.key"
    key_path.write_bytes(key)
    key_path.chmod(0o600)
    print(f"root CA written to {out}/root.crt")
    return 0


def _cmd_org(args) -> int:
    out = Path(args.out)
    cert, key = certs.generate_org_ca(
        args.name,
        (out / "root.crt").read_bytes(),
        (out / "root.key").read_bytes(),
        days=args.days,
    )
    (out / f"{args.name}.crt").write_bytes(cert)
    key_path = out / f"{args.name}.key"
    key_path.write_bytes(key)
    key_path.chmod(0o600)
    print(f"org CA written to {out}/{args.name}.crt")
    return 0


def _cmd_node(args) -> int:
    out = Path(args.out)
    paths = certs.write_node_dir(
        out,
        args.name,
        (out / f"{args.org}.crt").read_bytes(),
        (out / f"{args.org}.key").read_bytes(),
        (out / "root.crt").read_bytes(),
        sans=args.san or None,
    )
    print(f"node cert written to {paths['cert']}")
    print(f"peer id: {paths['peer_id']}")
    return 0


def _cmd_revoke(args) -> int:
    out = Path(args.out)
    crl_path = out / f"{args.org}.crl"
    revoked = [Path(c).read_bytes() for c in args.cert]
    # Carry forward serials already revoked: re-issuing the CRL must never
    # silently un-revoke certificates from earlier invocations.
    prior_serials: list[int] = []
    if crl_path.exists():
        for crl in certs.load_crls_from_pem(crl_path):
            prior_serials.extend(rc.serial_number for rc in crl)
    crl_pem = certs.generate_crl(
        (out / f"{args.org}.crt").read_bytes(),
        (out / f"{args.org}.key").read_bytes(),
        revoked,
        days=args.days,
        extra_revoked_serials=prior_serials,
    )
    crl_path.write_bytes(crl_pem)
    total = len(set(prior_serials)) + len(revoked)
    print(f"CRL written to {crl_path} ({len(revoked)} new, {total} total entries)")
    print("note: nodes load CRLs at startup only; restart nodes to apply")
    print(
        f"note: CRL expires in {args.days} days — an expired CRL blocks ALL "
        "peers on CRL-checking nodes; re-issue before then"
    )
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="hypha-certutil", description=__doc__)
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("root", help="generate the root CA")
    p.add_argument("--out", default="pki")
    p.add_argument("--name", default="hypha-root")
    p.add_argument("--days", type=int, default=3650)
    p.set_defaults(fn=_cmd_root)

    p = sub.add_parser("org", help="generate an org CA signed by the root")
    p.add_argument("--out", default="pki")
    p.add_argument("--name", required=True)
    p.add_argument("--days", type=int, default=1825)
    p.set_defaults(fn=_cmd_org)

    p = sub.add_parser("node", help="generate a node cert signed by an org CA")
    p.add_argument("--out", default="pki")
    p.add_argument("--org", required=True)
    p.add_argument("--name", required=True)
    p.add_argument("--san", action="append", default=[])
    p.set_defaults(fn=_cmd_node)

    p = sub.add_parser("revoke", help="generate a CRL revoking node certs")
    p.add_argument("--out", default="pki")
    p.add_argument("--org", required=True)
    p.add_argument("--cert", action="append", required=True)
    p.add_argument("--days", type=int, default=365, help="CRL validity (re-issuance deadline)")
    p.set_defaults(fn=_cmd_revoke)
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
