"""Transports and framed streams.

The fabric's wire unit is a *stream*: an ordered, reliable, bidirectional
byte pipe. Control messages ride in *frames* — an 8-byte little-endian
length followed by a CBOR body, with a hard header cap — matching the
reference's pull-stream wire shape (reference:
crates/network/src/stream_pull.rs:21-103: 8-byte LE length + bounded
header, 1 MiB cap). Bulk tensor bytes are written raw after the header
frame, never CBOR-wrapped.

Two transports:

  * :class:`MemoryTransport` — in-process fabric for tests, the role
    ``libp2p-swarm-test`` plays in the reference (SURVEY.md §4): real
    concurrent streams, no sockets.
  * :class:`TcpTransport` — asyncio TCP, optionally wrapped in mTLS
    (ssl.SSLContext built by :mod:`hypha_tpu.certs`); one TCP connection
    per logical stream (parallel streams beat multiplexing on throughput,
    reference rfc/2025-03-25-libp2p_network_stack.md:17-29).
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any, AsyncIterator, Awaitable, Callable

from .. import aio
from .. import codec

__all__ = [
    "FrameError",
    "MAX_FRAME",
    "Stream",
    "Transport",
    "MemoryTransport",
    "TcpTransport",
    "read_frame",
    "write_frame",
]

# Bound on a single control frame (headers, RPC bodies). Tensor payloads are
# raw bytes and unaffected. Reference caps stream headers at 1 MiB
# (crates/network/src/stream_pull.rs:28); RPC bodies get 32 MiB headroom for
# large specs.
MAX_FRAME = 32 * 1024 * 1024
# StreamReader buffer limit. asyncio's 64 KiB default caps every read() at
# 64 KiB, which on the bulk-push path costs one event-loop pass + one
# worker-thread hop per 64 KiB — a first-order throughput limit on a
# single-core host (measured in DISTBENCH: the 4 MiB limit nearly doubled
# loopback stream throughput).
STREAM_BUFFER_LIMIT = 4 * 1024 * 1024

_LEN = struct.Struct("<Q")


class FrameError(ValueError):
    pass


class Stream:
    """A bidirectional byte stream. Concrete transports subclass."""

    async def read(self, n: int = 65536) -> bytes:
        """Read up to n bytes; b'' on EOF."""
        raise NotImplementedError

    async def read_exactly(self, n: int) -> bytes:
        chunks: list[bytes] = []
        got = 0
        while got < n:
            chunk = await self.read(n - got)
            if not chunk:
                raise FrameError(f"EOF after {got}/{n} bytes")
            chunks.append(chunk)
            got += len(chunk)
        return b"".join(chunks)

    async def write(self, data: bytes) -> None:
        raise NotImplementedError

    async def close(self) -> None:
        """Close the write side (half-close); reader sees EOF after drain."""
        raise NotImplementedError

    async def abort(self) -> None:
        """Tear down both directions."""
        await self.close()

    # -- framing ------------------------------------------------------------
    async def write_frame(self, obj: Any) -> int:
        return await write_frame(self, obj)

    async def read_frame(self, max_size: int = MAX_FRAME) -> Any:
        return await read_frame(self, max_size)


async def write_frame(stream: Stream, obj: Any) -> int:
    """Write one length-prefixed frame; returns the frame's wire size
    (prefix + body) so callers can account per-protocol control bytes
    without re-serializing."""
    body = codec.dumps(obj)
    if len(body) > MAX_FRAME:
        raise FrameError(f"frame too large: {len(body)}")
    await stream.write(_LEN.pack(len(body)) + body)
    return 8 + len(body)


async def read_frame(stream: Stream, max_size: int = MAX_FRAME) -> Any:
    header = await stream.read_exactly(8)
    (n,) = _LEN.unpack(header)
    if n > max_size:
        raise FrameError(f"frame of {n} bytes exceeds cap {max_size}")
    return codec.loads(await stream.read_exactly(n))


AcceptCallback = Callable[[Stream], Awaitable[None]]


class Transport:
    """Creates and accepts streams addressed by transport-specific strings."""

    async def listen(self, addr: str, on_stream: AcceptCallback) -> str:
        """Start accepting; returns the bound address (port resolved)."""
        raise NotImplementedError

    async def dial(self, addr: str) -> Stream:
        raise NotImplementedError

    async def close(self) -> None:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Memory transport
# ---------------------------------------------------------------------------


class _MemoryStream(Stream):
    """One direction-pair of queues; EOF is modeled with a None sentinel."""

    def __init__(self, rx: asyncio.Queue, tx: asyncio.Queue) -> None:
        self._rx = rx
        self._tx = tx
        self._buf = b""
        self._eof = False
        self._closed = False

    @classmethod
    def pair(cls) -> tuple["_MemoryStream", "_MemoryStream"]:
        # Bounded queues provide backpressure like a TCP window.
        a2b: asyncio.Queue = asyncio.Queue(maxsize=64)
        b2a: asyncio.Queue = asyncio.Queue(maxsize=64)
        return cls(b2a, a2b), cls(a2b, b2a)

    async def read(self, n: int = 65536) -> bytes:
        if not self._buf:
            if self._eof:
                return b""
            chunk = await self._rx.get()
            if chunk is None:
                self._eof = True
                return b""
            self._buf = chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    async def write(self, data: bytes) -> None:
        if self._closed:
            raise FrameError("write on closed stream")
        if data:
            await self._tx.put(bytes(data))

    async def close(self) -> None:
        if not self._closed:
            self._closed = True
            await self._tx.put(None)


class MemoryTransport(Transport):
    """In-process fabric; a shared hub maps addresses to listeners."""

    def __init__(self, hub: dict[str, AcceptCallback] | None = None) -> None:
        # All transports created from one hub can reach each other.
        self.hub: dict[str, AcceptCallback] = hub if hub is not None else {}
        self._listening: list[str] = []
        self._tasks: set[asyncio.Task] = set()
        self._counter = 0

    def shared(self) -> "MemoryTransport":
        """Another transport on the same hub (another in-process node)."""
        return MemoryTransport(self.hub)

    async def listen(self, addr: str, on_stream: AcceptCallback) -> str:
        if not addr or addr.endswith(":0"):
            self._counter += 1
            addr = f"mem:{id(self.hub) & 0xFFFF}-{len(self.hub)}-{self._counter}"
        if addr in self.hub:
            raise OSError(f"address in use: {addr}")
        self.hub[addr] = on_stream
        self._listening.append(addr)
        return addr

    async def dial(self, addr: str) -> Stream:
        try:
            on_stream = self.hub[addr]
        except KeyError:
            raise ConnectionRefusedError(addr) from None
        ours, theirs = _MemoryStream.pair()
        aio.spawn(on_stream(theirs), tasks=self._tasks, what="fabric accept")
        return ours

    async def close(self) -> None:
        for addr in self._listening:
            self.hub.pop(addr, None)
        self._listening.clear()
        await aio.reap(*list(self._tasks))


# ---------------------------------------------------------------------------
# TCP transport
# ---------------------------------------------------------------------------


class _TcpStream(Stream):
    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        self._reader = reader
        self._writer = writer

    async def read(self, n: int = 65536) -> bytes:
        return await self._reader.read(n)

    async def write(self, data: bytes) -> None:
        self._writer.write(data)
        await self._writer.drain()

    async def close(self) -> None:
        try:
            if self._writer.can_write_eof():
                self._writer.write_eof()
            else:  # TLS cannot half-close; full close after drain
                self._writer.close()
        except (ConnectionError, RuntimeError):
            pass

    async def abort(self) -> None:
        try:
            self._writer.close()
        except ConnectionError:
            pass

    def sendfile_transport(self):
        """The underlying transport, for ``loop.sendfile`` (kernel zero-copy
        file→socket on plain TCP; asyncio falls back internally under TLS)."""
        return self._writer.transport

    def raw_socket_handoff(self):
        """Hand the raw socket to a thread-side drain, or None.

        The receiver mirror of ``sendfile_transport``: bulk pushes drain
        fastest with blocking ``recv_into`` straight into an mmap of the
        destination file (one kernel→page-cache copy, no event-loop
        scheduling per chunk — DISTBENCH r4's remaining gap). Only valid
        on plain TCP (TLS bytes need the event-loop's decrypt) and only
        when the caller will consume the stream to EOF: reading is paused
        here and never resumed. Returns ``(socket, buffered)`` where
        ``buffered`` is whatever the event loop had already read ahead.
        """
        if self._writer.get_extra_info("ssl_object") is not None:
            return None
        sock = self._writer.get_extra_info("socket")
        if sock is None:
            return None
        try:
            self._writer.transport.pause_reading()
        except (NotImplementedError, RuntimeError):
            return None
        try:
            buffered = bytes(self._reader._buffer)
            self._reader._buffer.clear()
        except (AttributeError, TypeError):
            # Private-API drift (StreamReader._buffer): undo the pause so
            # the fallback read loop isn't left waiting on a transport
            # that will never feed it.
            try:
                self._writer.transport.resume_reading()
            except (NotImplementedError, RuntimeError):
                pass
            return None
        return sock, buffered

    async def drain(self) -> None:
        await self._writer.drain()

    def peer_certificate(self) -> dict | None:
        ssl_obj = self._writer.get_extra_info("ssl_object")
        return ssl_obj.getpeercert() if ssl_obj else None

    def peer_certificate_der(self) -> bytes | None:
        ssl_obj = self._writer.get_extra_info("ssl_object")
        return ssl_obj.getpeercert(binary_form=True) if ssl_obj else None


class TcpTransport(Transport):
    """addr format: ``host:port``. TLS contexts from hypha_tpu.certs."""

    def __init__(self, server_ssl=None, client_ssl=None) -> None:
        self._server_ssl = server_ssl
        self._client_ssl = client_ssl
        self._servers: list[asyncio.base_events.Server] = []
        self._conn_tasks: set[asyncio.Task] = set()

    async def listen(self, addr: str, on_stream: AcceptCallback) -> str:
        host, _, port = addr.rpartition(":")
        host = host or "127.0.0.1"

        async def handle(reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
            # Track the handler task: since Python 3.12 Server.wait_closed()
            # blocks until every handler returns, so close() must be able to
            # cancel handlers parked on idle reads or undrained pushes.
            task = asyncio.current_task()
            if task is not None:
                self._conn_tasks.add(task)
                task.add_done_callback(self._conn_tasks.discard)
            stream = _TcpStream(reader, writer)
            try:
                await on_stream(stream)
            finally:
                try:
                    writer.close()
                except ConnectionError:
                    pass

        server = await asyncio.start_server(
            handle, host, int(port), ssl=self._server_ssl,
            limit=STREAM_BUFFER_LIMIT,
        )
        self._servers.append(server)
        bound = server.sockets[0].getsockname()
        return f"{host}:{bound[1]}"

    async def dial(self, addr: str) -> Stream:
        host, _, port = addr.rpartition(":")
        server_hostname = None
        if self._client_ssl is not None:
            # PeerID auth happens at the fabric layer (cert-key-hash), not
            # via DNS names; disable hostname checks like the reference's
            # mTLS fork does (rfc/2025-05-30_mtls.md).
            server_hostname = ""
        reader, writer = await asyncio.open_connection(
            host, int(port), ssl=self._client_ssl,
            server_hostname=server_hostname, limit=STREAM_BUFFER_LIMIT,
        )
        return _TcpStream(reader, writer)

    async def close(self) -> None:
        for server in self._servers:
            server.close()
        for task in list(self._conn_tasks):
            task.cancel()
        for server in self._servers:
            await aio.wait_quiet(server.wait_closed())
        self._servers.clear()


async def copy_stream(
    src: Stream | AsyncIterator[bytes], dst: Stream, chunk: int = 1 << 20
) -> int:
    """Pump bytes src→dst; returns byte count. The fabric's io::copy."""
    total = 0
    if isinstance(src, Stream):
        while True:
            data = await src.read(chunk)
            if not data:
                break
            await dst.write(data)
            total += len(data)
    else:
        async for data in src:
            await dst.write(data)
            total += len(data)
    return total
