"""Fabric utilities.

``batched`` mirrors the reference's ``Batched<S>`` stream adapter — window an
async stream by *count limit OR time window*, whichever trips first
(reference: crates/network/src/utils.rs:50-110; used to window auction
requests, crates/worker/src/arbiter.rs:89-93).
"""

from __future__ import annotations

import asyncio
from typing import Any, AsyncIterator

__all__ = ["batched"]


async def batched(
    source: AsyncIterator[Any], limit: int, window_s: float
) -> AsyncIterator[list[Any]]:
    """Yield non-empty batches: up to ``limit`` items or whatever arrived
    within ``window_s`` of the batch's first item. Ends when the source ends.

    The pending ``anext`` is kept alive across window boundaries — a
    ``wait_for``-style cancel would tear down the source generator itself
    and silently end the stream after the first quiet window.
    """
    pending: asyncio.Task | None = None
    try:
        while True:
            if pending is None:
                pending = asyncio.ensure_future(anext(source))
            try:
                first = await pending
            except StopAsyncIteration:
                pending = None
                return
            pending = None
            batch = [first]
            deadline = asyncio.get_running_loop().time() + window_s
            while len(batch) < limit:
                remaining = deadline - asyncio.get_running_loop().time()
                if remaining <= 0:
                    break
                if pending is None:
                    pending = asyncio.ensure_future(anext(source))
                done, _ = await asyncio.wait({pending}, timeout=remaining)
                if not done:
                    break  # window closed; keep the read pending for later
                task, pending = pending, None
                try:
                    batch.append(task.result())
                except StopAsyncIteration:
                    yield batch
                    return
            yield batch
    finally:
        if pending is not None:
            pending.cancel()
