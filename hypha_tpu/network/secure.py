"""mTLS-secured node construction.

Ties L0 (certs) to L1 (fabric): every connection is mutual-TLS against the
root of trust, and the node's fabric identity is *derived from its
certificate* — PeerID = hash of the cert public key — so a peer cannot claim
an identity its certificate doesn't prove (reference:
crates/network/src/cert.rs:30-79 identity_from_private_key;
transport construction crates/scheduler/src/network.rs:109-131).
"""

from __future__ import annotations

from pathlib import Path

from .. import certs
from .fabric import TcpTransport
from .node import Node

__all__ = ["secure_node"]


def secure_node(
    cert_file: str | Path,
    key_file: str | Path,
    trust_file: str | Path,
    crl_file: str | Path | None = None,
    bootstrap: list[str] | None = None,
    registry_server: bool = False,
    **node_kwargs,
) -> Node:
    """A Node whose transport is mTLS and whose peer id is its cert-key hash.

    The handshake's claimed ``from`` id is checked against the TLS-layer
    certificate on every inbound stream; a mismatch aborts the stream.
    """
    cert_path = Path(cert_file)
    transport = TcpTransport(
        server_ssl=certs.make_server_context(cert_path, key_file, trust_file, crl_file),
        client_ssl=certs.make_client_context(cert_path, key_file, trust_file, crl_file),
    )
    peer_id = certs.peer_id_from_cert_pem(cert_path.read_bytes())

    # One-connection-per-stream means this runs per message; certs are
    # immutable, so cache the DER -> peer-id derivation.
    id_cache: dict[bytes, str] = {}

    def expected_peer_id(stream) -> str | None:
        der = getattr(stream, "peer_certificate_der", lambda: None)()
        # Under TLS a missing client cert is impossible (CERT_REQUIRED);
        # None here means a non-TLS transport, where no check applies.
        if not der:
            return None
        pid = id_cache.get(der)
        if pid is None:
            pid = certs.peer_id_from_cert_der(der)
            if len(id_cache) > 256:
                id_cache.clear()
            id_cache[der] = pid
        return pid

    return Node(
        transport,
        peer_id=peer_id,
        bootstrap=bootstrap,
        registry_server=registry_server,
        expected_peer_id=expected_peer_id,
        # The node-cert key signs gossip frames (reference signs gossipsub
        # messages with the swarm keypair, scheduler/network.rs:132-136);
        # receivers verify self-certifying key-hash == origin.
        gossip_key=certs.load_private_key_from_pem(key_file),
        **node_kwargs,
    )
