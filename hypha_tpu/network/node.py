"""The Node: one identity on the fabric, with typed services.

This is the framework's equivalent of a composed libp2p swarm + the
Action/Driver/Interface triads of the reference's ``hypha-network``
(reference: crates/network/src/lib.rs:37-47). One asyncio accept-loop per
node owns every inbound stream (the "driver"); the public async methods are
the "interfaces":

  * typed CBOR RPC with fluent, first-wins handler registration
    (reference: crates/network/src/request_response.rs:44-55 fluent API,
    :503-519 first-wins matching, auto-unregister on drop :492-500);
  * gossip pub/sub with flood + message-id dedup
    (reference: crates/network/src/gossipsub.rs);
  * record/provider discovery anchored on gateway registry servers
    (reference: crates/network/src/kad.rs — Kademlia anchored on gateways);
  * raw push/pull tensor byte streams with bounded headers and inbound
    accept limits (reference: crates/network/src/stream_push.rs:16-89,
    stream_pull.rs:21-146).

Wire handshake (every stream): dialer sends one frame
``{from, proto, addr}`` — ``addr`` is the dialer's primary listen address so
the responder can dial back (the identify role). Under mTLS the responder
verifies ``from`` equals the certificate-derived peer id.
"""

from __future__ import annotations

import asyncio
import logging
import socket
import time
import uuid
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Awaitable, Callable

from .. import aio, messages
from ..telemetry.ft_metrics import SCALE_METRICS
from .fabric import MAX_FRAME, FrameError, Stream, Transport, copy_stream

__all__ = [
    "Node",
    "RequestError",
    "HandlerRegistration",
    "Subscription",
    "PushStream",
    "PROTOCOL_GOSSIP",
    "PROTOCOL_REGISTRY",
    "PROTOCOL_PUSH",
    "PROTOCOL_PULL",
]

log = logging.getLogger("hypha.network")

PROTOCOL_GOSSIP = "/hypha-gossip/0.0.1"
PROTOCOL_REGISTRY = "/hypha-registry/0.0.1"
# Circuit relay through the gateway — the fabric's answer to the reference's
# libp2p relay server + circuit listen addresses (crates/gateway/src/
# network.rs:41-48 relay::Behaviour; crates/network/src/listen.rs:25-131
# relay-circuit listeners). Streams between two NAT'd peers are spliced
# byte-for-byte at the gateway.
PROTOCOL_RELAY = "/hypha-relay/0.0.1"
# Direct-connection upgrade over an established circuit — the fabric's
# DCUtR role (reference: dcutr in every node's behaviour,
# crates/scheduler/src/network.rs:46-95): peers exchange their direct
# addresses through the relay and both sides attempt direct dials; once one
# lands in the address book, _stream_to's direct-first ordering migrates
# traffic off the circuit.
PROTOCOL_DCUTR = "/hypha-dcutr/0.0.1"
# Per-peer cooldown between upgrade attempts (a NAT that never opens would
# otherwise burn a dial volley on every relayed RPC).
DCUTR_RETRY_S = 30.0
# Tensor stream protocol ids follow the reference names
# (crates/network/src/stream_push.rs:16, stream_pull.rs:21).
PROTOCOL_PUSH = "/hypha-tensor-stream/push"
PROTOCOL_PULL = "/hypha-tensor-stream/pull"

# Header frames on tensor streams are capped at 1 MiB
# (reference: crates/network/src/stream_pull.rs:28).
MAX_STREAM_HEADER = 1024 * 1024
# Inbound tensor streams accepted concurrently per protocol
# (reference: accept_with_limit(.., 8), stream_push.rs:56).
ACCEPT_LIMIT = 8
# Providers age out unless re-announced (clients refresh every 30 s).
PROVIDER_TTL = 90.0
# How long the relay waits for the reserved peer to dial back and accept a
# circuit before failing the dialer's connect.
RELAY_ACCEPT_TIMEOUT = 15.0
# Concurrent relayed circuits one dialer may hold open on a gateway; each
# circuit pins two sockets + a splice task for its lifetime.
RELAY_MAX_CIRCUITS_PER_PEER = 8
# Per-gateway bound on one registry op (dial + request + reply).
REGISTRY_OP_TIMEOUT = 10.0

_SEEN_CAP = 4096  # gossip dedup cache entries


class RequestError(RuntimeError):
    """Remote handler failed or RPC transport failed."""


class ExcludedAddressError(ConnectionError):
    """Dial target falls inside a configured ``exclude_cidrs`` range."""


def _parse_cidrs(cidrs: list[str]):
    import ipaddress

    return [ipaddress.ip_network(c, strict=False) for c in cidrs]


# Signed gossip frames carry a timestamp covered by the signature; frames
# outside this window (stale or future-dated) are dropped, bounding replay
# of captured frames to the window even after the seen-cache evicts them.
GOSSIP_MAX_SKEW_S = 120.0


def _gossip_seen_key(
    msg_id: str, sig: bytes | None, canonical: bytes = b""
) -> str:
    """Dedup key binding the message id to the signature AND the canonical
    signed bytes, so a forged frame (altered body/origin/ts, or a reused
    genuine signature over altered data) can never occupy the genuine
    frame's dedup slot — while byte-identical flood copies still dedup
    cheaply (one sha256, no Ed25519 verify) and repeated identical
    forgeries dedup too."""
    if sig is None:
        return msg_id
    import hashlib

    return msg_id + ":" + hashlib.sha256(canonical + sig).hexdigest()[:16]


def _gossip_sign_bytes(
    topic: str, msg_id: str, origin: str, ts_ns: int, body: bytes
) -> bytes:
    """Canonical byte string covered by a gossip signature: every field a
    relay could tamper with, under a domain-separation prefix."""
    from .. import codec

    return codec.dumps(["hypha-gossip-sig", topic, msg_id, origin, ts_ns, body])


def _gossip_verify(
    topic: str, msg_id: str, origin: str, ts_ns: int, body: bytes, key: bytes, sig: bytes
) -> bool:
    """Self-certifying verification: the embedded SPKI public key must hash
    to the claimed origin peer id (same derivation as cert identities), and
    the Ed25519 signature must cover the canonical frame bytes. No key
    distribution needed — the id IS the key hash."""
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric import ed25519
    from cryptography.hazmat.primitives.serialization import load_der_public_key

    from ..certs import peer_id_from_spki_der

    try:
        if peer_id_from_spki_der(key) != origin:
            return False
        pub = load_der_public_key(key)
        if not isinstance(pub, ed25519.Ed25519PublicKey):
            return False
        pub.verify(sig, _gossip_sign_bytes(topic, msg_id, origin, ts_ns, body))
        return True
    except (InvalidSignature, ValueError, TypeError):
        return False


def _addr_host(addr: str) -> str:
    return addr.rpartition(":")[0].strip("[]")


def _addr_ip(addr: str):
    """The literal IP of a ``host:port`` fabric address, or None for
    non-IP addresses (memory transport, hostnames)."""
    import ipaddress

    try:
        return ipaddress.ip_address(_addr_host(addr))
    except ValueError:
        return None


@dataclass(slots=True)
class _Handler:
    protocol: str
    msg_type: type | None
    fn: Callable[[str, Any], Awaitable[Any]]
    semaphore: asyncio.Semaphore
    registration: "HandlerRegistration"
    predicate: Callable[[Any], bool] | None = None

    def matches(self, msg: Any) -> bool:
        if self.msg_type is not None and not isinstance(msg, self.msg_type):
            return False
        return self.predicate is None or bool(self.predicate(msg))


class HandlerRegistration:
    """Handle returned by ``respond_with``; unregister via close()/ctx-mgr.

    Mirrors the reference's auto-unregister-on-drop handler streams
    (crates/network/src/request_response.rs:492-500).
    """

    def __init__(self, node: "Node") -> None:
        self._node = node
        self._handler: _Handler | None = None
        self.closed = False

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self._node._unregister(self._handler)

    def __enter__(self) -> "HandlerRegistration":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class HandlerBuilder:
    """Fluent RPC handler registration: ``node.on(proto, Type)
    .concurrency(8).respond_with(handler)`` — reference fluent API shape
    (crates/network/src/request_response.rs:44-55)."""

    def __init__(self, node: "Node", protocol: str, msg_type: type | None) -> None:
        self._node = node
        self._protocol = protocol
        self._msg_type = msg_type
        self._concurrency = 16
        self._predicate: Callable[[Any], bool] | None = None

    def concurrency(self, n: int) -> "HandlerBuilder":
        self._concurrency = n
        return self

    def match(self, predicate: Callable[[Any], bool]) -> "HandlerBuilder":
        """Only dispatch messages the predicate accepts — handlers are
        matched first-wins (request_response.rs:222-259), so predicates let
        several handlers of the same type share a protocol (e.g. one
        DataScheduler per dataset)."""
        self._predicate = predicate
        return self

    def respond_with(
        self, fn: Callable[[str, Any], Awaitable[Any]]
    ) -> HandlerRegistration:
        """fn(peer_id, msg) -> response message (raised errors become
        RequestError at the caller)."""
        reg = HandlerRegistration(self._node)
        handler = _Handler(
            protocol=self._protocol,
            msg_type=self._msg_type,
            fn=fn,
            semaphore=asyncio.Semaphore(self._concurrency),
            registration=reg,
            predicate=self._predicate,
        )
        reg._handler = handler
        self._node._register(handler)
        return reg

    def into_stream(self, buffer: int = 64) -> "RequestStream":
        """Async iterator of (peer, msg, respond) triples."""
        stream = RequestStream(buffer)

        async def fn(peer: str, msg: Any) -> Any:
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            await stream._queue.put((peer, msg, fut))
            return await fut

        stream.registration = self.respond_with(fn)
        return stream


class RequestStream:
    def __init__(self, buffer: int) -> None:
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=buffer)
        self.registration: HandlerRegistration | None = None

    def __aiter__(self) -> "RequestStream":
        return self

    async def __anext__(self) -> tuple[str, Any, Callable[[Any], None]]:
        peer, msg, fut = await self._queue.get()

        def respond(response: Any) -> None:
            if not fut.done():
                fut.set_result(response)

        return peer, msg, respond

    def close(self) -> None:
        if self.registration:
            self.registration.close()


class Subscription:
    """A live gossip subscription; async-iterate (from_peer, msg)."""

    def __init__(self, node: "Node", topic: str, buffer: int = 256) -> None:
        self._node = node
        self.topic = topic
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=buffer)
        self.closed = False

    def _deliver(self, from_peer: str, msg: Any) -> None:
        if self.closed:
            return
        try:
            self._queue.put_nowait((from_peer, msg))
        except asyncio.QueueFull:
            log.warning("gossip subscriber slow; dropping message on %s", self.topic)

    def __aiter__(self) -> "Subscription":
        return self

    async def __anext__(self) -> tuple[str, Any]:
        if self.closed:
            raise StopAsyncIteration
        item = await self._queue.get()
        if item is None:  # close() sentinel
            raise StopAsyncIteration
        return item

    async def close(self) -> None:
        if not self.closed:
            self.closed = True
            await self._node._unsubscribe(self)
            # Wake a consumer already blocked in __anext__.
            try:
                self._queue.put_nowait(None)
            except asyncio.QueueFull:
                pass


class PushConsumer:
    """A routed inbound-push subscription (see Node.consume_pushes)."""

    def __init__(
        self, node: "Node", predicate: Callable[["PushStream"], bool], buffer: int
    ) -> None:
        self._node = node
        self.predicate = predicate
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=buffer)
        self.closed = False

    async def next(self, timeout: float | None = None) -> "PushStream":
        getter = self._queue.get()
        return await (getter if timeout is None else asyncio.wait_for(getter, timeout))

    def __aiter__(self) -> "PushConsumer":
        return self

    async def __anext__(self) -> "PushStream":
        return await self._queue.get()

    def close(self) -> None:
        """Stop routing to this consumer. Anything already buffered but
        undrained is released so senders aren't pinned forever."""
        if self.closed:
            return
        self.closed = True
        try:
            self._node._push_consumers.remove(self)
        except ValueError:
            pass
        while not self._queue.empty():
            push = self._queue.get_nowait()
            push.finish()


def _write_and_hash(f, data: bytes, hasher) -> None:
    """One executor hop for write + digest (hashlib releases the GIL on
    large buffers, so both stay off the event loop)."""
    f.write(data)
    hasher.update(data)


def _drain_socket_to_file(sock, buffered: bytes, path) -> int:
    """Blocking drain: recv_into an mmap of ``path`` until EOF.

    Runs in a worker thread with reading paused on the asyncio transport
    (fabric.raw_socket_handoff), so this thread is the socket's only
    reader. The file is grown in 64 MiB steps and truncated to the exact
    byte count at EOF; ``recv_into`` against the mmap writes kernel
    buffers straight into the page cache.

    asyncio hands out a TransportSocket that forbids mode changes (and the
    O_NONBLOCK status is shared with the transport's writer side anyway),
    so the fd is dup()ed into a real socket object and drained
    non-blocking with select() — which also gives the idle timeout a
    thread needs, since it can't be cancelled and a dead sender must
    surface as ConnectionError instead of a leaked thread."""
    import mmap as _mmap
    import os
    import select as _select

    grow = 64 << 20
    total = 0
    s = socket.socket(fileno=os.dup(sock.fileno()))
    try:
        with open(path, "wb+") as f:
            if buffered:
                f.write(buffered)
                total = len(buffered)
            f.truncate(total + grow)
            mm = _mmap.mmap(f.fileno(), 0)
            try:
                while True:
                    if total == mm.size():
                        f.truncate(total + grow)
                        mm.resize(total + grow)
                    try:
                        n = s.recv_into(memoryview(mm)[total:])
                    except (BlockingIOError, InterruptedError):
                        # poll, not select: select() raises on fds >= 1024,
                        # and a large fleet's node can easily sit above that.
                        p = _select.poll()
                        p.register(s, _select.POLLIN)
                        if not p.poll(60_000):
                            raise ConnectionError("push drain timed out")
                        continue
                    if n == 0:
                        break
                    total += n
            finally:
                mm.close()
            f.truncate(total)
    finally:
        s.close()
    return total


@dataclass(slots=True)
class PushStream:
    """An accepted inbound push: header + raw byte reader."""

    peer: str
    resource: Any
    stream: Stream
    _done: Callable[[], None] = field(default=lambda: None)

    async def read_all(self, chunk: int = 1 << 20) -> bytes:
        parts = []
        while True:
            data = await self.stream.read(chunk)
            if not data:
                break
            parts.append(data)
        self.finish()
        return b"".join(parts)

    async def save_to(self, path, chunk: int = 1 << 22, hasher=None) -> int:
        """Stream to disk without buffering the whole payload (the reference
        file-mediates all tensor transfers, bridge.rs:392-504).

        Default path: 4 MiB buffered reads with thread-offloaded writes —
        chunk size, not the thread hop, is the first-order cost (r4 sweep).

        Opt-in fast path (``HYPHA_RAW_DRAIN=1``, plain-TCP push connections
        only): the raw socket is handed to a dedicated thread that
        ``recv_into``s an mmap of the destination file — one
        kernel→page-cache copy, zero event-loop involvement. This closes
        DISTBENCH r4's named double-copy gap and measures ~26% faster on a
        CLEAN page cache (972 vs 771 MB/s singles), but under sustained
        writeback pressure on a slow virtio disk the mmap page-fault path
        throttles harder than write() and LOSES (DISTBENCH_r05 A/B:
        ~220-530 vs ~760-780 sustained) — so it stays off by default and
        is the right switch only for hosts with fast local disks. TLS /
        mux / relay streams always use the buffered path (their bytes
        must pass through the event loop).

        ``hasher``: optional hashlib object updated with every chunk as it
        is written — a receiver that needs a digest of the payload (the
        durable PS journal's dedup key) gets it in the same pass instead
        of re-reading the file; requesting one forces the buffered path,
        since the raw-drain handoff never surfaces the bytes."""
        import os as _os

        handoff = None
        if hasher is None and _os.environ.get("HYPHA_RAW_DRAIN") == "1":
            handoff = getattr(self.stream, "raw_socket_handoff", None)
        handoff = handoff() if handoff is not None else None
        if handoff is not None:
            sock, buffered = handoff
            try:
                total = await asyncio.to_thread(
                    _drain_socket_to_file, sock, buffered, path
                )
            finally:
                # finish() even on a failed drain — otherwise the accept
                # semaphore slot leaks and _handle_push waits forever; 8
                # timed-out senders would wedge all inbound pushes.
                self.finish()
            credit = getattr(self.stream, "credit_inbound", None)
            if credit is not None:
                credit(total)
            return total
        loop = asyncio.get_running_loop()
        total = 0
        try:
            # open() seeks/stats on the calling thread — off the loop too.
            f = await asyncio.to_thread(open, path, "wb")
            try:
                while True:
                    data = await self.stream.read(chunk)
                    if not data:
                        break
                    if hasher is None:
                        await loop.run_in_executor(None, f.write, data)
                    else:
                        await loop.run_in_executor(
                            None, _write_and_hash, f, data, hasher
                        )
                    total += len(data)
            finally:
                await asyncio.to_thread(f.close)
        finally:
            # Same wedge as the raw path: a sender dying mid-push must
            # still release the accept-semaphore slot, or ACCEPT_LIMIT
            # failed senders stop all inbound pushes.
            self.finish()
        return total

    def finish(self) -> None:
        """Release the accept slot and let the transport close the stream.
        Called automatically by read_all/save_to at EOF."""
        self._done()


class _LocalFileStream(Stream):
    """A read-only Stream over a local file — the payload carrier for
    :meth:`Node.inject_push` (a broadcast relay handing its own node the
    wire it just saved, without a loopback dial)."""

    def __init__(self, path) -> None:
        self._path = path
        self._f = None
        self._eof = False

    async def read(self, n: int = 65536) -> bytes:
        if self._eof:
            return b""
        if self._f is None:
            self._f = await asyncio.to_thread(open, self._path, "rb")
        data = await asyncio.get_running_loop().run_in_executor(
            None, self._f.read, n
        )
        if not data:
            self._eof = True
        return data

    async def write(self, data: bytes) -> None:
        raise OSError("injected push streams are read-only")

    async def close(self) -> None:
        if self._f is not None:
            f, self._f = self._f, None
            await asyncio.to_thread(f.close)

    async def abort(self) -> None:
        await self.close()


class _CountingStream(Stream):
    """Wraps a stream, crediting reads to the node's inbound byte counter
    (the reference's bandwidth-instrumented muxer role,
    crates/telemetry/src/bandwidth.rs:30-62)."""

    def __init__(self, inner: Stream, node: "Node") -> None:
        self._inner = inner
        self._node = node

    async def read(self, n: int = 65536) -> bytes:
        data = await self._inner.read(n)
        self._node.bytes_in += len(data)
        return data

    async def write(self, data: bytes) -> None:
        await self._inner.write(data)
        self._node.bytes_out += len(data)

    def raw_socket_handoff(self):
        inner = getattr(self._inner, "raw_socket_handoff", None)
        return inner() if inner is not None else None

    def credit_inbound(self, n: int) -> None:
        self._node.bytes_in += n

    async def close(self) -> None:
        await self._inner.close()

    async def abort(self) -> None:
        await self._inner.abort()


class _RelayStream(Stream):
    """A stream riding a gateway circuit. The TLS certificate on the socket
    is the *gateway's*, so certificate-derived identity checks don't apply;
    instead the stream carries the peer id the (cert-verified, trusted
    infrastructure) gateway attested for the far end. End-to-end payload
    privacy through the relay matches the deployment's trust in gateways —
    the reference's relay server likewise terminates transport security per
    hop (crates/gateway/src/network.rs:41-48)."""

    def __init__(self, inner: Stream, attested_peer: str) -> None:
        self._inner = inner
        self.attested_peer = attested_peer

    async def read(self, n: int = 65536) -> bytes:
        return await self._inner.read(n)

    async def write(self, data: bytes) -> None:
        await self._inner.write(data)

    async def close(self) -> None:
        await self._inner.close()

    async def abort(self) -> None:
        await self._inner.abort()


class Node:
    """One fabric identity: listen addresses, peerstore, typed services."""

    def __init__(
        self,
        transport: Transport,
        peer_id: str | None = None,
        bootstrap: list[str] | None = None,
        registry_server: bool = False,
        expected_peer_id: Callable[[Stream], str | None] | None = None,
        relay_server: bool | None = None,
        relay_listen: bool = False,
        advertise_listen: bool = True,
        exclude_cidrs: list[str] | None = None,
        gossip_key=None,
    ) -> None:
        self.transport = transport
        self.peer_id = peer_id or f"peer-{uuid.uuid4().hex[:16]}"
        self.listen_addrs: list[str] = []
        self.external_addrs: list[str] = []
        self._bootstrap_addrs = list(bootstrap or [])
        self._bootstrap_peers: set[str] = set()
        self._bootstrapped = asyncio.Event()
        self._registry_server = registry_server
        self._expected_peer_id = expected_peer_id
        # peerstore: peer_id -> ordered unique addrs
        self._peers: dict[str, list[str]] = {}
        # RPC handlers, first-wins in registration order per protocol
        self._handlers: dict[str, list[_Handler]] = {}
        # gossip state
        self._subs: dict[str, list[Subscription]] = {}
        self._gossip_peers: set[str] = set()
        self._seen: OrderedDict[str, None] = OrderedDict()
        # registry server state (gateway role)
        self._records: dict[str, bytes] = {}
        self._providers: dict[str, dict[str, float]] = {}  # key -> peer -> ts
        self._addr_book: dict[str, list[str]] = {}  # registered peer addrs
        self._provided: set[str] = set()  # keys this node announces (client)
        # tensor streams
        self._push_queue: asyncio.Queue = asyncio.Queue()
        self._push_consumers: list["PushConsumer"] = []
        self._push_sem = asyncio.Semaphore(ACCEPT_LIMIT)
        self._pull_sem = asyncio.Semaphore(ACCEPT_LIMIT)
        self._pull_handler: Callable[[str, Any, Stream], Awaitable[None]] | None = None
        self._tasks: set[asyncio.Task] = set()
        self._closed = False
        # relay (gateway circuit) state: gateways serve circuits by default
        # (reference: the gateway IS the relay server, gateway/network.rs:44)
        self._relay_server = registry_server if relay_server is None else relay_server
        self._relay_listen = relay_listen
        self._advertise_listen = advertise_listen
        self._relay_controls: dict[str, Stream] = {}  # reserved peer -> ctrl
        self._relay_pending: dict[str, dict] = {}  # circuit id -> record
        self._relay_active: dict[str, int] = {}  # dialer peer -> live circuits
        self._dcutr_last: dict[str, float] = {}  # peer -> last upgrade try
        # Addresses never dialed, enforced on EVERY dial — the reference
        # checks its CIDR exclusion list on each outbound connection
        # (crates/network/src/dial.rs:28-41,164).
        self._exclude_nets = _parse_cidrs(exclude_cidrs or [])
        # Ed25519PrivateKey (the node-cert key) for gossip message signing —
        # the reference signs gossipsub messages with the swarm keypair
        # (crates/scheduler/src/network.rs:132-136). When a key is present
        # the mesh is permissioned and unsigned/invalid frames are DROPPED;
        # keyless (dev-mode) nodes accept unsigned frames but still reject
        # frames whose signature fails to verify.
        self._gossip_key = gossip_key
        # inbound/outbound byte counters (telemetry bandwidth role,
        # reference crates/telemetry/src/bandwidth.rs)
        self.bytes_in = 0
        self.bytes_out = 0
        self.bytes_relayed = 0

    # ------------------------------------------------------------------ core

    def _spawn(self, coro, what: str = "") -> asyncio.Task:
        return aio.spawn(coro, tasks=self._tasks, what=what, logger=log)

    async def start(self, listen: list[str] | None = None) -> None:
        for addr in listen or ["", ]:
            bound = await self.transport.listen(addr, self._on_stream)
            self.listen_addrs.append(bound)
        if self._bootstrap_addrs:
            self._spawn(self._bootstrap_loop())
            if self._relay_listen:
                # Keep a circuit reservation alive at every gateway — the
                # reference's relay-circuit listen addresses
                # (crates/network/src/listen.rs:25-131).
                for gw in self._bootstrap_addrs:
                    self._spawn(self._relay_reserve_loop(gw))
        else:
            self._bootstrapped.set()  # self-anchored (tests / gateway itself)

    async def stop(self) -> None:
        self._closed = True
        # Wake consumers blocked on push_streams()/next_push().
        self._push_queue.put_nowait(None)
        for consumer in list(self._push_consumers):
            consumer.close()
        for sub_list in self._subs.values():
            for sub in list(sub_list):
                sub.closed = True
                try:
                    sub._queue.put_nowait(None)
                except asyncio.QueueFull:
                    pass
        await aio.reap(*list(self._tasks))
        await self.transport.close()

    def add_peer_addr(self, peer_id: str, addr: str) -> None:
        addrs = self._peers.setdefault(peer_id, [])
        if addr and addr not in addrs:
            addrs.append(addr)

    def primary_addr(self) -> str:
        if self.external_addrs:
            return self.external_addrs[0]
        return self.listen_addrs[0] if self.listen_addrs else ""

    async def dial(self, addr: str, proto: str = PROTOCOL_REGISTRY) -> str:
        """Dial an address to learn/verify the peer behind it (identify).
        Under mTLS the claimed id must match the certificate-derived one."""
        stream = await self._open_raw(addr, proto)
        try:
            await stream.write_frame({"t": "identify"})
            reply = await stream.read_frame()
            peer = reply.get("peer", "")
            if peer and self._expected_peer_id is not None:
                actual = self._expected_peer_id(stream)
                if actual is not None and actual != peer:
                    raise RequestError(
                        f"{addr} claims {peer} but presents certificate of {actual}"
                    )
            if peer:
                self.add_peer_addr(peer, addr)
            return peer
        finally:
            await stream.close()

    # -------------------------------------------------------------- accepting

    async def _on_stream(self, stream: Stream) -> None:
        try:
            hello = await stream.read_frame(MAX_STREAM_HEADER)
            peer = hello.get("from", "")
            proto = hello.get("proto", "")
            addr = hello.get("addr", "")
        except Exception as e:
            log.debug("bad handshake: %s", e)
            await stream.abort()
            return
        if isinstance(stream, _RelayStream):
            # Identity through a circuit comes from the gateway's attestation
            # (the gateway cert-verified the dialer); the socket cert is the
            # gateway's and proves nothing about the far end.
            if stream.attested_peer and peer != stream.attested_peer:
                log.warning(
                    "relayed peer id %s does not match gateway attestation %s",
                    peer, stream.attested_peer,
                )
                await stream.abort()
                return
        elif self._expected_peer_id is not None:
            expected = self._expected_peer_id(stream)
            if expected is not None and expected != peer:
                log.warning("peer id %s does not match certificate %s", peer, expected)
                await stream.abort()
                return
        if peer and addr:
            self.add_peer_addr(peer, addr)
        owned = True  # push streams hand ownership to the consumer
        try:
            if proto == PROTOCOL_GOSSIP:
                await self._handle_gossip(peer, stream)
            elif proto == PROTOCOL_RELAY:
                await self._handle_relay(peer, stream)
            elif proto == PROTOCOL_DCUTR:
                await self._handle_dcutr(peer, stream)
            elif proto == PROTOCOL_REGISTRY:
                await self._handle_registry(peer, stream)
            elif proto == PROTOCOL_PUSH:
                await self._handle_push(peer, stream)
                owned = False
            elif proto == PROTOCOL_PULL:
                await self._handle_pull(peer, stream)
            else:
                await self._handle_rpc(peer, proto, stream)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            log.debug("stream error (%s from %s): %s", proto, peer, e)
        finally:
            if owned:
                await stream.close()

    # ------------------------------------------------------------------- rpc

    def on(self, protocol: str, msg_type: type | None = None) -> HandlerBuilder:
        return HandlerBuilder(self, protocol, msg_type)

    def _register(self, handler: _Handler) -> None:
        self._handlers.setdefault(handler.protocol, []).append(handler)

    def _unregister(self, handler: _Handler | None) -> None:
        if handler is None:
            return
        lst = self._handlers.get(handler.protocol, [])
        if handler in lst:
            lst.remove(handler)

    async def _handle_rpc(self, peer: str, proto: str, stream: Stream) -> None:
        body = await stream.read_frame()
        try:
            msg = messages.decode(body)
        except Exception as e:
            await stream.write_frame({"ok": False, "error": f"decode: {e}"})
            return
        handler = next(
            (h for h in self._handlers.get(proto, []) if h.matches(msg)), None
        )
        if handler is None:
            await stream.write_frame(
                {"ok": False, "error": f"no handler for {type(msg).__name__} on {proto}"}
            )
            return
        async with handler.semaphore:
            try:
                response = await handler.fn(peer, msg)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.debug("handler error on %s: %s", proto, e)
                await stream.write_frame({"ok": False, "error": str(e)})
                return
        sent = await stream.write_frame(
            {"ok": True, "body": messages.encode(response)}
        )
        SCALE_METRICS.note_control(proto, sent)

    async def request(
        self, peer_id: str, protocol: str, msg: Any, timeout: float = 30.0
    ) -> Any:
        """Typed RPC to a peer; raises RequestError on failure."""
        try:
            return await asyncio.wait_for(
                self._request_inner(peer_id, protocol, msg), timeout
            )
        except asyncio.TimeoutError:
            raise RequestError(
                f"request {type(msg).__name__} to {peer_id} timed out"
            ) from None

    async def _request_inner(self, peer_id: str, protocol: str, msg: Any) -> Any:
        stream = await self._stream_to(peer_id, protocol)
        try:
            # PreEncoded payloads skip re-serialization: a scheduler
            # fanning one membership snapshot out to N parameter-service
            # shards encodes it once (hypha_tpu.messages.PreEncoded) and
            # every send ships the same bytes.
            pre = getattr(msg, "__pre_encoded__", None)
            sent = await stream.write_frame(
                pre if pre is not None else messages.encode(msg)
            )
            SCALE_METRICS.note_control(protocol, sent)
            reply = await stream.read_frame()
        except (FrameError, ConnectionError, OSError) as e:
            raise RequestError(f"rpc to {peer_id} failed: {e}") from e
        finally:
            await stream.close()
        if not isinstance(reply, dict) or "ok" not in reply:
            raise RequestError(f"malformed rpc reply from {peer_id}")
        if not reply["ok"]:
            raise RequestError(reply.get("error", "remote error"))
        return messages.decode(reply["body"])

    # ---------------------------------------------------------------- dialing

    async def _check_dialable(self, addr: str) -> None:
        """Every outbound dial funnels through here — the reference enforces
        its CIDR exclusion on each dial attempt against the *resolved*
        connection address (dial.rs:28-41,164), so hostnames are resolved
        and every A/AAAA answer checked; spelling an excluded IP as a DNS
        name does not evade the policy."""
        if not self._exclude_nets:
            return
        ips = []
        ip = _addr_ip(addr)
        if ip is not None:
            ips = [ip]
        else:
            host = _addr_host(addr)
            if host:
                import ipaddress
                import socket

                try:
                    infos = await asyncio.get_running_loop().getaddrinfo(
                        host, None, type=socket.SOCK_STREAM
                    )
                    ips = [ipaddress.ip_address(i[4][0]) for i in infos]
                except (OSError, ValueError):
                    # Not a resolvable host — a transport-specific address
                    # (memory fabric etc.); no IP policy applies.
                    return
        for ip in ips:
            for net in self._exclude_nets:
                if ip.version == net.version and ip in net:
                    raise ExcludedAddressError(f"{addr} is in excluded CIDR {net}")

    async def _open_raw(self, addr: str, proto: str) -> Stream:
        await self._check_dialable(addr)
        stream = await self.transport.dial(addr)
        await stream.write_frame(
            {"from": self.peer_id, "proto": proto, "addr": self.primary_addr()}
        )
        return stream

    async def _stream_to(self, peer_id: str, proto: str) -> Stream:
        try:
            return await self._stream_to_known(peer_id, proto)
        except RequestError as first:
            # Every known route failed. A peer that RESTARTED (PS crash
            # recovery, ft.durable) re-registers with the gateway under
            # fresh addresses, but a stale peerstore entry would otherwise
            # shadow the lookup forever — purge and re-resolve once.
            stale = self._peers.pop(peer_id, None)
            found = await self._lookup_peer(peer_id)
            if not any(a for a in found if not stale or a not in stale):
                if stale:
                    self._peers.setdefault(peer_id, stale)
                raise
            try:
                return await self._stream_to_known(peer_id, proto)
            except RequestError:
                raise first

    async def _stream_to_known(self, peer_id: str, proto: str) -> Stream:
        addrs = list(self._peers.get(peer_id, []))
        if not addrs:
            found = await self._lookup_peer(peer_id)
            addrs = list(found)
        # Direct routes first; circuit routes are the fallback. If the peer
        # advertises no relay address, its gateways still might hold a
        # reservation — try ours last (dial-fallback-to-relay).
        addrs.sort(key=lambda a: a.startswith("relay:"))
        if not any(a.startswith("relay:") for a in addrs):
            addrs += [f"relay:{gw}" for gw in self._bootstrap_addrs]
        last_err: Exception | None = None
        for addr in addrs:
            if addr.startswith("relay:"):
                try:
                    stream = await self._dial_via_relay(
                        addr[len("relay:"):], peer_id, proto
                    )
                except (ConnectionError, OSError, FrameError, RequestError) as e:
                    last_err = e
                    continue
                # Circuit in use → try to upgrade to a direct connection in
                # the background (DCUtR role); future dials prefer direct.
                self._maybe_upgrade_direct(addr[len("relay:"):], peer_id)
                return stream
            try:
                stream = await self._open_raw(addr, proto)
            except (ConnectionError, OSError) as e:
                last_err = e
                continue
            # Under mTLS, the server's certificate must prove the peer id we
            # meant to reach (PeerID = cert-key-hash; rfc/2025-05-30_mtls.md).
            if self._expected_peer_id is not None:
                actual = self._expected_peer_id(stream)
                if actual is not None and actual != peer_id:
                    await stream.abort()
                    known = self._peers.get(peer_id, [])
                    if addr in known:  # a concurrent call may have removed it
                        known.remove(addr)
                    last_err = RequestError(
                        f"{addr} presented certificate of {actual}, wanted {peer_id}"
                    )
                    continue
            return stream
        raise RequestError(f"no route to {peer_id}: {last_err}")

    # ----------------------------------------------------------------- relay
    #
    # Wire (all frames ride PROTOCOL_RELAY streams after the normal hello):
    #   listener -> gateway   {"t":"reserve"}            long-lived control
    #   gateway  -> listener  {"t":"incoming","circuit","from"}   on control
    #   dialer   -> gateway   {"t":"connect","target"}   becomes circuit leg A
    #   listener -> gateway   {"t":"accept","circuit"}   becomes circuit leg B
    # After both legs ack'd the gateway splices A<->B byte-for-byte; the
    # dialer then speaks the ordinary stream protocol through the circuit.
    # Reference: crates/gateway/src/network.rs:41-48 (relay server),
    # crates/network/src/listen.rs:25-131 (circuit listen addresses).

    async def _handle_relay(self, peer: str, stream: Stream) -> None:
        frame = await stream.read_frame()
        t = frame.get("t")
        if not self._relay_server:
            await stream.write_frame({"ok": False, "error": "not a relay server"})
            return
        if t == "reserve":
            old = self._relay_controls.get(peer)
            self._relay_controls[peer] = stream
            if old is not None:
                await old.abort()
            await stream.write_frame({"ok": True})
            log.debug("relay reservation for %s", peer)
            try:
                # Park until the listener drops; EOF tears the reservation.
                while await stream.read(65536):
                    pass
            finally:
                if self._relay_controls.get(peer) is stream:
                    del self._relay_controls[peer]
        elif t == "connect":
            target = frame.get("target", "")
            ctrl = self._relay_controls.get(target)
            if ctrl is None:
                await stream.write_frame(
                    {"ok": False, "error": f"no relay reservation for {target}"}
                )
                return
            # Per-peer circuit cap: a splice pins two sockets and a pump
            # task for the circuit's lifetime, so an uncapped dialer could
            # hold arbitrarily many gateway FDs (VERDICT r3 weak #6 — the
            # reference bounds relayed connections the same way its stream
            # accepts are bounded, stream_push.rs:56).
            if self._relay_active.get(peer, 0) >= RELAY_MAX_CIRCUITS_PER_PEER:
                await stream.write_frame(
                    {"ok": False,
                     "error": f"relay circuit cap reached for {peer}"}
                )
                return
            circuit = uuid.uuid4().hex
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._relay_pending[circuit] = {"dialer": peer, "fut": fut}
            self._relay_active[peer] = self._relay_active.get(peer, 0) + 1
            try:
                try:
                    await ctrl.write_frame(
                        {"t": "incoming", "circuit": circuit, "from": peer}
                    )
                    leg_b, done = await asyncio.wait_for(fut, RELAY_ACCEPT_TIMEOUT)
                except (asyncio.TimeoutError, FrameError, ConnectionError, OSError) as e:
                    self._relay_pending.pop(circuit, None)
                    await stream.write_frame(
                        {"ok": False, "error": f"relay accept failed: {e!r}"}
                    )
                    return
                try:
                    # The ok-frame write can itself fail (dialer timed out
                    # and dropped); done.set() must run regardless or the
                    # parked accept handler and the listener leg leak.
                    await stream.write_frame({"ok": True, "peer": target})
                    await self._splice(stream, leg_b)
                finally:
                    done.set()
            finally:
                n = self._relay_active.get(peer, 1) - 1
                if n <= 0:
                    self._relay_active.pop(peer, None)
                else:
                    self._relay_active[peer] = n
        elif t == "accept":
            rec = self._relay_pending.pop(frame.get("circuit", ""), None)
            if rec is None or rec["fut"].done():
                await stream.write_frame({"ok": False, "error": "unknown circuit"})
                return
            await stream.write_frame({"ok": True, "peer": rec["dialer"]})
            done = asyncio.Event()
            rec["fut"].set_result((stream, done))
            # Hold the accept handler open for the life of the circuit — the
            # transport closes the socket when this returns.
            await done.wait()
        else:
            await stream.write_frame({"ok": False, "error": f"unknown relay op {t!r}"})

    async def _splice(self, a: Stream, b: Stream) -> None:
        """Pump bytes both ways until both directions EOF; half-close each
        destination as its source drains so in-flight replies survive."""

        async def pump(src: Stream, dst: Stream) -> None:
            try:
                self.bytes_relayed += await copy_stream(src, dst)
            finally:
                try:
                    await dst.close()
                except (ConnectionError, OSError):
                    pass

        await asyncio.gather(pump(a, b), pump(b, a), return_exceptions=True)

    async def _relay_reserve_loop(self, gw_addr: str) -> None:
        """Keep one circuit reservation alive at ``gw_addr``; advertise the
        circuit address so other peers can route to us through it."""
        backoff = 0.25
        relay_addr = f"relay:{gw_addr}"
        while not self._closed:
            try:
                stream = await self._open_raw(gw_addr, PROTOCOL_RELAY)
                try:
                    await stream.write_frame({"t": "reserve"})
                    reply = await stream.read_frame()
                    if not reply.get("ok", False):
                        raise RequestError(reply.get("error", "reserve refused"))
                    if relay_addr not in self.external_addrs:
                        self.external_addrs.append(relay_addr)
                    log.debug("relay reservation live at %s", gw_addr)
                    backoff = 0.25
                    while True:
                        frame = await stream.read_frame()
                        if frame.get("t") == "incoming":
                            self._spawn(
                                self._relay_accept(gw_addr, frame.get("circuit", ""))
                            )
                finally:
                    await stream.abort()
            except asyncio.CancelledError:
                raise
            except (ConnectionError, OSError, FrameError, RequestError) as e:
                log.debug("relay reservation at %s dropped: %s", gw_addr, e)
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, 5.0)

    async def _relay_accept(self, gw_addr: str, circuit: str) -> None:
        """Dial back to the gateway to complete an announced circuit, then
        serve it like any inbound stream."""
        try:
            stream = await self._open_raw(gw_addr, PROTOCOL_RELAY)
        except (ConnectionError, OSError) as e:
            log.debug("relay accept dial to %s failed: %s", gw_addr, e)
            return
        try:
            await stream.write_frame({"t": "accept", "circuit": circuit})
            reply = await stream.read_frame()
            if not reply.get("ok", False):
                raise RequestError(reply.get("error", "accept refused"))
            dialer = reply.get("peer", "")
        except (FrameError, ConnectionError, OSError, RequestError) as e:
            log.debug("relay accept for circuit %s failed: %s", circuit, e)
            await stream.abort()
            return
        await self._on_stream(_RelayStream(stream, attested_peer=dialer))

    async def _dial_via_relay(self, gw_addr: str, target: str, proto: str) -> Stream:
        """Open a circuit to ``target`` through the gateway at ``gw_addr``.
        Returns the raw circuit; the caller speaks ``proto`` through it
        starting with the ordinary hello frame."""
        stream = await self._open_raw(gw_addr, PROTOCOL_RELAY)
        try:
            await stream.write_frame({"t": "connect", "target": target})
            reply = await asyncio.wait_for(
                stream.read_frame(), RELAY_ACCEPT_TIMEOUT + 5.0
            )
        except (FrameError, ConnectionError, OSError, asyncio.TimeoutError) as e:
            await stream.abort()
            raise RequestError(f"relay connect via {gw_addr} failed: {e!r}") from e
        if not reply.get("ok", False):
            await stream.abort()
            raise RequestError(reply.get("error", "relay connect refused"))
        attested = reply.get("peer", "")
        if attested and attested != target:
            await stream.abort()
            raise RequestError(f"relay attested {attested}, wanted {target}")
        relayed = _RelayStream(stream, attested_peer=attested)
        await relayed.write_frame(
            {"from": self.peer_id, "proto": proto, "addr": self.primary_addr()}
        )
        return relayed

    # ----------------------------------------------------------------- dcutr
    #
    # Wire (one PROTOCOL_DCUTR stream through a circuit, dialer-initiated):
    #   dialer   -> listener  {"t":"holepunch","addrs":[...direct addrs]}
    #   listener -> dialer    {"ok":true,"addrs":[...direct addrs]}
    # Then BOTH sides attempt direct dials of the other's list (the
    # simultaneous attempts are what open NAT pinholes for TCP; on an open
    # network the first reverse dial simply lands). A working address enters
    # the address book via dial()'s identify, after which _stream_to's
    # direct-before-relay ordering routes around the gateway.

    def _direct_addrs(self) -> list[str]:
        # Wildcard binds (0.0.0.0 / [::]) are listenable but not dialable;
        # advertising them would waste slots in the capped dial volley.
        out = []
        for a in [*self.listen_addrs, *self.external_addrs]:
            if a.startswith("relay:"):
                continue
            host = a.rsplit(":", 1)[0].strip("[]")
            if host in ("0.0.0.0", "::", ""):
                continue
            out.append(a)
        return out

    def _maybe_upgrade_direct(self, gw_addr: str, peer_id: str) -> None:
        """Throttled background direct-upgrade attempt for ``peer_id``.
        (No book-based skip: the book may hold direct addrs that do NOT
        work — that is exactly why this dial fell back to the relay.)"""
        now = time.monotonic()
        if now - self._dcutr_last.get(peer_id, -DCUTR_RETRY_S) < DCUTR_RETRY_S:
            return
        self._prune_dcutr(now)
        self._dcutr_last[peer_id] = now
        self._spawn(self._direct_upgrade(gw_addr, peer_id))

    def _prune_dcutr(self, now: float) -> None:
        """Entries older than the retry window carry no throttle information;
        dropping them bounds the table against peers churning fresh ids."""
        if len(self._dcutr_last) < 1024:
            return
        cutoff = now - DCUTR_RETRY_S
        self._dcutr_last = {
            p: t for p, t in self._dcutr_last.items() if t >= cutoff
        }

    # Peer-supplied candidate lists are capped: each failed candidate costs
    # up to a 5 s dial wait, so an uncapped hostile list would pin a
    # background task for hours.
    DCUTR_MAX_CANDIDATES = 8

    async def _try_direct(self, peer_id: str, addrs: list[str]) -> None:
        """Dial candidates until one identifies as ``peer_id``; dial()
        records the working address in the address book."""
        for addr in addrs[: self.DCUTR_MAX_CANDIDATES]:
            try:
                got = await asyncio.wait_for(self.dial(addr), 5.0)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.debug("dcutr: direct dial %s failed: %s", addr, e)
                continue
            if got == peer_id:
                log.debug("dcutr: direct route to %s via %s", peer_id, addr)
                return
        log.debug("dcutr: no direct route to %s (tried %d)", peer_id, len(addrs))

    async def _direct_upgrade(self, gw_addr: str, target: str) -> None:
        """Dialer side: exchange direct addresses over a fresh circuit, then
        race a direct dial while the listener dials us back."""
        try:
            stream = await self._dial_via_relay(gw_addr, target, PROTOCOL_DCUTR)
        except (ConnectionError, OSError, FrameError, RequestError) as e:
            log.debug("dcutr: circuit to %s failed: %s", target, e)
            return
        try:
            await stream.write_frame(
                {"t": "holepunch", "addrs": self._direct_addrs()}
            )
            reply = await asyncio.wait_for(stream.read_frame(), 10.0)
        except (FrameError, ConnectionError, OSError, asyncio.TimeoutError) as e:
            log.debug("dcutr: exchange with %s failed: %s", target, e)
            return
        finally:
            await stream.close()
        if reply.get("ok"):
            addrs = [a for a in reply.get("addrs", []) if isinstance(a, str)]
            await self._try_direct(target, addrs)

    async def _handle_dcutr(self, peer: str, stream: Stream) -> None:
        frame = await stream.read_frame()
        if frame.get("t") != "holepunch":
            await stream.write_frame({"ok": False, "error": "unknown dcutr op"})
            return
        await stream.write_frame({"ok": True, "addrs": self._direct_addrs()})
        # The dial-back volley is throttled like the initiating side — a
        # peer opening dcutr streams in a loop must not multiply background
        # dial tasks (the address list is additionally capped in
        # _try_direct).
        now = time.monotonic()
        if now - self._dcutr_last.get(peer, -DCUTR_RETRY_S) < DCUTR_RETRY_S:
            return
        self._prune_dcutr(now)
        self._dcutr_last[peer] = now
        addrs = [a for a in frame.get("addrs", []) if isinstance(a, str)]
        # Dial back outside the circuit's lifetime.
        self._spawn(self._try_direct(peer, addrs))

    # ---------------------------------------------------------------- gossip

    def add_gossip_peer(self, peer_id: str) -> None:
        if peer_id != self.peer_id:
            self._gossip_peers.add(peer_id)

    async def subscribe(self, topic: str, buffer: int = 256) -> Subscription:
        sub = Subscription(self, topic, buffer)
        self._subs.setdefault(topic, []).append(sub)
        return sub

    async def _unsubscribe(self, sub: Subscription) -> None:
        lst = self._subs.get(sub.topic, [])
        if sub in lst:
            lst.remove(sub)

    async def publish(self, topic: str, msg: Any) -> None:
        """Flood ``msg`` to the mesh. When the node has a ``gossip_key``
        (every mTLS node does), the frame carries an Ed25519 signature by
        the origin's cert key and receivers verify key-hash == origin, so
        the ``origin`` delivered to subscribers is authenticated end-to-end
        across relays (reference: signed gossipsub,
        crates/scheduler/src/network.rs:132-136), and a signed timestamp
        bounds replay of captured frames to GOSSIP_MAX_SKEW_S. Within that
        window a mesh member can still re-flood a captured frame, so treat
        gossip as advertisement, not authorization — security-relevant
        follow-ups (offers, leases, dispatch) run over cert-verified RPC.
        Keyless dev-mode nodes flood unsigned and accept unsigned."""
        msg_id = uuid.uuid4().hex
        body = messages.encode(msg)
        key = sig = None
        ts_ns = time.time_ns()
        if self._gossip_key is not None:
            from cryptography.hazmat.primitives import serialization

            key = self._gossip_key.public_key().public_bytes(
                serialization.Encoding.DER,
                serialization.PublicFormat.SubjectPublicKeyInfo,
            )
            canonical = _gossip_sign_bytes(topic, msg_id, self.peer_id, ts_ns, body)
            sig = self._gossip_key.sign(canonical)
            self._mark_seen(_gossip_seen_key(msg_id, sig, canonical))
        else:
            self._mark_seen(_gossip_seen_key(msg_id, None))
        self._deliver_local(topic, self.peer_id, body)
        await self._gossip_fanout(
            topic, msg_id, self.peer_id, body, exclude=set(),
            key=key, sig=sig, ts_ns=ts_ns,
        )

    def _mark_seen(self, msg_id: str) -> bool:
        """Returns True if this id is new."""
        if msg_id in self._seen:
            return False
        self._seen[msg_id] = None
        while len(self._seen) > _SEEN_CAP:
            self._seen.popitem(last=False)
        return True

    def _deliver_local(self, topic: str, origin: str, body: bytes) -> None:
        subs = self._subs.get(topic)
        if not subs:
            return
        try:
            msg = messages.decode(body)
        except Exception as e:
            log.debug("dropping undecodable gossip on %s: %s", topic, e)
            return
        for sub in list(subs):
            sub._deliver(origin, msg)

    async def _gossip_fanout(
        self,
        topic: str,
        msg_id: str,
        origin: str,
        body: bytes,
        exclude: set[str],
        key: bytes | None = None,
        sig: bytes | None = None,
        ts_ns: int = 0,
    ) -> None:
        frame = {
            "t": "pub",
            "topic": topic,
            "id": msg_id,
            "origin": origin,
            "data": body,
        }
        if key is not None and sig is not None:
            # Relays forward the ORIGIN's key+signature untouched, so
            # verification is end-to-end regardless of the flood path.
            frame["key"] = key
            frame["sig"] = sig
            frame["ts"] = ts_ns
        targets = [p for p in self._gossip_peers if p not in exclude]
        # Fire in parallel; unreachable peers are dropped from the mesh.
        results = await asyncio.gather(
            *(self._send_gossip(p, frame) for p in targets), return_exceptions=True
        )
        for peer, res in zip(targets, results):
            if isinstance(res, Exception):
                log.debug("gossip peer %s unreachable: %s", peer, res)
                self._gossip_peers.discard(peer)

    async def _send_gossip(self, peer_id: str, frame: dict) -> None:
        stream = await self._stream_to(peer_id, PROTOCOL_GOSSIP)
        try:
            await stream.write_frame(frame)
        finally:
            await stream.close()

    async def _handle_gossip(self, peer: str, stream: Stream) -> None:
        frame = await stream.read_frame()
        # Any peer speaking gossip to us joins our mesh (bidirectional flood).
        if peer:
            self.add_gossip_peer(peer)
        t = frame.get("t")
        if t == "pub":
            msg_id = frame.get("id", "")
            topic = frame.get("topic", "")
            origin = frame.get("origin", peer)
            body = frame.get("data", b"")
            key, sig = frame.get("key"), frame.get("sig")
            ts_ns = int(frame.get("ts", 0))
            # Dedup keyed on (id, canonical-bytes, sig) BEFORE the Ed25519
            # verify: identical flood copies of a genuine frame short-circuit
            # without paying verification, while any forgery reusing the id
            # hashes to a different key, misses the cache, fails verification
            # — and cannot poison the dedup entry of the real message.
            canonical = (
                _gossip_sign_bytes(topic, msg_id, origin, ts_ns, body)
                if sig is not None
                else b""
            )
            if not self._mark_seen(_gossip_seen_key(msg_id, sig, canonical)):
                return
            if key is not None and sig is not None:
                if abs(time.time_ns() - ts_ns) > GOSSIP_MAX_SKEW_S * 1e9:
                    log.warning(
                        "dropping gossip on %s: frame from %s outside the "
                        "freshness window (replay or clock skew)", topic, origin,
                    )
                    return
                if not _gossip_verify(topic, msg_id, origin, ts_ns, body, key, sig):
                    log.warning(
                        "dropping gossip on %s: bad signature for origin %s "
                        "(relayed by %s)", topic, origin, peer,
                    )
                    return
            elif self._gossip_key is not None:
                # This node runs a signed mesh; unsigned frames are dropped
                # (reference: gossipsub ValidationMode::Strict).
                log.warning(
                    "dropping unsigned gossip on %s from %s", topic, peer
                )
                return
            self._deliver_local(topic, origin, body)
            self._spawn(
                self._gossip_fanout(
                    topic, msg_id, origin, body, exclude={peer},
                    key=key, sig=sig, ts_ns=ts_ns,
                )
            )
        # "sub"/"unsub" frames are accepted for forward-compat; flood
        # forwarding does not require remote subscription state.

    # -------------------------------------------------------------- discovery

    async def _bootstrap_loop(self) -> None:
        """Dial every gateway until at least one registration succeeds; keep
        registrations and provider announcements fresh (the reference's kad
        bootstrap + identify role). Unreachable gateways back off
        exponentially (250 ms → 5 s)."""
        backoff = 0.25
        while not self._closed:
            ok = False
            for addr in self._bootstrap_addrs:
                try:
                    peer = await self._register_with_gateway(addr)
                    if peer:
                        self._bootstrap_peers.add(peer)
                        self.add_gossip_peer(peer)
                        ok = True
                except (ConnectionError, OSError, FrameError, RequestError) as e:
                    log.debug("bootstrap dial %s failed: %s", addr, e)
            if ok:
                backoff = 0.25
                self._bootstrapped.set()
                for key in list(self._provided):  # refresh provider TTLs
                    try:
                        await self.provide(key)
                    except RequestError:
                        pass
                await asyncio.sleep(30.0)  # refresh registration
            else:
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 5.0)

    async def _register_with_gateway(self, addr: str) -> str:
        stream = await self._open_raw(addr, PROTOCOL_REGISTRY)
        try:
            await stream.write_frame(
                {"t": "register", "peer": self.peer_id, "addrs": self._my_addrs()}
            )
            reply = await stream.read_frame()
            peer = reply.get("peer", "")
            if peer:
                self.add_peer_addr(peer, addr)
            return peer
        finally:
            await stream.close()

    def _my_addrs(self) -> list[str]:
        """Addresses to advertise. A NAT'd node (``advertise_listen=False``)
        publishes only its external/circuit addresses — its listen addrs are
        private-network noise to other peers; the DCUtR exchange is the
        channel that hands those candidates to a peer at upgrade time."""
        if not self._advertise_listen:
            return list(dict.fromkeys(self.external_addrs))
        return list(dict.fromkeys(self.external_addrs + self.listen_addrs))

    async def wait_for_bootstrap(self, timeout: float = 60.0) -> None:
        await asyncio.wait_for(self._bootstrapped.wait(), timeout)

    # Registry ops that mutate state replicate to EVERY reachable gateway —
    # the reference's records/providers replicate across the Kademlia DHT
    # (crates/network/src/kad.rs:482-700); with first-reachable-only writes
    # a gateway crash lost records until the 30 s refresh re-announced them
    # (VERDICT r3 missing #3).
    _REGISTRY_WRITE_OPS = frozenset({"put", "provide", "unprovide"})

    async def _registry_one(self, addr: str, frame: dict) -> dict:
        # Bounded per gateway: with writes fanning out to every gateway, an
        # accepting-but-silent one must not stall the op (the healthy
        # gateways are the whole point of replication). Timeout surfaces as
        # ConnectionError so the caller's failover handles it uniformly.
        async def op() -> dict:
            stream = await self._open_raw(addr, PROTOCOL_REGISTRY)
            try:
                await stream.write_frame(frame)
                return await stream.read_frame()
            finally:
                await stream.close()

        try:
            # wait_for, not asyncio.timeout: the latter is Python 3.11+.
            return await asyncio.wait_for(op(), REGISTRY_OP_TIMEOUT)
        except (TimeoutError, asyncio.TimeoutError) as e:
            raise ConnectionError(f"registry op timed out at {addr}") from e

    async def _registry_call(self, frame: dict) -> dict:
        """Run a registry op against gateways (or locally if self-anchored).

        Writes go to all reachable gateways (success = at least one ack);
        ``find`` merges providers across gateways; other reads return the
        first POSITIVE answer, falling back to a negative one only when no
        gateway answers positively — so a lookup keeps resolving while the
        gateway that took the original write is down.
        """
        if self._registry_server or not self._bootstrap_addrs:
            return self._registry_apply("", frame)
        t = frame.get("t")
        last: Exception | None = None
        if t in self._REGISTRY_WRITE_OPS:
            # Concurrent fan-out: k unreachable gateways must cost one
            # REGISTRY_OP_TIMEOUT, not k of them — write ops run inside
            # periodic re-announce loops that share the event loop with
            # lease heartbeats.
            results = await asyncio.gather(
                *(self._registry_one(a, frame) for a in self._bootstrap_addrs),
                return_exceptions=True,
            )
            acks: list[dict] = []
            for r in results:
                if isinstance(r, (ConnectionError, OSError, FrameError)):
                    last = r
                elif isinstance(r, BaseException):
                    raise r
                else:
                    acks.append(r)
            for reply in acks:
                if reply.get("ok", False):
                    return reply
            if acks:
                return acks[0]
            raise RequestError(f"no gateway reachable: {last}")
        if t == "find":
            merged: dict[str, dict] = {}
            reached = False
            for addr in self._bootstrap_addrs:
                try:
                    reply = await self._registry_one(addr, frame)
                except (ConnectionError, OSError, FrameError) as e:
                    last = e
                    continue
                reached = True
                for p in reply.get("providers", []):
                    merged.setdefault(p.get("peer", ""), p)
            if not reached:
                raise RequestError(f"no gateway reachable: {last}")
            return {"ok": True, "providers": list(merged.values())}
        negative: dict | None = None
        for addr in self._bootstrap_addrs:
            try:
                reply = await self._registry_one(addr, frame)
            except (ConnectionError, OSError, FrameError) as e:
                last = e
                continue
            if reply.get("ok", False):
                return reply
            if negative is None:
                negative = reply
        if negative is not None:
            return negative
        raise RequestError(f"no gateway reachable: {last}")

    async def put_record(self, key: str, value: bytes) -> None:
        reply = await self._registry_call({"t": "put", "key": key, "value": value})
        if not reply.get("ok", False):
            raise RequestError(reply.get("error", "put failed"))

    async def get_record(self, key: str) -> bytes | None:
        reply = await self._registry_call({"t": "get", "key": key})
        return reply.get("value") if reply.get("ok", False) else None

    async def provide(self, key: str) -> None:
        self._provided.add(key)  # re-announced by the bootstrap refresh loop
        reply = await self._registry_call(
            {"t": "provide", "key": key, "peer": self.peer_id, "addrs": self._my_addrs()}
        )
        if not reply.get("ok", False):
            raise RequestError(reply.get("error", "provide failed"))

    async def unprovide(self, key: str) -> None:
        """Withdraw a provider announcement: stop the refresh loop from
        re-announcing AND delete the registry entry now (clients must not
        keep discovering a dead server until the TTL sweep)."""
        self._provided.discard(key)
        try:
            await self._registry_call(
                {"t": "unprovide", "key": key, "peer": self.peer_id}
            )
        except RequestError as e:
            # Best effort: with the refresh stopped, PROVIDER_TTL ages the
            # entry out anyway.
            log.debug("unprovide %s failed: %s", key, e)

    async def find_providers(self, key: str) -> list[str]:
        reply = await self._registry_call({"t": "find", "key": key})
        providers = reply.get("providers", [])
        for p in providers:
            for a in p.get("addrs", []):
                self.add_peer_addr(p["peer"], a)
        return [p["peer"] for p in providers]

    async def _lookup_peer(self, peer_id: str) -> list[str]:
        try:
            reply = await self._registry_call({"t": "lookup", "peer": peer_id})
        except RequestError:
            return []
        addrs = reply.get("addrs", []) if reply.get("ok", False) else []
        for a in addrs:
            self.add_peer_addr(peer_id, a)
        return addrs

    def _registry_apply(self, from_peer: str, frame: dict) -> dict:
        """Server-side registry ops (gateway role, kad Mode::Server)."""
        t = frame.get("t")
        if t == "identify":
            return {"ok": True, "peer": self.peer_id}
        if t == "register":
            # Identity comes from the handshake (cert-verified under mTLS),
            # never from the frame body — a trusted-but-malicious peer must
            # not be able to overwrite another peer's address book entry.
            peer, addrs = from_peer or frame.get("peer", ""), frame.get("addrs", [])
            if peer:
                self._addr_book[peer] = list(addrs)
                self.add_gossip_peer(peer)
                for a in addrs:
                    self.add_peer_addr(peer, a)
            return {"ok": True, "peer": self.peer_id}
        if t == "put":
            self._records[frame.get("key", "")] = frame.get("value", b"")
            return {"ok": True}
        if t == "get":
            key = frame.get("key", "")
            if key in self._records:
                return {"ok": True, "value": self._records[key]}
            return {"ok": False, "error": f"no record {key!r}"}
        if t == "provide":
            key, peer = frame.get("key", ""), from_peer or frame.get("peer", "")
            self._providers.setdefault(key, {})[peer] = time.time()
            if frame.get("addrs"):
                self._addr_book[peer] = list(frame["addrs"])
            return {"ok": True}
        if t == "unprovide":
            key, peer = frame.get("key", ""), from_peer or frame.get("peer", "")
            self._providers.get(key, {}).pop(peer, None)
            return {"ok": True}
        if t == "find":
            # Drop providers that stopped refreshing (crashed data nodes must
            # age out; clients re-announce every 30 s from _bootstrap_loop).
            entries = self._providers.get(frame.get("key", ""), {})
            cutoff = time.time() - PROVIDER_TTL
            for p in [p for p, ts in entries.items() if ts < cutoff]:
                del entries[p]
            out = [
                {"peer": p, "addrs": self._addr_book.get(p, [])} for p in entries
            ]
            return {"ok": True, "providers": out}
        if t == "lookup":
            peer = frame.get("peer", "")
            addrs = self._addr_book.get(peer)
            if addrs is None:
                return {"ok": False, "error": f"unknown peer {peer}"}
            return {"ok": True, "addrs": addrs}
        return {"ok": False, "error": f"unknown registry op {t!r}"}

    async def _handle_registry(self, peer: str, stream: Stream) -> None:
        frame = await stream.read_frame()
        if not self._registry_server and frame.get("t") not in ("identify",):
            await stream.write_frame({"ok": False, "error": "not a registry server"})
            return
        await stream.write_frame(self._registry_apply(peer, frame))

    # --------------------------------------------------------- tensor streams

    async def push(self, peer_id: str, resource: Any, source) -> int:
        """Open a push stream: header frame, then raw bytes from ``source``
        (bytes | file path | async byte iterator). Returns bytes sent."""
        stream = await self._stream_to(peer_id, PROTOCOL_PUSH)
        try:
            await stream.write_frame(messages.encode(resource))
            if isinstance(
                source, (bytes, bytearray, memoryview, str)
            ) or hasattr(source, "__fspath__"):
                # Lump-sum accounting keeps the sendfile fast path.
                n = await self._write_source(stream, source)
                self.bytes_out += n
            else:
                # Streamed (iterator) sources credit the outbound counter
                # chunk by chunk: a slow / throttled transfer must read as
                # its true rate on the bandwidth gauges, not as one burst
                # at completion (the metrics plane's link rollups compare
                # rates across peers).
                n = await self._write_source(_CountingStream(stream, self), source)
            return n
        finally:
            await stream.close()

    async def _write_source(self, stream: Stream, source) -> int:
        """Stream bytes | file path | async iterator | Stream into ``stream``."""
        if isinstance(source, (bytes, bytearray, memoryview)):
            data = bytes(source)
            await stream.write(data)
            return len(data)
        if isinstance(source, str) or hasattr(source, "__fspath__"):
            loop = asyncio.get_running_loop()
            # Zero-copy fast path (the data node's hot serve loop, reference
            # tensor_data.rs:8-16 io::copy): kernel sendfile on plain TCP;
            # asyncio streams the fallback itself under TLS.
            transport = getattr(stream, "sendfile_transport", lambda: None)()
            if transport is not None:
                f = await asyncio.to_thread(open, source, "rb")
                try:
                    return await loop.sendfile(transport, f, fallback=True)
                except (AttributeError, NotImplementedError, RuntimeError):
                    pass  # transport without sendfile support: chunked copy
                finally:
                    await asyncio.to_thread(f.close)
            total = 0
            f = await asyncio.to_thread(open, source, "rb")
            try:
                while True:
                    chunk = await loop.run_in_executor(None, f.read, 1 << 20)
                    if not chunk:
                        break
                    await stream.write(chunk)
                    total += len(chunk)
            finally:
                await asyncio.to_thread(f.close)
            return total
        return await copy_stream(source, stream)

    async def _handle_push(self, peer: str, stream: Stream) -> None:
        header = await stream.read_frame(MAX_STREAM_HEADER)
        resource = messages.decode(header)
        await self._push_sem.acquire()
        finished = asyncio.Event()

        def done() -> None:
            if not finished.is_set():
                finished.set()
                self._push_sem.release()

        push = PushStream(
            peer=peer,
            resource=resource,
            stream=_CountingStream(stream, self),
            _done=done,
        )
        # Route to the first registered consumer whose predicate matches;
        # unmatched pushes land on the shared default queue. Predicate
        # routing is what lets one node host several stream consumers at
        # once (a parameter-server job AND a train job's receive, or two
        # jobs' bridges) without eating each other's transfers.
        target = self._push_queue
        for consumer in self._push_consumers:
            try:
                matches = consumer.predicate(push)
            except Exception:
                matches = False
            if matches:
                target = consumer._queue
                break
        await target.put(push)
        # Keep the transport connection alive until the consumer drains it
        # (TCP closes the socket when the accept callback returns).
        await finished.wait()

    async def inject_push(
        self,
        peer: str,
        resource: Any,
        path,
        on_done: Callable[[], None] | None = None,
    ) -> None:
        """Deliver a LOCAL push into this node's own consumer routing.

        A broadcast-tree relay (hypha_tpu.stream.reduce.BroadcastRelay)
        receives a wire addressed to its subtree and must also hand it to
        the training loop on the SAME node — dialing oneself would burn a
        socket and an accept slot for a file already on local disk.
        ``peer`` attributes the push to its true origin (the sending hop),
        so receiver-side allowlists behave exactly as for a wire push.
        ``on_done`` fires when the consumer finishes with the stream
        (save_to/read_all EOF), after which the caller may unlink ``path``.
        Bypasses the inbound accept semaphore deliberately: local delivery
        must not contend with (or deadlock behind) 8 slow remote senders.
        """
        stream = _LocalFileStream(path)
        fired = False

        def done() -> None:
            nonlocal fired
            if fired:
                return
            fired = True
            # Best-effort file-handle cleanup; the event loop is running,
            # so schedule rather than await.
            aio.spawn(stream.close(), what="inject_push close", logger=log)
            if on_done is not None:
                on_done()

        push = PushStream(
            peer=peer, resource=resource, stream=stream, _done=done
        )
        target = self._push_queue
        for consumer in self._push_consumers:
            try:
                matches = consumer.predicate(push)
            except Exception:
                matches = False
            if matches:
                target = consumer._queue
                break
        await target.put(push)

    def consume_pushes(
        self, predicate: Callable[[PushStream], bool], buffer: int = 64
    ) -> "PushConsumer":
        """Register a routed push consumer (first registered, first matched).
        Close it to unroute; buffered pushes can still be drained after.

        Pushes that arrived BEFORE registration (e.g. a parameter-server
        broadcast landing between two of the executor's receive windows) sit
        on the default queue; reclaim the matching ones now.
        """
        consumer = PushConsumer(self, predicate, buffer)
        self._push_consumers.append(consumer)
        leftover = []
        while not self._push_queue.empty():
            item = self._push_queue.get_nowait()
            if item is None:  # stop sentinel: keep for other consumers
                leftover.append(item)
                continue
            try:
                matched = predicate(item)
            except Exception:
                matched = False
            if matched and not consumer._queue.full():
                consumer._queue.put_nowait(item)
            else:
                leftover.append(item)
        for item in leftover:
            self._push_queue.put_nowait(item)
        return consumer

    async def push_streams(self) -> AsyncIterator[PushStream]:
        """Async iterator over accepted inbound pushes; terminates on node
        stop. ``read_all``/``save_to`` release the accept slot at EOF."""
        while not self._closed:
            item = await self._push_queue.get()
            if item is None:  # stop() sentinel; re-arm for other consumers
                self._push_queue.put_nowait(None)
                return
            yield item

    async def next_push(self, timeout: float | None = None) -> PushStream:
        getter = self._push_queue.get()
        item = await (getter if timeout is None else asyncio.wait_for(getter, timeout))
        if item is None:
            self._push_queue.put_nowait(None)
            raise RequestError("node stopped")
        return item

    def on_pull(self, handler: Callable[[str, Any], Awaitable[Any]]) -> None:
        """Register the pull server: handler(peer, resource) returns the
        payload source (bytes | file path | async iterator). A status frame
        precedes the payload on the wire, so handler failures surface as
        RequestError at the puller instead of an empty payload
        (reference: data node serve loop, hypha-data.rs:187-209)."""
        self._pull_handler = handler

    async def _handle_pull(self, peer: str, stream: Stream) -> None:
        header = await stream.read_frame(MAX_STREAM_HEADER)
        resource = messages.decode(header)
        async with self._pull_sem:
            if self._pull_handler is None:
                await stream.write_frame({"ok": False, "error": "no pull handler"})
                return
            try:
                source = await self._pull_handler(peer, resource)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                await stream.write_frame({"ok": False, "error": str(e)})
                return
            await stream.write_frame({"ok": True})
            self.bytes_out += await self._write_source(stream, source)

    async def pull(self, peer_id: str, resource: Any) -> Stream:
        """Open a pull stream: send the bounded resource header, check the
        status frame, return the byte stream of the payload (reference:
        stream_pull.rs:66-103 — 8-byte LE length + bounded header)."""
        stream = await self._stream_to(peer_id, PROTOCOL_PULL)
        try:
            await stream.write_frame(messages.encode(resource))
            status = await stream.read_frame()
        except (FrameError, ConnectionError, OSError) as e:
            await stream.abort()
            raise RequestError(f"pull from {peer_id} failed: {e}") from e
        if not status.get("ok", False):
            await stream.abort()
            raise RequestError(status.get("error", "pull refused"))
        return _CountingStream(stream, self)
