"""Stream-multiplexing transport: many logical streams, one connection.

The reference's transport stack layers a muxer (yamux) over TCP+TLS and also
runs QUIC — both give N logical streams per connection/handshake
(crates/scheduler/src/network.rs:109-131). The base fabric here deliberately
uses one TCP connection per stream for BULK throughput (the reference's own
RFC measured parallel streams beating yamux, rfc/2025-03-25:17-29), but that
costs a TCP+mTLS handshake per RPC — painful on the chatty auction path.
``MuxTransport`` is the second transport: it wraps any base
:class:`Transport` and multiplexes logical streams over one persistent
connection per remote address.

Wire format (one muxed connection): frames of

    [4B stream_id LE][1B flag][4B length LE][payload]

flags: 1=OPEN (dialer-initiated stream; ids odd from dialer, even from
listener), 2=DATA, 3=CLOSE (half-close, EOF after drain), 4=RESET (abort).
Per-stream inbound buffers are bounded (``window`` bytes); a sender that
overruns a slow consumer blocks on the shared connection — the documented
head-of-line tradeoff vs the parallel-connection base transport (use that
for bulk tensor pushes; mux for RPC).

TLS identity: logical streams expose the underlying connection's peer
certificate, so PeerID = cert-key-hash checks work unchanged.
"""

from __future__ import annotations

import asyncio
import logging
import struct
from typing import Callable

from .. import aio
from .fabric import AcceptCallback, Stream, Transport

__all__ = ["MuxTransport"]

log = logging.getLogger("hypha.network.mux")

_HDR = struct.Struct("<IBI")
_OPEN, _DATA, _CLOSE, _RESET = 1, 2, 3, 4
_MAX_CHUNK = 1 << 20


class _MuxStream(Stream):
    """One logical stream riding a muxed connection."""

    def __init__(self, conn: "_MuxConn", sid: int) -> None:
        self._conn = conn
        self.sid = sid
        self._rx: asyncio.Queue = asyncio.Queue()
        self._buf = b""
        self._eof = False
        self._closed = False
        # Window accounting: bytes queued here but not yet read. Credited
        # back either by read() or — for streams closed/reset/aborted with
        # unread data — by _detach(), so an abandoned stream can never stall
        # the connection's window permanently.
        self._undrained = 0
        self._detached = False

    # -- reading ------------------------------------------------------------
    async def read(self, n: int = 65536) -> bytes:
        if not self._buf:
            if self._eof:
                return b""
            chunk = await self._rx.get()
            if chunk is None:
                self._eof = True
                return b""
            if not self._detached:
                self._undrained -= len(chunk)
                self._conn._credit(len(chunk))
            self._buf = chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _deliver(self, data: bytes | None) -> None:
        if data is not None and not self._detached:
            self._undrained += len(data)
        self._rx.put_nowait(data)

    def _detach(self) -> None:
        """Return any unread bytes to the connection window (the stream may
        still be drained afterwards; those reads no longer credit)."""
        if not self._detached:
            self._detached = True
            if self._undrained:
                self._conn._credit(self._undrained)
                self._undrained = 0

    # -- writing ------------------------------------------------------------
    async def write(self, data: bytes) -> None:
        if self._closed:
            raise ConnectionError("write on closed mux stream")
        mv = memoryview(bytes(data))
        for off in range(0, len(mv), _MAX_CHUNK):
            await self._conn.send(self.sid, _DATA, mv[off : off + _MAX_CHUNK])

    async def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                await self._conn.send(self.sid, _CLOSE, b"")
            except (ConnectionError, OSError):
                pass

    async def abort(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                await self._conn.send(self.sid, _RESET, b"")
            except (ConnectionError, OSError):
                pass
        # Unregister so late frames for this sid are dropped, and return the
        # window credit for anything buffered.
        self._conn._streams.pop(self.sid, None)
        self._detach()
        self._deliver(None)

    # -- identity pass-through ---------------------------------------------
    def peer_certificate(self):
        fn = getattr(self._conn.base, "peer_certificate", None)
        return fn() if fn else None

    def peer_certificate_der(self):
        fn = getattr(self._conn.base, "peer_certificate_der", None)
        return fn() if fn else None


class _MuxConn:
    """One muxed base connection: frame pump + stream table."""

    def __init__(
        self,
        base: Stream,
        dialer: bool,
        on_stream: AcceptCallback | None,
        window: int = 4 << 20,
    ) -> None:
        self.base = base
        self._dialer = dialer
        self._on_stream = on_stream
        self._streams: dict[int, _MuxStream] = {}
        self._next_id = 1 if dialer else 2
        self._wlock = asyncio.Lock()
        self._window = window
        self._inflight = 0
        self._has_credit = asyncio.Event()
        self._has_credit.set()
        self.closed = False
        self._tasks: set[asyncio.Task] = set()
        self._pump_task = aio.spawn(self._pump(), what="mux pump", logger=log)

    def _credit(self, n: int) -> None:
        self._inflight -= n
        if self._inflight <= self._window:
            self._has_credit.set()

    async def send(self, sid: int, flag: int, payload) -> None:
        if self.closed:
            raise ConnectionError("mux connection closed")
        async with self._wlock:
            # ONE write per frame, always: a caller's wait_for() cancelling
            # between a split header/payload pair would tear the frame and
            # desync every stream on the connection. The concatenation copy
            # (~30 us/MiB) is the price of cancellation atomicity.
            await self.base.write(_HDR.pack(sid, flag, len(payload)) + bytes(payload))

    def open_stream(self) -> _MuxStream:
        sid = self._next_id
        self._next_id += 2
        stream = _MuxStream(self, sid)
        self._streams[sid] = stream
        return stream

    async def _pump(self) -> None:
        try:
            while True:
                # Flow control: stop reading the base socket while undrained
                # inbound buffers exceed the window — kernel TCP backpressure
                # then throttles the remote sender. (Connection-level, not
                # per-stream credits: the head-of-line tradeoff in the module
                # docstring. Never gate WRITES on local inbound state — that
                # couples directions and can deadlock request/reply pairs.)
                await self._has_credit.wait()
                hdr = await self.base.read_exactly(_HDR.size)
                sid, flag, length = _HDR.unpack(hdr)
                if length > _MAX_CHUNK:
                    # Our writer chunks at _MAX_CHUNK; a larger claim is a
                    # corrupt or hostile peer — drop the connection rather
                    # than buffering toward the advertised size.
                    log.warning("mux frame of %d bytes exceeds cap; dropping conn", length)
                    break
                payload = await self.base.read_exactly(length) if length else b""
                if flag == _OPEN:
                    if self._on_stream is None:
                        # Dial-side connection with no inbound handler: a
                        # registered-but-unconsumed stream would eat window
                        # credit forever. Refuse it — from a spawned task,
                        # never awaiting a write inside the read pump (a
                        # non-draining peer could wedge the connection).
                        aio.spawn(
                            self._reset_quietly(sid),
                            tasks=self._tasks,
                            what="mux stream reset",
                            logger=log,
                        )
                        continue
                    stream = _MuxStream(self, sid)
                    self._streams[sid] = stream
                    if payload:
                        self._inflight += len(payload)
                        stream._deliver(payload)
                    aio.spawn(
                        self._serve(stream),
                        tasks=self._tasks,
                        what="mux stream serve",
                        logger=log,
                    )
                elif flag == _DATA:
                    stream = self._streams.get(sid)
                    if stream is not None:
                        self._inflight += len(payload)
                        if self._inflight > self._window:
                            self._has_credit.clear()
                        stream._deliver(payload)
                elif flag in (_CLOSE, _RESET):
                    stream = self._streams.pop(sid, None)
                    if stream is not None:
                        stream._detach()
                        stream._deliver(None)
        except asyncio.CancelledError:
            raise  # finally still tears the connection down
        except Exception:
            pass
        finally:
            await self._teardown()

    async def _reset_quietly(self, sid: int) -> None:
        try:
            await self.send(sid, _RESET, b"")
        except (ConnectionError, OSError):
            pass

    async def _serve(self, stream: _MuxStream) -> None:
        try:
            await self._on_stream(stream)
        finally:
            await stream.close()

    async def _teardown(self) -> None:
        self.closed = True
        for stream in list(self._streams.values()):
            stream._detach()
            stream._deliver(None)
        self._streams.clear()
        for task in list(self._tasks):
            task.cancel()
        try:
            await self.base.abort()
        except Exception:
            pass

    async def close(self) -> None:
        await aio.reap(self._pump_task)


class MuxTransport(Transport):
    """Wraps a base transport; one persistent muxed connection per address."""

    def __init__(self, base: Transport) -> None:
        self.base = base
        self._conns: dict[str, _MuxConn] = {}
        self._dial_locks: dict[str, asyncio.Lock] = {}
        self._accepted: list[_MuxConn] = []

    async def listen(self, addr: str, on_stream: AcceptCallback) -> str:
        async def on_conn(base_stream: Stream) -> None:
            conn = _MuxConn(base_stream, dialer=False, on_stream=on_stream)
            self._accepted.append(conn)
            # Hold the base accept open for the connection's lifetime, then
            # prune — a long-lived listener with client churn must not
            # accumulate dead connections.
            try:
                await aio.wait_quiet(conn._pump_task)
            finally:
                try:
                    self._accepted.remove(conn)
                except ValueError:
                    pass

        return await self.base.listen(addr, on_conn)

    async def dial(self, addr: str) -> Stream:
        lock = self._dial_locks.setdefault(addr, asyncio.Lock())
        async with lock:
            conn = self._conns.get(addr)
            if conn is None or conn.closed:
                base_stream = await self.base.dial(addr)
                conn = _MuxConn(base_stream, dialer=True, on_stream=None)
                self._conns[addr] = conn
        stream = conn.open_stream()
        await conn.send(stream.sid, _OPEN, b"")
        return stream

    async def close(self) -> None:
        for conn in list(self._conns.values()) + list(self._accepted):
            await conn.close()
        self._conns.clear()
        self._accepted.clear()
        await self.base.close()
