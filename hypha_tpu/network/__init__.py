"""L1 networking: the framework's communication fabric.

A ground-up asyncio re-design of the role the reference's ``hypha-network``
crate plays (reference: crates/network/src/lib.rs:37-47): typed CBOR RPC with
fluent handler registration, gossip pub/sub, record/provider discovery, and
raw push/pull byte streams for tensor data — over pluggable transports
(in-process memory fabric for tests, TCP(+mTLS) for deployments).

Where the reference composes libp2p behaviours driven by one event loop
(Action/Driver/Interface triads, crates/network/src/gossipsub.rs:51-232), this
fabric keeps the same load-bearing property — a single owner task per node
processing every wire event, with typed async interfaces for applications —
expressed natively in asyncio: the :class:`~hypha_tpu.network.node.Node`
accept-loop is the driver; its methods are the interface; transports replace
the swarm.

Design notes (TPU-first):
  * Every logical stream is its own transport stream (the reference found
    parallel streams outperform multiplexing: rfc/2025-03-25 ~1 GB/s with
    parallel streams); tensor payloads are raw bytes after a bounded header.
  * Discovery is gateway-anchored (the reference's Kademlia is likewise
    anchored on gateway bootstrap nodes in ``Mode::Server``,
    crates/gateway/src/network.rs:152); records/providers live on gateways,
    clients cache.
  * Gossip is flood-with-dedup over the connected mesh — behaviorally
    equivalent to gossipsub for the single topic the product uses
    (``hypha/worker`` auction ads) at datacenter scale.
"""

from .fabric import (
    FrameError,
    MemoryTransport,
    Stream,
    TcpTransport,
    Transport,
    read_frame,
    write_frame,
)
from .node import HandlerRegistration, Node, RequestError

__all__ = [
    "Node",
    "RequestError",
    "HandlerRegistration",
    "Transport",
    "MemoryTransport",
    "TcpTransport",
    "Stream",
    "FrameError",
    "read_frame",
    "write_frame",
]
