"""L0 security: PKI generation, loading, and mTLS contexts.

Behavioral parity with the reference's ``hypha-certutil`` crate and
``crates/network/src/cert.rs``:

  * three-tier Ed25519 hierarchy — root CA → org CA → node certificates
    with SANs (reference: crates/certutil/src/main.rs:20-87);
  * **PeerID = hash of the certificate public key** so transport identity
    and cryptographic identity coincide (reference:
    crates/network/src/cert.rs:30-79; rfc/2025-05-30_mtls.md:1-60);
  * PEM loading for cert chains, private keys and CRLs
    (cert.rs: load_certs_from_pem/load_private_key_from_pem/
    load_crls_from_pem);
  * mutual TLS where both sides require and verify the peer chain against
    the root of trust, with optional CRL checking (the reference forks
    libp2p-tls to swap self-signed certs for WebPKI mTLS with CRLs).

CRLs are loaded at context-build time only, matching the reference's
"CRLs are only loaded from disk during node initialization" limitation —
rotating a CRL requires a node restart (documented reference behavior).
"""

from __future__ import annotations

import datetime
import hashlib
import ssl
from pathlib import Path

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ed25519
from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

__all__ = [
    "generate_root_ca",
    "generate_org_ca",
    "generate_node_cert",
    "generate_crl",
    "peer_id_from_cert_pem",
    "peer_id_from_cert_der",
    "load_certs_from_pem",
    "load_private_key_from_pem",
    "load_crls_from_pem",
    "make_server_context",
    "make_client_context",
    "write_node_dir",
]

_ONE_DAY = datetime.timedelta(days=1)


def _name(common_name: str, org: str | None = None) -> x509.Name:
    attrs = [x509.NameAttribute(NameOID.COMMON_NAME, common_name)]
    if org:
        attrs.append(x509.NameAttribute(NameOID.ORGANIZATION_NAME, org))
    return x509.Name(attrs)


def _validity(days: int) -> tuple[datetime.datetime, datetime.datetime]:
    now = datetime.datetime.now(datetime.timezone.utc)
    return now - _ONE_DAY, now + datetime.timedelta(days=days)


def generate_root_ca(
    common_name: str = "hypha-root", days: int = 3650
) -> tuple[bytes, bytes]:
    """Self-signed Ed25519 root CA. Returns (cert_pem, key_pem)."""
    key = ed25519.Ed25519PrivateKey.generate()
    name = _name(common_name)
    not_before, not_after = _validity(days)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(not_before)
        .not_valid_after(not_after)
        .add_extension(x509.BasicConstraints(ca=True, path_length=1), critical=True)
        .add_extension(
            x509.KeyUsage(
                digital_signature=True,
                key_cert_sign=True,
                crl_sign=True,
                content_commitment=False,
                key_encipherment=False,
                data_encipherment=False,
                key_agreement=False,
                encipher_only=False,
                decipher_only=False,
            ),
            critical=True,
        )
        .add_extension(
            x509.SubjectKeyIdentifier.from_public_key(key.public_key()), critical=False
        )
        .sign(key, algorithm=None)  # Ed25519 signs without a separate digest
    )
    return _pem(cert), _key_pem(key)


def generate_org_ca(
    common_name: str, root_cert_pem: bytes, root_key_pem: bytes, days: int = 1825
) -> tuple[bytes, bytes]:
    """Org-level intermediate CA signed by the root."""
    root_cert = x509.load_pem_x509_certificate(root_cert_pem)
    root_key = load_private_key_from_pem(root_key_pem)
    key = ed25519.Ed25519PrivateKey.generate()
    not_before, not_after = _validity(days)
    cert = (
        x509.CertificateBuilder()
        .subject_name(_name(common_name))
        .issuer_name(root_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(not_before)
        .not_valid_after(not_after)
        .add_extension(x509.BasicConstraints(ca=True, path_length=0), critical=True)
        .add_extension(
            x509.KeyUsage(
                digital_signature=True,
                key_cert_sign=True,
                crl_sign=True,
                content_commitment=False,
                key_encipherment=False,
                data_encipherment=False,
                key_agreement=False,
                encipher_only=False,
                decipher_only=False,
            ),
            critical=True,
        )
        .add_extension(
            x509.SubjectKeyIdentifier.from_public_key(key.public_key()), critical=False
        )
        .add_extension(
            x509.AuthorityKeyIdentifier.from_issuer_public_key(
                root_cert.public_key()
            ),
            critical=False,
        )
        .sign(root_key, algorithm=None)
    )
    return _pem(cert), _key_pem(key)


def generate_node_cert(
    common_name: str,
    org_cert_pem: bytes,
    org_key_pem: bytes,
    sans: list[str] | None = None,
    days: int = 825,
) -> tuple[bytes, bytes]:
    """Leaf certificate for one node, usable as both TLS client and server
    (every peer both dials and listens). SANs default to localhost."""
    org_cert = x509.load_pem_x509_certificate(org_cert_pem)
    org_key = load_private_key_from_pem(org_key_pem)
    key = ed25519.Ed25519PrivateKey.generate()
    not_before, not_after = _validity(days)
    san_entries: list[x509.GeneralName] = []
    for san in sans or ["localhost", "127.0.0.1"]:
        try:
            import ipaddress

            san_entries.append(x509.IPAddress(ipaddress.ip_address(san)))
        except ValueError:
            san_entries.append(x509.DNSName(san))
    cert = (
        x509.CertificateBuilder()
        .subject_name(_name(common_name))
        .issuer_name(org_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(not_before)
        .not_valid_after(not_after)
        .add_extension(x509.BasicConstraints(ca=False, path_length=None), critical=True)
        .add_extension(x509.SubjectAlternativeName(san_entries), critical=False)
        .add_extension(
            x509.ExtendedKeyUsage(
                [ExtendedKeyUsageOID.SERVER_AUTH, ExtendedKeyUsageOID.CLIENT_AUTH]
            ),
            critical=False,
        )
        .add_extension(
            x509.AuthorityKeyIdentifier.from_issuer_public_key(org_cert.public_key()),
            critical=False,
        )
        .sign(org_key, algorithm=None)
    )
    return _pem(cert), _key_pem(key)


def generate_crl(
    org_cert_pem: bytes,
    org_key_pem: bytes,
    revoked_cert_pems: list[bytes],
    days: int = 365,
    extra_revoked_serials: list[int] | None = None,
) -> bytes:
    """Certificate revocation list signed by the org CA.

    AVAILABILITY NOTE: with ``VERIFY_CRL_CHECK_LEAF`` OpenSSL hard-fails
    *all* verification once the CRL's next_update passes — an expired CRL
    cuts the node off from every peer, not just revoked ones. ``days`` is
    therefore a re-issuance deadline; regenerate CRLs well before it.

    ``extra_revoked_serials`` carries forward serials from a previous CRL
    so re-issuing never silently un-revokes certificates.
    """
    org_cert = x509.load_pem_x509_certificate(org_cert_pem)
    org_key = load_private_key_from_pem(org_key_pem)
    now = datetime.datetime.now(datetime.timezone.utc)
    builder = (
        x509.CertificateRevocationListBuilder()
        .issuer_name(org_cert.subject)
        .last_update(now - _ONE_DAY)
        .next_update(now + datetime.timedelta(days=days))
    )
    serials = {
        x509.load_pem_x509_certificate(pem).serial_number
        for pem in revoked_cert_pems
    }
    serials.update(extra_revoked_serials or [])
    for serial in sorted(serials):
        builder = builder.add_revoked_certificate(
            x509.RevokedCertificateBuilder()
            .serial_number(serial)
            .revocation_date(now - _ONE_DAY)
            .build()
        )
    crl = builder.sign(org_key, algorithm=None)
    return crl.public_bytes(serialization.Encoding.PEM)


# ---------------------------------------------------------------------------
# Identity: PeerID = multihash-style digest of the SubjectPublicKeyInfo
# ---------------------------------------------------------------------------


def peer_id_from_spki_der(spki: bytes) -> str:
    """PeerID from a DER SubjectPublicKeyInfo — the one identity derivation
    shared by the cert layer and gossip message signing (a gossip frame's
    embedded key must hash to its claimed origin)."""
    return "12H" + hashlib.sha256(spki).hexdigest()[:40]


def peer_id_from_cert_der(der: bytes) -> str:
    cert = x509.load_der_x509_certificate(der)
    spki = cert.public_key().public_bytes(
        serialization.Encoding.DER, serialization.PublicFormat.SubjectPublicKeyInfo
    )
    return peer_id_from_spki_der(spki)


def peer_id_from_cert_pem(pem: bytes) -> str:
    cert = x509.load_pem_x509_certificate(pem)
    return peer_id_from_cert_der(
        cert.public_bytes(serialization.Encoding.DER)
    )


# ---------------------------------------------------------------------------
# Loading (cert.rs parity)
# ---------------------------------------------------------------------------


def load_certs_from_pem(path: str | Path) -> list[x509.Certificate]:
    return x509.load_pem_x509_certificates(Path(path).read_bytes())


def load_private_key_from_pem(pem_or_path: bytes | str | Path):
    data = (
        pem_or_path
        if isinstance(pem_or_path, bytes)
        else Path(pem_or_path).read_bytes()
    )
    return serialization.load_pem_private_key(data, password=None)


def load_crls_from_pem(path: str | Path) -> list[x509.CertificateRevocationList]:
    data = Path(path).read_bytes()
    crls = []
    start = 0
    marker = b"-----BEGIN X509 CRL-----"
    while True:
        i = data.find(marker, start)
        if i < 0:
            break
        j = data.find(b"-----END X509 CRL-----", i)
        block = data[i : j + len(b"-----END X509 CRL-----")]
        crls.append(x509.load_pem_x509_crl(block))
        start = j + 1
    return crls


# ---------------------------------------------------------------------------
# mTLS contexts
# ---------------------------------------------------------------------------


def _mtls_context(
    purpose: ssl.Purpose,
    cert_file: str | Path,
    key_file: str | Path,
    trust_file: str | Path,
    crl_file: str | Path | None = None,
) -> ssl.SSLContext:
    ctx = ssl.SSLContext(
        ssl.PROTOCOL_TLS_SERVER
        if purpose is ssl.Purpose.CLIENT_AUTH
        else ssl.PROTOCOL_TLS_CLIENT
    )
    ctx.minimum_version = ssl.TLSVersion.TLSv1_3
    ctx.load_cert_chain(str(cert_file), str(key_file))
    ctx.load_verify_locations(str(trust_file))
    ctx.verify_mode = ssl.CERT_REQUIRED
    # Identity is the cert-key hash (peer id), not a DNS name.
    ctx.check_hostname = False
    if crl_file is not None:
        ctx.load_verify_locations(str(crl_file))
        ctx.verify_flags |= ssl.VERIFY_CRL_CHECK_LEAF
    return ctx


def make_server_context(
    cert_file: str | Path,
    key_file: str | Path,
    trust_file: str | Path,
    crl_file: str | Path | None = None,
) -> ssl.SSLContext:
    """Server side of mTLS: presents the node chain, requires client certs."""
    return _mtls_context(ssl.Purpose.CLIENT_AUTH, cert_file, key_file, trust_file, crl_file)


def make_client_context(
    cert_file: str | Path,
    key_file: str | Path,
    trust_file: str | Path,
    crl_file: str | Path | None = None,
) -> ssl.SSLContext:
    """Client side of mTLS: presents the node chain, verifies the server."""
    return _mtls_context(ssl.Purpose.SERVER_AUTH, cert_file, key_file, trust_file, crl_file)


def write_node_dir(
    out_dir: str | Path,
    node_name: str,
    org_cert_pem: bytes,
    org_key_pem: bytes,
    root_cert_pem: bytes,
    sans: list[str] | None = None,
) -> dict[str, Path]:
    """Generate and lay out one node's credentials:

      <out>/<name>.crt   — node cert + org CA (the chain the node presents)
      <out>/<name>.key   — node private key (0600)
      <out>/trust.crt    — root CA (what the node trusts)

    Returns the paths plus the node's derived peer id under key "peer_id".
    """
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    cert_pem, key_pem = generate_node_cert(node_name, org_cert_pem, org_key_pem, sans)
    cert_path = out / f"{node_name}.crt"
    key_path = out / f"{node_name}.key"
    trust_path = out / "trust.crt"
    cert_path.write_bytes(cert_pem + org_cert_pem)
    key_path.write_bytes(key_pem)
    key_path.chmod(0o600)
    # Always (re)write: a regenerated root must not leave a stale trust
    # anchor behind, or every later handshake fails inscrutably.
    trust_path.write_bytes(root_cert_pem)
    return {
        "cert": cert_path,
        "key": key_path,
        "trust": trust_path,
        "peer_id": peer_id_from_cert_pem(cert_pem),  # type: ignore[dict-item]
    }


def _pem(cert: x509.Certificate) -> bytes:
    return cert.public_bytes(serialization.Encoding.PEM)


def _key_pem(key: ed25519.Ed25519PrivateKey) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )
