"""Generic lease ledger.

Behavioral parity with the reference's ``hypha-leases`` crate
(reference: crates/leases/src/lib.rs:20-130):

  * ``Lease`` pairs an id, an arbitrary leasable payload and a **wall-clock**
    expiry — wall-clock on purpose so that leases survive process suspend and
    are comparable across peers (reference note crates/leases/src/lib.rs:23-27);
  * ``Ledger`` supports insert/get/remove/renew/list/list_expired;
  * ``renew`` resets expiry to *now + duration* (not old-expiry + duration),
    matching crates/leases/src/lib.rs:103-114.

The ledger is synchronous and lock-guarded; it is safe from asyncio tasks
(single-threaded) and from threads (the runtime's prune loop).
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Generic, TypeVar

__all__ = ["Lease", "Ledger", "LeaseNotFound"]

T = TypeVar("T")


class LeaseNotFound(KeyError):
    pass


@dataclass(slots=True)
class Lease(Generic[T]):
    leasable: T
    timeout: float  # absolute wall-clock seconds (time.time())
    id: str = field(default_factory=lambda: str(uuid.uuid4()))

    def is_expired(self, now: float | None = None) -> bool:
        return (time.time() if now is None else now) >= self.timeout

    def remaining(self, now: float | None = None) -> float:
        return max(0.0, self.timeout - (time.time() if now is None else now))


class Ledger(Generic[T]):
    """Thread-safe store of live leases keyed by lease id."""

    def __init__(self, clock: Callable[[], float] = time.time) -> None:
        self._clock = clock
        self._lock = threading.Lock()
        self._leases: dict[str, Lease[T]] = {}

    def insert(self, leasable: T, duration: float, lease_id: str | None = None) -> Lease[T]:
        lease = Lease(leasable=leasable, timeout=self._clock() + duration)
        if lease_id is not None:
            lease.id = lease_id
        with self._lock:
            self._leases[lease.id] = lease
        return lease

    def get(self, lease_id: str) -> Lease[T]:
        with self._lock:
            try:
                return self._leases[lease_id]
            except KeyError:
                raise LeaseNotFound(lease_id) from None

    def try_get(self, lease_id: str) -> Lease[T] | None:
        with self._lock:
            return self._leases.get(lease_id)

    def remove(self, lease_id: str) -> Lease[T]:
        with self._lock:
            try:
                return self._leases.pop(lease_id)
            except KeyError:
                raise LeaseNotFound(lease_id) from None

    def renew(self, lease_id: str, duration: float) -> Lease[T]:
        """Reset expiry to now + duration (crates/leases/src/lib.rs:103-114)."""
        with self._lock:
            try:
                lease = self._leases[lease_id]
            except KeyError:
                raise LeaseNotFound(lease_id) from None
            lease.timeout = self._clock() + duration
            return lease

    def list(self) -> list[Lease[T]]:
        with self._lock:
            return list(self._leases.values())

    def list_expired(self) -> list[Lease[T]]:
        now = self._clock()
        with self._lock:
            return [l for l in self._leases.values() if l.is_expired(now)]

    def remove_expired(self) -> list[Lease[T]]:
        """Atomically pop every expired lease (used by the worker prune loop)."""
        now = self._clock()
        with self._lock:
            expired = [l for l in self._leases.values() if l.is_expired(now)]
            for l in expired:
                del self._leases[l.id]
            return expired

    def find(self, pred: Callable[[Lease[T]], bool]) -> Lease[T] | None:
        with self._lock:
            for l in self._leases.values():
                if pred(l):
                    return l
        return None

    def __len__(self) -> int:
        with self._lock:
            return len(self._leases)
