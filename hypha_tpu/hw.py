"""Hardware/backend detection.

The one question the framework keeps asking is "is the active JAX backend a
real accelerator?" — to pick the pallas flash kernel vs the XLA dense path,
and to select pallas interpret mode for CPU tests. The answer must NOT be a
string compare against ``"tpu"``: remote-TPU PJRT plugins register under
their own platform names (this environment's tunnel registers as ``"axon"``)
while still being TPU hardware that lowers pallas-TPU kernels. Anything that
is not the CPU backend is treated as hardware.

Reference seam: the reference picks its compute device via torch/Accelerate
device strings (``executors/accelerate/src/hypha/accelerate_executor/
training.py``); this is the TPU-native equivalent of that selection.
"""

from __future__ import annotations


# Backends that are definitely NOT TPUs: the CPU backend and GPU platform
# names. Anything else (tpu itself, or a remote-TPU plugin under its own
# name) is treated as TPU hardware.
_NON_TPU_BACKENDS = frozenset({"cpu", "gpu", "cuda", "rocm", "metal"})


def is_accelerator() -> bool:
    """True when the active JAX backend is TPU-class hardware that lowers
    the pallas-TPU kernels (pltpu VMEM scratch etc.). GPU backends count as
    non-TPU: they'd fail to lower the kernels, so they take the XLA dense
    path like CPU does."""
    import jax

    return jax.default_backend().lower() not in _NON_TPU_BACKENDS


def interpret_default() -> bool:
    """Pallas interpret-mode default: interpret everywhere except on a
    TPU-class backend."""
    return not is_accelerator()


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions — the ONE copy of the shim.

    jax >= 0.6 exports ``shard_map`` at the top level with a ``check_vma``
    kwarg; older releases keep it in ``jax.experimental.shard_map`` under
    the ``check_rep`` spelling. Every shard_map site in the repo (ring
    attention, pipeline parallelism, tests) goes through here so the next
    jax API move is a one-line fix instead of a hunt.
    """
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map as _experimental

        return _experimental(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )
    return shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=check_vma,
    )
