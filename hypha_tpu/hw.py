"""Hardware/backend detection.

The one question the framework keeps asking is "is the active JAX backend a
real accelerator?" — to pick the pallas flash kernel vs the XLA dense path,
and to select pallas interpret mode for CPU tests. The answer must NOT be a
string compare against ``"tpu"``: remote-TPU PJRT plugins register under
their own platform names (this environment's tunnel registers as ``"axon"``)
while still being TPU hardware that lowers pallas-TPU kernels. Anything that
is not the CPU backend is treated as hardware.

Reference seam: the reference picks its compute device via torch/Accelerate
device strings (``executors/accelerate/src/hypha/accelerate_executor/
training.py``); this is the TPU-native equivalent of that selection.
"""

from __future__ import annotations


# Backends that are definitely NOT TPUs: the CPU backend and GPU platform
# names. Anything else (tpu itself, or a remote-TPU plugin under its own
# name) is treated as TPU hardware.
_NON_TPU_BACKENDS = frozenset({"cpu", "gpu", "cuda", "rocm", "metal"})


def is_accelerator() -> bool:
    """True when the active JAX backend is TPU-class hardware that lowers
    the pallas-TPU kernels (pltpu VMEM scratch etc.). GPU backends count as
    non-TPU: they'd fail to lower the kernels, so they take the XLA dense
    path like CPU does."""
    import jax

    return jax.default_backend().lower() not in _NON_TPU_BACKENDS


def interpret_default() -> bool:
    """Pallas interpret-mode default: interpret everywhere except on a
    TPU-class backend."""
    return not is_accelerator()
