"""Readiness probing over ``/hypha-health/0.0.1``.

Every node type serves the same two-message health protocol
(reference: crates/messages/src/lib.rs:47-63 — ``{} -> {healthy: bool}``)
and the ``probe`` CLI subcommand dials it as a deployment smoke test
(reference: crates/scheduler/src/bin/hypha-scheduler.rs:494-535).
"""

from __future__ import annotations

from typing import Callable

from .messages import PROTOCOL_HEALTH, HealthRequest, HealthResponse
from .network.node import HandlerRegistration, Node

__all__ = ["serve_health", "probe"]


def serve_health(node: Node, ready: Callable[[], bool] = lambda: True) -> HandlerRegistration:
    """Register the health responder; ``ready`` is the node-specific readiness
    predicate (the worker's is listen+bootstrap,
    reference: crates/worker/src/bin/hypha-worker.rs:85-87,199-200)."""

    async def on_health(_peer: str, _msg: HealthRequest) -> HealthResponse:
        return HealthResponse(healthy=bool(ready()))

    return node.on(PROTOCOL_HEALTH, HealthRequest).respond_with(on_health)


async def probe(node: Node, addr: str, timeout: float = 10.0) -> bool:
    """Dial ``addr`` and ask whether the peer is healthy."""
    peer = await node.dial(addr)
    resp = await node.request(peer, PROTOCOL_HEALTH, HealthRequest(), timeout=timeout)
    return isinstance(resp, HealthResponse) and resp.healthy
