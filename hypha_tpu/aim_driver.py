"""The metrics sink the scheduler's AimConnector posts to.

Reference: drivers/aim-driver/main.py — a 13-line FastAPI shim exposing
``POST /status`` and forwarding ``AimMetrics{worker_id, round, metric_name,
value}`` into ``aim.Run.track``. Here: a dependency-free asyncio HTTP
server; metrics go to the AIM run when ``aim`` is importable, and always
to a JSONL file + log so the sink is useful without the dashboard.

Run: ``python -m hypha_tpu.aim_driver --port 8875 [--out metrics.jsonl]``
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
from pathlib import Path

__all__ = ["StatusSink", "serve"]

log = logging.getLogger("hypha.aim_driver")


class StatusSink:
    def __init__(self, out_path: str | Path | None = None) -> None:
        from collections import deque

        self.out_path = Path(out_path) if out_path else None
        # Bounded: a multi-day job posts metrics forever; keep the tail for
        # introspection, count the rest.
        self.received: "deque[dict]" = deque(maxlen=4096)
        self.total = 0
        try:
            import aim  # type: ignore

            self._run = aim.Run()
        except Exception:
            self._run = None

    def track(self, payload: dict) -> None:
        if not isinstance(payload, dict):
            raise TypeError(f"status payload must be an object, got {type(payload).__name__}")
        self.received.append(payload)
        self.total += 1
        if self.out_path is not None:
            with open(self.out_path, "a") as f:
                f.write(json.dumps(payload) + "\n")
        if self._run is not None:
            self._run.track(
                payload.get("value"),
                name=payload.get("metric_name"),
                step=payload.get("round"),
                context={"worker": payload.get("worker_id")},
            )
        else:
            log.info(
                "metric %s[%s] round=%s = %s",
                payload.get("metric_name"),
                payload.get("worker_id"),
                payload.get("round"),
                payload.get("value"),
            )


async def _handle(sink: StatusSink, reader, writer) -> None:
    try:
        while True:
            request_line = await reader.readline()
            if not request_line:
                return
            parts = request_line.decode("latin-1").split()
            if len(parts) < 2:
                return
            method, path = parts[0], parts[1]
            headers = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode("latin-1").partition(":")
                headers[k.strip().lower()] = v.strip()
            length = int(headers.get("content-length", "0"))
            body = await reader.readexactly(length) if length else b""
            if method == "POST" and path == "/status":
                try:
                    sink.track(json.loads(body or b"{}"))
                    status, reply = 200, b'{"ok": true}'
                except (json.JSONDecodeError, TypeError) as e:
                    status, reply = 400, json.dumps({"error": str(e)}).encode()
            else:
                status, reply = 404, b'{"error": "no route"}'
            writer.write(
                f"HTTP/1.1 {status} {'OK' if status == 200 else 'ERR'}\r\n"
                f"content-type: application/json\r\n"
                f"content-length: {len(reply)}\r\n\r\n".encode() + reply
            )
            await writer.drain()
            if headers.get("connection", "").lower() == "close":
                return
    except (asyncio.IncompleteReadError, ConnectionError):
        pass
    finally:
        try:
            writer.close()
        except ConnectionError:
            pass


async def serve(
    host: str = "127.0.0.1", port: int = 8875, out_path: str | None = None
):
    """Start the sink server; returns (server, sink)."""
    sink = StatusSink(out_path)
    server = await asyncio.start_server(
        lambda r, w: _handle(sink, r, w), host, port
    )
    return server, sink


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="hypha-aim-driver", description="hypha metrics status sink"
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8875)
    parser.add_argument("--out", help="also append metrics to this JSONL file")
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO, format="%(name)s %(message)s")

    async def run() -> None:
        server, _sink = await serve(args.host, args.port, args.out)
        addr = server.sockets[0].getsockname()
        log.info("aim driver on %s:%s", addr[0], addr[1])
        async with server:
            await server.serve_forever()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
