"""Layered configuration with provenance, validation and documented emit.

Behavioral parity with the reference's ``hypha-config`` crate
(crates/config/src/lib.rs): a builder layering

    dataclass defaults ← TOML file ← HYPHA_* env ← OTEL_* env ← CLI overrides

(figment layering, crates/scheduler/src/bin/hypha-scheduler.rs:537-543),
a ``ConfigWithMetadata`` wrapper that remembers **which layer set every
key** so errors point at the exact file/env/flag source (miette-style
``find_metadata``, lib.rs:418-436), a ``validate()`` hook (lib.rs:438-451),
a doc-comment-preserving TOML emitter for ``init`` (``to_toml``,
lib.rs:544) and TLS loading helpers on the wrapper (lib.rs:464-540).

Config schemas are plain dataclasses; field docs come from
``field(metadata={"doc": ...})`` and nested sections from nested
dataclasses.
"""

from __future__ import annotations

import dataclasses
import os

try:
    import tomllib
except ImportError:  # Python < 3.11: the backport ships the same API
    import tomli as tomllib  # type: ignore[no-redef]
import typing
from dataclasses import MISSING, dataclass, field, fields, is_dataclass
from pathlib import Path
from typing import Any, Callable, TypeVar

__all__ = [
    "ConfigError",
    "Provenance",
    "ConfigWithMetadata",
    "LayeredConfigBuilder",
    "builder",
    "to_toml",
    "TLSConfig",
]

T = TypeVar("T")


class ConfigError(ValueError):
    """A config problem, pointing at the layer that caused it."""

    def __init__(self, message: str, provenance: "Provenance | None" = None) -> None:
        if provenance is not None:
            message = f"{message} (set by {provenance.source})"
        super().__init__(message)
        self.provenance = provenance


@dataclass(frozen=True, slots=True)
class Provenance:
    """Where a key's value came from (lib.rs ConfigWithMetadata metadata)."""

    key: str  # dotted path, e.g. "offer.price"
    source: str  # "default" | "file:<path>" | "env:<VAR>" | "cli"


@dataclass
class TLSConfig:
    """Credential file locations (lib.rs:464-540 TLSConfig).

    PeerID is derived from the certificate key (rfc/2025-05-30_mtls.md);
    ``load()`` returns a ready mTLS-secured Node factory input.
    """

    cert: str = field(default="", metadata={"doc": "node certificate chain (PEM)"})
    key: str = field(default="", metadata={"doc": "node private key (PEM)"})
    trust: str = field(default="", metadata={"doc": "trusted root CA (PEM)"})
    crls: str = field(default="", metadata={"doc": "certificate revocation lists (PEM), optional"})

    def enabled(self) -> bool:
        return bool(self.cert and self.key and self.trust)

    def validate_files(self) -> None:
        for name in ("cert", "key", "trust"):
            p = getattr(self, name)
            if p and not Path(p).is_file():
                raise ConfigError(f"tls.{name}: no such file {p!r}")
        if self.crls and not Path(self.crls).is_file():
            raise ConfigError(f"tls.crls: no such file {self.crls!r}")


# --------------------------------------------------------------------------
# dict <-> dataclass with provenance
# --------------------------------------------------------------------------


def _type_hints(cls) -> dict[str, Any]:
    return typing.get_type_hints(cls)


def _coerce(value: Any, hint: Any, key: str, source: str) -> Any:
    """Coerce a layered raw value to the field's annotated type."""
    origin = typing.get_origin(hint)
    if origin is typing.Union:  # Optional[X]
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        if value is None:
            return None
        return _coerce(value, args[0], key, source) if len(args) == 1 else value
    if is_dataclass(hint):
        if not isinstance(value, dict):
            raise ConfigError(
                f"{key}: expected a table for {hint.__name__}, got {type(value).__name__}",
                Provenance(key, source),
            )
        return _build_dataclass(hint, value, source, prefix=key + ".")[0]
    if hint is bool:
        if isinstance(value, bool):
            return value
        if isinstance(value, str):
            if value.lower() in ("1", "true", "yes", "on"):
                return True
            if value.lower() in ("0", "false", "no", "off"):
                return False
        raise ConfigError(f"{key}: not a bool: {value!r}", Provenance(key, source))
    if hint is int:
        try:
            return int(value)
        except (TypeError, ValueError):
            raise ConfigError(f"{key}: not an int: {value!r}", Provenance(key, source))
    if hint is float:
        try:
            return float(value)
        except (TypeError, ValueError):
            raise ConfigError(f"{key}: not a float: {value!r}", Provenance(key, source))
    if hint is str:
        return str(value)
    if origin in (list, tuple):
        if isinstance(value, str):
            value = [v.strip() for v in value.split(",") if v.strip()]
        args = typing.get_args(hint)
        inner = args[0] if args else str
        return [_coerce(v, inner, f"{key}[]", source) for v in value]
    if origin is dict or hint is dict:
        # Plain-dict fields (free-form tables): strip the layering tags that
        # _tag_layer attached to what it thought were config leaves.
        return _untag(value)
    return value


def _untag(value: Any) -> Any:
    if isinstance(value, tuple) and len(value) == 2 and isinstance(value[1], str):
        return _untag(value[0])
    if isinstance(value, dict):
        return {k: _untag(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_untag(v) for v in value]
    return value


def _build_dataclass(
    cls, data: dict, source: str, prefix: str = ""
) -> tuple[Any, dict[str, Provenance]]:
    hints = _type_hints(cls)
    kwargs: dict[str, Any] = {}
    meta: dict[str, Provenance] = {}
    known = {f.name for f in fields(cls)}
    for k in data:
        if k not in known:
            raise ConfigError(
                f"unknown config key {prefix}{k!r} (known: {sorted(known)})",
                Provenance(prefix + k, source),
            )
    for f in fields(cls):
        key = prefix + f.name
        if f.name in data:
            raw = data[f.name]
            src = source
            if isinstance(raw, tuple) and len(raw) == 2 and isinstance(raw[1], str):
                raw, src = raw  # (value, source) pair from env/cli layering
            hint = hints[f.name]
            hint_dc = hint
            if typing.get_origin(hint) is typing.Union:
                args = [a for a in typing.get_args(hint) if a is not type(None)]
                hint_dc = args[0] if len(args) == 1 else hint
            if is_dataclass(hint_dc) and isinstance(raw, dict):
                value, sub = _build_dataclass(hint_dc, raw, src, prefix=key + ".")
                kwargs[f.name] = value
                meta.update(sub)
            else:
                kwargs[f.name] = _coerce(raw, hint, key, src)
            meta[key] = Provenance(key, src)
        elif f.default is not MISSING or f.default_factory is not MISSING:  # type: ignore[misc]
            meta[key] = Provenance(key, "default")
            hint = hints[f.name]
            if is_dataclass(hint):
                meta.update(_default_meta(hint, key + "."))
        else:
            raise ConfigError(f"missing required config key {key!r}")
    try:
        return cls(**kwargs), meta
    except (TypeError, ValueError) as e:
        raise ConfigError(f"{prefix or cls.__name__}: {e}") from e


def _default_meta(cls, prefix: str) -> dict[str, Provenance]:
    """Provenance entries for every key of an all-default section."""
    meta: dict[str, Provenance] = {}
    hints = _type_hints(cls)
    for f in fields(cls):
        key = prefix + f.name
        meta[key] = Provenance(key, "default")
        if is_dataclass(hints[f.name]):
            meta.update(_default_meta(hints[f.name], key + "."))
    return meta


def _deep_merge(base: dict, overlay: dict) -> dict:
    out = dict(base)
    for k, v in overlay.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


# --------------------------------------------------------------------------
# builder
# --------------------------------------------------------------------------


class ConfigWithMetadata(typing.Generic[T]):
    """The built config plus per-key provenance (lib.rs:403-451)."""

    def __init__(self, value: T, metadata: dict[str, Provenance]) -> None:
        self.value = value
        self.metadata = metadata

    def find_metadata(self, key: str) -> Provenance | None:
        return self.metadata.get(key)

    def validate(self) -> "ConfigWithMetadata[T]":
        """Run the schema's ``validate()`` hook, wrapping failures with the
        offending key's provenance when the hook names one."""
        hook = getattr(self.value, "validate", None)
        if callable(hook):
            try:
                hook()
            except ConfigError:
                raise
            except (TypeError, ValueError) as e:
                key = getattr(e, "config_key", None)
                raise ConfigError(str(e), self.metadata.get(key)) from e
        return self


class LayeredConfigBuilder(typing.Generic[T]):
    """TOML ← HYPHA_* env ← OTEL_* env ← CLI overrides (figment layering)."""

    def __init__(self, cls: type[T]) -> None:
        self._cls = cls
        self._layers: list[tuple[dict, str]] = []

    def with_toml(self, path: str | Path) -> "LayeredConfigBuilder[T]":
        p = Path(path)
        try:
            data = tomllib.loads(p.read_text())
        except FileNotFoundError:
            raise ConfigError(f"config file not found: {p}")
        except tomllib.TOMLDecodeError as e:
            raise ConfigError(f"invalid TOML in {p}: {e}")
        self._layers.append((data, f"file:{p}"))
        return self

    def with_env(self, prefix: str = "HYPHA_") -> "LayeredConfigBuilder[T]":
        """``HYPHA_OFFER__PRICE=2.5`` sets ``offer.price`` (double underscore
        separates nesting; single underscores stay inside key names)."""
        data: dict = {}
        for var, raw in os.environ.items():
            if not var.startswith(prefix) or var == prefix:
                continue
            path = [p.lower() for p in var[len(prefix):].split("__")]
            node = data
            for part in path[:-1]:
                node = node.setdefault(part, {})
            node[path[-1]] = (raw, f"env:{var}")
        if data:
            self._layers.append((data, "env"))
        return self

    def with_overrides(
        self, overrides: dict, source: str = "cli"
    ) -> "LayeredConfigBuilder[T]":
        """Dotted keys allowed: {"offer.price": 2.0}."""
        data: dict = {}
        for k, v in overrides.items():
            if v is None:
                continue
            node = data
            parts = k.split(".")
            for part in parts[:-1]:
                node = node.setdefault(part, {})
            node[parts[-1]] = (v, source)
        if data:
            self._layers.append((data, source))
        return self

    def build(self) -> ConfigWithMetadata[T]:
        merged: dict = {}
        for data, _source in self._layers:
            merged = _deep_merge(merged, _tag_layer(data, _source))
        value, meta = _build_dataclass(self._cls, merged, "merged")
        return ConfigWithMetadata(value, meta)


def _tag_layer(data: dict, source: str) -> dict:
    """Attach the layer's source to every leaf (unless already tagged)."""
    out: dict = {}
    for k, v in data.items():
        if isinstance(v, dict):
            out[k] = _tag_layer(v, source)
        elif isinstance(v, tuple) and len(v) == 2 and isinstance(v[1], str):
            out[k] = v
        else:
            out[k] = (v, source)
    return out


def builder(cls: type[T]) -> LayeredConfigBuilder[T]:
    return LayeredConfigBuilder(cls)


# --------------------------------------------------------------------------
# documented TOML emitter (lib.rs to_toml)
# --------------------------------------------------------------------------


def _toml_value(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return repr(v)
    if isinstance(v, str):
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    if isinstance(v, (list, tuple)):
        return "[" + ", ".join(_toml_value(x) for x in v) + "]"
    raise ConfigError(f"cannot emit TOML for {type(v).__name__}: {v!r}")


def to_toml(config: Any, _prefix: str = "") -> str:
    """Emit a config instance as TOML with each field's doc as a comment —
    what ``init`` writes so operators get a self-describing file."""
    if not is_dataclass(config):
        raise ConfigError("to_toml needs a dataclass instance")
    lines: list[str] = []
    tables: list[str] = []
    for f in fields(config):
        v = getattr(config, f.name)
        doc = f.metadata.get("doc")
        if is_dataclass(v):
            name = f"{_prefix}{f.name}"
            sub = to_toml(v, _prefix=name + ".")
            header = []
            if doc:
                header.append(f"# {doc}")
            header.append(f"[{name}]")
            tables.append("\n".join(header) + "\n" + sub)
            continue
        if v is None or (isinstance(v, dict) and not v):
            if doc:
                lines.append(f"# {doc}")
            lines.append(f"# {f.name} = ...")
            continue
        if isinstance(v, dict):
            tables.append(
                f"[{_prefix}{f.name}]\n"
                + "\n".join(f"{k} = {_toml_value(x)}" for k, x in v.items())
                + "\n"
            )
            continue
        if doc:
            lines.append(f"# {doc}")
        lines.append(f"{f.name} = {_toml_value(v)}")
    body = "\n".join(lines)
    if body:
        body += "\n"
    return body + ("\n" if body and tables else "") + "\n".join(tables)
