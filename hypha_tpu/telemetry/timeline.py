"""Round timeline: merge per-node span/event files into a critical path.

``python -m hypha_tpu.telemetry.timeline <dir>`` reads every
``spans-<node>.jsonl`` (hypha_tpu.telemetry.trace) and
``events-<node>.jsonl`` (hypha_tpu.telemetry.flight) under ``dir``, aligns
per-node wall clocks on round-boundary anchors, and prints a per-round
critical-path breakdown — compute / encode / upload / quorum-wait / outer /
broadcast / merge, with the straggler peer named — as text, plus a machine
JSON (``--json <path>``, or ``timeline.json`` in the directory). The same
merge can be exported as OTLP JSON (:func:`to_otlp`) for any OTEL-native
viewer.

Clock alignment: per-node offsets cannot come from the wall stamps alone
(nodes skew by seconds in the deployments this repo targets), but round
boundaries are causal anchors — no node's round-``r`` span can START before
the scheduler's round-``r`` root span opened. For each non-reference node
the offset is the minimum over shared rounds of (node's earliest round-r
span start − scheduler's round-r start): the tightest round pins the skew
(up to that round's genuine scheduling lag, milliseconds on the links that
matter), and the min keeps every other round causally consistent. Offsets
shift only cross-node ordering and stall attribution; phase DURATIONS come
from each node's own clock and never change under alignment.

Torn tails: a crashed node's last line may be half-written. Like the
durable journal's recovery rule, a record that fails to decode ends that
file's read as clean EOF — everything before it is used.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from pathlib import Path
from typing import Any

__all__ = [
    "load_jsonl",
    "load_dir",
    "align_offsets",
    "build_timeline",
    "to_otlp",
    "render_text",
    "main",
]

# Span name -> headline phase in the per-round breakdown. ``fold`` folds
# into the aggregate row but is reported separately (it overlaps
# quorum_wait by construction).
PHASES = (
    "compute",
    "input_wait",
    "encode",
    "upload",
    "quorum_wait",
    "outer",
    "broadcast",
    "merge",
)
_SPAN_PHASE = {
    "inner_steps": "compute",
    # Input-pipeline stall (executor.dataset): the training thread blocked
    # on a slice acquisition mid-round. Peer-attributed, so a data-starved
    # worker is named on the round's critical path like a slow uploader.
    "input_wait": "input_wait",
    "encode": "encode",
    "upload": "upload",
    "quorum_wait": "quorum_wait",
    "outer_step": "outer",
    "broadcast": "broadcast",
    "merge": "merge",
}


def load_jsonl(path: str | Path) -> list[dict]:
    """Read one JSONL file, treating the first undecodable record as EOF
    (torn tail after a crash — same rule as the durable journal)."""
    out: list[dict] = []
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    break  # torn tail: everything before it stands
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        return []
    return out


def _is_span(rec: dict) -> bool:
    """Span-shaped: a name plus a numeric start. Anything else in a
    spans file (a metrics journal dropped under the wrong name, a foreign
    build's records) is skipped with a warning instead of crashing the
    merge downstream."""
    return isinstance(rec.get("name"), str) and isinstance(
        rec.get("start_ns"), (int, float)
    )


def load_dir(trace_dir: str | Path) -> tuple[list[dict], list[dict]]:
    """(spans, events) merged from every per-node file under the dir.

    Resilient by design: the trace directory is shared with the flight
    recorder's ``events-*.jsonl`` AND the metrics plane's
    ``metrics-*.jsonl`` journal — only span/event files are read, and a
    non-span record inside a spans file is skipped with a warning. A peer
    that has events but no spans file (it crashed before its first span
    flushed, or ran untraced) merges fine: its events still appear in the
    tail, it just contributes no phases.
    """
    trace_dir = Path(trace_dir)
    spans: list[dict] = []
    events: list[dict] = []
    for path in sorted(trace_dir.glob("spans-*.jsonl")):
        recs = load_jsonl(path)
        good = [r for r in recs if _is_span(r)]
        if len(good) != len(recs):
            print(
                f"[timeline] {path.name}: skipped {len(recs) - len(good)} "
                "non-span records",
                file=sys.stderr,
            )
        spans.extend(good)
    for path in sorted(trace_dir.glob("events-*.jsonl")):
        events.extend(load_jsonl(path))
    span_nodes = {s.get("node") or "node" for s in spans}
    event_nodes = {e.get("node") or "node" for e in events}
    missing = sorted(event_nodes - span_nodes)
    if spans and missing:
        print(
            f"[timeline] no spans for peer(s) {', '.join(missing)} "
            "(crashed before flushing, or untraced) — events merged, "
            "phases skipped",
            file=sys.stderr,
        )
    return spans, events


def _round_of(rec: dict) -> int | None:
    attrs = rec.get("attrs") or {}
    try:
        return int(attrs["round"]) if "round" in attrs else None
    except (TypeError, ValueError):
        return None


def _dur_s(rec: dict) -> float:
    """Span duration from the node's OWN clock (monotonic when present)."""
    m0, m1 = rec.get("mono_start_ns"), rec.get("mono_end_ns")
    if isinstance(m0, (int, float)) and isinstance(m1, (int, float)) and m1 >= m0:
        return (m1 - m0) / 1e9
    try:
        return max(
            (int(rec.get("end_ns", 0)) - int(rec.get("start_ns", 0))) / 1e9, 0.0
        )
    except (TypeError, ValueError):
        return 0.0


def reference_node(spans: list[dict]) -> str | None:
    """The node owning the per-round root spans (the scheduler), falling
    back to the node with the most spans."""
    roots = [s for s in spans if s.get("name") == "round"]
    if roots:
        return roots[0].get("node")
    counts: dict[str, int] = defaultdict(int)
    for s in spans:
        counts[s.get("node") or "node"] += 1
    return max(counts, key=counts.get) if counts else None


def align_offsets(
    spans: list[dict], ref: str | None = None
) -> dict[str, float]:
    """Per-node wall-clock offsets (seconds to ADD to a node's wall stamps).

    Anchored on round boundaries (module docstring); the reference node's
    offset is 0. Nodes sharing no round with the reference stay at 0.
    """
    ref = ref or reference_node(spans)
    offsets: dict[str, float] = {}
    if ref is None:
        return offsets
    ref_round_start: dict[int, int] = {}
    for s in spans:
        if s.get("node") == ref and s.get("name") == "round":
            r = _round_of(s)
            if r is not None:
                start = int(s.get("start_ns", 0))
                prev = ref_round_start.get(r)
                ref_round_start[r] = start if prev is None else min(prev, start)
    first_start: dict[str, dict[int, int]] = defaultdict(dict)
    for s in spans:
        node = s.get("node") or "node"
        if node == ref:
            continue
        r = _round_of(s)
        if r is None or r not in ref_round_start:
            continue
        start = int(s.get("start_ns", 0))
        prev = first_start[node].get(r)
        first_start[node][r] = start if prev is None else min(prev, start)
    offsets[ref] = 0.0
    for node, per_round in first_start.items():
        deltas = [
            (start - ref_round_start[r]) / 1e9 for r, start in per_round.items()
        ]
        # min: the tightest round pins the skew while keeping every round
        # causally consistent (no span realigned before its round opened).
        offsets[node] = -min(deltas) if deltas else 0.0
    return offsets


def build_timeline(trace_dir: str | Path) -> dict:
    """Merge a trace directory into the per-round critical-path breakdown."""
    spans, events = load_dir(trace_dir)
    ref = reference_node(spans)
    offsets = align_offsets(spans, ref)

    by_round: dict[int, list[dict]] = defaultdict(list)
    for s in spans:
        r = _round_of(s)
        if r is not None:
            by_round[r].append(s)

    rounds: list[dict] = []
    for r in sorted(by_round):
        recs = by_round[r]
        phases: dict[str, float] = {p: 0.0 for p in PHASES}
        phase_holder: dict[str, str | None] = {p: None for p in PHASES}
        uploads: list[tuple[float, str | None]] = []
        # The stall: the longest PEER-ATTRIBUTED span of the round — the
        # single "who was slow, doing what" answer. Container spans
        # (quorum_wait spans the collect window, broadcast spans the whole
        # fan-out) name no peer and are excluded; upload / fold / compute /
        # encode / merge spans each name one.
        stall: tuple[float, str | None, str | None] = (0.0, None, None)
        fold_s = 0.0
        wall = None
        for s in recs:
            name = s.get("name")
            dur = _dur_s(s)
            attrs = s.get("attrs") or {}
            peer = attrs.get("peer") or s.get("node")
            if name == "round":
                wall = dur if wall is None else max(wall, dur)
                continue
            if name == "fold":
                fold_s += dur
                if dur > stall[0]:
                    stall = (dur, name, peer)
                continue
            phase = _SPAN_PHASE.get(name or "")
            if phase is None:
                continue
            if phase == "upload":
                uploads.append((dur, peer))
            if phase not in ("quorum_wait", "broadcast", "outer") and dur > stall[0]:
                stall = (dur, name, peer)
            if dur > phases[phase]:
                phases[phase] = dur
                phase_holder[phase] = peer
        if wall is None:
            # No root span for the round (scheduler untraced): bound it by
            # the aligned extent of the round's spans.
            lo, hi = None, None
            for s in recs:
                off = offsets.get(s.get("node") or "node", 0.0)
                s0 = int(s.get("start_ns", 0)) / 1e9 + off
                end = s.get("end_ns")
                if not isinstance(end, (int, float)):  # foreign/torn record
                    end = s.get("start_ns", 0)
                s1 = int(end) / 1e9 + off
                lo = s0 if lo is None else min(lo, s0)
                hi = s1 if hi is None else max(hi, s1)
            wall = (hi - lo) if lo is not None and hi is not None else 0.0
        uploads.sort(reverse=True, key=lambda t: t[0])
        straggler = uploads[0][1] if uploads else None
        dominant = max(PHASES, key=lambda p: phases[p])
        rounds.append(
            {
                "round": r,
                "wall_s": round(wall, 6),
                "phases_s": {p: round(v, 6) for p, v in phases.items()},
                "phase_peers": phase_holder,
                "fold_s": round(fold_s, 6),
                "dominant": dominant,
                "dominant_peer": phase_holder[dominant],
                "stall_s": round(stall[0], 6),
                "stall_span": stall[1],
                "stall_peer": stall[2],
                "straggler": straggler,
                "upload_s_max": round(uploads[0][0], 6) if uploads else 0.0,
                "upload_s_second": (
                    round(uploads[1][0], 6) if len(uploads) > 1 else 0.0
                ),
                "spans": len(recs),
            }
        )
    # The tail is what explains the last stall — but events arrive as one
    # FILE per node, so chronological order needs a sort (aligned wall
    # time), not file concatenation order.
    events_by_time = sorted(
        events,
        key=lambda e: (
            int(e.get("t_wall_ns", 0)) / 1e9
            + offsets.get(e.get("node") or "node", 0.0)
        ),
    )
    return {
        "reference_node": ref,
        "clock_offsets_s": {n: round(o, 6) for n, o in offsets.items()},
        "rounds": rounds,
        "num_spans": len(spans),
        "num_events": len(events),
        "events": events_by_time[-64:],
    }


def to_otlp(spans: list[dict], resource: dict | None = None) -> dict:
    """Merged span records → OTLP/JSON ``resourceSpans`` (one scope per
    node), ingestible by any OTEL collector/viewer."""
    from .otlp import _attr_list

    by_node: dict[str, list[dict]] = defaultdict(list)
    for s in spans:
        by_node[s.get("node") or "node"].append(s)
    return {
        "resourceSpans": [
            {
                "resource": {
                    "attributes": _attr_list(
                        resource or {"service.name": "hypha"}
                    )
                },
                "scopeSpans": [
                    {
                        "scope": {"name": f"hypha.node.{node}"},
                        "spans": [
                            {
                                "traceId": s.get("trace_id", ""),
                                "spanId": s.get("span_id", ""),
                                **(
                                    {"parentSpanId": s["parent_id"]}
                                    if s.get("parent_id")
                                    else {}
                                ),
                                "name": s.get("name", ""),
                                "kind": 1,
                                "startTimeUnixNano": str(s.get("start_ns", 0)),
                                "endTimeUnixNano": str(
                                    s.get("end_ns", s.get("start_ns", 0))
                                ),
                                "attributes": _attr_list(s.get("attrs") or {}),
                                "status": {
                                    "code": 1 if s.get("ok", True) else 2
                                },
                            }
                            for s in node_spans
                        ],
                    }
                    for node, node_spans in sorted(by_node.items())
                ],
            }
        ]
    }


def render_text(timeline: dict) -> str:
    """The human critical-path table."""
    lines: list[str] = []
    offs = timeline.get("clock_offsets_s", {})
    lines.append(
        f"timeline: {timeline.get('num_spans', 0)} spans, "
        f"{timeline.get('num_events', 0)} events, "
        f"reference node {timeline.get('reference_node')!r}"
    )
    skewed = {n: o for n, o in offs.items() if abs(o) > 0.001}
    if skewed:
        lines.append(
            "clock offsets applied: "
            + ", ".join(f"{n}{o:+.3f}s" for n, o in sorted(skewed.items()))
        )
    header = (
        f"{'round':>5} {'wall':>8} "
        + " ".join(f"{p:>11}" for p in PHASES)
        + "  dominant (peer)"
    )
    lines.append(header)
    for row in timeline.get("rounds", []):
        phases = row["phases_s"]
        peer = row.get("dominant_peer") or row.get("straggler") or "-"
        lines.append(
            f"{row['round']:>5} {row['wall_s']:>7.3f}s "
            + " ".join(f"{phases[p]:>10.3f}s" for p in PHASES)
            + f"  {row['dominant']} ({peer})"
        )
        if row.get("stall_span"):
            lines.append(
                f"{'':>5} stall: {row['stall_span']} by {row['stall_peer']} "
                f"({row['stall_s']:.3f}s); slowest upload "
                f"{row['upload_s_max']:.3f}s by {row.get('straggler')} "
                f"(next {row['upload_s_second']:.3f}s)"
            )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hypha_tpu.telemetry.timeline",
        description="Merge per-node trace files into a round critical path",
    )
    parser.add_argument("trace_dir", help="directory of spans-*/events-*.jsonl")
    parser.add_argument(
        "--json",
        default=None,
        help="write the machine timeline here (default <dir>/timeline.json; "
        "'-' for stdout)",
    )
    parser.add_argument(
        "--otlp",
        default=None,
        help="also write the merged spans as OTLP JSON to this path",
    )
    args = parser.parse_args(argv)
    trace_dir = Path(args.trace_dir)
    if not trace_dir.is_dir():
        print(f"not a directory: {trace_dir}", file=sys.stderr)
        return 2
    timeline = build_timeline(trace_dir)
    print(render_text(timeline))
    out = args.json or str(trace_dir / "timeline.json")
    if out == "-":
        print(json.dumps(timeline, indent=2))
    else:
        Path(out).write_text(json.dumps(timeline, indent=2) + "\n")
        print(f"wrote {out}", file=sys.stderr)
    if args.otlp:
        spans, _events = load_dir(trace_dir)
        Path(args.otlp).write_text(json.dumps(to_otlp(spans)) + "\n")
        print(f"wrote {args.otlp}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
