"""Attribute-string parsing (crates/telemetry/src/attributes.rs).

``service.name=scheduler,deployment=prod`` → dict. Values keep their
string form (OTLP resource attributes are stringly typed at this layer).
"""

from __future__ import annotations

__all__ = ["parse_attributes"]


def parse_attributes(raw: str | None) -> dict[str, str]:
    out: dict[str, str] = {}
    if not raw:
        return out
    for pair in raw.split(","):
        pair = pair.strip()
        if not pair:
            continue
        key, sep, value = pair.partition("=")
        if not sep or not key.strip():
            raise ValueError(f"bad attribute {pair!r}: want key=value")
        out[key.strip()] = value.strip()
    return out
