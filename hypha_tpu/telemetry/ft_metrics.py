"""Fault-tolerance instruments: one shared bundle for the ft subsystem.

The φ detector, elastic parameter server and rejoin path all record into a
process-global :data:`FT_METRICS` bundle so in-process tests and ``bench.py
--chaos`` can read one snapshot regardless of which component did the work.
``register_on`` exposes the same values as observable gauges on a real
:class:`~hypha_tpu.telemetry.Meter` for OTLP export.
"""

from __future__ import annotations

from . import Counter, Histogram, Meter

__all__ = ["FTMetrics", "FT_METRICS", "register_on"]


class FTMetrics:
    def __init__(self) -> None:
        self.suspected_peers = Counter("hypha.ft.suspected_peers")
        self.degraded_rounds = Counter("hypha.ft.degraded_rounds")
        self.stale_deltas_dropped = Counter("hypha.ft.stale_deltas_dropped")
        self.rejoins = Counter("hypha.ft.rejoins")
        self.rejoin_latency_ms = Histogram(
            "hypha.ft.rejoin_latency", unit="ms",
            bounds=(50, 100, 250, 500, 1000, 2500, 5000, 10000, 30000),
        )

    def snapshot(self) -> dict:
        hist = self.rejoin_latency_ms.snapshot()
        return {
            "suspected_peers": self.suspected_peers.value(),
            "degraded_rounds": self.degraded_rounds.value(),
            "stale_deltas_dropped": self.stale_deltas_dropped.value(),
            "rejoins": self.rejoins.value(),
            "rejoin_latency_ms_sum": hist["sum"],
            "rejoin_latency_ms_count": hist["count"],
        }

    def reset(self) -> None:
        """Fresh instruments (tests and bench isolate runs this way)."""
        self.__init__()


FT_METRICS = FTMetrics()


def register_on(meter: Meter, metrics: FTMetrics = FT_METRICS) -> None:
    """Export the bundle through a Meter as observable gauges."""
    meter.observable_gauge(
        "hypha.ft.suspected_peers", metrics.suspected_peers.value
    )
    meter.observable_gauge(
        "hypha.ft.degraded_rounds", metrics.degraded_rounds.value
    )
    meter.observable_gauge(
        "hypha.ft.stale_deltas_dropped", metrics.stale_deltas_dropped.value
    )
    meter.observable_gauge("hypha.ft.rejoins", metrics.rejoins.value)
