"""Fault-tolerance and streaming-sync instruments: shared bundles.

The φ detector, elastic parameter server and rejoin path all record into a
process-global :data:`FT_METRICS` bundle so in-process tests and ``bench.py
--chaos`` can read one snapshot regardless of which component did the work.
:data:`STREAM_METRICS` does the same for the streaming outer sync
(hypha_tpu.stream): the training executor's flight thread and the
parameter server's per-fragment round loop both record here, and
``benchmarks/streambench.py`` reads one snapshot per mode. ``register_on``
exposes both bundles as observable gauges on a real
:class:`~hypha_tpu.telemetry.Meter` for OTLP export.
"""

from __future__ import annotations

import threading

from . import Counter, Histogram, Meter

__all__ = [
    "FTMetrics",
    "FT_METRICS",
    "StreamMetrics",
    "STREAM_METRICS",
    "ShardMetrics",
    "SHARD_METRICS",
    "ServeMetrics",
    "SERVE_METRICS",
    "HetMetrics",
    "HET_METRICS",
    "ScaleMetrics",
    "SCALE_METRICS",
    "DataMetrics",
    "DATA_METRICS",
    "register_on",
]


class FTMetrics:
    def __init__(self) -> None:
        self.suspected_peers = Counter("hypha.ft.suspected_peers")
        self.degraded_rounds = Counter("hypha.ft.degraded_rounds")
        self.stale_deltas_dropped = Counter("hypha.ft.stale_deltas_dropped")
        self.rejoins = Counter("hypha.ft.rejoins")
        # Durable-PS instruments (hypha_tpu.ft.durable): re-attempted fabric
        # operations (aio.retry), write-ahead journal bytes appended, and
        # completed parameter-server crash recoveries.
        self.retry_attempts = Counter("hypha.ft.retry_attempts")
        self.ps_journal_bytes = Counter("hypha.ps.journal_bytes")
        self.ps_recoveries = Counter("hypha.ps.recoveries")
        # Durable control plane (ft.durable DurableScheduler): completed
        # scheduler crash recoveries, executions re-adopted in place by the
        # SchedulerHello/AdoptAck handshake, and stale-generation control
        # messages dropped (the zombie-scheduler guard firing).
        self.scheduler_recoveries = Counter("hypha.scheduler.recoveries")
        self.adopted_executions = Counter("hypha.scheduler.adopted_executions")
        self.stale_generation_dropped = Counter(
            "hypha.scheduler.stale_generation_dropped"
        )
        self.rejoin_latency_ms = Histogram(
            "hypha.ft.rejoin_latency", unit="ms",
            bounds=(50, 100, 250, 500, 1000, 2500, 5000, 10000, 30000),
        )

    def snapshot(self) -> dict:
        hist = self.rejoin_latency_ms.snapshot()
        return {
            "suspected_peers": self.suspected_peers.value(),
            "degraded_rounds": self.degraded_rounds.value(),
            "stale_deltas_dropped": self.stale_deltas_dropped.value(),
            "rejoins": self.rejoins.value(),
            "retry_attempts": self.retry_attempts.value(),
            "ps_journal_bytes": self.ps_journal_bytes.value(),
            "ps_recoveries": self.ps_recoveries.value(),
            "scheduler_recoveries": self.scheduler_recoveries.value(),
            "adopted_executions": self.adopted_executions.value(),
            "stale_generation_dropped": self.stale_generation_dropped.value(),
            "rejoin_latency_ms_sum": hist["sum"],
            "rejoin_latency_ms_count": hist["count"],
        }

    def reset(self) -> None:
        """Fresh instruments (tests and bench isolate runs this way)."""
        self.__init__()


FT_METRICS = FTMetrics()


class StreamMetrics:
    """Streaming outer-sync instruments (hypha_tpu.stream).

    * ``bytes_in_flight``      — encoded delta bytes currently uploading /
      awaiting their broadcast on this worker (gauge semantics: flights
      add on launch, subtract on merge); ``peak_bytes_in_flight`` keeps
      the high-water mark — the number stream mode's F-way staggering is
      built to shrink.
    * ``overlap_fraction``     — of the wall-clock the sync spent in
      flight, the fraction the worker was computing inner steps instead
      of idling (0 in blocking mode, →1 when flight fully hides behind
      compute).
    * ``fragment_closes``      — per-fragment round-close counters on the
      parameter server (a stuck fragment shows up as one counter falling
      behind its siblings).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()  # flight thread + loop both record
        self._in_flight = 0.0
        self.peak_bytes_in_flight = 0.0
        self.flight_seconds = 0.0
        self.overlapped_seconds = 0.0
        self.synced_fragments = Counter("hypha.stream.synced_fragments")
        self.fragment_closes: dict[int, Counter] = {}
        # Meters registered via register_on: fragment ids only become known
        # as rounds close, so their counters attach to every registered
        # meter lazily at creation time.
        self._meters: list[Meter] = []

    def flight_started(self, nbytes: float) -> None:
        with self._lock:
            # float(): a numpy byte count must not promote the gauge to a
            # non-JSON-serializable scalar (metrics_snapshot JSON-safety).
            self._in_flight += float(nbytes)
            self.peak_bytes_in_flight = max(
                self.peak_bytes_in_flight, self._in_flight
            )

    def flight_landed(self, nbytes: float) -> None:
        """The flight thread is done with the wire — broadcast received OR
        the flight died (send error / severed bridge). Always paired with
        :meth:`flight_started` from the thread's exit path, so a failed
        job can never read as mid-upload for the process lifetime."""
        with self._lock:
            self._in_flight = max(0.0, self._in_flight - float(nbytes))

    def flight_finished(self, flight_s: float, overlapped_s: float) -> None:
        """One sync completed end to end (merge applied)."""
        flight_s, overlapped_s = float(flight_s), float(overlapped_s)
        with self._lock:
            self.flight_seconds += flight_s
            # Compute can't overlap more than the flight lasted (timer skew).
            self.overlapped_seconds += min(max(overlapped_s, 0.0), flight_s)
        self.synced_fragments.add(1)

    def bytes_in_flight(self) -> float:
        with self._lock:
            return self._in_flight

    def overlap_fraction(self) -> float:
        with self._lock:
            if self.flight_seconds <= 0.0:
                return 0.0
            return self.overlapped_seconds / self.flight_seconds

    def fragment_closed(self, fragment_id: int) -> None:
        """One (round, fragment) closed on the parameter server."""
        fragment_id = int(fragment_id)  # np.int64 keys break json.dumps
        with self._lock:
            counter = self.fragment_closes.get(fragment_id)
            created = counter is None
            if created:
                counter = Counter(
                    f"hypha.stream.fragment_closes.{fragment_id}"
                )
                self.fragment_closes[fragment_id] = counter
            meters = list(self._meters) if created else []
        for meter in meters:
            meter.observable_gauge(counter.name, counter.value)
        counter.add(1)

    def attach_meter(self, meter: Meter) -> None:
        """Export per-fragment close counters on ``meter``, including any
        fragment that only closes after this call (OTLP surface for 'one
        fragment falling behind its siblings')."""
        with self._lock:
            self._meters.append(meter)
            existing = list(self.fragment_closes.values())
        for counter in existing:
            meter.observable_gauge(counter.name, counter.value)

    def snapshot(self) -> dict:
        with self._lock:
            closes = {
                fid: c.value() for fid, c in sorted(self.fragment_closes.items())
            }
            flight_s = self.flight_seconds
            overlapped_s = self.overlapped_seconds
            in_flight = self._in_flight
            peak = self.peak_bytes_in_flight
        return {
            "bytes_in_flight": in_flight,
            "peak_bytes_in_flight": peak,
            "flight_seconds": flight_s,
            "overlapped_seconds": overlapped_s,
            "overlap_fraction": (
                overlapped_s / flight_s if flight_s > 0 else 0.0
            ),
            "synced_fragments": self.synced_fragments.value(),
            "fragment_closes": closes,
        }

    def reset(self) -> None:
        """Fresh instruments (tests and streambench isolate runs this way)."""
        self.__init__()


STREAM_METRICS = StreamMetrics()


class ShardMetrics:
    """Sharded parameter-service instruments (hypha_tpu.stream placement).

    * ``shard_rounds_closed``  — rounds this process closed as a PS shard
      (each shard closes only its owned rounds; on a worker node running
      several shard executors in tests the counter is their sum).
    * ``prefold_partials``     — tree-reduce partial sums accepted by the
      shard collectors (``PREFOLD_KEY`` pushes).
    * ``misrouted_pushes``     — deltas that arrived at a shard which does
      not own their round's fragment (a worker with a stale/mismatched
      placement map); dropped, never folded.
    * ``reduced_deltas``       — member deltas folded by group reducers on
      this node before anything reached a shard (the ingress the
      tree-reduce layer saved).
    """

    def __init__(self) -> None:
        self.shard_rounds_closed = Counter("hypha.shard.rounds_closed")
        self.prefold_partials = Counter("hypha.shard.prefold_partials")
        self.misrouted_pushes = Counter("hypha.shard.misrouted_pushes")
        self.reduced_deltas = Counter("hypha.shard.reduced_deltas")

    def snapshot(self) -> dict:
        return {
            "shard_rounds_closed": self.shard_rounds_closed.value(),
            "prefold_partials": self.prefold_partials.value(),
            "misrouted_pushes": self.misrouted_pushes.value(),
            "reduced_deltas": self.reduced_deltas.value(),
        }

    def reset(self) -> None:
        """Fresh instruments (tests and shardbench isolate runs this way)."""
        self.__init__()


SHARD_METRICS = ShardMetrics()


class ServeMetrics:
    """Serving-plane instruments (executor.pool paged mode + the request
    router in scheduler.serving).

    * ``free_blocks`` / ``queue_depth`` — gauges snapshotted by the live
      :class:`~hypha_tpu.executor.pool.DecodePool` at every serve-loop
      iteration (last-writer-wins across pools in one process; tests and
      servbench run one pool at a time).
    * ``admissions`` / ``preemptions`` / ``rejections`` — admitted groups,
      preempted-to-queue groups (recompute resume), and backpressure
      rejections (pool queue limit + router retry-after).
    * ``request latency`` — submit→resolve wall time per request, kept
      both as an OTLP histogram and as a bounded reservoir so
      :meth:`snapshot` can report p50/p95 directly (what SERVBENCH and
      the tests assert).
    * ``prefix cache`` — blocks hit/missed at admission, copy-on-write
      copies, LRU evictions, plus ``cached_blocks``/``shared_blocks``
      gauges (snapshotted per serve-loop iteration); the snapshot
      derives ``prefix_hit_rate`` from the hit/miss counters.
    * ``speculation`` — drafted vs accepted tokens per verify dispatch
      and the derived ``spec_accept_rate`` gauge.
    * ``attention occupancy`` — per-decode-dispatch gauges from the pool:
      ``attended_blocks`` (KV blocks the attention visited),
      ``occupied_fraction`` (allocated / dense-gather capacity) and the
      derived ``attended_ratio`` (attended / allocated — 1.0 under
      ragged paged attention, the dense overhead multiplier otherwise).
    * ``weight streaming`` — the serving (round, generation) gauges
      stamped at each hot swap, applied/deferred/rolled-back swap
      counters, and a stage→flip swap-latency reservoir (same
      quantile treatment as request latency; what SWAPBENCH asserts).
    * ``fleet cache`` — cross-worker prefix reuse: ``remote_prefix_hits``
      / ``remote_prefix_misses`` count KV blocks pulled from a peer vs
      pulls that fell back to recompute; ``blocks_shipped`` /
      ``block_bytes_shipped`` meter the holder side of every transfer
      (pulls and migrations); ``migrations`` counts preempted requests
      resumed on another worker; ``transfer_chosen`` /
      ``recompute_chosen`` record each side of the bandwidth-aware
      transfer-vs-recompute policy; ``directory_chains`` gauges the
      router's block-hash directory size (sum of backend digests).
    """

    _RESERVOIR = 2048

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._free_blocks = 0.0
        self._queue_depth = 0.0
        self._cached_blocks = 0.0
        self._shared_blocks = 0.0
        self._attended_blocks = 0.0
        self._allocated_blocks = 0.0
        self._occupied_fraction = 0.0
        self._weight_round = -1.0  # -1 = never swapped (dispatched params)
        self._weight_generation = -1.0
        self.admissions = Counter("hypha.serve.admissions")
        self.preemptions = Counter("hypha.serve.preemptions")
        self.rejections = Counter("hypha.serve.rejections")
        self.routed_requests = Counter("hypha.serve.routed_requests")
        self.ejections = Counter("hypha.serve.ejections")
        self.prefix_hit_blocks = Counter("hypha.serve.prefix_hit_blocks")
        self.prefix_miss_blocks = Counter("hypha.serve.prefix_miss_blocks")
        self.cow_copies = Counter("hypha.serve.cow_copies")
        self.cache_evictions = Counter("hypha.serve.cache_evictions")
        self.spec_proposed = Counter("hypha.serve.spec_proposed")
        self.spec_accepted = Counter("hypha.serve.spec_accepted")
        self.affinity_routed = Counter("hypha.serve.affinity_routed")
        self.remote_prefix_hits = Counter("hypha.serve.remote_prefix_hits")
        self.remote_prefix_misses = Counter(
            "hypha.serve.remote_prefix_misses"
        )
        self.blocks_shipped = Counter("hypha.serve.blocks_shipped")
        self.block_bytes_shipped = Counter(
            "hypha.serve.block_bytes_shipped"
        )
        self.migrations = Counter("hypha.serve.migrations")
        self.transfer_chosen = Counter("hypha.serve.transfer_chosen")
        self.recompute_chosen = Counter("hypha.serve.recompute_chosen")
        self._directory_chains = 0.0
        self.swap_applied = Counter("hypha.serve.swap_applied")
        self.swap_deferred = Counter("hypha.serve.swap_deferred")
        self.swap_rolled_back = Counter("hypha.serve.swap_rolled_back")
        self.request_latency_ms = Histogram(
            "hypha.serve.request_latency", unit="ms",
            bounds=(5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000),
        )
        self._latencies: list[float] = []
        self.swap_latency_ms = Histogram(
            "hypha.serve.swap_latency", unit="ms",
            bounds=(1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500),
        )
        self._swap_latencies: list[float] = []

    def weight_state(self, round_num: float, generation: float) -> None:
        """The (round, generation) the pool is serving after a swap —
        last-writer gauges, like pool_state."""
        with self._lock:
            self._weight_round = float(round_num)
            self._weight_generation = float(generation)

    def weight_round(self) -> float:
        with self._lock:
            return self._weight_round

    def weight_generation(self) -> float:
        with self._lock:
            return self._weight_generation

    def swap_finished(self, latency_ms: float) -> None:
        """Stage→flip wall time of one applied swap (request_swap to the
        chunk-boundary application on the serve thread)."""
        self.swap_latency_ms.record(latency_ms)
        with self._lock:
            self._swap_latencies.append(float(latency_ms))
            if len(self._swap_latencies) > self._RESERVOIR:
                del self._swap_latencies[
                    : len(self._swap_latencies) - self._RESERVOIR
                ]

    def pool_state(self, free_blocks: float, queue_depth: float) -> None:
        with self._lock:
            self._free_blocks = float(free_blocks)
            self._queue_depth = float(queue_depth)

    def attention_state(
        self,
        attended_blocks: float,
        allocated_blocks: float,
        capacity_blocks: float,
    ) -> None:
        """Occupancy of the LAST decode dispatch (last-writer gauges,
        like pool_state): KV blocks the attention actually visited,
        blocks the live lanes hold, and the dense-gather worst case
        (live lanes × max_blocks). Ragged attention makes attended ==
        allocated; dense gather pays attended == capacity regardless of
        occupancy — ``attended_ratio`` (attended / allocated) is the
        per-step multiplier the kernel spends over the useful work."""
        with self._lock:
            self._attended_blocks = float(attended_blocks)
            self._allocated_blocks = float(allocated_blocks)
            self._occupied_fraction = (
                float(allocated_blocks) / float(capacity_blocks)
                if capacity_blocks
                else 0.0
            )

    def attended_blocks(self) -> float:
        with self._lock:
            return self._attended_blocks

    def occupied_fraction(self) -> float:
        with self._lock:
            return self._occupied_fraction

    def attended_ratio(self) -> float:
        """Attended vs allocated blocks in the last decode dispatch:
        1.0 = the kernel visited exactly the occupied blocks (ragged);
        > 1.0 = dense gather overhead at partial occupancy."""
        with self._lock:
            if not self._allocated_blocks:
                return 0.0
            return self._attended_blocks / self._allocated_blocks

    def cache_state(self, cached_blocks: float, shared_blocks: float) -> None:
        with self._lock:
            self._cached_blocks = float(cached_blocks)
            self._shared_blocks = float(shared_blocks)

    def cached_blocks(self) -> float:
        with self._lock:
            return self._cached_blocks

    def shared_blocks(self) -> float:
        with self._lock:
            return self._shared_blocks

    def prefix_hit_rate(self) -> float:
        hit = self.prefix_hit_blocks.value()
        total = hit + self.prefix_miss_blocks.value()
        return hit / total if total else 0.0

    def directory_state(self, chains: float) -> None:
        """Size of the router's fleet-cache directory (total chain hashes
        across all backend digests) — last-writer gauge, like pool_state."""
        with self._lock:
            self._directory_chains = float(chains)

    def directory_chains(self) -> float:
        with self._lock:
            return self._directory_chains

    def remote_prefix_hit_rate(self) -> float:
        hit = self.remote_prefix_hits.value()
        total = hit + self.remote_prefix_misses.value()
        return hit / total if total else 0.0

    def spec_accept_rate(self) -> float:
        proposed = self.spec_proposed.value()
        return self.spec_accepted.value() / proposed if proposed else 0.0

    def request_finished(self, latency_ms: float) -> None:
        self.request_latency_ms.record(latency_ms)
        with self._lock:
            self._latencies.append(float(latency_ms))
            if len(self._latencies) > self._RESERVOIR:
                del self._latencies[: len(self._latencies) - self._RESERVOIR]

    def free_blocks(self) -> float:
        with self._lock:
            return self._free_blocks

    def queue_depth(self) -> float:
        with self._lock:
            return self._queue_depth

    def _quantile(self, q: float, which: str = "_latencies") -> float:
        with self._lock:
            lat = sorted(getattr(self, which))
        if not lat:
            return 0.0
        i = min(int(q * len(lat)), len(lat) - 1)
        return lat[i]

    def snapshot(self) -> dict:
        hist = self.request_latency_ms.snapshot()
        return {
            "free_blocks": self.free_blocks(),
            "queue_depth": self.queue_depth(),
            "admissions": self.admissions.value(),
            "preemptions": self.preemptions.value(),
            "rejections": self.rejections.value(),
            "routed_requests": self.routed_requests.value(),
            "ejections": self.ejections.value(),
            "prefix_hit_blocks": self.prefix_hit_blocks.value(),
            "prefix_miss_blocks": self.prefix_miss_blocks.value(),
            "prefix_hit_rate": self.prefix_hit_rate(),
            "cached_blocks": self.cached_blocks(),
            "shared_blocks": self.shared_blocks(),
            "attended_blocks": self.attended_blocks(),
            "occupied_fraction": self.occupied_fraction(),
            "attended_ratio": self.attended_ratio(),
            "cow_copies": self.cow_copies.value(),
            "cache_evictions": self.cache_evictions.value(),
            "spec_proposed": self.spec_proposed.value(),
            "spec_accepted": self.spec_accepted.value(),
            "spec_accept_rate": self.spec_accept_rate(),
            "affinity_routed": self.affinity_routed.value(),
            "remote_prefix_hits": self.remote_prefix_hits.value(),
            "remote_prefix_misses": self.remote_prefix_misses.value(),
            "remote_prefix_hit_rate": self.remote_prefix_hit_rate(),
            "blocks_shipped": self.blocks_shipped.value(),
            "block_bytes_shipped": self.block_bytes_shipped.value(),
            "migrations": self.migrations.value(),
            "transfer_chosen": self.transfer_chosen.value(),
            "recompute_chosen": self.recompute_chosen.value(),
            "directory_chains": self.directory_chains(),
            "request_latency_ms_count": hist["count"],
            "request_latency_ms_sum": hist["sum"],
            "request_latency_ms_p50": self._quantile(0.50),
            "request_latency_ms_p95": self._quantile(0.95),
            "weight_round": self.weight_round(),
            "weight_generation": self.weight_generation(),
            "swap_applied": self.swap_applied.value(),
            "swap_deferred": self.swap_deferred.value(),
            "swap_rolled_back": self.swap_rolled_back.value(),
            "swap_latency_ms_count": self.swap_latency_ms.snapshot()["count"],
            "swap_latency_ms_p50": self._quantile(0.50, "_swap_latencies"),
            "swap_latency_ms_p95": self._quantile(0.95, "_swap_latencies"),
        }

    def reset(self) -> None:
        """Fresh instruments (tests and servbench isolate runs this way)."""
        self.__init__()


SERVE_METRICS = ServeMetrics()


class HetMetrics:
    """WAN-heterogeneity instruments (hypha_tpu.ft.adaptive).

    * ``bandwidth_bps``       — per-peer measured upload bandwidth EWMA
      (the parameter server's LinkTable, timed around each delta save);
      exported as one lazy observable gauge per peer, like the stream
      bundle's per-fragment close counters.
    * ``assigned_steps``      — per-peer inner-step assignment for the
      current round (the StragglerController's output; on the PS side the
      adopted ``RoundMembership.inner_steps`` records here too).
    * ``codec counters``      — per-link codec selections: one counter per
      codec name plus the current per-peer choice, and a ``codec_switches``
      counter on the worker side (upload codec changed by a broadcast
      hint).
    * ``quorum_drops``        — workers whose delta missed an elastic
      round's close (expected − covered at deadline), total and by round:
      the number the straggler-adaptive controller exists to drive to 0.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._bandwidth: dict[str, float] = {}
        self._assigned: dict[str, int] = {}
        self._peer_codecs: dict[str, str] = {}
        self.codec_counts: dict[str, Counter] = {}
        self.codec_switches = Counter("hypha.het.codec_switches")
        self.quorum_drops = Counter("hypha.het.quorum_drops")
        self._drops_by_round: dict[int, int] = {}
        # Meters registered via register_on: peers and codecs only become
        # known as rounds run, so their gauges attach lazily.
        self._meters: list[Meter] = []

    # ------------------------------------------------------------ recording
    def note_bandwidth(self, peer: str, bps: float) -> None:
        with self._lock:
            created = peer not in self._bandwidth
            self._bandwidth[peer] = float(bps)
            meters = list(self._meters) if created else []
        for meter in meters:
            meter.observable_gauge(
                f"hypha.het.bandwidth_bps.{peer}",
                lambda p=peer: self._bandwidth.get(p, 0.0),
            )

    def note_assigned(self, peer: str, steps: int) -> None:
        with self._lock:
            created = peer not in self._assigned
            self._assigned[peer] = int(steps)
            meters = list(self._meters) if created else []
        for meter in meters:
            meter.observable_gauge(
                f"hypha.het.assigned_steps.{peer}",
                lambda p=peer: self._assigned.get(p, 0),
            )

    def note_codec(self, peer: str, codec: str) -> None:
        with self._lock:
            self._peer_codecs[peer] = codec
            counter = self.codec_counts.get(codec)
            created = counter is None
            if created:
                counter = Counter(f"hypha.het.codec.{codec}")
                self.codec_counts[codec] = counter
            meters = list(self._meters) if created else []
        for meter in meters:
            meter.observable_gauge(counter.name, counter.value)
        counter.add(1)

    def note_quorum_drop(self, round_num: int, peers) -> None:
        dropped = list(peers)
        if not dropped:
            return
        self.quorum_drops.add(len(dropped))
        # Flight-recorder breadcrumb: the drop is the symptom a stalled
        # round's forensics start from — which peers, which round, when.
        from .flight import FLIGHT

        FLIGHT.record(
            "ft.quorum_drop", round=int(round_num), peers=dropped,
        )
        with self._lock:
            self._drops_by_round[int(round_num)] = self._drops_by_round.get(
                int(round_num), 0
            ) + len(dropped)

    # ------------------------------------------------------------- querying
    def attach_meter(self, meter: Meter) -> None:
        """Export the per-peer/per-codec instruments on ``meter``, including
        peers first seen after this call."""
        with self._lock:
            self._meters.append(meter)
            bw_peers = list(self._bandwidth)
            step_peers = list(self._assigned)
            counters = list(self.codec_counts.values())
        for peer in bw_peers:
            meter.observable_gauge(
                f"hypha.het.bandwidth_bps.{peer}",
                lambda p=peer: self._bandwidth.get(p, 0.0),
            )
        for peer in step_peers:
            meter.observable_gauge(
                f"hypha.het.assigned_steps.{peer}",
                lambda p=peer: self._assigned.get(p, 0),
            )
        for counter in counters:
            meter.observable_gauge(counter.name, counter.value)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "bandwidth_bps": dict(self._bandwidth),
                "assigned_steps": dict(self._assigned),
                "peer_codecs": dict(self._peer_codecs),
                "codec_counts": {
                    c: k.value() for c, k in sorted(self.codec_counts.items())
                },
                "codec_switches": self.codec_switches.value(),
                "quorum_drops": self.quorum_drops.value(),
                "quorum_drops_by_round": dict(sorted(self._drops_by_round.items())),
            }

    def reset(self) -> None:
        """Fresh instruments (tests and hetbench isolate runs this way)."""
        self.__init__()


HET_METRICS = HetMetrics()


class ScaleMetrics:
    """Control-plane scale instruments (ROADMAP item 4 / ISSUE 14).

    * ``control_bytes``   — per-protocol control-plane wire bytes (request
      + response frames through ``Node``): membership updates
      (``/hypha-ft``), Status/ScheduleUpdate heartbeats
      (``/hypha-progress``), lease traffic (``/hypha-api``) — the numbers
      ``benchmarks/scalebench.py`` asserts sublinear. Tensor payloads
      (push/pull) deliberately do NOT record here; they are data plane.
    * ``tree folds/forwards`` — per-level reduce-tree activity: how many
      child contributions each level folded and how many cumulative
      partials it shipped up (``hypha_tpu.stream.reduce.GroupReducer``).
    * ``relay counters``  — broadcast-tree pushes delivered per hop and
      dead-relay failover expansions (``tree_broadcast``).
    * ``sched_progress_ms`` — the scheduler's per-message control-loop
      time (``BatchScheduler.on_progress``), the reservoir scalebench
      reads its scheduler-CPU-per-round numbers from.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._control: dict[str, Counter] = {}
        self.tree_folds: dict[int, Counter] = {}
        self.tree_forwards: dict[int, Counter] = {}
        self.relay_pushes = Counter("hypha.scale.relay_pushes")
        self.relay_failovers = Counter("hypha.scale.relay_failovers")
        self.sched_progress_ms = Histogram(
            "hypha.scale.sched_progress", unit="ms",
            bounds=(0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100),
        )
        # Meters registered via register_on: protocols and tree levels
        # only become known as traffic flows, so their gauges attach
        # lazily (the het bundle's discipline).
        self._meters: list[Meter] = []

    # ------------------------------------------------------------ recording
    @staticmethod
    def _proto_key(protocol: str) -> str:
        # "/hypha-progress/0.0.1" -> "hypha-progress"
        return protocol.strip("/").split("/", 1)[0] or "unknown"

    def note_control(self, protocol: str, nbytes: int) -> None:
        key = self._proto_key(protocol)
        with self._lock:
            counter = self._control.get(key)
            created = counter is None
            if created:
                counter = Counter(f"hypha.scale.control_bytes.{key}")
                self._control[key] = counter
            meters = list(self._meters) if created else []
        for meter in meters:
            meter.observable_gauge(counter.name, counter.value)
        counter.add(int(nbytes))

    def _level_counter(
        self, table: dict[int, Counter], level: int, stem: str
    ) -> Counter:
        level = int(level)
        with self._lock:
            counter = table.get(level)
            created = counter is None
            if created:
                counter = Counter(f"hypha.scale.{stem}.l{level}")
                table[level] = counter
            meters = list(self._meters) if created else []
        for meter in meters:
            meter.observable_gauge(counter.name, counter.value)
        return counter

    def note_tree_fold(self, level: int) -> None:
        self._level_counter(self.tree_folds, level, "tree_folds").add(1)

    def note_tree_forward(self, level: int) -> None:
        self._level_counter(self.tree_forwards, level, "tree_forwards").add(1)

    def note_sched_progress(self, ms: float) -> None:
        self.sched_progress_ms.record(float(ms))

    # ------------------------------------------------------------- querying
    def control_bytes(self) -> dict[str, int]:
        with self._lock:
            return {k: int(c.value()) for k, c in sorted(self._control.items())}

    def attach_meter(self, meter: Meter) -> None:
        """Export the lazy per-protocol/per-level instruments, including
        ones first seen after this call."""
        with self._lock:
            self._meters.append(meter)
            counters = (
                list(self._control.values())
                + list(self.tree_folds.values())
                + list(self.tree_forwards.values())
            )
        for counter in counters:
            meter.observable_gauge(counter.name, counter.value)

    def snapshot(self) -> dict:
        hist = self.sched_progress_ms.snapshot()
        with self._lock:
            folds = {
                f"l{lv}": int(c.value())
                for lv, c in sorted(self.tree_folds.items())
            }
            forwards = {
                f"l{lv}": int(c.value())
                for lv, c in sorted(self.tree_forwards.items())
            }
        return {
            "control_bytes": self.control_bytes(),
            "tree_folds": folds,
            "tree_forwards": forwards,
            "relay_pushes": self.relay_pushes.value(),
            "relay_failovers": self.relay_failovers.value(),
            "sched_progress_ms_sum": hist["sum"],
            "sched_progress_ms_count": hist["count"],
        }

    def reset(self) -> None:
        """Fresh instruments (tests and scalebench isolate runs this way)."""
        self.__init__()


SCALE_METRICS = ScaleMetrics()


class DataMetrics:
    """Input-pipeline instruments (executor.dataset / ISSUE 15).

    * ``input_wait_seconds``    — wall-clock the TRAINING thread spent
      blocked waiting for the next batch (the number the async pipeline
      exists to drive to ~0); ``input_waits`` counts the waits.
    * ``boundary_wait_seconds`` — the subset of input waits spent
      acquiring a SLICE (slice-boundary stall: scheduler round-trip +
      data-node pull + disk write on the sync path, queue wait on the
      prefetch path); ``boundary_waits`` counts them.
    * ``slice_fetch_seconds``   — time actually pulling slices, wherever
      it ran (training thread or the background prefetcher), plus
      ``slices_fetched`` / ``bytes_pulled``.
    * ``prefetch_queue_depth``  — ready-and-unconsumed prefetched slices
      (gauge: last sample; ``peak`` kept separately) and
      ``prefetch_errors`` (fetch attempts the prefetcher retried).
    * ``cache hits/misses``     — on-disk slice-LRU outcomes
      (worker.slice_cache), plus evictions and corrupt-entry refetches.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.input_wait_seconds = 0.0
        self.input_waits = 0
        self.boundary_wait_seconds = 0.0
        self.boundary_waits = 0
        self.slice_fetch_seconds = 0.0
        self._queue_depth = 0.0
        self.peak_queue_depth = 0.0
        self.slices_fetched = Counter("hypha.data.slices_fetched")
        self.bytes_pulled = Counter("hypha.data.bytes_pulled")
        self.prefetch_errors = Counter("hypha.data.prefetch_errors")
        self.cache_hits = Counter("hypha.data.cache_hits")
        self.cache_misses = Counter("hypha.data.cache_misses")
        self.cache_evictions = Counter("hypha.data.cache_evictions")
        self.cache_corrupt = Counter("hypha.data.cache_corrupt")

    def note_input_wait(self, seconds: float) -> None:
        """The training LOOP waited this long for its next batch (recorded
        per ``next(stream)`` by the executor; includes host assembly and
        any slice acquisition that ran inline)."""
        with self._lock:
            self.input_wait_seconds += max(float(seconds), 0.0)
            self.input_waits += 1

    def note_boundary_wait(self, seconds: float) -> None:
        """A slice acquisition blocked the stream this long (a SUBSET of
        the input waits above — kept separately so the slice-boundary
        stall is assertable on its own)."""
        with self._lock:
            self.boundary_wait_seconds += max(float(seconds), 0.0)
            self.boundary_waits += 1

    def note_fetch(self, seconds: float) -> None:
        """One slice materialized (training thread or prefetcher); wire
        bytes are credited separately by the pulling connector —
        cache-hit fetches move no bytes."""
        with self._lock:
            self.slice_fetch_seconds += max(float(seconds), 0.0)
        self.slices_fetched.add(1)

    def note_queue_depth(self, depth: float) -> None:
        with self._lock:
            self._queue_depth = float(depth)
            self.peak_queue_depth = max(self.peak_queue_depth, float(depth))

    def queue_depth(self) -> float:
        with self._lock:
            return self._queue_depth

    def input_wait_s(self) -> float:
        with self._lock:
            return self.input_wait_seconds

    def mean_boundary_wait_s(self) -> float:
        with self._lock:
            if not self.boundary_waits:
                return 0.0
            return self.boundary_wait_seconds / self.boundary_waits

    def snapshot(self) -> dict:
        with self._lock:
            wait_s = self.input_wait_seconds
            waits = self.input_waits
            boundary_s = self.boundary_wait_seconds
            boundaries = self.boundary_waits
            fetch_s = self.slice_fetch_seconds
            depth = self._queue_depth
            peak = self.peak_queue_depth
        return {
            "input_wait_seconds": wait_s,
            "input_waits": waits,
            "boundary_wait_seconds": boundary_s,
            "boundary_waits": boundaries,
            "mean_boundary_wait_s": boundary_s / boundaries if boundaries else 0.0,
            "slice_fetch_seconds": fetch_s,
            "slices_fetched": self.slices_fetched.value(),
            "bytes_pulled": self.bytes_pulled.value(),
            "prefetch_queue_depth": depth,
            "peak_prefetch_queue_depth": peak,
            "prefetch_errors": self.prefetch_errors.value(),
            "cache_hits": self.cache_hits.value(),
            "cache_misses": self.cache_misses.value(),
            "cache_evictions": self.cache_evictions.value(),
            "cache_corrupt": self.cache_corrupt.value(),
        }

    def reset(self) -> None:
        """Fresh instruments (tests and databench isolate runs this way)."""
        self.__init__()


DATA_METRICS = DataMetrics()


def register_on(
    meter: Meter,
    metrics: FTMetrics = FT_METRICS,
    stream: StreamMetrics = STREAM_METRICS,
    shard: ShardMetrics = SHARD_METRICS,
    serve: "ServeMetrics" = None,
    het: "HetMetrics" = None,
) -> None:
    """Export the bundles through a Meter as observable gauges."""
    meter.observable_gauge(
        "hypha.ft.suspected_peers", metrics.suspected_peers.value
    )
    meter.observable_gauge(
        "hypha.ft.degraded_rounds", metrics.degraded_rounds.value
    )
    meter.observable_gauge(
        "hypha.ft.stale_deltas_dropped", metrics.stale_deltas_dropped.value
    )
    meter.observable_gauge("hypha.ft.rejoins", metrics.rejoins.value)
    meter.observable_gauge(
        "hypha.ft.retry_attempts", metrics.retry_attempts.value
    )
    meter.observable_gauge(
        "hypha.ps.journal_bytes", metrics.ps_journal_bytes.value
    )
    meter.observable_gauge("hypha.ps.recoveries", metrics.ps_recoveries.value)
    meter.observable_gauge(
        "hypha.stream.bytes_in_flight", stream.bytes_in_flight
    )
    meter.observable_gauge(
        "hypha.stream.peak_bytes_in_flight",
        lambda: stream.peak_bytes_in_flight,
    )
    meter.observable_gauge(
        "hypha.stream.overlap_fraction", stream.overlap_fraction
    )
    meter.observable_gauge(
        "hypha.stream.synced_fragments", stream.synced_fragments.value
    )
    meter.observable_gauge(
        "hypha.shard.rounds_closed", shard.shard_rounds_closed.value
    )
    meter.observable_gauge(
        "hypha.shard.prefold_partials", shard.prefold_partials.value
    )
    meter.observable_gauge(
        "hypha.shard.misrouted_pushes", shard.misrouted_pushes.value
    )
    meter.observable_gauge(
        "hypha.shard.reduced_deltas", shard.reduced_deltas.value
    )
    serve = serve if serve is not None else SERVE_METRICS
    meter.observable_gauge("hypha.serve.free_blocks", serve.free_blocks)
    meter.observable_gauge("hypha.serve.queue_depth", serve.queue_depth)
    meter.observable_gauge("hypha.serve.admissions", serve.admissions.value)
    meter.observable_gauge("hypha.serve.preemptions", serve.preemptions.value)
    meter.observable_gauge("hypha.serve.rejections", serve.rejections.value)
    meter.observable_gauge(
        "hypha.serve.routed_requests", serve.routed_requests.value
    )
    meter.observable_gauge("hypha.serve.ejections", serve.ejections.value)
    meter.observable_gauge(
        "hypha.serve.prefix_hit_blocks", serve.prefix_hit_blocks.value
    )
    meter.observable_gauge(
        "hypha.serve.prefix_miss_blocks", serve.prefix_miss_blocks.value
    )
    meter.observable_gauge(
        "hypha.serve.prefix_hit_rate", serve.prefix_hit_rate
    )
    meter.observable_gauge(
        "hypha.serve.cached_blocks", serve.cached_blocks
    )
    meter.observable_gauge(
        "hypha.serve.shared_blocks", serve.shared_blocks
    )
    meter.observable_gauge(
        "hypha.serve.attended_blocks", serve.attended_blocks
    )
    meter.observable_gauge(
        "hypha.serve.occupied_fraction", serve.occupied_fraction
    )
    meter.observable_gauge("hypha.serve.cow_copies", serve.cow_copies.value)
    meter.observable_gauge(
        "hypha.serve.cache_evictions", serve.cache_evictions.value
    )
    meter.observable_gauge(
        "hypha.serve.spec_accept_rate", serve.spec_accept_rate
    )
    meter.observable_gauge(
        "hypha.serve.affinity_routed", serve.affinity_routed.value
    )
    meter.observable_gauge(
        "hypha.serve.remote_prefix_hits", serve.remote_prefix_hits.value
    )
    meter.observable_gauge(
        "hypha.serve.remote_prefix_misses", serve.remote_prefix_misses.value
    )
    meter.observable_gauge(
        "hypha.serve.blocks_shipped", serve.blocks_shipped.value
    )
    meter.observable_gauge(
        "hypha.serve.block_bytes_shipped", serve.block_bytes_shipped.value
    )
    meter.observable_gauge("hypha.serve.migrations", serve.migrations.value)
    meter.observable_gauge(
        "hypha.serve.transfer_chosen", serve.transfer_chosen.value
    )
    meter.observable_gauge(
        "hypha.serve.recompute_chosen", serve.recompute_chosen.value
    )
    meter.observable_gauge(
        "hypha.serve.directory_chains", serve.directory_chains
    )
    meter.observable_gauge("hypha.serve.weight_round", serve.weight_round)
    meter.observable_gauge(
        "hypha.serve.weight_generation", serve.weight_generation
    )
    meter.observable_gauge(
        "hypha.serve.swap_applied", serve.swap_applied.value
    )
    meter.observable_gauge(
        "hypha.serve.swap_deferred", serve.swap_deferred.value
    )
    meter.observable_gauge(
        "hypha.serve.swap_rolled_back", serve.swap_rolled_back.value
    )
    data = DATA_METRICS
    meter.observable_gauge("hypha.data.input_wait_seconds", data.input_wait_s)
    meter.observable_gauge(
        "hypha.data.prefetch_queue_depth", data.queue_depth
    )
    meter.observable_gauge(
        "hypha.data.slices_fetched", data.slices_fetched.value
    )
    meter.observable_gauge("hypha.data.bytes_pulled", data.bytes_pulled.value)
    meter.observable_gauge("hypha.data.cache_hits", data.cache_hits.value)
    meter.observable_gauge("hypha.data.cache_misses", data.cache_misses.value)
    het = het if het is not None else HET_METRICS
    meter.observable_gauge("hypha.het.quorum_drops", het.quorum_drops.value)
    meter.observable_gauge(
        "hypha.het.codec_switches", het.codec_switches.value
    )
    meter.observable_gauge(
        "hypha.scale.relay_pushes", SCALE_METRICS.relay_pushes.value
    )
    meter.observable_gauge(
        "hypha.scale.relay_failovers", SCALE_METRICS.relay_failovers.value
    )
    # Per-fragment close counters (and the heterogeneity bundle's per-peer
    # bandwidth / assigned-step gauges + per-codec counters, and the scale
    # bundle's per-protocol control bytes + per-level tree counters)
    # attach lazily — fragment ids, peers and protocols only exist once
    # traffic flows.
    stream.attach_meter(meter)
    het.attach_meter(meter)
    SCALE_METRICS.attach_meter(meter)
