"""Declarative SLO rules over the live metrics plane.

A rule is one line of text — the shape node_config's ``slo_rules`` list
and the docs teach:

    serve.request_latency_ms.p99 <= 250
    round_wall_s <= 30
    hypha.het.quorum_drops == 0
    silent_s <= 15
    node.bandwidth_out_mbps >= 0.5 @peer

Grammar: ``<metric>[.<agg>] <op> <threshold> [@peer|@fleet]``.

  * ``metric`` — a gauge/counter family in the
    :class:`~hypha_tpu.telemetry.series.TimeSeriesStore` (counters are
    evaluated on their CUMULATIVE total), one of the derived series
    (``round_wall_s``, ``silent_s``), or a summary family with a
    quantile ``agg`` (``p50``/``p95``/``p99``/``max``).
  * ``op`` — ``<= < >= > ==``; the rule HOLDS while the comparison is
    true and BREACHES when it is not.
  * scope — ``@fleet`` (default) evaluates one rolled-up value
    (sum for counters, quantile-merge for summaries, max for gauges);
    ``@peer`` evaluates every reporting peer separately and names the
    offender. ``silent_s`` is always per-peer.

Breaches are edge-triggered: :class:`SLOWatchdog` fires once per
``(rule, peer)`` on entry, records a ``slo.breach`` flight event, and
re-arms when the rule holds again (``slo.recovered``). Enforcement is
deliberately out of scope — the watchdog emits
:class:`SLOAdvisory` values for the orchestrator to log, the same
advisory-not-actuator posture as ``RoundMembership`` snapshots.
"""

from __future__ import annotations

import logging
import math
import time
from dataclasses import dataclass, field

from ..messages import declare_values, register
from .flight import FLIGHT
from .series import TimeSeriesStore

__all__ = [
    "SLORule",
    "SLOAdvisory",
    "SLOWatchdog",
    "parse_slo_rule",
    "parse_slo_rules",
]

log = logging.getLogger("hypha.telemetry.slo")

_OPS = {
    "<=": lambda a, b: a <= b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    ">": lambda a, b: a > b,
    "==": lambda a, b: a == b,
}
_AGGS = ("p50", "p95", "p99", "max", "sum", "last")
_DERIVED = ("round_wall_s", "silent_s")


@dataclass(slots=True)
class SLORule:
    """One parsed objective (see module docstring for the text grammar)."""

    name: str
    metric: str
    op: str
    threshold: float
    agg: str = ""  # "" = default per metric kind
    scope: str = "fleet"  # "fleet" | "peer"

    def holds(self, value: float) -> bool:
        if value is None or (isinstance(value, float) and math.isnan(value)):
            return True  # no data is not a breach; silence has its own rule
        return _OPS[self.op](float(value), self.threshold)

    def text(self) -> str:
        agg = f".{self.agg}" if self.agg else ""
        scope = " @peer" if self.scope == "peer" else ""
        return f"{self.metric}{agg} {self.op} {self.threshold:g}{scope}"


@register
@dataclass(slots=True)
class SLOAdvisory:
    """The watchdog's breach notice — logged by the orchestrator, never
    enforced (the RoundMembership posture: an agreed observation, with
    actuation left to a future PR). ``round`` is the scheduler round the
    breach was observed at, so advisories order against the run."""

    job_id: str = ""
    rule: str = ""
    metric: str = ""
    peer: str = ""  # "" = fleet scope
    value: float = 0.0
    threshold: float = 0.0
    round: int = 0
    breached: bool = True  # False = recovery notice


declare_values("SLOAdvisory")


def parse_slo_rule(text: str) -> SLORule:
    """Parse one ``<metric>[.<agg>] <op> <value> [@scope]`` line."""
    raw = text.strip()
    scope = "fleet"
    if raw.endswith("@peer"):
        scope, raw = "peer", raw[: -len("@peer")].strip()
    elif raw.endswith("@fleet"):
        raw = raw[: -len("@fleet")].strip()
    op = None
    for candidate in ("<=", ">=", "==", "<", ">"):
        if candidate in raw:
            op = candidate
            break
    if op is None:
        raise ValueError(f"SLO rule {text!r}: no comparison operator")
    lhs, _, rhs = raw.partition(op)
    lhs = lhs.strip()
    try:
        threshold = float(rhs.strip())
    except ValueError:
        raise ValueError(f"SLO rule {text!r}: bad threshold {rhs.strip()!r}") from None
    agg = ""
    metric = lhs
    head, dot, tail = lhs.rpartition(".")
    if dot and tail in _AGGS:
        metric, agg = head, tail
    if not metric:
        raise ValueError(f"SLO rule {text!r}: empty metric")
    if metric == "silent_s":
        scope = "peer"
    return SLORule(
        name=raw, metric=metric, op=op, threshold=threshold, agg=agg,
        scope=scope,
    )


def parse_slo_rules(texts) -> list[SLORule]:
    return [parse_slo_rule(t) for t in (texts or []) if str(t).strip()]


class SLOWatchdog:
    """Evaluates rules against a :class:`TimeSeriesStore`; edge-triggered.

    ``check()`` is cheap (dict reads over latest values) and is run by the
    collector after every ingested report plus on a slow periodic tick
    (silence rules need wall-clock to advance even when nothing reports).
    """

    def __init__(
        self,
        rules: list[SLORule],
        store: TimeSeriesStore,
        job_id: str = "",
        on_advisory=None,
        round_fn=None,
    ) -> None:
        self.rules = list(rules)
        self.store = store
        self.job_id = job_id
        self.on_advisory = on_advisory
        self._round_fn = round_fn or (lambda: 0)
        self._breached: set[tuple[str, str]] = set()
        self.breaches = 0  # total breach edges (observability/tests)

    # ------------------------------------------------------------ values
    def _values(self, rule: SLORule, now: float) -> dict[str, float]:
        """scope key ("" = fleet) -> value to compare."""
        store = self.store
        if rule.metric == "silent_s":
            return {
                p: store.silent_for(p, now)
                for p in store.peers()
                if store.last_seen(p) is not None
            }
        if rule.metric == "round_wall_s":
            walls = store.round_walls()
            # The OPEN round's age counts too: a hung round (quorum wedge,
            # dead PS) never produces its completed-gap sample, and the
            # watchdog exists precisely for that case — compare the larger
            # of the last completed wall and the current round's age.
            open_age = store.open_round_age(now)
            last_wall = walls[max(walls)] if walls else 0.0
            if not walls and open_age <= 0.0:
                return {}
            return {"": max(last_wall, open_age)}
        if rule.agg in ("p50", "p95", "p99", "max"):
            if rule.scope == "peer":
                summaries = store.snapshot()["summaries"]
                return {
                    peer: float(s[rule.agg])
                    for peer, metrics in summaries.items()
                    for s in (metrics.get(rule.metric),)
                    if s and s.get(rule.agg) is not None
                }
            merged = store.fleet_quantiles(rule.metric)
            if merged.get("count", 0) > 0 and merged.get(rule.agg) is not None:
                return {"": float(merged[rule.agg])}
            if rule.agg == "max":
                # No summary family under this name: fall through to the
                # gauge rollups (a "<gauge>.max <= X" rule stays usable).
                pass
            else:
                return {}
        per_peer = store.fleet_last(rule.metric)
        if not per_peer:
            return {}
        if rule.scope == "peer":
            return dict(per_peer)
        if rule.agg == "sum":
            return {"": float(sum(per_peer.values()))}
        cumulative = store.fleet_cumulative(rule.metric)
        if cumulative and rule.agg in ("", "last") and rule.op == "==":
            # Counter-flavored equality rules (quorum_drops == 0) read the
            # cumulative total, not the latest per-interval rate.
            return {"": cumulative}
        return {"": float(max(per_peer.values()))}

    # ------------------------------------------------------------- check
    def check(self, now: float | None = None) -> list[SLOAdvisory]:
        now = time.time() if now is None else now
        advisories: list[SLOAdvisory] = []
        for rule in self.rules:
            for peer, value in self._values(rule, now).items():
                key = (rule.name, peer)
                ok = rule.holds(value)
                if not ok and key not in self._breached:
                    self._breached.add(key)
                    self.breaches += 1
                    adv = self._advise(rule, peer, value, breached=True)
                    advisories.append(adv)
                elif ok and key in self._breached:
                    self._breached.discard(key)
                    advisories.append(
                        self._advise(rule, peer, value, breached=False)
                    )
        return advisories

    def _advise(
        self, rule: SLORule, peer: str, value: float, breached: bool
    ) -> SLOAdvisory:
        adv = SLOAdvisory(
            job_id=self.job_id,
            rule=rule.text(),
            metric=rule.metric,
            peer=peer,
            value=float(value) if math.isfinite(value) else -1.0,
            threshold=rule.threshold,
            round=int(self._round_fn() or 0),
            breached=breached,
        )
        FLIGHT.record(
            "slo.breach" if breached else "slo.recovered",
            rule=adv.rule, metric=adv.metric, peer=adv.peer,
            value=adv.value, threshold=adv.threshold, round=adv.round,
            job=adv.job_id,
        )
        (log.warning if breached else log.info)(
            "SLO %s: %s %s (value %.6g vs %s %g)%s",
            "breach" if breached else "recovered",
            adv.rule, f"peer={peer}" if peer else "fleet",
            adv.value, rule.op, rule.threshold,
            " — advisory only, enforcement is future work" if breached else "",
        )
        if self.on_advisory is not None:
            try:
                self.on_advisory(adv)
            except Exception:  # advisories must never break ingest
                log.exception("SLO advisory callback failed")
        return adv

    def state(self) -> dict:
        """JSON-safe view for ``telemetry.top`` / MetricsQuery."""
        return {
            "rules": [r.text() for r in self.rules],
            "breached": sorted(
                f"{name}{f' [{peer}]' if peer else ''}"
                for name, peer in self._breached
            ),
            "breaches": self.breaches,
        }
