"""Time-series primitives for the live metrics plane.

Three concerns, all pure data (no fabric, no asyncio):

  * **Reservoir summaries** — a node never ships raw latency reservoirs;
    it ships the compact ``{count, sum, min, max, p50, p95, p99}`` shape
    (:func:`summarize`), and the scheduler re-pools per-peer summaries
    into a fleet quantile estimate (:func:`merge_summaries`).

  * **The bounded store** — :class:`TimeSeriesStore` keeps one ring of
    ``(t, value)`` points per ``(peer, metric)`` plus round-indexed
    *quality* series (loss curves and friends), with fleet rollups
    (sum / max / last-per-peer / merged quantiles) and an outlier probe
    used by the SLO watchdog and ``telemetry.top``.

  * **Exporters** — :func:`prometheus_text` renders the store in the
    Prometheus exposition format; :func:`to_otlp_metrics` emits OTLP/JSON
    ``resourceMetrics`` reusing the attribute encoding in
    :mod:`hypha_tpu.telemetry.otlp`.

Quantile-merge error bounds (tested in tests/test_metrics_plane.py):
each input summary pins its CDF at five knots (min, p50, p95, p99, max)
and is piecewise-linear between them, so the merged estimate's error
versus the exact pooled quantile is bounded by the value gap between the
ADJACENT knots that bracket the pooled rank in each contributing peer:

  * a single summary reads back its own knot values exactly;
  * identical per-peer distributions merge near-exactly — <= 5% relative
    at p50/p95, <= 10% at p99 (only sampling error and the sparse
    p99–max segment remain) on the pinned log-normal corpus;
  * tail quantiles (p95/p99) stay tight (<= 15%, measured ~1–3%) even
    for adversarially disjoint mixtures, because knots are dense there;
  * mid-rank quantiles under disjoint mixtures can drift up to a peer's
    p50–p95 knot gap — the merged p50 is only guaranteed to lie inside
    the bracketing-knot envelope (the test asserts exactly that), so
    alert on fleet p95/p99, not fleet medians, when peers are wildly
    heterogeneous.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Iterable

__all__ = [
    "QUANTILES",
    "summarize",
    "merge_summaries",
    "TimeSeriesStore",
    "prometheus_text",
    "to_otlp_metrics",
]

QUANTILES = (0.50, 0.95, 0.99)

# Default ring capacity per (peer, metric) series: at the 1 s default
# report interval this holds ~8.5 minutes of live history per metric —
# the journal, not the ring, is the durable record.
DEFAULT_CAPACITY = 512


def _quantile(sorted_values: list[float], q: float) -> float:
    """Nearest-rank quantile over an already-sorted list."""
    if not sorted_values:
        return 0.0
    i = min(int(q * len(sorted_values)), len(sorted_values) - 1)
    return sorted_values[i]


def summarize(values: Iterable[float]) -> dict:
    """Compact reservoir summary — what a :class:`MetricsReport` ships
    instead of the raw reservoir."""
    vals = sorted(float(v) for v in values)
    if not vals:
        return {"count": 0.0, "sum": 0.0}
    return {
        "count": float(len(vals)),
        "sum": float(sum(vals)),
        "min": vals[0],
        "max": vals[-1],
        "p50": _quantile(vals, 0.50),
        "p95": _quantile(vals, 0.95),
        "p99": _quantile(vals, 0.99),
    }


_SUMMARY_KNOTS = (
    (0.0, "min"), (0.50, "p50"), (0.95, "p95"), (0.99, "p99"), (1.0, "max")
)


def _knots(summary: dict) -> list[tuple[float, float]]:
    """(rank, value) CDF knots a summary pins: CDF(v_p50) = 0.50 etc."""
    return [
        (r, float(summary[k]))
        for r, k in _SUMMARY_KNOTS
        if summary.get(k) is not None
    ]


def _cdf_at(knots: list[tuple[float, float]], v: float) -> float:
    """Piecewise-linear CDF through a summary's knots, clamped to [0,1]."""
    if not knots:
        return 0.0
    if v <= knots[0][1]:
        return knots[0][0] if v == knots[0][1] else 0.0
    if v >= knots[-1][1]:
        return 1.0
    for (r0, v0), (r1, v1) in zip(knots, knots[1:]):
        if v0 <= v <= v1:
            if v1 <= v0:
                return r1
            return r0 + (r1 - r0) * (v - v0) / (v1 - v0)
    return 1.0


def merge_summaries(summaries: Iterable[dict]) -> dict:
    """Pool per-peer summaries into one fleet summary.

    Each summary's recorded quantiles pin its CDF at five knots; the
    pooled CDF is the count-weighted mixture of the per-peer piecewise-
    linear CDFs, inverted by bisection for each target quantile (see the
    module docstring for the error bound — a single summary or identical
    per-peer distributions read back their own knot values exactly).
    ``count``/``sum`` merge exactly; ``min``/``max`` are exact envelopes.
    """
    summaries = [s for s in summaries if s and float(s.get("count", 0) or 0) > 0]
    if not summaries:
        return {"count": 0.0, "sum": 0.0}
    total = sum(float(s["count"]) for s in summaries)
    merged: dict[str, float] = {
        "count": total,
        "sum": float(sum(float(s.get("sum", 0.0)) for s in summaries)),
        "min": min(float(s.get("min", math.inf)) for s in summaries),
        "max": max(float(s.get("max", -math.inf)) for s in summaries),
    }
    per_peer = [
        (float(s["count"]), _knots(s)) for s in summaries if _knots(s)
    ]
    if not per_peer:
        return merged

    def pooled_cdf(v: float) -> float:
        return (
            sum(c * _cdf_at(k, v) for c, k in per_peer) / total
        )

    lo0 = merged["min"]
    hi0 = merged["max"]
    for q in QUANTILES:
        lo, hi = lo0, hi0
        for _ in range(48):  # bisection to ~2^-48 of the value range
            mid = (lo + hi) / 2.0
            if pooled_cdf(mid) < q:
                lo = mid
            else:
                hi = mid
        merged[f"p{int(q * 100)}"] = hi
    return merged


class _Series:
    __slots__ = ("points", "cumulative")

    def __init__(self, capacity: int) -> None:
        self.points: deque[tuple[float, Any]] = deque(maxlen=capacity)
        self.cumulative = 0.0  # counters: running total of shipped deltas


class TimeSeriesStore:
    """Bounded per-peer / per-metric ring buffers with fleet rollups.

    Thread-safe: the collector ingests from the event loop while
    ``telemetry.top`` / the SLO watchdog snapshot from anywhere.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self._capacity = max(int(capacity), 2)
        self._lock = threading.Lock()
        # (peer, metric) -> ring of (t_wall, value)
        self._gauges: dict[tuple[str, str], _Series] = {}
        # (peer, metric) -> ring of (t_wall, summary dict)
        self._summaries: dict[tuple[str, str], _Series] = {}
        # metric -> peer -> {round: value} (training-quality curves)
        self._quality: dict[str, dict[str, dict[int, float]]] = {}
        self._last_seen: dict[str, float] = {}
        self._round_seen: dict[int, float] = {}  # round -> first report t

    # ------------------------------------------------------------- ingest
    def note_peer(self, peer: str, t: float | None = None) -> None:
        with self._lock:
            self._last_seen[str(peer)] = time.time() if t is None else t

    def note_round(self, round_num: int, t: float | None = None) -> None:
        """First sighting of a round (feeds the round-wall SLO series)."""
        t = time.time() if t is None else t
        with self._lock:
            self._round_seen.setdefault(int(round_num), t)

    def record_gauge(
        self, peer: str, metric: str, value: float, t: float | None = None
    ) -> None:
        t = time.time() if t is None else t
        key = (str(peer), str(metric))
        with self._lock:
            series = self._gauges.get(key)
            if series is None:
                series = self._gauges[key] = _Series(self._capacity)
            series.points.append((t, float(value)))
            self._last_seen[key[0]] = max(self._last_seen.get(key[0], 0.0), t)

    def record_delta(
        self,
        peer: str,
        metric: str,
        delta: float,
        interval_s: float,
        t: float | None = None,
    ) -> None:
        """One counter delta: stores the per-interval RATE as the gauge
        point and keeps the cumulative total queryable."""
        t = time.time() if t is None else t
        key = (str(peer), str(metric))
        rate = float(delta) / interval_s if interval_s > 0 else float(delta)
        with self._lock:
            series = self._gauges.get(key)
            if series is None:
                series = self._gauges[key] = _Series(self._capacity)
            series.cumulative += float(delta)
            series.points.append((t, rate))
            self._last_seen[key[0]] = max(self._last_seen.get(key[0], 0.0), t)

    def record_summary(
        self, peer: str, metric: str, summary: dict, t: float | None = None
    ) -> None:
        t = time.time() if t is None else t
        key = (str(peer), str(metric))
        with self._lock:
            series = self._summaries.get(key)
            if series is None:
                series = self._summaries[key] = _Series(self._capacity)
            series.points.append((t, dict(summary)))

    def record_quality(
        self, peer: str, metric: str, round_num: int, value: float
    ) -> None:
        with self._lock:
            self._quality.setdefault(str(metric), {}).setdefault(
                str(peer), {}
            )[int(round_num)] = float(value)

    # -------------------------------------------------------------- reads
    def peers(self) -> list[str]:
        with self._lock:
            return sorted(self._last_seen)

    def metrics(self, peer: str | None = None) -> list[str]:
        with self._lock:
            names = {
                m
                for (p, m) in (*self._gauges, *self._summaries)
                if peer is None or p == peer
            }
        return sorted(names)

    def latest(self, peer: str, metric: str) -> float | None:
        with self._lock:
            series = self._gauges.get((str(peer), str(metric)))
            if series is None or not series.points:
                return None
            return float(series.points[-1][1])

    def cumulative(self, peer: str, metric: str) -> float:
        with self._lock:
            series = self._gauges.get((str(peer), str(metric)))
            return series.cumulative if series is not None else 0.0

    def series(self, peer: str, metric: str) -> list[tuple[float, float]]:
        with self._lock:
            series = self._gauges.get((str(peer), str(metric)))
            return list(series.points) if series is not None else []

    def last_seen(self, peer: str) -> float | None:
        with self._lock:
            return self._last_seen.get(str(peer))

    def silent_for(self, peer: str, now: float | None = None) -> float:
        """Seconds since the peer's last report (inf = never reported)."""
        now = time.time() if now is None else now
        seen = self.last_seen(peer)
        return math.inf if seen is None else max(now - seen, 0.0)

    # ------------------------------------------------------------ rollups
    def fleet_last(self, metric: str) -> dict[str, float]:
        """peer -> latest value of ``metric`` (the per-peer rollup base)."""
        out: dict[str, float] = {}
        with self._lock:
            for (p, m), series in self._gauges.items():
                if m == metric and series.points:
                    out[p] = float(series.points[-1][1])
        return out

    def fleet_sum(self, metric: str) -> float:
        return float(sum(self.fleet_last(metric).values()))

    def fleet_max(self, metric: str) -> float:
        vals = self.fleet_last(metric)
        return float(max(vals.values())) if vals else 0.0

    def fleet_cumulative(self, metric: str) -> float:
        with self._lock:
            return float(
                sum(
                    s.cumulative
                    for (p, m), s in self._gauges.items()
                    if m == metric
                )
            )

    def average_rate(self, peer: str, metric: str) -> float | None:
        """Cumulative shipped deltas / observed wall — the steady-state
        rate of a counter series, immune to one quiet final interval."""
        with self._lock:
            series = self._gauges.get((str(peer), str(metric)))
            if series is None or len(series.points) < 2:
                return None
            span = series.points[-1][0] - series.points[0][0]
            if span <= 0:
                return None
            return series.cumulative / span

    def fleet_average_rate(self, metric: str) -> dict[str, float]:
        out: dict[str, float] = {}
        for peer in self.peers():
            rate = self.average_rate(peer, metric)
            if rate is not None:
                out[peer] = rate
        return out

    def fleet_peak(self, metric: str) -> dict[str, float]:
        """peer -> max recorded point of ``metric``.

        The rollup that separates a bandwidth-capped link from its idle
        siblings: a blocking round drags every peer's AVERAGE down to the
        straggler's pace (everyone waits), but only the capped peer's
        burst rate never exceeds its cap.
        """
        out: dict[str, float] = {}
        with self._lock:
            for (p, m), series in self._gauges.items():
                if m == metric and series.points:
                    out[p] = float(max(v for _t, v in series.points))
        return out

    def fleet_quantiles(self, metric: str) -> dict:
        """Quantile-merge the newest per-peer summaries of ``metric``."""
        with self._lock:
            latest = [
                series.points[-1][1]
                for (p, m), series in self._summaries.items()
                if m == metric and series.points
            ]
        return merge_summaries(latest)

    def outlier(
        self,
        metric: str,
        min_ratio: float = 3.0,
        values: dict[str, float] | None = None,
    ) -> tuple[str, float] | None:
        """The peer whose latest ``metric`` deviates most from the fleet
        median — ``None`` unless it deviates by at least ``min_ratio``
        (multiplicatively for all-positive gauges like bandwidth, where a
        bw-capped link sits orders of magnitude under its siblings).
        ``values`` substitutes another per-peer rollup (e.g.
        :meth:`fleet_average_rate`) for the latest-value one.
        """
        vals = dict(values) if values is not None else self.fleet_last(metric)
        if len(vals) < 2:
            return None
        ordered = sorted(vals.values())
        median = ordered[len(ordered) // 2]
        best: tuple[str, float] | None = None
        best_score = 0.0
        for peer, v in vals.items():
            if median > 0 and v > 0:
                score = max(v / median, median / v)
            else:
                spread = (ordered[-1] - ordered[0]) or 1.0
                score = 1.0 + abs(v - median) / spread * min_ratio
            if score > best_score:
                best_score = score
                best = (peer, v)
        if best is None or best_score < min_ratio:
            return None
        return best

    # ------------------------------------------------------ quality curves
    def quality_series(self, metric: str) -> dict[str, dict[int, float]]:
        """peer -> {round: value} for one training-quality metric."""
        with self._lock:
            return {
                p: dict(rounds)
                for p, rounds in self._quality.get(str(metric), {}).items()
            }

    def quality_rounds(self, metric: str) -> dict[int, dict[str, float]]:
        """round -> {peer: value} (the loss-curve orientation)."""
        out: dict[int, dict[str, float]] = {}
        for peer, rounds in self.quality_series(metric).items():
            for r, v in rounds.items():
                out.setdefault(r, {})[peer] = v
        return dict(sorted(out.items()))

    def round_walls(self) -> dict[int, float]:
        """round -> wall seconds between its first report and the next
        round's (the SLO watchdog's ``round_wall_s`` source)."""
        with self._lock:
            seen = sorted(self._round_seen.items())
        return {
            r0: t1 - t0 for (r0, t0), (_r1, t1) in zip(seen, seen[1:])
        }

    def open_round_age(self, now: float | None = None) -> float:
        """Seconds since the NEWEST round was first sighted — the age of
        the round currently open (0 before any round). A hung round shows
        up here, never in :meth:`round_walls`."""
        now = time.time() if now is None else now
        with self._lock:
            if not self._round_seen:
                return 0.0
            return max(now - max(self._round_seen.values()), 0.0)

    # ----------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        """One JSON-safe view (``telemetry.top`` and MetricsQuery)."""
        with self._lock:
            gauges: dict[str, dict[str, float]] = {}
            for (p, m), series in self._gauges.items():
                if series.points:
                    gauges.setdefault(p, {})[m] = float(series.points[-1][1])
            summaries: dict[str, dict[str, dict]] = {}
            for (p, m), series in self._summaries.items():
                if series.points:
                    summaries.setdefault(p, {})[m] = dict(series.points[-1][1])
            quality = {
                m: {
                    p: {str(r): v for r, v in sorted(rounds.items())}
                    for p, rounds in peers.items()
                }
                for m, peers in self._quality.items()
            }
            last_seen = dict(self._last_seen)
            rounds_seen = {str(r): t for r, t in sorted(self._round_seen.items())}
        return {
            "gauges": gauges,
            "summaries": summaries,
            "quality": quality,
            "last_seen": last_seen,
            "rounds_seen": rounds_seen,
        }


# ---------------------------------------------------------------------------
# Exporters
# ---------------------------------------------------------------------------

def _prom_name(metric: str) -> str:
    out = []
    for ch in metric:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    name = "".join(out)
    return name if not name[:1].isdigit() else f"_{name}"


def prometheus_text(store: TimeSeriesStore) -> str:
    """Prometheus exposition-format dump of the store's latest values.

    Gauges render with a ``peer`` label; reservoir summaries render as
    ``<name>{peer=...,quantile=...}`` plus ``_count``/``_sum`` (the
    classic summary type); quality curves render their latest round.
    """
    lines: list[str] = []
    snap = store.snapshot()
    by_metric: dict[str, dict[str, float]] = {}
    for peer, metrics in snap["gauges"].items():
        for m, v in metrics.items():
            by_metric.setdefault(m, {})[peer] = v
    for metric in sorted(by_metric):
        name = _prom_name(metric)
        lines.append(f"# TYPE {name} gauge")
        for peer, v in sorted(by_metric[metric].items()):
            lines.append(f'{name}{{peer="{peer}"}} {v:g}')
    sum_by_metric: dict[str, dict[str, dict]] = {}
    for peer, metrics in snap["summaries"].items():
        for m, s in metrics.items():
            sum_by_metric.setdefault(m, {})[peer] = s
    for metric in sorted(sum_by_metric):
        name = _prom_name(metric)
        lines.append(f"# TYPE {name} summary")
        for peer, s in sorted(sum_by_metric[metric].items()):
            for q in QUANTILES:
                key = f"p{int(q * 100)}"
                if key in s:
                    lines.append(
                        f'{name}{{peer="{peer}",quantile="{q:g}"}} {s[key]:g}'
                    )
            lines.append(f'{name}_count{{peer="{peer}"}} {s.get("count", 0):g}')
            lines.append(f'{name}_sum{{peer="{peer}"}} {s.get("sum", 0):g}')
    for metric, peers in sorted(snap["quality"].items()):
        name = _prom_name(f"quality.{metric}")
        lines.append(f"# TYPE {name} gauge")
        for peer, rounds in sorted(peers.items()):
            if not rounds:
                continue
            last_round = max(rounds, key=int)
            lines.append(
                f'{name}{{peer="{peer}",round="{last_round}"}} '
                f"{rounds[last_round]:g}"
            )
    return "\n".join(lines) + "\n" if lines else ""


def to_otlp_metrics(store: TimeSeriesStore, resource: dict | None = None) -> dict:
    """OTLP/JSON ``resourceMetrics`` for the store's latest values —
    the same shape :class:`~hypha_tpu.telemetry.otlp.OtlpJsonExporter`
    posts, ingestible by any OTEL collector."""
    from .otlp import _attr_list

    now = str(time.time_ns())
    snap = store.snapshot()
    metrics: list[dict] = []
    by_metric: dict[str, dict[str, float]] = {}
    for peer, peer_metrics in snap["gauges"].items():
        for m, v in peer_metrics.items():
            by_metric.setdefault(m, {})[peer] = v
    for metric, peers in sorted(by_metric.items()):
        metrics.append(
            {
                "name": metric,
                "gauge": {
                    "dataPoints": [
                        {
                            "asDouble": v,
                            "timeUnixNano": now,
                            "attributes": _attr_list({"peer": peer}),
                        }
                        for peer, v in sorted(peers.items())
                    ]
                },
            }
        )
    for metric, peers in sorted(snap["quality"].items()):
        metrics.append(
            {
                "name": f"hypha.quality.{metric}",
                "gauge": {
                    "dataPoints": [
                        {
                            "asDouble": v,
                            "timeUnixNano": now,
                            "attributes": _attr_list(
                                {"peer": peer, "round": int(r)}
                            ),
                        }
                        for peer, rounds in sorted(peers.items())
                        for r, v in sorted(rounds.items(), key=lambda kv: int(kv[0]))
                    ]
                },
            }
        )
    return {
        "resourceMetrics": [
            {
                "resource": {
                    "attributes": _attr_list(
                        resource or {"service.name": "hypha"}
                    )
                },
                "scopeMetrics": [
                    {"scope": {"name": "hypha.metrics_plane"}, "metrics": metrics}
                ],
            }
        ]
    }
