"""Cross-peer round tracing: the Dapper-style span plane for outer rounds.

A DiLoCo outer round is a distributed request — scheduler opens the round,
workers run ``inner_steps`` → ``encode`` → ``upload``, the parameter server
runs ``fold`` / ``quorum_wait`` / ``outer_step`` / ``broadcast``, workers
``merge`` — and this module is the propagation fabric that lets every node
file its spans under ONE trace per round:

  * the scheduler's per-round root span context travels as a
    ``<trace_id>-<parent_span_id>`` string (:data:`~hypha_tpu.messages.
    TRACEPARENT_KEY`) inside SCHEDULE_UPDATE responses, fabric push
    headers, and the round-tagged protocol messages — all None/absent by
    default, so tracing OFF ships today's exact wire bytes;
  * every node appends finished spans to ``spans-<node>.jsonl`` under the
    shared trace directory (one JSON object per line, wall + monotonic
    timestamps, the round/fragment/shard/peer/codec attribute vocabulary);
  * ``python -m hypha_tpu.telemetry.timeline <dir>`` merges the files,
    realigns per-node clocks on round anchors, and prints the per-round
    critical path.

The recorder is deliberately NOT the OTLP tracer in ``telemetry/__init__``:
that one is contextvar-scoped to ``with`` blocks on one thread, while round
spans here begin on one call path and finish on another (a collect loop, a
flight thread) and must serialize to per-node files for offline merge.
Records are file-backed so a crashed node's spans survive for forensics —
the complement of the flight recorder's in-memory ring.

Process-global switch: :func:`enable` (benches, tests) or the
``HYPHA_TRACE_DIR`` / ``HYPHA_TRACE_NODE`` environment (executor
subprocesses inherit tracing through their environment). Disabled, every
helper is a cheap no-op returning ``None`` — instrumentation sites never
branch on config themselves.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, IO

from ..messages import TRACEPARENT_KEY
from . import _rand_id
from .flight import _SAFE_NODE

__all__ = [
    "TRACEPARENT_KEY",
    "TraceSpan",
    "NodeTracing",
    "parse_traceparent",
    "enable",
    "disable",
    "active",
    "begin",
    "finish",
    "span",
    "inject",
    "traceparent_of",
    "reparent",
]

# Span names the round trace vocabulary uses (docs/observability.md):
# scheduler root; worker compute/ship/merge; PS aggregate/step/fan-out;
# serving route/prefill/decode. Kept here so the timeline tool and the
# docs share one list.
ROUND_SPANS = (
    "round",
    "inner_steps",
    "encode",
    "upload",
    "fold",
    "quorum_wait",
    "outer_step",
    "broadcast",
    "merge",
)
SERVE_SPANS = ("route", "prefill", "decode")

# One id generator for the whole telemetry package: os.urandom, NOT the
# global random module — deterministic chaos runs seed the global RNG,
# and seeded ids would collide across nodes in one merged timeline.
_rand_hex = _rand_id


def parse_traceparent(value: Any) -> tuple[str, str] | None:
    """``"<32-hex trace id>-<16-hex span id>"`` → the pair, else None.

    Malformed values (wrong length, non-hex, non-string — e.g. a peer
    running a different build) are treated as absent, never an error: a
    bad trace context must not break the data plane.
    """
    if not isinstance(value, str):
        return None
    trace_id, sep, span_id = value.partition("-")
    if not sep or len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16)
        int(span_id, 16)
    except ValueError:
        return None
    return trace_id, span_id


@dataclass(slots=True)
class TraceSpan:
    """One round-trace span; finished spans serialize to the node file."""

    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    node: str
    start_ns: int  # wall clock (time.time_ns)
    start_mono_ns: int  # monotonic (per-node skew-free durations)
    attributes: dict[str, Any] = field(default_factory=dict)
    end_ns: int | None = None
    end_mono_ns: int | None = None
    status_ok: bool = True

    @property
    def traceparent(self) -> str:
        return f"{self.trace_id}-{self.span_id}"

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def to_record(self) -> dict:
        return {
            "node": self.node,
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns if self.end_ns is not None else self.start_ns,
            "mono_start_ns": self.start_mono_ns,
            "mono_end_ns": (
                self.end_mono_ns
                if self.end_mono_ns is not None
                else self.start_mono_ns
            ),
            "ok": self.status_ok,
            "attrs": self.attributes,
        }


class NodeTracing:
    """Span recorder for one trace directory.

    Thread-safe: spans begin/finish from the event loop, training threads
    and stream flight threads alike. Each span is written as one line at
    finish time with an immediate flush, so a crash loses at most the
    spans still open — and a torn final line, which the timeline merger
    tolerates as clean EOF (the durable journal's torn-tail rule).

    ``node`` is the default identity stamped on spans; per-span overrides
    exist because the in-process bench harness runs every role in one
    process and each component labels its own spans (scheduler / psw / w0…).
    """

    def __init__(self, trace_dir: str | Path, node: str = "node") -> None:
        self.trace_dir = Path(trace_dir)
        self.trace_dir.mkdir(parents=True, exist_ok=True)
        self.node = str(node)
        self._lock = threading.Lock()
        self._files: dict[str, IO[str]] = {}
        self._closed = False

    # ------------------------------------------------------------- spans
    def begin(
        self,
        name: str,
        parent: "TraceSpan | str | None" = None,
        attrs: dict | None = None,
        node: str | None = None,
    ) -> TraceSpan:
        """Open a span. ``parent`` is a local span, a wire traceparent
        string, or None (starts a fresh trace)."""
        if isinstance(parent, TraceSpan):
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            parsed = parse_traceparent(parent)
            if parsed is not None:
                trace_id, parent_id = parsed
            else:
                trace_id, parent_id = _rand_hex(16), None
        return TraceSpan(
            name=name,
            trace_id=trace_id,
            span_id=_rand_hex(8),
            parent_id=parent_id,
            node=str(node) if node else self.node,
            start_ns=time.time_ns(),
            start_mono_ns=time.monotonic_ns(),
            attributes=dict(attrs or {}),
        )

    def finish(self, span: TraceSpan, ok: bool = True) -> TraceSpan:
        span.end_ns = time.time_ns()
        span.end_mono_ns = time.monotonic_ns()
        span.status_ok = span.status_ok and ok
        self._write(span)
        return span

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        parent: "TraceSpan | str | None" = None,
        attrs: dict | None = None,
        node: str | None = None,
    ):
        s = self.begin(name, parent=parent, attrs=attrs, node=node)
        try:
            yield s
        except BaseException:
            s.status_ok = False
            raise
        finally:
            self.finish(s)

    # --------------------------------------------------------------- io
    def _write(self, span: TraceSpan) -> None:
        line = json.dumps(span.to_record(), default=str) + "\n"
        with self._lock:
            if self._closed:
                return
            f = self._files.get(span.node)
            if f is None:
                safe = _SAFE_NODE.sub("-", span.node) or "node"
                path = self.trace_dir / f"spans-{safe}.jsonl"
                f = open(path, "a", encoding="utf-8")
                self._files[span.node] = f
            f.write(line)
            f.flush()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            for f in self._files.values():
                try:
                    f.close()
                except OSError:
                    pass
            self._files.clear()


# ---------------------------------------------------------------------------
# Process-global switch
# ---------------------------------------------------------------------------

_ACTIVE: NodeTracing | None = None
_ENV_CHECKED = False
_STATE_LOCK = threading.Lock()


def enable(trace_dir: str | Path, node: str = "node") -> NodeTracing:
    """Turn tracing on for this process, writing under ``trace_dir``."""
    global _ACTIVE, _ENV_CHECKED
    with _STATE_LOCK:
        if _ACTIVE is not None:
            _ACTIVE.close()
        _ACTIVE = NodeTracing(trace_dir, node)
        _ENV_CHECKED = True
        return _ACTIVE


def disable() -> None:
    global _ACTIVE, _ENV_CHECKED
    with _STATE_LOCK:
        if _ACTIVE is not None:
            _ACTIVE.close()
        _ACTIVE = None
        _ENV_CHECKED = True  # an explicit disable wins over the env


def _reset_for_tests() -> None:
    """Forget the cached env decision so monkeypatched env is re-read."""
    global _ACTIVE, _ENV_CHECKED
    with _STATE_LOCK:
        if _ACTIVE is not None:
            _ACTIVE.close()
        _ACTIVE = None
        _ENV_CHECKED = False


def active() -> NodeTracing | None:
    """The process recorder, or None when tracing is off (the default).

    The environment is consulted once: ``HYPHA_TRACE_DIR`` turns tracing
    on (``HYPHA_TRACE_NODE`` names this process's spans), which is how the
    process train executor inherits the bench's ``--trace`` flag.
    """
    global _ACTIVE, _ENV_CHECKED
    if _ENV_CHECKED:
        return _ACTIVE
    with _STATE_LOCK:
        if not _ENV_CHECKED:
            trace_dir = os.environ.get("HYPHA_TRACE_DIR")
            if trace_dir:
                _ACTIVE = NodeTracing(
                    trace_dir,
                    os.environ.get("HYPHA_TRACE_NODE", f"pid{os.getpid()}"),
                )
            _ENV_CHECKED = True
    return _ACTIVE


# ------------------------------------------------------------ no-op helpers


def begin(
    name: str,
    parent: "TraceSpan | str | None" = None,
    attrs: dict | None = None,
    node: str | None = None,
) -> TraceSpan | None:
    """Open a span iff tracing is on; None otherwise (pass to finish)."""
    t = active()
    if t is None:
        return None
    return t.begin(name, parent=parent, attrs=attrs, node=node)


def finish(span: "TraceSpan | None", ok: bool = True) -> None:
    if span is None:
        return
    t = active()
    if t is not None:
        t.finish(span, ok=ok)


@contextlib.contextmanager
def span(
    name: str,
    parent: "TraceSpan | str | None" = None,
    attrs: dict | None = None,
    node: str | None = None,
):
    """Context-managed span; yields None (and records nothing) when off."""
    t = active()
    if t is None:
        yield None
        return
    with t.span(name, parent=parent, attrs=attrs, node=node) as s:
        yield s


def inject(header: dict, context: "TraceSpan | str | None") -> dict:
    """Stamp a trace context into a push/broadcast header, in place.

    ``context`` None (tracing off, or no round context yet) leaves the
    header untouched — no new key, today's exact wire bytes.
    """
    if context is None:
        return header
    header[TRACEPARENT_KEY] = (
        context.traceparent if isinstance(context, TraceSpan) else str(context)
    )
    return header


def traceparent_of(span: "TraceSpan | None") -> str | None:
    return span.traceparent if span is not None else None


def reparent(span: "TraceSpan | None", context: "TraceSpan | str | None") -> None:
    """Late-bind an UNFINISHED, still-parentless span into a trace.

    The parameter server's quorum_wait span opens before any push of the
    round has arrived; the first delta's header then names the round's
    trace. Spans serialize at finish, so rewriting the ids before that is
    safe. A span that already has a parent keeps it.
    """
    if span is None or span.parent_id is not None:
        return
    parsed = parse_traceparent(
        context.traceparent if isinstance(context, TraceSpan) else context
    )
    if parsed is not None:
        span.trace_id, span.parent_id = parsed
