"""The live metrics plane: ``/hypha-metrics/0.0.1``.

PR 10 made the fleet *traceable after the fact*; this module makes it
*observable while it runs*. Every node periodically samples its process
metric registry (the FT/stream/shard/serve/het bundles plus its fabric
byte counters) into a compact :class:`MetricsReport` delta — counters as
deltas since the last report, gauges as last-value, reservoirs as
``{p50, p95, p99, max}`` summaries — and pushes it to the scheduler's
:class:`MetricsCollector`, which:

  * folds reports into a bounded per-peer/per-metric ring store
    (:class:`~hypha_tpu.telemetry.series.TimeSeriesStore`) with fleet
    rollups (sum / max / quantile-merge / outlier);
  * persists a round-stamped ``metrics-<job>.jsonl`` journal next to the
    trace spans (``benchmarks/convergence.py``'s future loss-curve feed);
  * evaluates declarative SLO rules (:mod:`hypha_tpu.telemetry.slo`),
    firing flight-recorder events and :class:`~hypha_tpu.telemetry.slo.
    SLOAdvisory` notices the orchestrator logs;
  * answers :class:`MetricsQuery` RPCs with a rollup snapshot — the feed
    for ``python -m hypha_tpu.telemetry.top <addr>``.

Training-quality series (inner loss EWMA, pseudo-gradient norms,
tokens/s) do NOT ride this protocol: workers already send round-tagged
METRICS progress and the PS round-tagged UPDATED notifies, so quality
points piggy-back those existing channels (gated by the same
``report_metrics_s`` config) and the orchestrator forwards them into the
collector via :meth:`MetricsCollector.ingest_quality` — loss curves
become first-class without a second round-tagged stream.

Reporting defaults OFF. Off ships byte-identical wire: the executor
configs' ``report_metrics_s``/``metrics_peer`` fields are None-default
(omitted from the wire), no node speaks ``/hypha-metrics`` and no
existing message or push header gains a key — pinned by the goldens in
tests/test_metrics_plane.py, the same discipline as tracing (PR 10) and
the adaptive fields (PR 8).
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable

from .. import aio
from ..messages import declare_protocol, register
from . import Counter
from .flight import _SAFE_NODE
from .series import TimeSeriesStore, summarize
from .slo import SLOWatchdog, parse_slo_rules

__all__ = [
    "PROTOCOL_METRICS",
    "MetricsReport",
    "MetricsAck",
    "MetricsQuery",
    "MetricsPage",
    "RegistrySampler",
    "MetricsReporter",
    "MetricsCollector",
    "DEFAULT_INTERVAL_S",
]

log = logging.getLogger("hypha.telemetry.metrics_plane")

PROTOCOL_METRICS = "/hypha-metrics/0.0.1"

DEFAULT_INTERVAL_S = 1.0


@register
@dataclass(slots=True)
class MetricsReport:
    """One node's periodic registry delta.

    Piggy-backs the peer/round/generation tags every other channel
    carries: ``round`` is the sender's current outer round (0 before the
    first), ``generation`` the scheduler generation it last adopted
    (None — the only value a never-restarted job ships — is omitted from
    the wire, the durable-control-plane discipline). ``seq`` is a
    per-reporter monotone so the collector can spot dropped reports.
    """

    job_id: str = ""
    peer: str = ""
    round: int = 0
    seq: int = 0
    interval_s: float = 0.0
    counters: dict = field(default_factory=dict)  # name -> delta
    gauges: dict = field(default_factory=dict)  # name -> last value
    summaries: dict = field(default_factory=dict)  # name -> summary dict
    generation: int | None = None


@register
@dataclass(slots=True)
class MetricsAck:
    ok: bool = True


@register
@dataclass(slots=True)
class MetricsQuery:
    """``telemetry.top`` → collector: hand me the rollup snapshot."""

    job_id: str = ""  # "" = whatever job the collector serves


@register
@dataclass(slots=True)
class MetricsPage:
    job_id: str = ""
    round: int = 0
    snapshot: dict = field(default_factory=dict)


declare_protocol(
    PROTOCOL_METRICS,
    "MetricsReport",
    "MetricsAck",
    "MetricsQuery",
    "MetricsPage",
)


# ---------------------------------------------------------------------------
# Sampling: process registry -> one report's worth of deltas
# ---------------------------------------------------------------------------


def _walk_counters(obj: Any, out: dict[str, Counter]) -> None:
    if isinstance(obj, Counter):
        out[obj.name] = obj
        return
    if isinstance(obj, dict):
        # list(): the lazy per-fragment/per-codec dicts are inserted into
        # by data-plane threads while the reporter samples — iterating
        # the live view would raise "dict changed size during iteration".
        for v in list(obj.values()):
            _walk_counters(v, out)


class RegistrySampler:
    """Samples the process metric surfaces into report-shaped deltas.

    * counters — every :class:`~hypha_tpu.telemetry.Counter` in the five
      shared bundles (including the lazily-created per-fragment/per-codec
      dicts), shipped as the delta since this sampler's last call;
    * gauges — the bundles' last-value state (queue depth, free blocks,
      bytes in flight, per-peer bandwidth/steps) plus this NODE's fabric
      byte counters (as deltas: the collector derives Mbit/s from them);
    * summaries — the serve latency reservoir compressed to
      ``{count, sum, min, max, p50, p95, p99}`` via
      :func:`~hypha_tpu.telemetry.series.summarize`.

    One process hosting several in-process nodes (the bench harness)
    shares one registry, so process-bundle values repeat across its
    reporters — per-NODE truth lives in the fabric byte counters, which
    is what the fleet bandwidth rollups read. Real deployments run one
    node per process and see no aliasing.
    """

    def __init__(self, node=None) -> None:
        self.node = node
        self._last: dict[str, float] = {}
        self._last_reservoir = 0

    def _delta(self, name: str, value: float) -> float:
        prev = self._last.get(name, 0.0)
        self._last[name] = value
        return max(value - prev, 0.0)

    def sample(self) -> tuple[dict, dict, dict]:
        from .ft_metrics import (
            FT_METRICS,
            HET_METRICS,
            SERVE_METRICS,
            SHARD_METRICS,
            STREAM_METRICS,
        )

        counters: dict[str, float] = {}
        found: dict[str, Counter] = {}
        for bundle in (
            FT_METRICS, STREAM_METRICS, SHARD_METRICS, SERVE_METRICS,
            HET_METRICS,
        ):
            _walk_counters(vars(bundle), found)
        for name, counter in found.items():
            delta = self._delta(name, float(counter.value()))
            if delta:
                counters[name] = delta
        if self.node is not None:
            for name, value in (
                ("node.bytes_in", float(self.node.bytes_in)),
                ("node.bytes_out", float(self.node.bytes_out)),
            ):
                # ALWAYS shipped, zero included: the collector derives
                # bandwidth gauges from these, and an omitted quiet
                # interval would freeze an idle peer's gauge at its last
                # burst rate forever.
                counters[name] = self._delta(name, value)
        gauges: dict[str, float] = {
            "hypha.serve.free_blocks": SERVE_METRICS.free_blocks(),
            "hypha.serve.queue_depth": SERVE_METRICS.queue_depth(),
            "hypha.stream.bytes_in_flight": STREAM_METRICS.bytes_in_flight(),
            "hypha.stream.overlap_fraction": STREAM_METRICS.overlap_fraction(),
        }
        het = HET_METRICS.snapshot()
        for peer, bps in het["bandwidth_bps"].items():
            gauges[f"hypha.het.bandwidth_bps.{peer}"] = float(bps)
        for peer, steps in het["assigned_steps"].items():
            gauges[f"hypha.het.assigned_steps.{peer}"] = float(steps)
        summaries: dict[str, dict] = {}
        with SERVE_METRICS._lock:
            latencies = list(SERVE_METRICS._latencies)
        # Re-ship when new requests FINISHED — judged by the histogram's
        # monotone count, never by the reservoir's length (the reservoir
        # is trimmed to a bounded window, so its length saturates while
        # traffic keeps flowing and quantiles keep moving).
        finished = self.request_count()
        if latencies and finished > self._last_reservoir:
            self._last_reservoir = finished
            summaries["hypha.serve.request_latency_ms"] = summarize(latencies)
        return counters, gauges, summaries

    @staticmethod
    def request_count() -> float:
        from .ft_metrics import SERVE_METRICS

        return float(SERVE_METRICS.request_latency_ms.snapshot()["count"])


# ---------------------------------------------------------------------------
# Reporter: one per node, pushes deltas to the collector
# ---------------------------------------------------------------------------


class MetricsReporter:
    """Periodic :class:`MetricsReport` push loop for one node.

    Failures are logged-and-dropped: the metrics plane must never stall
    or fail the data plane. ``round_fn``/``generation_fn`` late-bind the
    sender's current round / adopted scheduler generation (executors pass
    closures over their live execution state).
    """

    def __init__(
        self,
        node,
        collector_peer: str,
        job_id: str,
        peer: str | None = None,
        interval_s: float = DEFAULT_INTERVAL_S,
        sampler: RegistrySampler | None = None,
        round_fn: Callable[[], int] | None = None,
        generation_fn: Callable[[], int | None] | None = None,
    ) -> None:
        self.node = node
        self.collector_peer = collector_peer
        self.job_id = job_id
        self.peer = peer or getattr(node, "peer_id", "node")
        self.interval_s = max(float(interval_s), 0.05)
        self.sampler = sampler or RegistrySampler(node)
        self._round_fn = round_fn or (lambda: 0)
        self._generation_fn = generation_fn or (lambda: None)
        self._seq = 0
        self._last_t: float | None = None
        self._task: asyncio.Task | None = None
        self.sent = 0
        self.dropped = 0

    def start(self) -> "MetricsReporter":
        if self._task is None:
            self._task = aio.spawn(
                self._loop(), what=f"metrics reporter {self.peer}", logger=log
            )
        return self

    async def stop(self, flush: bool = True) -> None:
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            await aio.reap(task)
        if flush:
            # Final sample so a short job's tail (the last round's counters)
            # reaches the collector before the node tears down.
            await self._send_once()

    async def _loop(self) -> None:
        # First report immediately: a short job must appear in the store
        # before its first interval elapses.
        while True:
            try:
                await self._send_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                # One bad sample (a racing registry mutation, a hostile
                # gauge) must not kill the loop for the rest of the job —
                # a dead reporter reads as a silent node.
                log.exception("metrics sample from %s failed", self.peer)
            await asyncio.sleep(self.interval_s)

    async def _send_once(self) -> None:
        counters, gauges, summaries = self.sampler.sample()
        gen = self._generation_fn()
        # Stamp the MEASURED window, not the nominal cadence: a busy event
        # loop (jit compiles, big transfers) delays sends, and a delta
        # divided by the nominal interval would read as a burst that never
        # happened (rates, not deltas, are what the rollups compare).
        now = time.monotonic()
        elapsed = (
            self.interval_s
            if self._last_t is None
            else max(now - self._last_t, 1e-3)
        )
        self._last_t = now
        report = MetricsReport(
            job_id=self.job_id,
            peer=self.peer,
            round=int(self._round_fn() or 0),
            seq=self._seq,
            interval_s=elapsed,
            counters=counters,
            gauges=gauges,
            summaries=summaries,
            # Stamped only once a scheduler restart actually happened
            # (generation >= 2), the durable-control-plane discipline.
            generation=gen if gen is not None and gen >= 2 else None,
        )
        self._seq += 1
        try:
            await self.node.request(
                self.collector_peer, PROTOCOL_METRICS, report, timeout=10.0
            )
            self.sent += 1
        except asyncio.CancelledError:
            raise
        except Exception as e:  # metrics must never break the data plane
            self.dropped += 1
            log.debug("metrics report from %s dropped: %s", self.peer, e)


# ---------------------------------------------------------------------------
# Collector: scheduler-side aggregation + journal + SLO evaluation
# ---------------------------------------------------------------------------

# Slow tick for silence-flavored SLO rules: wall-clock must advance the
# watchdog even when no report arrives (that absence IS the signal).
_SWEEP_INTERVAL_S = 1.0


class MetricsCollector:
    """Aggregates the fleet's reports for one job.

    ``journal_dir`` — where ``metrics-<job>.jsonl`` lands (the trace
    directory when tracing is on; None disables the journal). One JSON
    object per line: report records (round-stamped per-peer deltas),
    quality records (the loss-curve feed), and SLO breach records.
    """

    def __init__(
        self,
        node,
        job_id: str,
        store: TimeSeriesStore | None = None,
        slo_rules=None,
        journal_dir: str | Path | None = None,
        on_advisory=None,
        round_fn: Callable[[], int] | None = None,
    ) -> None:
        self.node = node
        self.job_id = job_id
        self.store = store or TimeSeriesStore()
        self._round_fn = round_fn or (lambda: 0)
        self.watchdog = SLOWatchdog(
            parse_slo_rules(slo_rules),
            self.store,
            job_id=job_id,
            on_advisory=on_advisory,
            round_fn=self._round_fn,
        )
        self.journal_path: Path | None = None
        if journal_dir is not None:
            safe = _SAFE_NODE.sub("-", str(job_id)[:8]) or "job"
            self.journal_path = Path(journal_dir) / f"metrics-{safe}.jsonl"
        self._reg = None
        self._sweep_task: asyncio.Task | None = None
        self._journal_lock = None  # created lazily on the running loop
        self._journal_tasks: set[asyncio.Task] = set()
        self.reports = 0

    # ------------------------------------------------------------- wiring
    def start(self) -> "MetricsCollector":
        # Prefix match: executors report under their per-role job ids
        # (<base>-w0, <base>-ps2 …), all children of the collector's base
        # job id. An empty collector id accepts everything (tests).
        self._reg = (
            self.node.on(PROTOCOL_METRICS, MetricsReport)
            .match(
                lambda m: not self.job_id
                or not m.job_id
                or m.job_id.startswith(self.job_id)
            )
            .respond_with(self._on_report)
        )
        self._query_reg = (
            self.node.on(PROTOCOL_METRICS, MetricsQuery)
            .match(
                lambda m: not self.job_id
                or not m.job_id
                or m.job_id.startswith(self.job_id)
            )
            .respond_with(self._on_query)
        )
        self._sweep_task = aio.spawn(
            self._sweep(), what="metrics SLO sweep", logger=log
        )
        return self

    async def close(self) -> None:
        if self._reg is not None:
            self._reg.close()
            self._reg = None
        if getattr(self, "_query_reg", None) is not None:
            self._query_reg.close()
            self._query_reg = None
        task, self._sweep_task = self._sweep_task, None
        if task is not None:
            task.cancel()
            await aio.reap(task)
        if self._journal_tasks:
            # Spawned quality-journal appends must land before the caller
            # reads the file (never cancelled: a lost record is a gap in
            # the loss curve).
            await asyncio.gather(
                *list(self._journal_tasks), return_exceptions=True
            )

    async def _sweep(self) -> None:
        while True:
            await asyncio.sleep(_SWEEP_INTERVAL_S)
            # Edge-triggered advisories fire exactly once: a breach whose
            # edge lands on the sweep (silence rules' primary path — all
            # reporters dead) must reach the journal here or nowhere.
            now = time.time()
            for rec in self._slo_records(self.watchdog.check(now), now):
                await self._journal(rec)

    # ------------------------------------------------------------- ingest
    async def _on_report(self, peer: str, report: MetricsReport) -> MetricsAck:
        t = time.time()
        label = report.peer or peer
        store = self.store
        store.note_peer(label, t)
        if report.round:
            store.note_round(report.round, t)
        interval = float(report.interval_s or 0.0)
        for name, delta in report.counters.items():
            try:
                store.record_delta(label, str(name), float(delta), interval, t)
            except (TypeError, ValueError):
                continue
        # Derived link-rate gauges from the fabric byte deltas — what the
        # fleet bandwidth rollup (and the bw-cap outlier probe) reads.
        for raw, derived in (
            ("node.bytes_out", "node.bandwidth_out_mbps"),
            ("node.bytes_in", "node.bandwidth_in_mbps"),
        ):
            delta = report.counters.get(raw)
            if delta is not None and interval > 0:
                try:
                    store.record_gauge(
                        label, derived, float(delta) * 8.0 / 1e6 / interval, t
                    )
                except (TypeError, ValueError):
                    pass
        for name, value in report.gauges.items():
            try:
                store.record_gauge(label, str(name), float(value), t)
            except (TypeError, ValueError):
                continue
        for name, summary in report.summaries.items():
            if isinstance(summary, dict):
                store.record_summary(label, str(name), summary, t)
        # Deliberately unfenced: MetricsReport.generation is an
        # observability tag, and gap-free curves across kill/rejoin are
        # the product — dropping a stale generation's report would punch
        # holes in exactly the window an operator is staring at.
        self.reports += 1  # hypha-lint: disable=handler-mutates-before-guard
        await self._journal(
            {
                "type": "report",
                "t": t,
                "peer": label,
                "round": report.round,
                "seq": report.seq,
                # The measured window rides along so offline readers
                # (telemetry.top dir mode) reconstruct the same rates
                # and derived bandwidth gauges as the live store.
                "interval_s": interval,
                "counters": dict(report.counters),
                "gauges": dict(report.gauges),
                "summaries": dict(report.summaries),
            }
        )
        for rec in self._slo_records(self.watchdog.check(t), t):
            await self._journal(rec)
        return MetricsAck(ok=True)

    @staticmethod
    def _slo_records(advisories, t: float) -> list[dict]:
        return [
            {
                "type": "slo",
                "t": t,
                "rule": adv.rule,
                "peer": adv.peer,
                "value": adv.value,
                "threshold": adv.threshold,
                "round": adv.round,
                "breached": adv.breached,
            }
            for adv in advisories
        ]

    def ingest_quality(
        self, peer: str, round_num: int, metrics: dict
    ) -> None:
        """Round-tagged training-quality point from the progress channel
        (worker METRICS / PS UPDATED) — the loss-curve feed. Synchronous:
        called from the orchestrator's progress handler; the journal write
        is spawned off-loop."""
        t = time.time()
        self.store.note_peer(peer, t)
        self.store.note_round(round_num, t)
        clean: dict[str, float] = {}
        for name, value in (metrics or {}).items():
            try:
                v = float(value)
            except (TypeError, ValueError):
                continue
            clean[str(name)] = v
            self.store.record_quality(peer, str(name), round_num, v)
        records: list[dict] = []
        if clean and self.journal_path is not None:
            records.append(
                {
                    "type": "quality",
                    "t": t,
                    "peer": peer,
                    "round": int(round_num),
                    **clean,
                }
            )
        # Advisories whose EDGE happens on a quality ingest (a round-wall
        # rule tripping between reports) must reach the journal too, or
        # the offline SLO state diverges from what the live watchdog saw.
        records.extend(self._slo_records(self.watchdog.check(t), t))
        for rec in records:
            if self.journal_path is None:
                break
            try:
                aio.spawn(
                    self._journal(rec),
                    tasks=self._journal_tasks,
                    what="metrics quality journal",
                    logger=log,
                )
            except RuntimeError:  # no loop (sync tests)
                self._journal_sync(rec)

    def ingest_serve_load(
        self, backend: str, queue_depth: float, free_blocks: float
    ) -> None:
        """ServeLoad heartbeat relay from a ServingSupervisor sharing this
        scheduler node — serve queue depths join the same plane."""
        t = time.time()
        self.store.record_gauge(backend, "hypha.serve.queue_depth", queue_depth, t)
        self.store.record_gauge(backend, "hypha.serve.free_blocks", free_blocks, t)

    # ------------------------------------------------------------ queries
    async def _on_query(self, peer: str, query: MetricsQuery) -> MetricsPage:
        return MetricsPage(
            job_id=self.job_id,
            round=int(self._round_fn() or 0),
            snapshot={**self.store.snapshot(), "slo": self.watchdog.state()},
        )

    # ------------------------------------------------------------ journal
    def _journal_sync(self, record: dict) -> None:
        if self.journal_path is None:
            return
        try:
            self.journal_path.parent.mkdir(parents=True, exist_ok=True)
            with open(self.journal_path, "a", encoding="utf-8") as f:
                f.write(json.dumps(record, default=str) + "\n")
        except OSError as e:
            log.warning("metrics journal write failed: %s", e)

    async def _journal(self, record: dict) -> None:
        if self.journal_path is None:
            return
        if self._journal_lock is None:
            self._journal_lock = asyncio.Lock()
        async with self._journal_lock:
            await asyncio.to_thread(self._journal_sync, record)
