"""Telemetry: tracing spans, metrics and OTLP export — self-contained.

Role parity with the reference's ``hypha-telemetry`` crate
(crates/telemetry/src/{tracing,logging,metrics}.rs + bandwidth.rs):

  * every binary wires providers at startup from config, with standard
    ``OTEL_*`` environment variables taking precedence
    (docs/worker.md:188-218; ``Env::prefixed("OTEL_")``);
  * traces use a parent-based ratio sampler;
  * metrics export on a 1-second interval (the binaries' setting);
  * transport bandwidth is instrumented per node
    (``hypha.bandwidth.inbound.bytes``/``outbound.bytes``).

The OTEL SDK is not available in this environment, so the subsystem is
implemented natively: spans/instruments record in-process and export over
OTLP/HTTP+JSON (the standard ``/v1/traces`` / ``/v1/metrics`` endpoints)
when an endpoint is configured; otherwise recording still works (tests
read it back via an injected exporter) and export is a no-op.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging as _pylog
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from .attributes import parse_attributes
from .otlp import OtlpJsonExporter

__all__ = [
    "Telemetry",
    "Tracer",
    "Span",
    "Meter",
    "Counter",
    "Histogram",
    "LogRecord",
    "LogBridge",
    "init_telemetry",
    "instrument_node",
    "global_telemetry",
    "metrics_snapshot",
    "parse_attributes",
    "OtlpJsonExporter",
]

log = _pylog.getLogger("hypha.telemetry")

# Reference binaries export metrics every second
# (crates/scheduler/src/bin/hypha-scheduler.rs metric reader interval).
METRIC_EXPORT_INTERVAL_S = 1.0

_current_span: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "hypha_current_span", default=None
)


def _rand_id(nbytes: int) -> str:
    # os.urandom, NOT the global random module: deterministic chaos runs
    # (ft/chaos.py) seed the global RNG, which would make trace/span ids
    # deterministic — and collide across nodes in one merged timeline.
    return os.urandom(nbytes).hex()


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    start_ns: int
    attributes: dict[str, Any] = field(default_factory=dict)
    end_ns: int | None = None
    status_ok: bool = True
    sampled: bool = True

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def record_error(self, err: BaseException) -> None:
        self.status_ok = False
        self.attributes["error.type"] = type(err).__name__
        self.attributes["error.message"] = str(err)


@dataclass
class LogRecord:
    """One exported log record (OTLP LogRecord shape)."""

    scope: str
    time_ns: int
    severity_number: int
    severity_text: str
    body: str
    attributes: dict[str, Any] = field(default_factory=dict)
    trace_id: str | None = None
    span_id: str | None = None


# Python logging levels -> OTLP severity numbers (spec table).
_SEVERITY = {
    _pylog.DEBUG: (5, "DEBUG"),
    _pylog.INFO: (9, "INFO"),
    _pylog.WARNING: (13, "WARN"),
    _pylog.ERROR: (17, "ERROR"),
    _pylog.CRITICAL: (21, "FATAL"),
}


def _severity_for(levelno: int) -> tuple[int, str]:
    for lvl in sorted(_SEVERITY, reverse=True):
        if levelno >= lvl:
            return _SEVERITY[lvl]
    return 1, "TRACE"


class LogBridge(_pylog.Handler):
    """Bridges Python ``logging`` records into the OTLP log export, the way
    the reference's tracing layer forwards events to its OTLP log provider
    (crates/telemetry/src/logging.rs). Records are correlated with the
    context's current span (traceId/spanId) when one is active."""

    def __init__(self, telemetry: "Telemetry", level: int = _pylog.INFO) -> None:
        super().__init__(level)
        self._telemetry = telemetry

    def emit(self, record: _pylog.LogRecord) -> None:
        try:
            num, text = _severity_for(record.levelno)
            span = _current_span.get()
            attrs: dict[str, Any] = {
                "code.function": record.funcName,
                "code.filepath": record.pathname,
                "code.lineno": record.lineno,
            }
            if record.exc_info and record.exc_info[0] is not None:
                attrs["exception.type"] = record.exc_info[0].__name__
                attrs["exception.message"] = str(record.exc_info[1])
            self._telemetry._record_log(
                LogRecord(
                    scope=record.name,
                    time_ns=int(record.created * 1e9),
                    severity_number=num,
                    severity_text=text,
                    body=record.getMessage(),
                    attributes=attrs,
                    trace_id=span.trace_id if span is not None else None,
                    span_id=span.span_id if span is not None else None,
                )
            )
        except Exception:  # a logging handler must never raise
            self.handleError(record)


class Tracer:
    def __init__(self, scope: str, telemetry: "Telemetry") -> None:
        self.scope = scope
        self._telemetry = telemetry

    @contextlib.contextmanager
    def span(self, name: str, attributes: dict | None = None):
        """Start a span as a child of the context's current span.

        Sampling is parent-based with a configured ratio for roots
        (docs/worker.md:195-199 ``parentbased_traceidratio``)."""
        parent = _current_span.get()
        if parent is not None:
            trace_id = parent.trace_id
            parent_id = parent.span_id
            sampled = parent.sampled
        else:
            trace_id = _rand_id(16)
            parent_id = None
            sampled = random.random() < self._telemetry.sample_ratio
        span = Span(
            name=name,
            trace_id=trace_id,
            span_id=_rand_id(8),
            parent_id=parent_id,
            start_ns=time.time_ns(),
            attributes=dict(attributes or {}),
            sampled=sampled,
        )
        token = _current_span.set(span)
        try:
            yield span
        except BaseException as e:
            span.record_error(e)
            raise
        finally:
            span.end_ns = time.time_ns()
            _current_span.reset(token)
            if span.sampled:
                self._telemetry._record_span(self.scope, span)


class Counter:
    """Monotonic sum instrument."""

    def __init__(self, name: str, unit: str = "") -> None:
        self.name = name
        self.unit = unit
        self._value = 0.0
        self._lock = threading.Lock()

    def add(self, amount: float, **_attrs) -> None:
        if amount < 0:
            raise ValueError("counter increments must be non-negative")
        with self._lock:
            # float() here, not at read time: a numpy/jax scalar increment
            # would otherwise promote the accumulator to np.float32 and
            # leak a non-JSON-serializable scalar into every snapshot
            # (pinned by the metrics_snapshot JSON-safety property test).
            self._value += float(amount)

    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-boundary histogram instrument."""

    DEFAULT_BOUNDS = (1, 5, 10, 50, 100, 500, 1000, 5000, 10000)

    def __init__(self, name: str, unit: str = "", bounds: tuple = DEFAULT_BOUNDS):
        self.name = name
        self.unit = unit
        self.bounds = tuple(bounds)
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def record(self, value: float, **_attrs) -> None:
        value = float(value)  # numpy/jax scalars must not taint the sum
        with self._lock:
            self._sum += value
            self._count += 1
            for i, b in enumerate(self.bounds):
                if value <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "sum": self._sum,
                "count": self._count,
                "bucket_counts": list(self._counts),
                "bounds": list(self.bounds),
            }


class Meter:
    def __init__(self, scope: str, telemetry: "Telemetry") -> None:
        self.scope = scope
        self._telemetry = telemetry

    def counter(self, name: str, unit: str = "") -> Counter:
        return self._telemetry._instrument(self.scope, name, lambda: Counter(name, unit))

    def histogram(self, name: str, unit: str = "", bounds=Histogram.DEFAULT_BOUNDS) -> Histogram:
        return self._telemetry._instrument(
            self.scope, name, lambda: Histogram(name, unit, bounds)
        )

    def observable_gauge(self, name: str, callback: Callable[[], float], unit: str = "") -> None:
        self._telemetry._gauges[(self.scope, name)] = (callback, unit)

    def remove_gauges(self) -> None:
        """Drop every observable gauge under this meter's scope — called at
        node teardown so the registry (and its callback closures over the
        node) does not outlive the fabric it instruments."""
        for key in [k for k in self._telemetry._gauges if k[0] == self.scope]:
            del self._telemetry._gauges[key]


class Telemetry:
    """Provider bundle: tracers, meters, the export loop, shutdown.

    The reference initializes three OTLP providers per binary
    (hypha-scheduler.rs:55-94); here one object owns all three concerns.
    """

    def __init__(
        self,
        service_name: str = "hypha",
        endpoint: str = "",
        sample_ratio: float = 1.0,
        attributes: dict | None = None,
        exporter=None,
        export_interval: float = METRIC_EXPORT_INTERVAL_S,
    ) -> None:
        self.service_name = service_name
        self.sample_ratio = sample_ratio
        self.resource = {"service.name": service_name, **(attributes or {})}
        self.exporter = exporter or (
            OtlpJsonExporter(endpoint, self.resource) if endpoint else None
        )
        self._instruments: dict[tuple[str, str], Any] = {}
        self._gauges: dict[tuple[str, str], tuple[Callable[[], float], str]] = {}
        self._spans: list[tuple[str, Span]] = []
        self._logs: list[LogRecord] = []
        self._log_handlers: list[LogBridge] = []
        self._lock = threading.Lock()
        self._export_interval = export_interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if self.exporter is not None:
            self._thread = threading.Thread(
                target=self._export_loop, name="hypha-telemetry", daemon=True
            )
            self._thread.start()

    # -- factories ----------------------------------------------------------
    def tracer(self, scope: str) -> Tracer:
        return Tracer(scope, self)

    def meter(self, scope: str) -> Meter:
        return Meter(scope, self)

    # -- recording ----------------------------------------------------------
    def _instrument(self, scope: str, name: str, factory):
        key = (scope, name)
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                inst = factory()
                self._instruments[key] = inst
            return inst

    def _record_span(self, scope: str, span: Span) -> None:
        with self._lock:
            self._spans.append((scope, span))
            # Bound memory if no exporter drains the buffer.
            if len(self._spans) > 4096:
                del self._spans[: len(self._spans) - 4096]

    def _record_log(self, record: LogRecord) -> None:
        with self._lock:
            self._logs.append(record)
            if len(self._logs) > 4096:
                del self._logs[: len(self._logs) - 4096]

    def attach_logging(
        self, logger: str = "", level: int = _pylog.INFO
    ) -> LogBridge:
        """Install the OTLP log bridge on ``logger`` (default: root), so
        ordinary ``logging`` calls flow to the collector alongside spans and
        metrics — the reference's logging provider role
        (crates/telemetry/src/logging.rs)."""
        handler = LogBridge(self, level)
        _pylog.getLogger(logger).addHandler(handler)
        self._log_handlers.append(handler)
        return handler

    # -- export -------------------------------------------------------------
    def _drain(self) -> tuple[list, dict, dict, list]:
        with self._lock:
            spans = self._spans
            self._spans = []
            logs = self._logs
            self._logs = []
            instruments = dict(self._instruments)
        gauges = {}
        for key, (cb, unit) in list(self._gauges.items()):
            try:
                gauges[key] = (cb(), unit)
            except Exception as e:
                # A raising gauge callback (e.g. reading state mid-teardown)
                # must not kill the export thread or mask shutdown errors.
                log.warning("observable gauge %s raised: %s", key, e)
        return spans, instruments, gauges, logs

    def flush(self) -> None:
        if self.exporter is None:
            return
        spans, instruments, gauges, logs = self._drain()
        try:
            if spans:
                self.exporter.export_spans(spans)
            self.exporter.export_metrics(instruments, gauges)
            if logs and hasattr(self.exporter, "export_logs"):
                self.exporter.export_logs(logs)
        except Exception as e:  # export must never break the node
            log.warning("telemetry export failed: %s", e)

    def _export_loop(self) -> None:
        while not self._stop.wait(self._export_interval):
            self.flush()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._log_handlers:
            loggers = [
                lg
                for lg in list(_pylog.Logger.manager.loggerDict.values())
                if isinstance(lg, _pylog.Logger)
            ] + [_pylog.getLogger()]
            for handler in self._log_handlers:
                for lg in loggers:
                    if handler in lg.handlers:
                        lg.removeHandler(handler)
            self._log_handlers.clear()
        self.flush()

    # -- test/introspection --------------------------------------------------
    def finished_spans(self) -> list[tuple[str, Span]]:
        with self._lock:
            return list(self._spans)


def init_telemetry(
    service_name: str = "hypha",
    endpoint: str = "",
    sample_ratio: float = 1.0,
    attributes: str | dict | None = None,
    exporter=None,
) -> Telemetry:
    """Build the provider bundle; standard ``OTEL_*`` env vars win over the
    passed config (reference: ``Env::prefixed("OTEL_")`` layered last)."""
    endpoint = os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT", endpoint)
    service_name = os.environ.get("OTEL_SERVICE_NAME", service_name)
    ratio_env = os.environ.get("OTEL_TRACES_SAMPLER_ARG")
    if ratio_env:
        try:
            sample_ratio = float(ratio_env)
        except ValueError:
            log.warning("bad OTEL_TRACES_SAMPLER_ARG %r ignored", ratio_env)
    attrs = parse_attributes(attributes) if isinstance(attributes, str) else dict(attributes or {})
    env_attrs = os.environ.get("OTEL_RESOURCE_ATTRIBUTES")
    if env_attrs:
        attrs.update(parse_attributes(env_attrs))
    telemetry = Telemetry(
        service_name=service_name,
        endpoint=endpoint,
        sample_ratio=sample_ratio,
        attributes=attrs,
        exporter=exporter,
    )
    if telemetry.exporter is not None:
        # Logs flow to the same collector as spans/metrics — the reference
        # installs its log provider globally at binary startup
        # (crates/telemetry/src/logging.rs).
        telemetry.attach_logging()
    return telemetry


def instrument_node(meter: Meter, node) -> None:
    """Bandwidth instrumentation: observable counters over the node's
    transport byte counters (the reference wraps the muxer —
    crates/telemetry/src/bandwidth.rs:30-62; our fabric counts in
    _CountingStream and the frame layer)."""
    meter.observable_gauge(
        "hypha.bandwidth.inbound.bytes", lambda: float(node.bytes_in), unit="By"
    )
    meter.observable_gauge(
        "hypha.bandwidth.outbound.bytes", lambda: float(node.bytes_out), unit="By"
    )


# Process-global provider: components that create fabrics WITHOUT going
# through a cli.py entrypoint (worker runtimes hosting PS shards, serving
# workers, bench harnesses) register their bandwidth gauges here, so one
# snapshot sees every fabric in the process. No exporter: recording only —
# init_telemetry stays the export-wired path for real deployments.
_GLOBAL: "Telemetry | None" = None
_GLOBAL_LOCK = threading.Lock()


def global_telemetry() -> Telemetry:
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = Telemetry(service_name="hypha", exporter=None)
        return _GLOBAL


def metrics_snapshot() -> dict:
    """One JSON-safe snapshot of every process metrics surface: the FT /
    stream / shard / serve / heterogeneity bundles plus the global
    registry's observable gauges (per-node bandwidth among them). This is
    what ``bench.py`` dumps next to every ``*BENCH_*.json`` artifact so
    future benches get metrics without bespoke plumbing."""
    gauges: dict[str, float] = {}
    telemetry = global_telemetry()
    for (scope, name), (cb, _unit) in sorted(telemetry._gauges.items()):
        try:
            gauges[f"{scope}/{name}"] = float(cb())
        except Exception:  # a torn-down node's gauge must not kill the dump
            continue
    return {
        "ft": FT_METRICS.snapshot(),
        "stream": STREAM_METRICS.snapshot(),
        "shard": SHARD_METRICS.snapshot(),
        "serve": SERVE_METRICS.snapshot(),
        "het": HET_METRICS.snapshot(),
        "scale": SCALE_METRICS.snapshot(),
        "data": DATA_METRICS.snapshot(),
        "gauges": gauges,
        "aio_task_failures": _aio_task_failures(),
    }


def _aio_task_failures() -> float:
    from ..aio import TASK_FAILURES  # lazy: aio imports this package

    return TASK_FAILURES.value()


# Fault-tolerance instruments (import at the bottom: ft_metrics uses the
# Counter/Histogram classes defined above).
from .ft_metrics import (  # noqa: E402
    DATA_METRICS,
    FT_METRICS,
    HET_METRICS,
    SCALE_METRICS,
    SERVE_METRICS,
    SHARD_METRICS,
    STREAM_METRICS,
    FTMetrics,
    ServeMetrics,
)

__all__ += [
    "FT_METRICS",
    "FTMetrics",
    "SCALE_METRICS",
    "SERVE_METRICS",
    "ServeMetrics",
]
