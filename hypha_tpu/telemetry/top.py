"""``python -m hypha_tpu.telemetry.top <addr|dir>`` — live fleet view.

A terminal ``top`` for a running job: per-peer round progress, loss,
tokens/s, link bandwidth, serve queue depth / free blocks, and the SLO
state, refreshed in place.

Two sources:

  * ``<dir>``  — a directory holding the collector's
    ``metrics-<job>.jsonl`` journal (next to the trace spans). The tool
    re-reads the journal each refresh and rebuilds the same
    :class:`~hypha_tpu.telemetry.series.TimeSeriesStore` view offline —
    works on a finished run or over a shared filesystem.
  * ``<addr>`` — a live scheduler's listen address. The tool dials it,
    learns the peer id, and polls :class:`~hypha_tpu.telemetry.
    metrics_plane.MetricsQuery` for the collector's rollup snapshot.

``--once`` prints a single frame and exits (tests, scripting, piping);
``--json`` dumps the raw snapshot instead of the table.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import sys
import time
from pathlib import Path
from typing import Any

from .series import TimeSeriesStore
from .slo import SLOWatchdog  # noqa: F401  (re-exported shape in snapshots)

__all__ = ["snapshot_from_dir", "render", "main"]


# ------------------------------------------------------------------ sources


def snapshot_from_dir(path: Path) -> dict:
    """Rebuild a store snapshot from ``metrics-*.jsonl`` journals.

    Torn tails read as clean EOF (the durable-journal rule); SLO breach
    records reconstruct the breached set as of the journal's end.
    """
    from .timeline import load_jsonl

    store = TimeSeriesStore()
    breached: dict[str, bool] = {}
    breaches = 0
    for journal in sorted(Path(path).glob("metrics-*.jsonl")):
        for rec in load_jsonl(journal):
            kind = rec.get("type")
            t = float(rec.get("t", 0) or 0)
            peer = str(rec.get("peer", "") or "")
            if kind == "report":
                store.note_peer(peer, t)
                if rec.get("round"):
                    store.note_round(int(rec["round"]), t)
                try:
                    interval = float(rec.get("interval_s", 1.0) or 1.0)
                except (TypeError, ValueError):
                    interval = 1.0
                for name, delta in (rec.get("counters") or {}).items():
                    try:
                        store.record_delta(peer, name, float(delta), interval, t)
                    except (TypeError, ValueError):
                        continue
                # Same derived link-rate gauges as the live collector, so
                # the offline table's Mb/s columns match the live view.
                for raw, derived in (
                    ("node.bytes_out", "node.bandwidth_out_mbps"),
                    ("node.bytes_in", "node.bandwidth_in_mbps"),
                ):
                    delta = (rec.get("counters") or {}).get(raw)
                    if delta is not None and interval > 0:
                        try:
                            store.record_gauge(
                                peer, derived,
                                float(delta) * 8.0 / 1e6 / interval, t,
                            )
                        except (TypeError, ValueError):
                            pass
                for name, value in (rec.get("gauges") or {}).items():
                    try:
                        store.record_gauge(peer, name, float(value), t)
                    except (TypeError, ValueError):
                        continue
                for name, summary in (rec.get("summaries") or {}).items():
                    if isinstance(summary, dict):
                        store.record_summary(peer, name, summary, t)
            elif kind == "quality":
                store.note_round(int(rec.get("round", 0) or 0), t)
                for name, value in rec.items():
                    if name in ("type", "t", "peer", "round"):
                        continue
                    try:
                        store.record_quality(
                            peer, name, int(rec.get("round", 0) or 0),
                            float(value),
                        )
                    except (TypeError, ValueError):
                        continue
            elif kind == "slo":
                key = f"{rec.get('rule')}" + (f" [{peer}]" if peer else "")
                if rec.get("breached"):
                    breached[key] = True
                    breaches += 1
                else:
                    breached.pop(key, None)
    snap = store.snapshot()
    snap["slo"] = {
        "rules": [],
        "breached": sorted(k for k, v in breached.items() if v),
        "breaches": breaches,
    }
    return snap


async def snapshot_from_addr(addr: str, timeout: float = 10.0) -> dict:
    """Dial a live scheduler and fetch the collector's snapshot."""
    from ..network import Node, TcpTransport
    from .metrics_plane import PROTOCOL_METRICS, MetricsPage, MetricsQuery

    node = Node(TcpTransport(), peer_id=f"top-{int(time.time() * 1e3) & 0xFFFF}")
    await node.start(["127.0.0.1:0"])
    try:
        peer = await node.dial(addr)
        page = await node.request(
            peer, PROTOCOL_METRICS, MetricsQuery(), timeout=timeout
        )
        if not isinstance(page, MetricsPage):
            raise RuntimeError(f"unexpected reply {type(page).__name__}")
        return dict(page.snapshot)
    finally:
        await node.stop()


# ---------------------------------------------------------------- rendering


def _fmt(v: Any, digits: int = 3) -> str:
    if v is None:
        return "-"
    try:
        f = float(v)
    except (TypeError, ValueError):
        return str(v)
    if math.isnan(f):
        return "-"
    if f and (abs(f) >= 10000 or abs(f) < 0.001):
        return f"{f:.2e}"
    return f"{f:.{digits}g}"


def _peer_round(snap: dict, peer: str) -> int | None:
    rounds = [
        max((int(r) for r in series), default=None)
        for metric, peers in (snap.get("quality") or {}).items()
        for p, series in peers.items()
        if p == peer and series
    ]
    rounds = [r for r in rounds if r is not None]
    return max(rounds) if rounds else None


def _weight_round(gauges: dict, peer: str):
    """Live weight streaming: the round this peer is SERVING (the
    ``hypha.serve.weight_round`` gauge). Negative = never swapped —
    dispatched params, or a peer that isn't serving at all — rendered
    blank rather than as a misleading -1."""
    v = (gauges.get(peer) or {}).get("hypha.serve.weight_round")
    return None if v is None or v < 0 else v


def render(snap: dict, now: float | None = None) -> str:
    """One frame: the per-peer table + fleet line + SLO state."""
    now = time.time() if now is None else now
    gauges: dict[str, dict[str, float]] = snap.get("gauges") or {}
    quality: dict = snap.get("quality") or {}
    last_seen: dict = snap.get("last_seen") or {}
    peers = sorted(set(gauges) | set(last_seen))
    cols = (
        ("round", lambda p: _peer_round(snap, p)),
        ("loss", lambda p: _latest_quality(quality, "loss", p)),
        ("tok/s", lambda p: _latest_quality(quality, "tokens_per_s", p)),
        ("steps", lambda p: _latest_quality(quality, "inner_steps", p)),
        ("up Mb/s", lambda p: (gauges.get(p) or {}).get("node.bandwidth_out_mbps")),
        ("down Mb/s", lambda p: (gauges.get(p) or {}).get("node.bandwidth_in_mbps")),
        ("queue", lambda p: (gauges.get(p) or {}).get("hypha.serve.queue_depth")),
        ("blocks", lambda p: (gauges.get(p) or {}).get("hypha.serve.free_blocks")),
        ("w.round", lambda p: _weight_round(gauges, p)),
        ("silent s", lambda p: (now - last_seen[p]) if p in last_seen else None),
    )
    lines: list[str] = []
    rounds = sorted(int(r) for r in (snap.get("rounds_seen") or {}))
    head = f"hypha top — {len(peers)} peers"
    if rounds:
        head += f", round {rounds[-1]}"
    lines.append(head)
    lines.append(
        f"{'peer':>10} " + " ".join(f"{name:>10}" for name, _fn in cols)
    )
    for peer in peers:
        row = [f"{peer:>10}"]
        for _name, fn in cols:
            row.append(f"{_fmt(fn(peer)):>10}")
        lines.append(" ".join(row))
    slo = snap.get("slo") or {}
    breached = slo.get("breached") or []
    if slo.get("rules"):
        lines.append(f"SLO rules: {len(slo['rules'])}")
    lines.append(
        "SLO: "
        + (
            "OK"
            if not breached
            else f"{len(breached)} BREACHED — " + "; ".join(breached)
        )
    )
    # FLEET latency quantiles: pool every peer's summary (one slow
    # backend must not be hidden behind whichever peer iterates last).
    from .series import merge_summaries

    per_peer = [
        s
        for peer_summaries in (snap.get("summaries") or {}).values()
        for s in (peer_summaries.get("hypha.serve.request_latency_ms"),)
        if s
    ]
    latency = merge_summaries(per_peer) if per_peer else None
    if latency and latency.get("count"):
        lines.append(
            "serve latency ms: "
            f"p50 {_fmt(latency.get('p50'))} "
            f"p95 {_fmt(latency.get('p95'))} "
            f"p99 {_fmt(latency.get('p99'))} "
            f"max {_fmt(latency.get('max'))}"
        )
    return "\n".join(lines)


def _latest_quality(quality: dict, metric: str, peer: str):
    series = (quality.get(metric) or {}).get(peer)
    if not series:
        return None
    return series[max(series, key=int)]


# --------------------------------------------------------------------- main


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hypha_tpu.telemetry.top",
        description="Live per-peer metrics view for a running hypha job",
    )
    parser.add_argument(
        "target", help="scheduler listen address, or a metrics-journal dir"
    )
    parser.add_argument(
        "--interval", type=float, default=2.0, help="refresh seconds"
    )
    parser.add_argument(
        "--once", action="store_true", help="print one frame and exit"
    )
    parser.add_argument(
        "--json", action="store_true", help="dump the raw snapshot as JSON"
    )
    args = parser.parse_args(argv)
    target = Path(args.target)
    is_dir = target.is_dir()

    def one_frame() -> dict:
        if is_dir:
            return snapshot_from_dir(target)
        return asyncio.run(snapshot_from_addr(args.target))

    try:
        while True:
            snap = one_frame()
            if args.json:
                out = json.dumps(snap, indent=2, default=str)
            else:
                out = render(snap)
            if not args.once:
                # In-place refresh: clear + home, like top(1).
                sys.stdout.write("\x1b[2J\x1b[H")
            print(out, flush=True)
            if args.once:
                return 0
            time.sleep(max(args.interval, 0.2))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
