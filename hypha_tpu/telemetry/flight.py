"""Flight recorder: a per-node bounded ring of structured events.

Crash forensics for the control plane. The durable journal
(``ft/durable.py``) records *what* folded; the flight recorder records
*when* and *why late*: chaos actions, quorum drops, fabric retries,
serving preemptions, PS generation bumps — every discrete event that
explains a stalled round after the fact. Events carry BOTH a monotonic
timestamp (skew-free per-node ordering and durations) and a wall anchor
(cross-node merge by the timeline tool), plus the same attribute
vocabulary the round spans use (round / peer / fragment / shard / codec).

Recording is always on: appending a dict to a bounded deque costs
nanoseconds, so instrumentation sites never branch on config. Spilling is
what gets configured — :meth:`FlightRecorder.configure` names the node and
an optional spill directory, and the ring is written to
``events-<node>.jsonl`` there on process exit (``atexit``, which also runs
on an unhandled-exception death) and on demand via :meth:`spill`.
``python -m hypha_tpu.telemetry.timeline`` merges these files with the
span files into one critical-path timeline.
"""

from __future__ import annotations

import atexit
import json
import os
import re
import signal as _signal
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any

__all__ = ["FlightRecorder", "FLIGHT", "DEFAULT_CAPACITY"]

DEFAULT_CAPACITY = 4096

_SAFE_NODE = re.compile(r"[^A-Za-z0-9._-]")


def _clean(value: Any) -> Any:
    """JSON-safe attribute values; containers shallow, everything else str."""
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    if isinstance(value, (list, tuple, set, frozenset)):
        return [_clean(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _clean(v) for k, v in value.items()}
    return str(value)


class FlightRecorder:
    """Bounded in-memory event ring with per-node JSONL spill."""

    def __init__(
        self, capacity: int = DEFAULT_CAPACITY, node: str = "node"
    ) -> None:
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=max(int(capacity), 1))
        self.node = str(node)
        self.spill_dir: Path | None = None
        self._atexit_registered = False

    # ------------------------------------------------------------ config
    def configure(
        self, node: str | None = None, spill_dir: str | Path | None = None
    ) -> None:
        """Name this process's events and/or arm the exit spill."""
        with self._lock:
            if node:
                self.node = str(node)
            if spill_dir is not None:
                self.spill_dir = Path(spill_dir)
                if not self._atexit_registered:
                    self._atexit_registered = True
                    atexit.register(self._spill_quiet)

    def disarm(self) -> None:
        """Forget the spill directory: later (untraced) work in the same
        process must not have its exit events appended into an earlier
        run's trace directory. The atexit hook stays registered but
        no-ops while disarmed."""
        with self._lock:
            self.spill_dir = None

    # --------------------------------------------------------- recording
    def record(self, event: str, node: str | None = None, **attrs: Any) -> None:
        """Append one event. ``node`` overrides the process default —
        the in-process bench harness labels each component's events."""
        rec: dict[str, Any] = {
            "t_mono_ns": time.monotonic_ns(),
            "t_wall_ns": time.time_ns(),
            "event": str(event),
            "node": str(node) if node else self.node,
        }
        if attrs:
            rec["attrs"] = {str(k): _clean(v) for k, v in attrs.items()}
        with self._lock:
            self._ring.append(rec)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # ------------------------------------------------------------- spill
    def spill(self, spill_dir: str | Path | None = None) -> list[Path]:
        """DRAIN the ring to ``events-<node>.jsonl`` files (one per node
        label seen), appending; returns the paths written. Draining makes
        spill idempotent across the on-demand + atexit pair — the exit
        hook writes only what arrived since the last explicit spill,
        never a duplicate of it."""
        target = Path(spill_dir) if spill_dir is not None else self.spill_dir
        if target is None:
            return []
        with self._lock:
            events = list(self._ring)
            self._ring.clear()
        if not events:
            return []
        target.mkdir(parents=True, exist_ok=True)
        by_node: dict[str, list[dict]] = {}
        for rec in events:
            by_node.setdefault(rec.get("node") or "node", []).append(rec)
        written: list[Path] = []
        for node, recs in sorted(by_node.items()):
            safe = _SAFE_NODE.sub("-", node) or "node"
            path = target / f"events-{safe}.jsonl"
            with open(path, "a", encoding="utf-8") as f:
                for rec in recs:
                    f.write(json.dumps(rec, default=str) + "\n")
            written.append(path)
        return written

    def _snapshot_lockfree(self) -> list[dict]:
        """Ring copy that NEVER blocks on the recorder lock.

        The SIGUSR2 handler runs in the main thread between bytecodes —
        if the interrupted frame holds ``self._lock`` (``record`` on a
        hot path, ``spill``), acquiring it from the handler would
        deadlock the process the tool exists to diagnose. ``deque``
        appends are themselves thread-safe; a concurrent mutation during
        the copy raises RuntimeError, which the retry absorbs.
        """
        for _ in range(8):
            try:
                return list(self._ring)
            except RuntimeError:
                continue
        return []

    def dump(self, path: str | Path | None = None) -> Path | None:
        """Live capture WITHOUT draining: write the ring's current
        contents to one file and leave the ring intact.

        The wedged-node tool: unlike :meth:`spill` (which drains, so the
        exit hook stays idempotent), ``dump`` is a read-only snapshot —
        an operator can take several while the node stays stuck and each
        shows the full recent history. Default target:
        ``events-<node>-dump.jsonl`` under the spill dir (or the CWD when
        no spill dir is armed). Deliberately LOCK-FREE end to end: it is
        the signal handler's body, and the interrupted frame may hold the
        recorder lock (attribute reads are atomic under the GIL).
        """
        events = self._snapshot_lockfree()
        base = self.spill_dir
        node = self.node
        if path is None:
            safe = _SAFE_NODE.sub("-", node) or "node"
            path = (base or Path(".")) / f"events-{safe}-dump.jsonl"
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            for rec in events:
                f.write(json.dumps(rec, default=str) + "\n")
        return path

    def arm_signal(self, signum: int | None = None) -> bool:
        """Install a ``SIGUSR2`` handler that :meth:`dump`\\ s the ring.

        ``kill -USR2 <pid>`` then captures a wedged node's recent events
        live — no RPC, no cooperation from the (possibly stuck) event
        loop: the handler only snapshots a deque and writes one file.
        Returns False when signals cannot be installed here (non-main
        thread, platforms without SIGUSR2) — callers treat that as a
        soft no.
        """
        if signum is None:
            signum = getattr(_signal, "SIGUSR2", None)
            if signum is None:  # platform without SIGUSR2
                return False

        def _on_signal(_signum, _frame) -> None:
            try:
                path = self.dump()
                # A signal handler can't log safely through arbitrary
                # handlers; a direct low-level write is async-signal-ish
                # enough for a diagnostics path.
                os.write(
                    2,
                    f"[flight] dumped ring to {path}\n".encode(
                        "utf-8", "replace"
                    ),
                )
            except Exception:
                pass

        try:
            _signal.signal(signum, _on_signal)
            return True
        except (ValueError, OSError):  # not the main thread
            return False

    def _spill_quiet(self) -> None:
        try:
            self.spill()
        except Exception:  # an exit hook must never mask the real exit
            pass


# The process ring every subsystem records into (chaos, retries, quorum
# drops, preemptions, generation bumps).
FLIGHT = FlightRecorder()
