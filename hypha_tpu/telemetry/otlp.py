"""OTLP/HTTP+JSON export (crates/telemetry/src/otlp.rs role).

Speaks the standard OTLP HTTP endpoints (``/v1/traces``, ``/v1/metrics``,
``/v1/logs``) in their JSON encoding, so any OTEL collector can ingest it.
Posts run on the telemetry thread; failures are logged and dropped — export
must never stall or crash a node.
"""

from __future__ import annotations

import json
import logging
import urllib.request

log = logging.getLogger("hypha.telemetry.otlp")


def _attr_list(attrs: dict) -> list:
    out = []
    for k, v in attrs.items():
        if isinstance(v, bool):
            value = {"boolValue": v}
        elif isinstance(v, int):
            value = {"intValue": str(v)}
        elif isinstance(v, float):
            value = {"doubleValue": v}
        else:
            value = {"stringValue": str(v)}
        out.append({"key": str(k), "value": value})
    return out


class OtlpJsonExporter:
    def __init__(self, endpoint: str, resource: dict, headers: dict | None = None):
        base = endpoint if "://" in endpoint else f"http://{endpoint}"
        self.base = base.rstrip("/")
        self.resource = resource
        self.headers = {"content-type": "application/json", **(headers or {})}
        self._warned = False

    def _post(self, path: str, payload: dict) -> None:
        req = urllib.request.Request(
            self.base + path,
            data=json.dumps(payload).encode(),
            headers=self.headers,
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=5):  # noqa: S310
                pass
        except Exception as e:
            # First failure at warning so a dead/mis-addressed collector is
            # visible; the steady-state repeats stay at debug.
            if not self._warned:
                self._warned = True
                log.warning("otlp export to %s failing: %s", self.base + path, e)
            else:
                log.debug("otlp post %s failed: %s", path, e)

    # ------------------------------------------------------------- traces
    def export_spans(self, spans: list) -> None:
        by_scope: dict[str, list] = {}
        for scope, span in spans:
            by_scope.setdefault(scope, []).append(span)
        payload = {
            "resourceSpans": [
                {
                    "resource": {"attributes": _attr_list(self.resource)},
                    "scopeSpans": [
                        {
                            "scope": {"name": scope},
                            "spans": [
                                {
                                    "traceId": s.trace_id,
                                    "spanId": s.span_id,
                                    **(
                                        {"parentSpanId": s.parent_id}
                                        if s.parent_id
                                        else {}
                                    ),
                                    "name": s.name,
                                    "kind": 1,
                                    "startTimeUnixNano": str(s.start_ns),
                                    "endTimeUnixNano": str(s.end_ns or s.start_ns),
                                    "attributes": _attr_list(s.attributes),
                                    "status": {"code": 1 if s.status_ok else 2},
                                }
                                for s in scope_spans
                            ],
                        }
                        for scope, scope_spans in by_scope.items()
                    ],
                }
            ]
        }
        self._post("/v1/traces", payload)

    # ------------------------------------------------------------ metrics
    def export_metrics(self, instruments: dict, gauges: dict) -> None:
        import time

        now = str(time.time_ns())
        metrics = []
        for (scope, name), inst in instruments.items():
            if hasattr(inst, "value"):  # Counter
                metrics.append(
                    {
                        "name": name,
                        "unit": inst.unit,
                        "sum": {
                            "aggregationTemporality": 2,  # cumulative
                            "isMonotonic": True,
                            "dataPoints": [
                                {"asDouble": inst.value(), "timeUnixNano": now}
                            ],
                        },
                    }
                )
            elif hasattr(inst, "snapshot"):  # Histogram
                snap = inst.snapshot()
                metrics.append(
                    {
                        "name": name,
                        "unit": inst.unit,
                        "histogram": {
                            "aggregationTemporality": 2,
                            "dataPoints": [
                                {
                                    "timeUnixNano": now,
                                    "count": str(snap["count"]),
                                    "sum": snap["sum"],
                                    "bucketCounts": [
                                        str(c) for c in snap["bucket_counts"]
                                    ],
                                    "explicitBounds": snap["bounds"],
                                }
                            ],
                        },
                    }
                )
        for (_scope, name), (value, unit) in gauges.items():
            metrics.append(
                {
                    "name": name,
                    "unit": unit,
                    "gauge": {"dataPoints": [{"asDouble": value, "timeUnixNano": now}]},
                }
            )
        if not metrics:
            return
        payload = {
            "resourceMetrics": [
                {
                    "resource": {"attributes": _attr_list(self.resource)},
                    "scopeMetrics": [{"scope": {"name": "hypha"}, "metrics": metrics}],
                }
            ]
        }
        self._post("/v1/metrics", payload)

    # --------------------------------------------------------------- logs
    def export_logs(self, records: list) -> None:
        """Standard OTLP ``/v1/logs`` export — parity with the reference's
        log pipeline (crates/telemetry/src/logging.rs: tracing events ->
        OTLP LogRecords alongside spans/metrics)."""
        by_scope: dict[str, list] = {}
        for rec in records:
            by_scope.setdefault(rec.scope, []).append(
                {
                    "timeUnixNano": str(rec.time_ns),
                    "severityNumber": rec.severity_number,
                    "severityText": rec.severity_text,
                    "body": {"stringValue": rec.body},
                    "attributes": _attr_list(rec.attributes),
                    **({"traceId": rec.trace_id} if rec.trace_id else {}),
                    **({"spanId": rec.span_id} if rec.span_id else {}),
                }
            )
        if not by_scope:
            return
        payload = {
            "resourceLogs": [
                {
                    "resource": {"attributes": _attr_list(self.resource)},
                    "scopeLogs": [
                        {"scope": {"name": scope}, "logRecords": recs}
                        for scope, recs in by_scope.items()
                    ],
                }
            ]
        }
        self._post("/v1/logs", payload)
