"""Live weight streaming: zero-downtime train→serve hot swaps.

A serving worker attaches to a training job's parameter-server broadcast
as one more leaf — directly in flat jobs, or as a relay child under a
broadcast tree (``stream.tree.with_serve_leaves``) — and follows the
model BEING TRAINED round by round, without restarts, without draining
the request queue, and without a separate checkpoint-publish pipeline.

The broadcast carries per-round outer UPDATES ``u_r`` (deltas), not
absolute weights: the served model is ``θ_r = θ_0 + Σ_{i<=r} u_i``.
Two invariants follow, and this module exists to hold them:

* **Contiguity.** Updates fold in strict round order starting at
  ``WeightFollow.round + 1`` (the round the dispatched params embody).
  Skipping a round would serve a model that never existed on any
  trainer. :class:`WeightStager` stages out-of-order arrivals and only
  releases complete rounds contiguous with what is already applied.
* **Atomicity.** A round's update spans many fragment wires; flipping
  leaves as fragments land would let an in-flight decode step read
  MIXED-round weights. The stager assembles the full round on the host
  first; the pool then applies it in one assignment at a chunk boundary
  (``DecodePool.request_swap`` → ``_apply_swap``), between dispatched
  programs, where nothing reads ``_vars`` concurrently.

:class:`WeightSubscriber` is the networked half: a
:class:`~hypha_tpu.worker.connectors.Connector` receive loop filtered to
the broadcast's resource tag, honouring the same results-stream protocol
markers train workers do — PS generation bumps (``ps_generation``),
resync announcements (no payload), and rejoin catch-ups (a CUMULATIVE
Σ of rounds; folding one as if it were a single round's delta would
double-apply history, so catch-ups are dropped and counted).

Failure posture: a permanently lost broadcast round wedges the follower
at its last applied round — by design, it keeps SERVING that round
(stale-but-consistent beats fresh-but-fictional). ``stats()`` exposes
the held-round count so operators can alert and re-dispatch.
"""

from __future__ import annotations

import asyncio
import logging
import tempfile
from pathlib import Path
from typing import Any

import numpy as np

from ..compress import read_delta
from ..ft.durable import RESYNC_KEY, restart_signal
from ..ft.rejoin import CATCHUP_KEY
from ..messages import FragmentTag, Receive, Reference, WeightFollow
from ..worker.connectors import Connector

__all__ = ["WeightStager", "WeightSubscriber", "follow_for"]

log = logging.getLogger("hypha.serving.weight_stream")


def follow_for(
    results_tag: str,
    ps_peers: list,
    *,
    groups: list | None = None,
    start_round: int = 0,
    ps_generation: int = 0,
    fragments: int = 0,
    pin_round: int | None = None,
    keep_previous: bool = False,
) -> WeightFollow:
    """Build a follower's :class:`WeightFollow` with the broadcast's
    Receive allowlist derived the way train workers derive theirs: the PS
    shard peers plus every relay head of the reduce ``groups`` — under a
    broadcast tree the follower's wire arrives from its assigned relay,
    and dead-relay failover can re-route it through ANY head, so all of
    them are admitted (an unlisted sender's push is silently dropped by
    the Connector, which would wedge the follower at its last round)."""
    heads = {g[0] for g in (groups or []) if len(g) >= 2}
    allowed = sorted({str(p) for p in ps_peers} | {str(h) for h in heads})
    return WeightFollow(
        results=Receive(Reference.from_peers(allowed, results_tag)),
        round=int(start_round),
        ps_generation=int(ps_generation),
        fragments=int(fragments),
        pin_round=pin_round,
        keep_previous=keep_previous,
    )


class WeightStager:
    """Round assembly for a weight-stream follower. Pure host state.

    Feed every decoded broadcast wire through :meth:`offer`; it returns
    the (possibly empty) list of ``(round, update)`` pairs that became
    ready — complete AND contiguous with ``applied_round`` — in apply
    order. Fragments of one round carry disjoint leaf subsets and merge
    by addition (sharded senders can overlap only on re-sends, which
    overwrite in staging first, so nothing folds twice).

    ``fragments`` pins the wire count a round needs before it can ship
    (stream-staggered jobs broadcast ONE due fragment per round, so the
    scheduler pins 1 there); 0 derives it from each wire's FragmentTag,
    with untagged wires counting as single-file rounds.
    """

    def __init__(
        self,
        *,
        start_round: int = 0,
        ps_generation: int = 0,
        fragments: int = 0,
    ) -> None:
        self.applied_round = int(start_round)
        self.generation = int(ps_generation)
        self.fragments = int(fragments)
        # round -> fragment_id -> leaf arrays (re-sends overwrite).
        self._staging: dict[int, dict[int, dict[str, np.ndarray]]] = {}
        self._expect: dict[int, int] = {}  # round -> wires needed
        self.dropped_stale = 0  # wires for rounds <= applied
        self.rounds_ready = 0
        self.generation_changes = 0

    # ----------------------------------------------------------- queries

    def held_rounds(self) -> list[int]:
        """Rounds staged (complete or not) but not yet releasable —
        non-empty long after traffic means a gap wedged the follower."""
        return sorted(self._staging)

    def _complete(self, round_num: int) -> bool:
        have = self._staging.get(round_num)
        if not have:
            return False
        need = self.fragments or self._expect.get(round_num, 1)
        return len(have) >= need

    # ---------------------------------------------------------- ingest

    def note_generation(self, ps_generation: Any) -> None:
        """Adopt a PS generation observed on a payload-less marker wire
        (resync announce / catch-up header). Round numbering continues
        across PS restarts, so staging is kept — a recovered PS re-sends
        its last committed round and re-sends simply overwrite."""
        if ps_generation is None:
            return
        gen = int(ps_generation)
        if gen != self.generation:
            self.generation_changes += 1
            self.generation = gen

    def offer(
        self,
        round_num: int,
        arrays: dict,
        *,
        fragment_id: int = 0,
        fragments: int = 1,
        ps_generation: Any = None,
    ) -> list[tuple[int, dict]]:
        """Stage one decoded wire; return newly releasable rounds.

        Stale wires (round already applied — a recovered PS re-sending
        its last committed round, or relay duplicates) drop with a
        counter. Future rounds stage until the gap closes.
        """
        self.note_generation(ps_generation)
        r = int(round_num)
        if r <= self.applied_round:
            self.dropped_stale += 1
            return []
        self._staging.setdefault(r, {})[int(fragment_id)] = arrays
        prev = self._expect.get(r, 1)
        self._expect[r] = max(prev, int(fragments), 1)
        ready: list[tuple[int, dict]] = []
        while self._complete(self.applied_round + 1):
            nxt = self.applied_round + 1
            parts = self._staging.pop(nxt)
            self._expect.pop(nxt, None)
            merged: dict[str, np.ndarray] = {}
            for fid in sorted(parts):
                for name, arr in parts[fid].items():
                    if name in merged:
                        merged[name] = merged[name] + np.asarray(arr)
                    else:
                        merged[name] = np.asarray(arr)
            self.applied_round = nxt
            self.rounds_ready += 1
            ready.append((nxt, merged))
        return ready


class WeightSubscriber:
    """The receive loop: broadcast wire → stager → pool swap request.

    ``pool`` needs ``request_swap(updates, *, round_num, generation,
    keep_previous)`` and ``pin_round`` — :class:`~hypha_tpu.executor.
    pool.DecodePool`'s swap surface (both thread-safe, so calling them
    from the event loop while the serve thread decodes is fine).
    Ownership of the Connector's node stays with the caller; ``stop``
    only cancels the receive task and removes the staging directory's
    leftover wires.
    """

    def __init__(
        self,
        node: Any,
        follow: WeightFollow,
        pool: Any,
        *,
        work_dir: Path | str | None = None,
    ) -> None:
        self.follow = follow
        self.pool = pool
        self._conn = Connector(node)
        self._dir = Path(work_dir) if work_dir is not None else None
        self._task: asyncio.Task | None = None
        self.stager = WeightStager(
            start_round=follow.round,
            ps_generation=follow.ps_generation,
            fragments=follow.fragments,
        )
        self.fragments_received = 0
        self.bytes_received = 0
        self.dropped_markers = 0  # resync announces + catch-up wires
        self.decode_errors = 0
        self.swaps_requested = 0

    # --------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Spawn the receive loop on the running event loop."""
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self.run())

    async def stop(self) -> None:
        if self._task is not None:
            from .. import aio

            await aio.reap(self._task)
            self._task = None

    def stats(self) -> dict:
        return {
            "applied_round": self.stager.applied_round,
            "ps_generation": self.stager.generation,
            "fragments_received": self.fragments_received,
            "bytes_received": self.bytes_received,
            "rounds_ready": self.stager.rounds_ready,
            "held_rounds": self.stager.held_rounds(),
            "dropped_stale": self.stager.dropped_stale,
            "dropped_markers": self.dropped_markers,
            "decode_errors": self.decode_errors,
            "swaps_requested": self.swaps_requested,
        }

    # ------------------------------------------------------------- loop

    async def run(self) -> None:
        """Receive broadcast wires until cancelled. The rollback pin (if
        any) applies before the first wire so no early swap races it."""
        if self.follow.results is None:
            raise ValueError("WeightFollow.results is required to subscribe")
        if self.follow.pin_round is not None:
            self.pool.pin_round(self.follow.pin_round)
        dest = self._dir or Path(tempfile.mkdtemp(prefix="weight-stream-"))
        async for rf in self._conn.receive(self.follow.results, dest):
            try:
                await self._handle(rf)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — one bad wire, not the loop
                self.decode_errors += 1
                log.exception("weight-stream wire from %s failed", rf.from_peer)
            finally:
                Path(rf.path).unlink(missing_ok=True)

    async def _handle(self, rf: Any) -> None:
        meta = rf.meta or {}
        gen, _resend = restart_signal(meta, self.stager.generation)
        if meta.get(RESYNC_KEY) or meta.get(CATCHUP_KEY):
            # Resync announcements carry no tensor payload. Catch-ups are
            # a rejoiner-targeted CUMULATIVE Σ of rounds — folding one as
            # a single round's delta would double-apply history.
            self.dropped_markers += 1
            self.stager.note_generation(gen)
            return
        tag = FragmentTag.from_header(meta)
        if tag is not None:
            round_num, fid, total = tag.round, tag.fragment_id, tag.fragments
        else:
            try:
                round_num = int(meta.get("round", 0) or 0)
            except (TypeError, ValueError):
                round_num = 0
            fid, total = 0, 1
        # Decode off the event loop: dequantize of a large fragment is
        # milliseconds of pure NumPy that must not stall other receives.
        arrays = await asyncio.to_thread(read_delta, Path(rf.path))
        self.fragments_received += 1
        self.bytes_received += int(rf.size or 0)
        for r, update in self.stager.offer(
            round_num,
            arrays,
            fragment_id=fid,
            fragments=total,
            ps_generation=gen,
        ):
            self.pool.request_swap(
                update,
                round_num=r,
                generation=self.stager.generation,
                keep_previous=self.follow.keep_previous,
            )
            self.swaps_requested += 1
