"""Serve-side subsystems that ride a TRAINING job's data plane.

Today: live weight streaming (:mod:`~hypha_tpu.serving.weight_stream`) —
a serving worker subscribes to the parameter server's per-round update
broadcast and hot-swaps the decode pool's weights with zero downtime.
"""

from .weight_stream import WeightStager, WeightSubscriber, follow_for

__all__ = ["WeightStager", "WeightSubscriber", "follow_for"]
