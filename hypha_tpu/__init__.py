"""hypha_tpu — a TPU-native decentralized DiLoCo training framework.

A ground-up re-design of the capabilities of hypha-space/hypha (Rust/libp2p/torch)
for TPU hardware: the inner optimizer loop is a jit/pjit-compiled JAX step sharded
over a TPU slice's ICI mesh; intra-slice aggregation lowers to XLA collectives;
the control plane (auction, leases, job bridge, slice scheduling, discovery) is an
asyncio/C++ runtime speaking CBOR-typed protocols.

Layer map (mirrors reference SURVEY.md §1):
  L0 security/PKI     -> hypha_tpu.certs
  L1 p2p networking   -> hypha_tpu.network   (transport fabric: rpc/pubsub/streams/discovery)
  L2 protocol vocab   -> hypha_tpu.messages, hypha_tpu.resources, hypha_tpu.leases
  L3 node runtimes    -> hypha_tpu.gateway / .scheduler / .worker / .data
  L4 execution layer  -> hypha_tpu.worker.executors + job bridge
  L5 ML executors     -> hypha_tpu.executor (JAX train + aggregate)
  L6 observability    -> hypha_tpu.telemetry
  L7 config           -> hypha_tpu.config

TPU compute path: hypha_tpu.models (flax), hypha_tpu.ops (pallas kernels),
hypha_tpu.parallel (mesh/sharding/collectives, ring attention context parallelism).
"""

__version__ = "0.1.0"
