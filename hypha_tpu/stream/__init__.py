"""Streaming outer sync: fragment-wise, compute-overlapped DiLoCo rounds.

Streaming DiLoCo (Douillard et al., 2025, PAPERS.md) removes the outer
round's hard barrier two ways, both reproduced here:

  * **fragments** — the parameter tree is partitioned into F size-balanced
    fragments and only ONE fragment synchronizes per outer round, on a
    staggered schedule (fragment ``r mod F`` is due at round ``r``), so
    peak bytes-in-flight shrinks ~F× while every parameter still syncs
    every F rounds;
  * **overlap** — the due fragment's delta is encoded and uploaded in the
    background while the worker keeps taking inner steps on the
    not-yet-synced params; when the broadcast lands, the outer update is
    merged with a *delayed-update correction* that re-anchors at the
    send-time snapshot, so the drift accrued in flight is shipped with the
    NEXT delta instead of being silently folded into (or clobbered by) the
    outer update.

Pieces:

  * :mod:`partition` — deterministic, size-balanced partition of a flat
    parameter tree into F fragments. Pure function of ``{name: size}``, so
    the parameter server and every worker compute the same fragments
    without exchanging a manifest.
  * :mod:`sync`      — the fragment schedule and the delayed-update
    correction algebra (pure tree ops over flat dicts), shared by the
    training executor, the tests and ``benchmarks/streambench.py``.

Selection is per job via ``sync_mode: blocking | overlap | stream`` on
:class:`~hypha_tpu.scheduler.job_config.DiLoCoJob` (default ``blocking`` —
bit-identical to the pre-streaming behavior).
"""

from __future__ import annotations

from .partition import fragment_of, partition_names, shard_names, shard_of
from .tree import (
    ancestors_of,
    build_reduce_groups,
    children_of,
    parent_of,
    subtree_of,
    top_targets,
    tree_levels,
    with_serve_leaves,
)
from .sync import (
    SYNC_MODES,
    effective_fragments,
    fragment_due,
    merge_corrected,
    next_owned_round,
    placement_parts,
    shard_owns_round,
    shards_due_at,
)

__all__ = [
    "SYNC_MODES",
    "partition_names",
    "fragment_of",
    "fragment_due",
    "effective_fragments",
    "merge_corrected",
    "shard_of",
    "shard_names",
    "placement_parts",
    "shard_owns_round",
    "shards_due_at",
    "next_owned_round",
    "build_reduce_groups",
    "children_of",
    "parent_of",
    "ancestors_of",
    "subtree_of",
    "top_targets",
    "tree_levels",
    "with_serve_leaves",
]
