"""Deterministic, size-balanced partition of a parameter tree.

The parameter server and every worker must agree on which tensors form
fragment ``k`` WITHOUT exchanging a manifest: a rejoiner may be dispatched
mid-job, and the PS never holds the full parameter tree (it learns tensor
names from the first delta frames it decodes). So the partition is a pure
function of the flat tensor names and element counts — both ends already
share those exactly (serialization.flatten_tree names are the wire
contract) — and of nothing else: no dict order, no hash seeds, no floats.

Algorithm: greedy longest-processing-time bin packing. Tensors sorted by
(size descending, name ascending) are assigned one by one to the lightest
fragment (ties broken by fragment index). LPT keeps the largest fragment
within ~4/3 of optimal, which is what bounds peak bytes-in-flight in
stream mode; the name tiebreaks make the result reproducible across
processes, Python versions and insertion orders.
"""

from __future__ import annotations

from typing import Mapping

__all__ = ["partition_names", "fragment_of", "shard_of", "shard_names"]


def partition_names(
    sizes: Mapping[str, int], fragments: int
) -> list[tuple[str, ...]]:
    """Split tensor names into ``fragments`` size-balanced groups.

    ``sizes`` maps flat tensor name -> element count (any non-negative
    weight works; byte counts give the same split for a uniform dtype).
    Returns a list of ``fragments`` name tuples, each sorted by name;
    every input name appears in exactly one tuple. Deterministic: the
    result depends only on the (name, size) multiset.
    """
    if fragments < 1:
        raise ValueError(f"fragments must be >= 1, got {fragments}")
    if fragments > 1 and len(sizes) < fragments:
        # An empty fragment's round would ship empty deltas and crash the
        # parameter server's outer step ("no deltas folded") — refuse the
        # misconfiguration up front, where the message can name the fix.
        raise ValueError(
            f"cannot split {len(sizes)} tensors into {fragments} fragments; "
            f"lower the job's num_fragments to at most {max(len(sizes), 1)}"
        )
    bins: list[list[str]] = [[] for _ in range(fragments)]
    loads = [0] * fragments
    # Sort by size DESC then name ASC: LPT order, fully tie-stable.
    for name in sorted(sizes, key=lambda n: (-int(sizes[n]), n)):
        i = min(range(fragments), key=lambda k: (loads[k], k))
        bins[i].append(name)
        loads[i] += int(sizes[name])
    return [tuple(sorted(b)) for b in bins]


def fragment_of(
    sizes: Mapping[str, int], fragments: int
) -> dict[str, int]:
    """Inverse view: flat tensor name -> fragment index."""
    out: dict[str, int] = {}
    for idx, names in enumerate(partition_names(sizes, fragments)):
        for name in names:
            out[name] = idx
    return out


def shard_of(fragment_id: int, num_shards: int) -> int:
    """Owning PS shard of a fragment: fragments round-robin over shards.

    The placement dimension of the sharded parameter service: as
    deterministic as the partition itself (a pure function of the indices),
    so the parameter-server shards, every worker, every reducer and every
    rejoiner agree on ownership with no manifest exchange. With the
    staggered stream schedule (fragment ``r mod F`` due at round ``r``)
    round-robin also spreads consecutive rounds across shards, so the
    pipelined broadcasts of adjacent rounds leave different shards' NICs.
    """
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    if fragment_id < 0:
        raise ValueError(f"fragment_id must be >= 0, got {fragment_id}")
    return fragment_id % num_shards


def shard_names(
    sizes: Mapping[str, int], fragments: int, num_shards: int, shard_id: int
) -> tuple[str, ...]:
    """All tensor names shard ``shard_id`` owns (its fragments' union)."""
    if not 0 <= shard_id < num_shards:
        raise ValueError(
            f"shard_id {shard_id} out of range for {num_shards} shards"
        )
    parts = partition_names(sizes, fragments)
    return tuple(
        sorted(
            name
            for f, names in enumerate(parts)
            if shard_of(f, num_shards) == shard_id
            for name in names
        )
    )
