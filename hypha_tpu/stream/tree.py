"""Multi-level reduce/broadcast tree placement — pure plan derivation.

A single-level ``GroupReducer`` (PR 6) cuts one shard's ingress from W
pushes to ~W/G partials — but the reducers themselves still push straight
to the shard, so ingress (and the PS's broadcast egress) stays linear in
the pool size past a constant factor. This module generalizes the plan to
a configurable depth ``d``: workers are chunked into deterministic
sorted-peer-id groups of ``G``, the group heads are chunked again, and so
on — a groups-of-groups tree whose top level is what actually talks to
the parameter-service shards. Shard ingress becomes ~W/G^d partials and
PS broadcast egress ~G top-level pushes (plus ungrouped leftovers).

Everything here is a pure function of ``(sorted worker peer ids,
group_size, depth)`` — the same contract as :mod:`partition`: every peer
(and a recovered scheduler re-deriving its plan from the journal) computes
the identical tree with no manifest exchange beyond the ``ShardMap``
announcement that already rides dispatched specs.

Representation: the **collapsed per-reducer group list** — for each node
with children, one group ``[reducer, *children]`` where the children may
come from different levels (a level-2 head folds its level-1 group AND the
other level-1 heads in its chunk). At ``depth=1`` this is byte-identical
to the single-level plan PR 6 shipped in ``ShardMap.groups``, which is
exactly why the wire needs no new placement field for the default.

The mechanics that consume the plan:

  * a LEAF routes its delta ``[parent, shard]`` with ANY failover
    (unchanged from single-level);
  * a MID-TREE reducer folds its children's pushes — raw deltas from leaf
    children, ``prefold``-tagged partials from reducer children — and
    forwards ONE cumulative partial to ITS parent with the same ANY
    failover, covers extending transitively;
  * broadcast mirrors the tree downward: the PS pushes each wire to the
    top-level reducers (and ungrouped workers) only; each relay re-pushes
    to its direct children, expanding a dead child relay to that child's
    children so a mid-tree death degrades fan-out instead of severing the
    subtree.
"""

from __future__ import annotations

__all__ = [
    "build_reduce_groups",
    "children_of",
    "parent_of",
    "ancestors_of",
    "subtree_of",
    "top_targets",
    "tree_levels",
    "with_serve_leaves",
]


def build_reduce_groups(
    peers, group_size: int, depth: int = 1
) -> list[list[str]]:
    """The deterministic tree plan as collapsed per-reducer groups.

    Chunk the sorted peer ids into groups of ``group_size``; each chunk's
    first member is its head. Repeat ``depth`` times over the heads —
    every level's non-head members attach to their chunk's head as
    children. Returns ``[head, *children]`` for every head that has
    children, in sorted-head order. ``depth=1`` reproduces the
    single-level plan exactly (singleton chunks contribute nothing).
    """
    group_size = int(group_size or 0)
    depth = int(depth or 1)
    if group_size < 2 or depth < 1:
        return []
    ordered = sorted(set(str(p) for p in peers))
    children: dict[str, list[str]] = {p: [] for p in ordered}
    current = ordered
    for _ in range(depth):
        if len(current) < 2:
            break
        chunks = [
            current[i : i + group_size]
            for i in range(0, len(current), group_size)
        ]
        nxt: list[str] = []
        for chunk in chunks:
            head = chunk[0]
            children[head].extend(chunk[1:])
            nxt.append(head)
        current = nxt
        if len(chunks) <= 1:
            break
    return [[p, *children[p]] for p in ordered if children[p]]


def children_of(groups) -> dict[str, list[str]]:
    """reducer peer -> its direct children (reduce members)."""
    return {str(g[0]): [str(c) for c in g[1:]] for g in (groups or []) if len(g) >= 2}


def parent_of(groups) -> dict[str, str]:
    """child peer -> its reducer (the ANY-failover first hop)."""
    out: dict[str, str] = {}
    for g in groups or []:
        for child in g[1:]:
            out[str(child)] = str(g[0])
    return out


def ancestors_of(groups, peer: str) -> list[str]:
    """``peer``'s reducer chain, nearest first (empty for a top-level
    reducer or an ungrouped worker). Broadcast wires can arrive from any
    of these — the worker's results allowlist must admit them all."""
    parents = parent_of(groups)
    chain: list[str] = []
    cur = str(peer)
    while cur in parents and parents[cur] not in chain:
        cur = parents[cur]
        chain.append(cur)
    return chain


def subtree_of(groups, peer: str) -> list[str]:
    """Every transitive child under ``peer`` (excluding ``peer``), in
    deterministic DFS order — the worker set a reducer's cumulative
    partial can cover, and the flatten target when a broadcast hop must
    route AROUND a dead relay."""
    kids = children_of(groups)
    out: list[str] = []
    stack = list(kids.get(str(peer), ()))
    seen: set[str] = set()
    while stack:
        cur = stack.pop(0)
        if cur in seen:
            continue
        seen.add(cur)
        out.append(cur)
        stack = list(kids.get(cur, ())) + stack
    return out


def top_targets(groups, peers) -> list[str]:
    """The parameter service's broadcast targets under a tree: every
    top-level reducer plus every ungrouped worker, restricted to
    ``peers`` (the live broadcast set) and keeping its order. A peer in
    ``peers`` whose every ancestor is absent from ``peers`` is also a
    target — a dead relay chain must not sever its subtree."""
    parents = parent_of(groups)
    live = [str(p) for p in peers]
    live_set = set(live)
    out: list[str] = []
    for p in live:
        anc = ancestors_of(groups, p)
        if p not in parents or not any(a in live_set for a in anc):
            out.append(p)
    return out


def with_serve_leaves(groups, serve_leaves) -> list[list[str]]:
    """The BROADCAST-ONLY plan: ``groups`` with serving subscribers
    attached as relay children (live weight streaming, PR 16).

    Serve peers consume update wires but never push deltas, so they must
    stay out of the REDUCE plan (a reducer folding a group that contains
    one would wait forever); this derives the downward fan-out plan the
    parameter service and the relays share instead. Each serve leaf is
    assigned round-robin to a relay head in sorted-head order — a pure
    function of ``(groups, sorted serve peer ids)``, so the PS's
    ``top_targets``/``tree_broadcast`` walk and every relay's
    ``children_of`` slice agree on the assignment with no extra wire.
    Leaves already present anywhere in ``groups`` are skipped (a peer
    that trains AND serves already receives every wire); with no relay
    heads the plan is returned unchanged — callers fall back to direct
    pushes, exactly the no-tree topology.
    """
    base = [list(g) for g in (groups or [])]
    heads = sorted({str(g[0]) for g in base if len(g) >= 2})
    members = {str(p) for g in base for p in g}
    leaves = [
        p
        for p in sorted({str(s) for s in (serve_leaves or [])})
        if p not in members
    ]
    if not heads or not leaves:
        return base
    by_head = {str(g[0]): g for g in base if len(g) >= 2}
    for i, leaf in enumerate(leaves):
        by_head[heads[i % len(heads)]].append(leaf)
    return base


def tree_levels(groups) -> dict[str, int]:
    """reducer peer -> its level (1 = folds only raw worker deltas;
    ``1 + max(child reducer levels)`` otherwise). Telemetry labels the
    per-level fold/forward counters with this."""
    kids = children_of(groups)

    def level(p: str, _seen=()) -> int:
        if p in _seen:  # defensive: a malformed plan must not recurse
            return 1
        subs = [
            level(c, (*_seen, p)) for c in kids.get(p, ()) if c in kids
        ]
        return 1 + max(subs, default=0)

    return {p: level(p) for p in kids}
