"""Fragment schedule and the delayed-update correction algebra.

Blocking DiLoCo merges the broadcast update ``u`` while compute is paused:

    θ ← θ_s + u ;  anchor ← θ            (θ_s = params at delta time)

Overlapped sync keeps stepping while ``u`` is in flight, so by merge time
the live params are θ_l = θ_s + d (``d`` = inner-step drift accrued during
flight). Folding ``u`` into θ_l naively and re-anchoring there would fold
``d`` into the anchor too — the drift would never be shipped, and the next
pseudo-gradient would silently exclude it. The correction re-anchors at
the SEND-TIME snapshot instead:

    θ      ← θ_l + u          (drift kept in the live params)
    anchor ← θ_s + u          (drift excluded from the anchor)

so Δθ_next = θ − anchor starts at exactly ``d``: the in-flight work rides
the next delta rather than vanishing. With zero flight time (d = 0) both
assignments coincide with blocking's — streaming is bit-identical to
blocking in that limit (pinned by tests/test_stream.py).

Everything operates on FLAT ``{name: array}`` dicts (the wire format's
view of the tree), restricted to one fragment's names, and reuses the
jitted tree ops from :mod:`hypha_tpu.executor.diloco` so the merge math is
the same compiled code blocking mode runs.
"""

from __future__ import annotations

from typing import Mapping

__all__ = [
    "SYNC_MODES",
    "DEFAULT_FRAGMENTS",
    "fragment_due",
    "effective_fragments",
    "placement_parts",
    "shard_owns_round",
    "shards_due_at",
    "next_owned_round",
    "merge_corrected",
]

# Per-job outer-sync modes (DiLoCoJob.sync_mode / JobSection.sync_mode):
#   blocking — ship Δθ, wait for the broadcast, merge (the seed behavior);
#   overlap  — one fragment (the whole tree) synced in the background while
#              inner steps continue;
#   stream   — F staggered fragments, one due per round, overlapped.
SYNC_MODES = ("blocking", "overlap", "stream")

# Streaming DiLoCo's ablations hold up to ~F=8; 4 is the paper's headline
# configuration and what `stream` uses when the job doesn't pick.
DEFAULT_FRAGMENTS = 4


def fragment_due(round_num: int, fragments: int) -> int:
    """The staggered schedule: fragment ``r mod F`` syncs at round ``r``.

    Every fragment syncs exactly once per F consecutive rounds, and its
    delta covers the F rounds of inner steps since its previous sync.
    """
    if fragments < 1:
        raise ValueError(f"fragments must be >= 1, got {fragments}")
    return round_num % fragments


def effective_fragments(sync_mode: str, fragments: int = 0) -> int:
    """Resolve the fragment count for a job's sync mode.

    ``blocking`` and ``overlap`` sync the whole tree as one fragment;
    ``stream`` uses the job's ``fragments`` (0 = :data:`DEFAULT_FRAGMENTS`).
    """
    if sync_mode not in SYNC_MODES:
        raise ValueError(
            f"sync_mode must be {'|'.join(SYNC_MODES)}, got {sync_mode!r}"
        )
    if sync_mode != "stream":
        return 1
    if fragments < 0:
        raise ValueError(f"fragments must be >= 0, got {fragments}")
    return int(fragments) or DEFAULT_FRAGMENTS


def placement_parts(
    sync_mode: str, fragments: int = 0, num_shards: int = 1
) -> int:
    """How many placement parts the parameter tree splits into.

    The unit of shard ownership (hypha_tpu.stream.partition.shard_of):

      * ``stream``           — the F staggered fragments, exactly as before
        (fragment ``r mod F`` due at round ``r``, owned by shard
        ``f mod N``);
      * ``blocking/overlap`` — with N > 1 shards the whole tree still syncs
        EVERY round, but as N sub-deltas: one part per shard, all due each
        round. N == 1 keeps the single whole-tree part (the seed path).
    """
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    if num_shards == 1 or sync_mode == "stream":
        return effective_fragments(sync_mode, fragments)
    if sync_mode not in SYNC_MODES:
        raise ValueError(
            f"sync_mode must be {'|'.join(SYNC_MODES)}, got {sync_mode!r}"
        )
    return num_shards


def shard_owns_round(
    sync_mode: str,
    round_num: int,
    fragments: int,
    num_shards: int,
    shard_id: int,
) -> bool:
    """Does shard ``shard_id`` aggregate anything at round ``round_num``?

    In blocking/overlap every shard owns a part of EVERY round; in stream
    mode only the due fragment's owner closes the round — the other shards
    skip it entirely (their own fragments come due on their own rounds).
    """
    if num_shards <= 1 or sync_mode != "stream":
        return True
    from .partition import shard_of

    return shard_of(fragment_due(round_num, fragments), num_shards) == shard_id


def shards_due_at(
    sync_mode: str, round_num: int, fragments: int, num_shards: int
) -> tuple[int, ...]:
    """The PS shards that close round ``round_num`` (the scheduler's round
    gate: UPDATED from every due shard advances the round).

    Stream mode: exactly one — the due fragment's owner. Blocking with
    N > 1 shards: all of them, each closing its own part-delta. N == 1:
    the single pre-shard PS.
    """
    if num_shards <= 1:
        return (0,)
    if sync_mode == "stream":
        from .partition import shard_of

        return (shard_of(fragment_due(round_num, fragments), num_shards),)
    return tuple(range(num_shards))


def next_owned_round(
    sync_mode: str,
    from_round: int,
    fragments: int,
    num_shards: int,
    shard_id: int,
) -> int:
    """The first round >= ``from_round`` that ``shard_id`` aggregates.

    Bounded: the stream schedule cycles every ``fragments`` rounds and
    round-robin placement gives every shard at least one fragment when
    ``fragments >= num_shards`` (validated at job construction)."""
    for r in range(from_round, from_round + max(fragments, 1)):
        if shard_owns_round(sync_mode, r, fragments, num_shards, shard_id):
            return r
    raise ValueError(
        f"shard {shard_id} owns no round in a cycle of {fragments} fragments "
        f"over {num_shards} shards"
    )


def merge_corrected(
    live: Mapping[str, object],
    snapshot: Mapping[str, object],
    update: Mapping[str, object],
) -> tuple[dict, dict]:
    """Apply one fragment's outer update with the delayed-update correction.

    ``live``     — the fragment's CURRENT params (θ_l = θ_s + drift);
    ``snapshot`` — the fragment's params when its delta was taken (θ_s);
    ``update``   — the decoded broadcast update ``u`` for the fragment.

    Returns ``(new_live, new_anchor)`` = (θ_l + u, θ_s + u) as flat dicts.
    Keys must match exactly — a mismatch means the two ends disagreed on
    the partition, which must fail loudly, not merge a partial fragment.
    """
    if set(live) != set(update) or set(snapshot) != set(update):
        raise ValueError(
            "fragment key mismatch: "
            f"live={sorted(live)} snapshot={sorted(snapshot)} "
            f"update={sorted(update)}"
        )
    from ..executor.diloco import merge_update

    live_d = {k: live[k] for k in update}
    snap_d = {k: snapshot[k] for k in update}
    upd_d = dict(update)
    return merge_update(live_d, upd_d), merge_update(snap_d, upd_d)
