"""Tree-reduce: a group reducer pre-folds worker deltas before the shards.

Sharding the parameter service (fragment-owned PS shards) scales the
*aggregate* outer-sync bandwidth, but each shard still takes one push per
worker per owned round — ingress fan-in grows linearly with the worker
count. The classic fix is hierarchical reduction (tree/ring all-reduce):
workers are deterministically grouped, one peer per group *pre-folds* its
group's deltas into a single sample-weighted partial sum and ships that —
cutting a shard's ingress from W pushes to roughly W/G partials (plus each
reducer's own direct delta; a node cannot push to itself).

Mechanics:

  * group members route their delta pushes ``[reducer, shard]`` with ANY
    failover (``TrainExecutorConfig.reduce_via``): a dead reducer degrades
    the group to direct-to-shard pushes instead of wedging the round;
  * the reducer (``reduce_members`` non-empty on its train spec) runs a
    :class:`GroupReducer` next to its training executor: it consumes
    pushes tagged with the job's per-shard updates tags, folds them with
    the SAME :class:`~hypha_tpu.stream.accum.RoundAccum` arithmetic the
    shards use (duplicate member re-sends un-fold the superseded delta
    first), and forwards the partial stamped ``prefold`` + the summed
    sample weight;
  * a partial flushes when every expected member reported, and again
    whenever a straggler or re-send lands later — each flush carries the
    CUMULATIVE partial, so the shard's duplicate-replacement path
    (un-fold the old partial, fold the new) keeps the round value-exact
    no matter how the group's arrivals interleave with the deadline;
  * members that never arrive are simply absent from the partial: the
    weighted-mean algebra composes over any subset split between the
    reducer and direct pushes, so quorum/deadline semantics at the shard
    are unchanged.

Quantized jobs re-encode the partial with the job's ``delta_codec`` and a
per-part error-feedback residual — the partial stream per part is as much
a time series as a worker's delta stream, so EF is unbiased for exactly
the reason it is on the PS broadcast path.

Multi-level trees (``reduce_tree_depth >= 2``, hypha_tpu.stream.tree): a
mid-tree reducer's children are themselves reducers, so its bucket holds a
MIX of raw leaf deltas and ``prefold``-tagged partials — partials fold
verbatim (``RoundAccum`` prefolded semantics) and their ``covers`` headers
union transitively, so the partial a top-level reducer ships still lists
exactly the WORKER peers it represents. A mid-tree reducer forwards its
cumulative partial to its own parent (``cfg.reduce_via``) with the same
``[parent, shard]`` ANY failover leaves use, so a dead parent degrades one
hop instead of severing the subtree; the shard's cover-set reconciliation
(hypha_tpu.worker.ps_executor) resolves any at-least-once overlap between
a failed-over partial and its ancestor's.

:class:`BroadcastRelay` runs the same tree DOWNWARD for update broadcasts:
the parameter service pushes each round's wire to the top-level reducers
(and ungrouped workers) only under the ``<results>.relay`` tag; each relay
injects the wire into its OWN training loop locally and re-pushes it to
its direct children — relay tag for child reducers, the plain results tag
for leaves — expanding a dead child relay to that child's children so a
mid-tree death costs fan-out, not the subtree's round.
"""

from __future__ import annotations

import asyncio
import logging
import os
import shutil
import tempfile
import uuid
from pathlib import Path

from .. import aio, compress
from ..messages import PREFOLD_KEY, SHARD_KEY, FragmentTag
from ..telemetry.ft_metrics import SCALE_METRICS, SHARD_METRICS
from .accum import RoundAccum
from .partition import shard_of
from .tree import children_of, subtree_of, tree_levels

__all__ = [
    "GroupReducer",
    "BroadcastRelay",
    "TreeRuntime",
    "maybe_start_reducer",
    "maybe_start_relay",
    "relay_tag",
    "tree_broadcast",
    "REDUCE_FLUSH_ENV",
]

log = logging.getLogger("hypha.stream.reduce")

# Seconds after a (round, part) bucket's first delta before an incomplete
# partial is flushed anyway — a dead member must not park the group's
# progress past the shard's own round deadline.
REDUCE_FLUSH_ENV = "HYPHA_REDUCE_FLUSH_S"
_FLUSH_DEFAULT = 5.0
_TICK_S = 0.25


def _flush_after() -> float:
    try:
        return float(os.environ.get(REDUCE_FLUSH_ENV, "") or _FLUSH_DEFAULT)
    except ValueError:
        return _FLUSH_DEFAULT


class TreeRuntime:
    """The runtime-side tree roles one dispatched train job gave this
    worker: its :class:`GroupReducer` (upward partial folding) and, when
    the job's broadcast tree is on, its :class:`BroadcastRelay` (downward
    wire fan-out). The worker runtimes hold one handle and ``await
    stop()`` on job teardown."""

    def __init__(self, reducer=None, relay=None) -> None:
        self.reducer = reducer
        self.relay = relay

    async def stop(self) -> None:
        if self.relay is not None:
            await self.relay.stop()
        if self.reducer is not None:
            await self.reducer.stop()


def maybe_start_reducer(node, spec) -> "TreeRuntime | None":
    """Start this worker's tree roles for a dispatched train job: a
    :class:`GroupReducer` when the spec names it as its group's reducer
    (non-empty ``reduce_members`` + a placement map), plus a
    :class:`BroadcastRelay` when the job's broadcast tree is on
    (``relay_results``). Returns the started runtime, or None for every
    other job — the worker runtimes call this on dispatch and ``await
    runtime.stop()`` on job teardown.

    Lives runtime-side (not in the training executor process): both roles
    consume fabric pushes, and the node lives in the runtime.
    """
    cfg = getattr(getattr(spec, "executor", None), "train", None)
    if cfg is None:
        return None
    members = getattr(cfg, "reduce_members", None)
    shard_map = getattr(cfg, "ps_shards", None)
    if not members or shard_map is None or not getattr(shard_map, "shards", None):
        return None
    reducer = GroupReducer(node, cfg)
    reducer.start()
    log.info(
        "group reducer started: %d members, %d shard(s)",
        len(members), len(shard_map.shards),
    )
    return TreeRuntime(reducer=reducer, relay=maybe_start_relay(node, spec))


class _Entry:
    """One accepted child contribution: the saved file, its weight, and
    whether it is a prefolded partial (and then, the workers it covers)."""

    __slots__ = ("path", "samples", "prefolded", "covers")

    def __init__(
        self, path: Path, samples: float, prefolded: bool, covers: frozenset
    ) -> None:
        self.path = path
        self.samples = samples
        self.prefolded = prefolded
        self.covers = covers


class _Bucket:
    """One (round, part)'s group state on the reducer.

    Entries are keyed like the shard's received table
    (``prefold:``-prefixed for partials): a mid-tree child sends BOTH its
    own raw delta and its group's partial, and the two must never collide
    as duplicates of each other.
    """

    def __init__(self) -> None:
        self.accum = RoundAccum()
        self.entries: dict[str, _Entry] = {}
        self.first_at: float | None = None
        self.flushed = 0  # partials shipped so far (re-flushes included)
        self.dirty = False  # folds since the last flush

    def covered(self) -> set[str]:
        """The worker peers this bucket's cumulative sum represents:
        direct senders plus every partial's transitive cover set."""
        out: set[str] = set()
        for entry in self.entries.values():
            out |= entry.covers
        return out


class GroupReducer:
    """Pre-fold this worker's group's deltas; forward partials per shard.

    ``cfg`` is the reducer worker's own ``TrainExecutorConfig`` — it
    carries the placement (``ps_shards``), the wire codec, and the group
    members (``reduce_members``, the OTHER members whose pushes land
    here). The reducer's own delta goes direct to the shard via its
    training loop, so it is never expected in a bucket.
    """

    def __init__(self, node, cfg, work_dir: Path | str | None = None) -> None:
        shard_map = cfg.ps_shards
        if shard_map is None or not shard_map.shards:
            raise ValueError("GroupReducer needs cfg.ps_shards placement")
        self.node = node
        self.cfg = cfg
        self.members = set(cfg.reduce_members or [])
        self.shards: list[str] = list(shard_map.shards)
        self.tags: list[str] = list(shard_map.tags)
        self.num_shards = len(self.shards)
        self.parts = int(shard_map.fragments) or 1
        # Multi-level placement (stream.tree): the parent this reducer
        # forwards partials to (None = top level, ship to the shard), the
        # full worker set its subtree can cover (bucket completeness), and
        # its level for the per-level telemetry counters.
        groups = list(getattr(shard_map, "groups", None) or [])
        self.parent = getattr(cfg, "reduce_via", None) or None
        peer_id = getattr(node, "peer_id", "")
        self.expected_cover = (
            set(subtree_of(groups, peer_id)) if groups else set(self.members)
        ) or set(self.members)
        self.level = tree_levels(groups).get(peer_id, 1) if groups else 1
        self._own_dir = work_dir is None
        self.work_dir = Path(
            work_dir
            if work_dir is not None
            else tempfile.mkdtemp(prefix="hypha-reduce-")
        )
        self.codec = compress.effective_codec(
            getattr(cfg, "delta_codec", "none"), getattr(cfg, "delta_dtype", "float32")
        )
        self._efs: dict[int, compress.ErrorFeedback | None] = {}
        self._buckets: dict[tuple[int, int], _Bucket] = {}
        self._flush_after = _flush_after()
        self._task: asyncio.Task | None = None
        self._consumer = None
        # test/bench hooks
        self.folds = 0
        self.unfolds = 0
        self.partials = 0

    # ---------------------------------------------------------- lifecycle

    def start(self) -> None:
        tags = set(self.tags)
        members = set(self.members)

        def wants(push) -> bool:
            # Tag AND sender: a small mesh can colocate this reducer with
            # a PS shard executor on one node (peer reuse), and consumers
            # route first-match — matching by tag alone would steal (and
            # drop) direct-to-shard deltas from workers outside the group.
            r = push.resource
            return (
                isinstance(r, dict)
                and r.get("resource") in tags
                and push.peer in members
            )

        self.work_dir.mkdir(parents=True, exist_ok=True)
        self._consumer = self.node.consume_pushes(wants)
        self._task = aio.spawn(
            self._run(), what="group reducer", logger=log
        )

    async def stop(self) -> None:
        if self._task is not None:
            await aio.reap(self._task)
            self._task = None
        if self._consumer is not None:
            self._consumer.close()
            self._consumer = None
        if self._own_dir:
            await asyncio.to_thread(
                shutil.rmtree, self.work_dir, ignore_errors=True
            )

    # --------------------------------------------------------------- loop

    async def _run(self) -> None:
        assert self._consumer is not None
        while True:
            try:
                push = await self._consumer.next(timeout=_TICK_S)
            except asyncio.TimeoutError:
                await self._flush_due()
                continue
            await self._ingest(push)
            await self._flush_due()

    async def _ingest(self, push) -> None:
        peer = push.peer
        meta = push.resource if isinstance(push.resource, dict) else {}
        if peer not in self.members:
            # Not ours to fold (mis-routed, or a peer outside the group):
            # drain so the sender's accept slot is released.
            log.warning("reducer: push from non-member %s dropped", peer)
            await push.read_all()
            return
        try:
            round_num = int(meta.get("round", 0))
        except (TypeError, ValueError):
            round_num = 0
        part = self._part_of(meta)
        if part is None:
            log.warning("reducer: untagged push from %s dropped", peer)
            await push.read_all()
            return
        dest = self.work_dir / f"in-{round_num}-{part}-{uuid.uuid4().hex[:8]}"
        await push.save_to(dest)
        try:
            samples = float(meta.get("num_samples", 1.0))
        except (TypeError, ValueError):
            samples = 1.0
        # A child reducer's forwarded partial folds VERBATIM (already
        # Σ samples·Δθ) and covers the workers its header lists; a raw
        # delta covers its sender. The entry key keeps a mid-tree child's
        # partial and its OWN direct delta apart.
        prefolded = bool(meta.get(PREFOLD_KEY))
        covers = (
            frozenset(str(p) for p in (meta.get("covers") or ()))
            if prefolded
            else frozenset((peer,))
        )
        key = f"prefold:{peer}" if prefolded else peer
        bucket = self._buckets.setdefault((round_num, part), _Bucket())
        if bucket.first_at is None:
            bucket.first_at = asyncio.get_running_loop().time()
        old = bucket.entries.pop(key, None)
        if old is not None:
            # Duplicate re-send: un-fold the superseded delta while its
            # file still holds the original bytes, exactly like the shard
            # does — the next flush ships the corrected cumulative sum.
            log.warning(
                "reducer: duplicate %s from %s (round %d part %d); "
                "replacing", "partial" if prefolded else "delta",
                peer, round_num, part,
            )
            await asyncio.to_thread(
                bucket.accum.fold, old.path, old.samples, -1.0, old.prefolded
            )
            self.unfolds += 1
            old.path.unlink(missing_ok=True)
        await asyncio.to_thread(
            bucket.accum.fold, dest, samples, 1.0, prefolded
        )
        self.folds += 1
        SHARD_METRICS.reduced_deltas.add(1)
        SCALE_METRICS.note_tree_fold(self.level)
        bucket.entries[key] = _Entry(dest, samples, prefolded, covers)
        bucket.dirty = True

    def _part_of(self, meta: dict) -> int | None:
        tag = FragmentTag.from_header(meta)
        if tag is not None:
            return int(tag.fragment_id)
        if SHARD_KEY in meta:
            # Blocking/overlap sharded pushes carry the target shard, and
            # part k is owned by shard k by construction (shard_of is the
            # identity when parts == num_shards).
            try:
                return int(meta[SHARD_KEY])
            except (TypeError, ValueError):
                return None
        resource = meta.get("resource")
        if resource in self.tags:
            return self.tags.index(resource)
        return None

    async def _flush_due(self) -> None:
        now = asyncio.get_running_loop().time()
        for (round_num, part), bucket in list(self._buckets.items()):
            if not bucket.dirty:
                continue
            # Complete when every worker in this reducer's SUBTREE is
            # represented — direct leaf deltas plus child partials' covers
            # (at depth 1 this is exactly "every member reported").
            complete = bucket.covered() >= self.expected_cover
            overdue = (
                bucket.first_at is not None
                and now - bucket.first_at >= self._flush_after
            )
            if complete or overdue or bucket.flushed:
                # bucket.flushed: a straggler landing after a deadline
                # flush re-ships the cumulative partial immediately — the
                # shard replaces the previous one, no second wait.
                await self._flush(round_num, part, bucket)

    async def _flush(self, round_num: int, part: int, bucket: _Bucket) -> None:
        owner = shard_of(part, self.num_shards)
        tag_header = None
        if self.parts > 1 or getattr(self.cfg, "sync_mode", "blocking") == "stream":
            tag_header = FragmentTag(
                round=round_num, fragment_id=part, fragments=self.parts
            ).header()
        if part not in self._efs:
            self._efs[part] = (
                compress.ErrorFeedback()
                if self.codec in compress.QUANT_CODECS
                else None
            )
        wire = self.work_dir / (
            f"partial-{round_num}-{part}-{bucket.flushed}.safetensors"
        )

        def encode() -> None:
            partial = bucket.accum.partial()
            if self.codec == "none":
                from safetensors.numpy import save_file

                save_file(partial, str(wire))
            else:
                compress.write_delta(
                    wire, partial, self.codec, ef=self._efs[part],
                    tag=tag_header,
                )

        await asyncio.to_thread(encode)
        header: dict = {
            "resource": self.tags[owner],
            "name": wire.name,
            "round": round_num,
            "num_samples": float(bucket.accum.total_samples),
            PREFOLD_KEY: True,
            # The worker peers this partial represents: the shard's close
            # condition counts covered WORKERS, not accepted files —
            # covers union TRANSITIVELY through child partials, so a
            # top-level flush still lists leaf workers, never reducers'
            # intermediate identities.
            "covers": sorted(bucket.covered()),
        }
        if tag_header:
            header.update(tag_header)
        if self.num_shards > 1:
            header[SHARD_KEY] = owner
        shard_peer = self.shards[owner]
        # Mid-tree reducers forward UP the tree: the parent first, the
        # owning shard as ANY failover (a dead parent degrades this
        # subtree to a direct-to-shard partial; the shard's cover-set
        # reconciliation absorbs any at-least-once overlap with the
        # parent's own partial).
        peers = (
            [self.parent, shard_peer]
            if self.parent and self.parent != shard_peer
            else [shard_peer]
        )
        from ..network.node import RequestError
        from ..worker.connectors import push_timeout

        async def any_once() -> None:
            last: Exception | None = None
            for peer in peers:
                try:
                    await self.node.push(peer, header, wire)
                    return
                except (RequestError, OSError) as e:
                    last = e
            raise RequestError(f"no peer accepted the partial: {last}")

        try:
            await aio.retry(
                any_once,
                attempts=3, base_delay=0.25,
                attempt_timeout=push_timeout(wire) * len(peers),
                retry_on=(RequestError, OSError),
                what=f"reduce partial to {peers}", logger=log,
            )
        except (RequestError, OSError, asyncio.TimeoutError) as e:
            # Tolerated: the members' ANY failover (and the shard's
            # quorum/deadline) own liveness; the reducer re-tries on the
            # next dirty flush.
            log.warning(
                "reducer: partial push r%d part %d to %s failed: %s",
                round_num, part, peers, e,
            )
            wire.unlink(missing_ok=True)
            return
        bucket.flushed += 1
        bucket.dirty = False
        self.partials += 1
        SCALE_METRICS.note_tree_forward(self.level)
        wire.unlink(missing_ok=True)
        log.info(
            "reducer: shipped partial r%d part %d -> %s "
            "(%d entries, covers %d workers, weight %.1f)",
            round_num, part,
            self.parent or f"shard {owner}",
            len(bucket.entries), len(bucket.covered()),
            bucket.accum.total_samples,
        )
        self._gc(round_num, part)

    def _gc(self, round_num: int, part: int) -> None:
        """Retire buckets for older rounds of the same part: workers ship
        a part's round r+1 only after merging round r, so anything older
        can no longer receive a late member delta worth folding."""
        for key in [
            k for k in self._buckets if k[1] == part and k[0] < round_num
        ]:
            for entry in self._buckets[key].entries.values():
                entry.path.unlink(missing_ok=True)
            del self._buckets[key]


# ---------------------------------------------------------------- broadcast


def relay_tag(results_tag: str) -> str:
    """The resource tag broadcast-tree wires travel under BETWEEN tree
    nodes. Distinct from the plain results tag so a relay's consumer and
    its own training loop's receive never race for the same push — the
    relay re-injects the plain-tagged copy locally."""
    return f"{results_tag}.relay"


async def tree_broadcast(
    node,
    header: dict,
    results_tag: str,
    groups,
    targets,
    wire_path: Path,
    *,
    allowed=None,
    concurrency: int = 8,
    attempts: int = 2,
    what: str = "tree broadcast",
    logger=log,
) -> tuple[int, int]:
    """Push one wire down a broadcast tree hop with failover expansion.

    ``targets`` get the push concurrently (bounded at ``concurrency``
    streams, ``_broadcast``'s discipline): a target that has children in
    ``groups`` receives it under the RELAY tag (its BroadcastRelay
    re-pushes to its subtree), a leaf under the plain results tag. A
    target whose push fails after ``attempts`` tries is expanded to its
    direct children — filtered by ``allowed`` (the live broadcast set)
    when given — so a dead mid-tree relay degrades this hop's fan-out
    instead of severing its subtree. Returns ``(delivered, lost)`` where
    ``lost`` counts leaf peers no path could reach (they catch up from the
    next round's broadcast, exactly like a failed direct push today).
    """
    from ..network.node import RequestError
    from ..worker.connectors import push_timeout

    kids = children_of(groups)

    async def push_one(peer: str) -> bool:
        hdr = dict(header)
        hdr["resource"] = (
            relay_tag(results_tag) if kids.get(peer) else results_tag
        )
        try:
            await aio.retry(
                lambda: node.push(peer, hdr, wire_path),
                attempts=attempts, base_delay=0.25,
                attempt_timeout=push_timeout(wire_path),
                retry_on=(RequestError, OSError),
                what=f"{what} to {peer}", logger=logger,
            )
            return True
        except (RequestError, OSError, asyncio.TimeoutError) as e:
            logger.warning("%s to %s failed: %s", what, peer, e)
            return False

    delivered = lost = 0
    frontier = [str(p) for p in targets]
    while frontier:
        # push_one never raises, so each wave is one bounded fan-out.
        outcomes = await aio.gather_bounded(
            [(lambda p=p: push_one(p)) for p in frontier],
            limit=concurrency,
        )
        next_frontier: list[str] = []
        for peer, ok in zip(frontier, outcomes):
            if ok:
                delivered += 1
                SCALE_METRICS.relay_pushes.add(1)
                continue
            children = [
                c
                for c in kids.get(peer, ())
                if allowed is None or c in allowed
            ]
            if children:
                # Route AROUND the dead relay: its children take the push
                # directly from this hop (grandparent failover).
                SCALE_METRICS.relay_failovers.add(1)
                logger.warning(
                    "%s: relay %s unreachable; expanding to %d children",
                    what, peer, len(children),
                )
                next_frontier.extend(children)
            else:
                lost += 1
        frontier = next_frontier
    return delivered, lost


class BroadcastRelay:
    """Re-push results-stream wires down this worker's subtree.

    The reduce tree run in reverse: the parameter service pushes each
    round's update wire to the TOP-level reducers only (and ungrouped
    workers) under the relay tag; each relay saves the wire once,
    re-injects a plain-tagged copy into its own node's push routing (the
    local training loop consumes it exactly as if the PS had pushed
    directly — same header, same sender attribution), and forwards it to
    its direct children with :func:`tree_broadcast`'s failover expansion.
    Headers ride VERBATIM (round, epoch, generation, shard, fragment tag,
    traceparent), so every stale-round / generation / epoch gate on the
    worker side behaves identically to the star topology.
    """

    def __init__(self, node, cfg, work_dir: Path | str | None = None) -> None:
        shard_map = cfg.ps_shards
        if shard_map is None:
            raise ValueError("BroadcastRelay needs cfg.ps_shards placement")
        self.node = node
        self.groups = list(getattr(shard_map, "groups", None) or [])
        # Live weight streaming: serve subscribers ride the SAME downward
        # fan-out as relay children (with_serve_leaves is pure over the
        # placement, so this relay and the PS derive one assignment), but
        # never join self.groups — reduce membership stays train-only.
        serve = list(getattr(shard_map, "serve_leaves", None) or [])
        from .tree import with_serve_leaves

        self.bcast_groups = (
            with_serve_leaves(self.groups, serve) if serve else self.groups
        )
        ref = cfg.results.ref
        self.results_tag = ref.resource or "results"
        self.children = children_of(self.bcast_groups).get(node.peer_id, [])
        self._own_dir = work_dir is None
        self.work_dir = Path(
            work_dir
            if work_dir is not None
            else tempfile.mkdtemp(prefix="hypha-relay-")
        )
        self._task: asyncio.Task | None = None
        self._consumer = None
        # test/bench hooks
        self.relayed = 0

    def start(self) -> None:
        tag = relay_tag(self.results_tag)

        def wants(push) -> bool:
            r = push.resource
            return isinstance(r, dict) and r.get("resource") == tag

        self.work_dir.mkdir(parents=True, exist_ok=True)
        self._consumer = self.node.consume_pushes(wants)
        self._task = aio.spawn(
            self._run(), what="broadcast relay", logger=log
        )

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            # Cancel until it sticks: a cancel landing in the same loop
            # iteration as local_done completing is SWALLOWED by 3.10's
            # wait_for (bpo-37658) — the task would resume its consumer
            # wait and a single reap would hang forever.
            while not task.done():
                task.cancel()
                await asyncio.wait({task}, timeout=0.5)
        if self._consumer is not None:
            self._consumer.close()
            self._consumer = None
        if self._own_dir:
            await asyncio.to_thread(
                shutil.rmtree, self.work_dir, ignore_errors=True
            )

    async def _run(self) -> None:
        assert self._consumer is not None
        # Sequential per push: the PS chains same-fragment fan-outs so
        # broadcast ORDER is part of the protocol — relaying two rounds of
        # one fragment concurrently could invert their arrival below.
        while True:
            push = await self._consumer.next()
            try:
                await self._relay(push)
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("broadcast relay failed for one wire")

    async def _relay(self, push) -> None:
        meta = push.resource if isinstance(push.resource, dict) else {}
        dest = self.work_dir / f"relay-{uuid.uuid4().hex[:12]}"
        await push.save_to(dest)
        self.relayed += 1
        # Subtree FIRST, own copy second: the invariant downstream
        # failover reasons from is "once the subtree's root merged round
        # r, the subtree was served round r" — injecting locally first
        # would let this node finish the round (and, in a crash, die)
        # with the fan-out still pending, silently starving its children.
        # The injected copy keeps the ORIGINAL sender attribution (the
        # parent hop), which the training loop's results allowlist admits
        # (the orchestrator lists each worker's ancestor chain next to
        # the shard peers).
        local_done = asyncio.Event()
        local_header = {**meta, "resource": self.results_tag}
        injected = False
        try:
            await tree_broadcast(
                self.node, meta, self.results_tag, self.bcast_groups,
                self.children, dest, what="relay", logger=log,
            )
            await self.node.inject_push(
                push.peer, local_header, dest, on_done=local_done.set
            )
            injected = True
        finally:
            # The local consumer owns its copy of the bytes once finish()
            # fires; a consumer that never drains (job mid-teardown) must
            # not pin the file — stop()'s rmtree sweeps the stragglers.
            # asyncio.wait, not wait_for: 3.10's wait_for can swallow a
            # cancellation racing the event (bpo-37658), which would eat
            # stop()'s cancel and wedge teardown.
            if injected:
                waiter = asyncio.create_task(local_done.wait())
                try:
                    done, _ = await asyncio.wait({waiter}, timeout=120)
                    if not done:
                        log.warning(
                            "relay: local consumer never drained the wire"
                        )
                finally:
                    waiter.cancel()
                dest.unlink(missing_ok=True)
            else:
                dest.unlink(missing_ok=True)


def maybe_start_relay(node, spec) -> "BroadcastRelay | None":
    """Start a :class:`BroadcastRelay` next to a dispatched train job when
    its spec turns the broadcast tree on (``relay_results``) and names
    this worker as a reducer (non-empty ``reduce_members``). Returns the
    started relay or None; the worker runtimes call this on dispatch and
    ``await relay.stop()`` on teardown, exactly like
    :func:`maybe_start_reducer`."""
    cfg = getattr(getattr(spec, "executor", None), "train", None)
    if cfg is None:
        return None
    if not getattr(cfg, "relay_results", None):
        return None
    members = getattr(cfg, "reduce_members", None)
    shard_map = getattr(cfg, "ps_shards", None)
    if not members or shard_map is None:
        return None
    relay = BroadcastRelay(node, cfg)
    relay.start()
    log.info("broadcast relay started: %d direct children", len(members))
    return relay
