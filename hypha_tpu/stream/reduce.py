"""Tree-reduce: a group reducer pre-folds worker deltas before the shards.

Sharding the parameter service (fragment-owned PS shards) scales the
*aggregate* outer-sync bandwidth, but each shard still takes one push per
worker per owned round — ingress fan-in grows linearly with the worker
count. The classic fix is hierarchical reduction (tree/ring all-reduce):
workers are deterministically grouped, one peer per group *pre-folds* its
group's deltas into a single sample-weighted partial sum and ships that —
cutting a shard's ingress from W pushes to roughly W/G partials (plus each
reducer's own direct delta; a node cannot push to itself).

Mechanics:

  * group members route their delta pushes ``[reducer, shard]`` with ANY
    failover (``TrainExecutorConfig.reduce_via``): a dead reducer degrades
    the group to direct-to-shard pushes instead of wedging the round;
  * the reducer (``reduce_members`` non-empty on its train spec) runs a
    :class:`GroupReducer` next to its training executor: it consumes
    pushes tagged with the job's per-shard updates tags, folds them with
    the SAME :class:`~hypha_tpu.stream.accum.RoundAccum` arithmetic the
    shards use (duplicate member re-sends un-fold the superseded delta
    first), and forwards the partial stamped ``prefold`` + the summed
    sample weight;
  * a partial flushes when every expected member reported, and again
    whenever a straggler or re-send lands later — each flush carries the
    CUMULATIVE partial, so the shard's duplicate-replacement path
    (un-fold the old partial, fold the new) keeps the round value-exact
    no matter how the group's arrivals interleave with the deadline;
  * members that never arrive are simply absent from the partial: the
    weighted-mean algebra composes over any subset split between the
    reducer and direct pushes, so quorum/deadline semantics at the shard
    are unchanged.

Quantized jobs re-encode the partial with the job's ``delta_codec`` and a
per-part error-feedback residual — the partial stream per part is as much
a time series as a worker's delta stream, so EF is unbiased for exactly
the reason it is on the PS broadcast path.
"""

from __future__ import annotations

import asyncio
import logging
import os
import shutil
import tempfile
import uuid
from pathlib import Path

from .. import aio, compress
from ..messages import PREFOLD_KEY, SHARD_KEY, FragmentTag
from ..telemetry.ft_metrics import SHARD_METRICS
from .accum import RoundAccum
from .partition import shard_of

__all__ = ["GroupReducer", "maybe_start_reducer", "REDUCE_FLUSH_ENV"]

log = logging.getLogger("hypha.stream.reduce")

# Seconds after a (round, part) bucket's first delta before an incomplete
# partial is flushed anyway — a dead member must not park the group's
# progress past the shard's own round deadline.
REDUCE_FLUSH_ENV = "HYPHA_REDUCE_FLUSH_S"
_FLUSH_DEFAULT = 5.0
_TICK_S = 0.25


def _flush_after() -> float:
    try:
        return float(os.environ.get(REDUCE_FLUSH_ENV, "") or _FLUSH_DEFAULT)
    except ValueError:
        return _FLUSH_DEFAULT


def maybe_start_reducer(node, spec) -> "GroupReducer | None":
    """Start a :class:`GroupReducer` next to a dispatched train job when
    its spec names THIS worker as its group's reducer (non-empty
    ``reduce_members`` + a placement map). Returns the started reducer, or
    None for every other job — the worker runtimes call this on dispatch
    and ``await reducer.stop()`` on job teardown.

    Lives runtime-side (not in the training executor process): the
    reducer consumes fabric pushes, and the node lives in the runtime.
    """
    cfg = getattr(getattr(spec, "executor", None), "train", None)
    if cfg is None:
        return None
    members = getattr(cfg, "reduce_members", None)
    shard_map = getattr(cfg, "ps_shards", None)
    if not members or shard_map is None or not getattr(shard_map, "shards", None):
        return None
    reducer = GroupReducer(node, cfg)
    reducer.start()
    log.info(
        "group reducer started: %d members, %d shard(s)",
        len(members), len(shard_map.shards),
    )
    return reducer


class _Bucket:
    """One (round, part)'s group state on the reducer."""

    def __init__(self) -> None:
        self.accum = RoundAccum()
        self.entries: dict[str, tuple[Path, float]] = {}  # peer -> file
        self.first_at: float | None = None
        self.flushed = 0  # partials shipped so far (re-flushes included)
        self.dirty = False  # folds since the last flush


class GroupReducer:
    """Pre-fold this worker's group's deltas; forward partials per shard.

    ``cfg`` is the reducer worker's own ``TrainExecutorConfig`` — it
    carries the placement (``ps_shards``), the wire codec, and the group
    members (``reduce_members``, the OTHER members whose pushes land
    here). The reducer's own delta goes direct to the shard via its
    training loop, so it is never expected in a bucket.
    """

    def __init__(self, node, cfg, work_dir: Path | str | None = None) -> None:
        shard_map = cfg.ps_shards
        if shard_map is None or not shard_map.shards:
            raise ValueError("GroupReducer needs cfg.ps_shards placement")
        self.node = node
        self.cfg = cfg
        self.members = set(cfg.reduce_members or [])
        self.shards: list[str] = list(shard_map.shards)
        self.tags: list[str] = list(shard_map.tags)
        self.num_shards = len(self.shards)
        self.parts = int(shard_map.fragments) or 1
        self._own_dir = work_dir is None
        self.work_dir = Path(
            work_dir
            if work_dir is not None
            else tempfile.mkdtemp(prefix="hypha-reduce-")
        )
        self.codec = compress.effective_codec(
            getattr(cfg, "delta_codec", "none"), getattr(cfg, "delta_dtype", "float32")
        )
        self._efs: dict[int, compress.ErrorFeedback | None] = {}
        self._buckets: dict[tuple[int, int], _Bucket] = {}
        self._flush_after = _flush_after()
        self._task: asyncio.Task | None = None
        self._consumer = None
        # test/bench hooks
        self.folds = 0
        self.unfolds = 0
        self.partials = 0

    # ---------------------------------------------------------- lifecycle

    def start(self) -> None:
        tags = set(self.tags)
        members = set(self.members)

        def wants(push) -> bool:
            # Tag AND sender: a small mesh can colocate this reducer with
            # a PS shard executor on one node (peer reuse), and consumers
            # route first-match — matching by tag alone would steal (and
            # drop) direct-to-shard deltas from workers outside the group.
            r = push.resource
            return (
                isinstance(r, dict)
                and r.get("resource") in tags
                and push.peer in members
            )

        self.work_dir.mkdir(parents=True, exist_ok=True)
        self._consumer = self.node.consume_pushes(wants)
        self._task = aio.spawn(
            self._run(), what="group reducer", logger=log
        )

    async def stop(self) -> None:
        if self._task is not None:
            await aio.reap(self._task)
            self._task = None
        if self._consumer is not None:
            self._consumer.close()
            self._consumer = None
        if self._own_dir:
            await asyncio.to_thread(
                shutil.rmtree, self.work_dir, ignore_errors=True
            )

    # --------------------------------------------------------------- loop

    async def _run(self) -> None:
        assert self._consumer is not None
        while True:
            try:
                push = await self._consumer.next(timeout=_TICK_S)
            except asyncio.TimeoutError:
                await self._flush_due()
                continue
            await self._ingest(push)
            await self._flush_due()

    async def _ingest(self, push) -> None:
        peer = push.peer
        meta = push.resource if isinstance(push.resource, dict) else {}
        if peer not in self.members:
            # Not ours to fold (mis-routed, or a peer outside the group):
            # drain so the sender's accept slot is released.
            log.warning("reducer: push from non-member %s dropped", peer)
            await push.read_all()
            return
        try:
            round_num = int(meta.get("round", 0))
        except (TypeError, ValueError):
            round_num = 0
        part = self._part_of(meta)
        if part is None:
            log.warning("reducer: untagged push from %s dropped", peer)
            await push.read_all()
            return
        dest = self.work_dir / f"in-{round_num}-{part}-{uuid.uuid4().hex[:8]}"
        await push.save_to(dest)
        try:
            samples = float(meta.get("num_samples", 1.0))
        except (TypeError, ValueError):
            samples = 1.0
        bucket = self._buckets.setdefault((round_num, part), _Bucket())
        if bucket.first_at is None:
            bucket.first_at = asyncio.get_running_loop().time()
        old = bucket.entries.pop(peer, None)
        if old is not None:
            # Duplicate re-send: un-fold the superseded delta while its
            # file still holds the original bytes, exactly like the shard
            # does — the next flush ships the corrected cumulative sum.
            log.warning(
                "reducer: duplicate delta from %s (round %d part %d); "
                "replacing", peer, round_num, part,
            )
            await asyncio.to_thread(
                bucket.accum.fold, old[0], old[1], -1.0
            )
            self.unfolds += 1
            old[0].unlink(missing_ok=True)
        await asyncio.to_thread(bucket.accum.fold, dest, samples)
        self.folds += 1
        SHARD_METRICS.reduced_deltas.add(1)
        bucket.entries[peer] = (dest, samples)
        bucket.dirty = True

    def _part_of(self, meta: dict) -> int | None:
        tag = FragmentTag.from_header(meta)
        if tag is not None:
            return int(tag.fragment_id)
        if SHARD_KEY in meta:
            # Blocking/overlap sharded pushes carry the target shard, and
            # part k is owned by shard k by construction (shard_of is the
            # identity when parts == num_shards).
            try:
                return int(meta[SHARD_KEY])
            except (TypeError, ValueError):
                return None
        resource = meta.get("resource")
        if resource in self.tags:
            return self.tags.index(resource)
        return None

    async def _flush_due(self) -> None:
        now = asyncio.get_running_loop().time()
        for (round_num, part), bucket in list(self._buckets.items()):
            if not bucket.dirty:
                continue
            complete = set(bucket.entries) >= self.members
            overdue = (
                bucket.first_at is not None
                and now - bucket.first_at >= self._flush_after
            )
            if complete or overdue or bucket.flushed:
                # bucket.flushed: a straggler landing after a deadline
                # flush re-ships the cumulative partial immediately — the
                # shard replaces the previous one, no second wait.
                await self._flush(round_num, part, bucket)

    async def _flush(self, round_num: int, part: int, bucket: _Bucket) -> None:
        owner = shard_of(part, self.num_shards)
        tag_header = None
        if self.parts > 1 or getattr(self.cfg, "sync_mode", "blocking") == "stream":
            tag_header = FragmentTag(
                round=round_num, fragment_id=part, fragments=self.parts
            ).header()
        if part not in self._efs:
            self._efs[part] = (
                compress.ErrorFeedback()
                if self.codec in compress.QUANT_CODECS
                else None
            )
        wire = self.work_dir / (
            f"partial-{round_num}-{part}-{bucket.flushed}.safetensors"
        )

        def encode() -> None:
            partial = bucket.accum.partial()
            if self.codec == "none":
                from safetensors.numpy import save_file

                save_file(partial, str(wire))
            else:
                compress.write_delta(
                    wire, partial, self.codec, ef=self._efs[part],
                    tag=tag_header,
                )

        await asyncio.to_thread(encode)
        header: dict = {
            "resource": self.tags[owner],
            "name": wire.name,
            "round": round_num,
            "num_samples": float(bucket.accum.total_samples),
            PREFOLD_KEY: True,
            # The worker peers this partial represents: the shard's close
            # condition counts covered WORKERS, not accepted files.
            "covers": sorted(bucket.entries),
        }
        if tag_header:
            header.update(tag_header)
        if self.num_shards > 1:
            header[SHARD_KEY] = owner
        peer = self.shards[owner]
        from ..network.node import RequestError
        from ..worker.connectors import push_timeout

        try:
            await aio.retry(
                lambda: self.node.push(peer, header, wire),
                attempts=3, base_delay=0.25,
                attempt_timeout=push_timeout(wire),
                retry_on=(RequestError, OSError),
                what=f"reduce partial to {peer}", logger=log,
            )
        except (RequestError, OSError, asyncio.TimeoutError) as e:
            # Tolerated: the members' ANY failover (and the shard's
            # quorum/deadline) own liveness; the reducer re-tries on the
            # next dirty flush.
            log.warning(
                "reducer: partial push r%d part %d to %s failed: %s",
                round_num, part, peer, e,
            )
            wire.unlink(missing_ok=True)
            return
        bucket.flushed += 1
        bucket.dirty = False
        self.partials += 1
        wire.unlink(missing_ok=True)
        log.info(
            "reducer: shipped partial r%d part %d -> shard %d "
            "(%d members, weight %.1f)",
            round_num, part, owner, len(bucket.entries),
            bucket.accum.total_samples,
        )
        self._gc(round_num, part)

    def _gc(self, round_num: int, part: int) -> None:
        """Retire buckets for older rounds of the same part: workers ship
        a part's round r+1 only after merging round r, so anything older
        can no longer receive a late member delta worth folding."""
        for key in [
            k for k in self._buckets if k[1] == part and k[0] < round_num
        ]:
            for path, _ in self._buckets[key].entries.values():
                path.unlink(missing_ok=True)
            del self._buckets[key]
