"""The streaming sample-weighted delta accumulator, shared fold/un-fold.

One class, three users:

  * the parameter-server executor folds each arriving delta into a running
    f32 partial sum as it lands (hypha_tpu.worker.ps_executor);
  * a recovered PS re-applies the journaled fold/un-fold sequence to
    rebuild the interrupted round's accumulator bit-exactly
    (hypha_tpu.ft.durable);
  * a tree-reduce group reducer pre-folds its group members' deltas into
    ONE partial sum per shard before anything reaches the parameter
    service (hypha_tpu.stream.reduce).

The arithmetic is deliberately identical at every level: ``fold`` adds
``np.float32(sign * samples) * Δ`` per tensor in arrival order, so a
reducer's partial sum is bit-equal to what the shard itself would have
accumulated from the same deltas in the same order — the property the
tree-reduce layer's correctness (and its tests) rest on.

``prefolded`` folds accept a partial sum that is ALREADY sample-weighted:
the payload adds verbatim (scaled only by ``sign`` for un-folds) while the
shipped ``samples`` header still advances the weight total, so the final
``mean`` divides by the true Σ samples across every level of the tree.

The sample weighting is also what keeps straggler-adaptive rounds
(hypha_tpu.ft.adaptive) unbiased: a worker assigned k/4 inner steps ships
``num_samples`` = the tokens it actually processed, so its delta enters
the mean at exactly its share of the round's data — unequal step counts
change the estimator's variance, never its expectation.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from .. import compress

__all__ = ["RoundAccum"]


class RoundAccum:
    """Streaming sample-weighted fold of one round's delta files.

    Holds ONE param-sized f32 tree (Σ samples·Δθ) instead of every
    worker's decoded delta: ``fold`` runs as each push lands (off the
    event loop via ``asyncio.to_thread``), ``fold(…, sign=-1)`` un-folds a
    replaced duplicate, and :meth:`mean` finishes the weighted mean when
    quorum closes — leaving only the Nesterov step on the critical path.
    """

    def __init__(self) -> None:
        self._acc: dict[str, np.ndarray] = {}
        self._shapes: dict[str, tuple] = {}
        self.total_samples = 0.0
        self.folds = 0

    def fold(
        self,
        path: Path,
        samples: float,
        sign: float = 1.0,
        prefolded: bool = False,
    ) -> None:
        tree = compress.read_delta(path)
        self.fold_tree(tree, samples, sign, prefolded)

    def fold_tree(
        self,
        tree: dict,
        samples: float,
        sign: float = 1.0,
        prefolded: bool = False,
    ) -> None:
        """Fold an already-decoded delta tree (the file-less entry point
        the group reducer uses on its own freshly decoded payloads)."""
        if self._shapes:
            if set(tree) != set(self._shapes):
                raise ValueError("workers sent deltas with mismatched keys")
        # A prefolded payload is already Σ samples·Δ — re-weighting it
        # would square the sample count; only the un-fold sign applies.
        scale = np.float32(sign) if prefolded else np.float32(sign * samples)
        for key, value in tree.items():
            arr = np.asarray(value, np.float32)
            shape = self._shapes.get(key)
            if shape is None:
                self._shapes[key] = arr.shape
            elif arr.shape != shape:
                raise ValueError(
                    f"delta {key!r}: mismatched shape {arr.shape} vs {shape}"
                )
            contrib = scale * arr
            prev = self._acc.get(key)
            if prev is None:
                self._acc[key] = contrib
            else:
                prev += contrib
        self.total_samples += sign * samples
        self.folds += 1 if sign > 0 else -1

    def mean(self) -> dict[str, np.ndarray]:
        """The sample-weighted mean ḡ = Σ samples·Δθ / Σ samples (f32)."""
        if not self._acc:
            raise ValueError("no deltas folded")
        denom = np.float32(max(self.total_samples, 1e-20))
        return {k: v / denom for k, v in self._acc.items()}

    def partial(self) -> dict[str, np.ndarray]:
        """The raw weighted partial sum Σ samples·Δθ (f32) — what a group
        reducer ships to its shard (header ``prefold`` + the weight), so
        the shard's own fold of it is bit-equal to having folded the
        members directly in the same order."""
        if not self._acc:
            raise ValueError("no deltas folded")
        return dict(self._acc)
