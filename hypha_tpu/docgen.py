"""Generate per-command CLI reference markdown from the argparse trees.

The reference auto-generates its CLI docs at build time (clap-markdown in
each crate's build.rs → docs/reference/*.md); this is the same role for
the argparse-based binaries. Output is deterministic, so a test can assert
the committed docs match a fresh render (no drift).

Regenerate:

    python -m hypha_tpu.docgen docs/reference
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

__all__ = ["render_tool", "write_reference", "TOOLS"]


def _tools() -> dict:
    from . import aim_driver, certutil, cli
    from .executor import training

    return {
        "hypha-tpu": (
            cli.build_parser,
            "Node runtimes: gateway / scheduler / worker / data, each with "
            "init / probe / run.",
        ),
        "hypha-certutil": (
            certutil.build_parser,
            "Dev PKI: root CA, org CAs, node certs, CRLs.",
        ),
        "hypha-training-executor": (
            training.build_parser,
            "The DiLoCo inner-loop executor the worker launches per job "
            "(normally spawned by the worker, not by hand).",
        ),
        "hypha-aim-driver": (
            aim_driver.build_parser,
            "Metrics status sink (JSONL / aim backend).",
        ),
    }


TOOLS = _tools


def _action_rows(parser: argparse.ArgumentParser) -> tuple[list, list]:
    """(positionals, options) rows, skipping help/subparser actions."""
    pos, opt = [], []
    for a in parser._actions:  # argparse offers no public walk API
        if isinstance(a, (argparse._HelpAction, argparse._SubParsersAction)):
            continue
        help_ = (a.help or "").replace("|", "\\|")
        if not a.option_strings:
            pos.append((a.metavar or a.dest, help_))
            continue
        flags = ", ".join(f"`{s}`" for s in a.option_strings)
        default = ""
        if a.default not in (None, False, argparse.SUPPRESS):
            default = f"`{a.default}`"
        req = "yes" if a.required else ""
        opt.append((flags, req, default, help_))
    return pos, opt


def _subparsers(parser: argparse.ArgumentParser) -> dict:
    for a in parser._actions:
        if isinstance(a, argparse._SubParsersAction):
            return dict(a.choices)
    return {}


def _render(parser: argparse.ArgumentParser, title: str, depth: int) -> list[str]:
    out = [f"{'#' * min(depth, 6)} `{title}`", ""]
    if parser.description:
        out += [parser.description.strip(), ""]
    usage = parser.format_usage().replace("usage: ", "").strip()
    out += ["**Usage:** `" + " ".join(usage.split()) + "`", ""]
    pos, opt = _action_rows(parser)
    if pos:
        out += ["| argument | description |", "|---|---|"]
        out += [f"| `{n}` | {h} |" for n, h in pos]
        out += [""]
    if opt:
        out += ["| option | required | default | description |", "|---|---|---|---|"]
        out += [f"| {f} | {r} | {d} | {h} |" for f, r, d, h in opt]
        out += [""]
    for name, sub in _subparsers(parser).items():
        out += _render(sub, f"{title} {name}", depth + 1)
    return out


def render_tool(name: str) -> str:
    build, blurb = _tools()[name]
    parser = build()
    lines = _render(parser, name, 1)
    # Insert the one-line tool blurb under the title.
    lines.insert(2, blurb)
    lines.insert(3, "")
    lines.append("")
    return "\n".join(lines)


def render_index() -> str:
    lines = [
        "# CLI reference",
        "",
        "Generated from the argparse trees by `python -m hypha_tpu.docgen "
        "docs/reference` — do not edit by hand (a test asserts these files "
        "match a fresh render).",
        "",
    ]
    for name, (_b, blurb) in _tools().items():
        lines.append(f"- [`{name}`]({name}.md) — {blurb}")
    lines.append("")
    return "\n".join(lines)


def write_reference(out_dir: Path) -> dict[str, str]:
    """Render everything; returns {relative filename: content}."""
    files = {"README.md": render_index()}
    for name in _tools():
        files[f"{name}.md"] = render_tool(name)
    out_dir.mkdir(parents=True, exist_ok=True)
    for rel, content in files.items():
        (out_dir / rel).write_text(content)
    return files


def main(argv: list[str] | None = None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    out = Path(args[0]) if args else Path("docs/reference")
    files = write_reference(out)
    print(f"wrote {len(files)} files to {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
