"""Per-node configuration schemas (the four binaries' Config structs).

Reference: each binary's config module — crates/worker/src/config.rs (the
richest: resources, offer pricing, executor table), crates/scheduler/src/
scheduler_config.rs (the DiLoCo job), and the shared network/TLS/telemetry
sections every binary carries. ``init`` emits these as documented TOML
(config crate ``to_toml``); ``run`` layers TOML ← HYPHA_* env ← CLI.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from .config import ConfigError, TLSConfig
from .ft.membership import FTConfig
from .messages import Adam, LRScheduler, LRSchedulerKind, ModelType, Nesterov, PriceRange
from .resources import Resources
from .scheduler.job_config import DiLoCoJob, DiLoCoRounds, JobResources

__all__ = [
    "NetworkConfig",
    "TelemetryConfig",
    "GatewayConfig",
    "DataNodeConfig",
    "WorkerConfig",
    "SchedulerConfig",
    "ResourcesConfig",
    "OfferConfigSection",
    "MultihostSection",
    "ExecutorSection",
    "JobSection",
]


@dataclass
class NetworkConfig:
    listen: list[str] = field(
        default_factory=lambda: ["127.0.0.1:0"],
        metadata={"doc": "addresses to listen on (host:port; port 0 = ephemeral)"},
    )
    external: list[str] = field(
        default_factory=list,
        metadata={"doc": "publicly reachable addresses to advertise"},
    )
    gateways: list[str] = field(
        default_factory=list,
        metadata={"doc": "gateway addresses to bootstrap from"},
    )
    exclude_cidrs: list[str] = field(
        default_factory=list,
        metadata={"doc": "CIDR ranges never dialed (scheduler network.rs CIDR exclusion)"},
    )
    relay: bool = field(
        default=True,
        metadata={"doc": "hold gateway circuit reservations so NAT'd peers can reach us"},
    )
    advertise_listen: bool = field(
        default=True,
        metadata={
            "doc": "publish listen addresses to discovery; NAT'd nodes set "
            "false (private addrs travel via the direct-upgrade exchange "
            "instead — the dcutr role)"
        },
    )
    mux: bool = field(
        default=False,
        metadata={
            "doc": "multiplex streams over one connection per peer "
            "(yamux-role second transport; lower RPC latency, bulk pushes "
            "prefer the default parallel connections)"
        },
    )


@dataclass
class TelemetryConfig:
    """OTLP export settings (crates/telemetry; OTEL_* env overrides win)."""

    endpoint: str = field(default="", metadata={"doc": "OTLP endpoint; empty = disabled"})
    protocol: str = field(default="http", metadata={"doc": "otlp protocol: http | grpc"})
    service_name: str = field(default="", metadata={"doc": "service.name resource attribute"})
    sample_ratio: float = field(default=1.0, metadata={"doc": "trace sampling ratio 0..1"})
    attributes: dict = field(
        default_factory=dict, metadata={"doc": "extra resource attributes (k = v)"}
    )

    def validate(self) -> None:
        if self.protocol != "http":
            # Only OTLP/HTTP+JSON is implemented; accepting "grpc" here would
            # silently export nothing (the exporter would POST JSON at a gRPC
            # port and drop every failure).
            raise ConfigError(
                f"telemetry.protocol: only 'http' is supported, got {self.protocol!r}"
            )
        if not 0.0 <= self.sample_ratio <= 1.0:
            raise ConfigError("telemetry.sample_ratio must be in [0, 1]")


@dataclass
class ResourcesConfig:
    """Sellable capacity (crates/worker config resources section)."""

    tpu: float = field(default=0.0, metadata={"doc": "TPU chips in this worker's slice"})
    gpu: float = field(default=0.0, metadata={"doc": "GPUs (reference compatibility)"})
    cpu: float = field(default=1.0, metadata={"doc": "CPU cores"})
    memory: float = field(default=1024.0, metadata={"doc": "memory in MB"})
    storage: float = field(default=0.0, metadata={"doc": "scratch storage in MB"})

    def to_resources(self) -> Resources:
        return Resources(
            tpu=self.tpu, gpu=self.gpu, cpu=self.cpu,
            memory=self.memory, storage=self.storage,
        )


@dataclass
class OfferConfigSection:
    """Auction pricing (crates/worker/src/config.rs:54-104)."""

    price: float = field(default=1.0, metadata={"doc": "asking price per weighted unit"})
    floor: float = field(default=0.0, metadata={"doc": "reject ads bidding below this"})
    strategy: str = field(
        default="flexible",
        metadata={"doc": "flexible = offer what was asked; whole = offer everything"},
    )

    def validate(self) -> None:
        if self.strategy not in ("flexible", "whole"):
            raise ConfigError(f"offer.strategy: unknown {self.strategy!r}")


@dataclass
class ExecutorSection:
    """Train-executor runtime (crates/worker/src/config.rs:114-191)."""

    runtime: str = field(
        default="in-process",
        metadata={"doc": "in-process (JAX in the worker) | process (spawn cmd)"},
    )
    cmd: str = field(default="", metadata={"doc": "command for runtime=process"})
    args: list[str] = field(
        default_factory=list,
        metadata={"doc": "args; {SOCKET_PATH} {WORK_DIR} {JOB_JSON} substituted"},
    )

    def validate(self) -> None:
        if self.runtime not in ("in-process", "process"):
            raise ConfigError(f"executor.runtime: unknown {self.runtime!r}")
        if self.runtime == "process" and not self.cmd:
            raise ConfigError("executor.runtime=process needs executor.cmd")


@dataclass
class GatewayConfig:
    name: str = field(default="gateway", metadata={"doc": "node name (cert CN)"})
    network: NetworkConfig = field(default_factory=NetworkConfig)
    tls: TLSConfig = field(default_factory=TLSConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)

    def validate(self) -> None:
        self.tls.validate_files()
        self.telemetry.validate()


@dataclass
class DataNodeConfig:
    name: str = field(default="data", metadata={"doc": "node name (cert CN)"})
    datasets: dict = field(
        default_factory=dict,
        metadata={"doc": "dataset name = directory of SafeTensors slice files"},
    )
    network: NetworkConfig = field(default_factory=NetworkConfig)
    tls: TLSConfig = field(default_factory=TLSConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)

    def validate(self) -> None:
        if not self.datasets:
            raise ConfigError("data node needs at least one [datasets] entry")
        self.tls.validate_files()
        self.telemetry.validate()


@dataclass
class MultihostSection:
    """Pod-slice membership: this worker process joins a multi-host JAX
    runtime before touching the backend, so one replica spans hosts
    (jax.distributed; parallel/multihost.py)."""

    coordinator_address: str = field(
        default="", metadata={"doc": "host:port of process 0; empty = single-host"}
    )
    num_processes: int = field(default=1, metadata={"doc": "processes in the slice"})
    process_id: int = field(default=0, metadata={"doc": "this process's rank"})

    def validate(self) -> None:
        if self.coordinator_address and self.num_processes < 2:
            raise ConfigError(
                "multihost.coordinator_address set but num_processes < 2"
            )
        if self.num_processes > 1 and not self.coordinator_address:
            # Half-configured pods must fail at startup — four workers each
            # running an independent "global" mesh would train silently
            # wrong, not loudly.
            raise ConfigError(
                "multihost.num_processes > 1 needs multihost.coordinator_address"
            )
        if not 0 <= self.process_id < max(self.num_processes, 1):
            raise ConfigError("multihost.process_id out of range")


@dataclass
class WorkerConfig:
    name: str = field(default="worker", metadata={"doc": "node name (cert CN)"})
    work_root: str = field(default="/tmp", metadata={"doc": "per-job work dirs live here"})
    resources: ResourcesConfig = field(default_factory=ResourcesConfig)
    offer: OfferConfigSection = field(default_factory=OfferConfigSection)
    executor: ExecutorSection = field(default_factory=ExecutorSection)
    multihost: MultihostSection = field(default_factory=MultihostSection)
    network: NetworkConfig = field(default_factory=NetworkConfig)
    tls: TLSConfig = field(default_factory=TLSConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)

    def validate(self) -> None:
        self.offer.validate()
        self.executor.validate()
        self.multihost.validate()
        self.tls.validate_files()
        self.telemetry.validate()
        if self.resources.to_resources().is_zero():
            raise ConfigError("worker resources are all zero — nothing to sell")


@dataclass
class JobSection:
    """The DiLoCo job (crates/scheduler/src/scheduler_config.rs:18-180)."""

    # Default job mirrors the reference's (scheduler_config.rs:79-102:
    # 2 workers, 100 rounds, 1200 samples/round, LeNet/MNIST).
    kind: str = field(
        default="train",
        metadata={"doc": "train (DiLoCo) | serve (inference deployment)"},
    )
    serve_name: str = field(
        default="", metadata={"doc": "serve jobs: name announced as serve:<name>"}
    )
    serve_max_new_tokens: int = field(
        default=256, metadata={"doc": "serve jobs: per-request generation cap"}
    )
    serve_max_batch: int = field(
        default=8, metadata={"doc": "serve jobs: prompts per request cap"}
    )
    serve_workers: int = field(
        default=1,
        metadata={
            "doc": "serve jobs: routed deployments to keep alive (>1 turns "
            "the supervisor into a request router with health ejection)"
        },
    )
    serve_queue_limit: int = field(
        default=0,
        metadata={
            "doc": "serve jobs: queue-depth backpressure — reject with "
            "retry-after beyond this many queued requests (0 = unbounded)"
        },
    )
    serve_block_size: int = field(
        default=0,
        metadata={
            "doc": "serve jobs: paged KV block size in positions "
            "(0 = fixed-slot pool, the pre-paging behavior)"
        },
    )
    serve_blocks: int = field(
        default=0,
        metadata={"doc": "serve jobs: physical KV blocks (0 = derive)"},
    )
    serve_prefill_chunk: int = field(
        default=0,
        metadata={
            "doc": "serve jobs: chunked-prefill tokens per decode chunk "
            "(0 = derive: 4x block size)"
        },
    )
    serve_eos_token_id: int = field(
        default=-1,
        metadata={
            "doc": "serve jobs: EOS token freeing KV rows early "
            "(-1 = use the model config's eos_token_id)"
        },
    )
    serve_prefix_cache: bool = field(
        default=False,
        metadata={
            "doc": "serve jobs: automatic prefix caching — shared prompt "
            "prefixes reuse cached KV blocks (paged mode only)"
        },
    )
    serve_spec_ngram: int = field(
        default=0,
        metadata={
            "doc": "serve jobs: speculative decoding via n-gram prompt "
            "lookup, verified by the chunked-prefill program (0 = off; "
            "paged mode only)"
        },
    )
    serve_spec_draft: int = field(
        default=0,
        metadata={
            "doc": "serve jobs: max draft tokens per speculation verify "
            "(0 = derive: prefill chunk - 1)"
        },
    )
    serve_ragged: bool = field(
        default=False,
        metadata={
            "doc": "serve jobs: ragged paged attention — decode visits "
            "occupied KV blocks only, occupancy-proportional cost "
            "(paged mode only; off = dense gather, bit-identical)"
        },
    )
    serve_kv_quant: str = field(
        default="",
        metadata={
            "doc": "serve jobs: KV block quantization — 'int8' stores "
            "K/V blocks as int8 with per-position max-abs scales "
            "(~4x more lanes per byte of KV); '' = full precision "
            "(paged mode only)"
        },
    )
    serve_spec_layers: int = field(
        default=0,
        metadata={
            "doc": "serve jobs: model-draft speculation — self-draft "
            "with the first N layers of the served model, verified by "
            "the chunked-prefill program (0 = off; paged mode only)"
        },
    )
    serve_prefix_affinity: bool = field(
        default=False,
        metadata={
            "doc": "serve jobs: route requests by prompt-prefix hash so "
            "shared-prefix traffic lands where the cache is warm "
            "(routed deployments only)"
        },
    )
    serve_fleet_cache: bool = field(
        default=False,
        metadata={
            "doc": "serve jobs: fleet-wide prefix cache — backends "
            "advertise cached chain hashes on heartbeats, the router "
            "routes to actual holders and names a pull source so cold "
            "workers fetch KV blocks instead of re-prefilling "
            "(requires serve_prefix_cache)"
        },
    )
    serve_kv_migration: bool = field(
        default=False,
        metadata={
            "doc": "serve jobs: migrate a preempted request's KV blocks "
            "+ cursor to a less-loaded worker instead of recomputing "
            "from scratch (requires serve_prefix_cache)"
        },
    )
    serve_digest_k: int = field(
        default=32,
        metadata={
            "doc": "serve jobs: fleet-cache digest bound — top-K hot "
            "chain hashes piggybacked per ServeLoad heartbeat"
        },
    )
    dataset: str = field(
        default="mnist", metadata={"doc": "dataset name announced by a data node"}
    )
    model_family: str = field(
        default="lenet", metadata={"doc": "gpt2 | llama | mixtral | lenet"}
    )
    model_preset: str = field(default="", metadata={"doc": "named preset, e.g. small"})
    model_config: dict = field(
        default_factory=dict, metadata={"doc": "model config overrides"}
    )
    model_seed: int = field(default=0, metadata={"doc": "init seed (same on all workers)"})
    model_type: str = field(
        default="image-classification",
        metadata={"doc": "ModelType selector (38 variants)"},
    )
    update_rounds: int = field(default=100, metadata={"doc": "outer rounds"})
    avg_samples_between_updates: int = field(
        default=1200, metadata={"doc": "round sample budget"}
    )
    max_batch_size: int = field(default=600, metadata={"doc": "per-worker batch cap"})
    num_workers: int = field(default=2, metadata={"doc": "DiLoCo replicas to buy"})
    inner_lr: float = field(default=1e-4, metadata={"doc": "AdamW learning rate"})
    inner_weight_decay: float = field(default=0.0, metadata={"doc": "AdamW weight decay"})
    outer_lr: float = field(default=0.7, metadata={"doc": "Nesterov outer LR"})
    outer_momentum: float = field(default=0.9, metadata={"doc": "Nesterov momentum"})
    lr_schedule: str = field(
        default="constant",
        metadata={"doc": "constant | cosine-with-warmup | linear-with-warmup | wsd"},
    )
    warmup_steps: int = field(default=0, metadata={"doc": "LR warmup steps"})
    total_steps: int = field(default=0, metadata={"doc": "LR schedule horizon"})
    worker_tpu: float = field(default=1.0, metadata={"doc": "chips required per replica"})
    worker_cpu: float = field(default=1.0, metadata={"doc": "cores required per replica"})
    worker_memory: float = field(default=100.0, metadata={"doc": "MB required per replica"})
    ps_cpu: float = field(default=1.0, metadata={"doc": "cores for the parameter server"})
    ps_memory: float = field(default=100.0, metadata={"doc": "MB for the parameter server"})
    worker_bid: float = field(default=1.0, metadata={"doc": "auction bid per worker"})
    worker_max_price: float = field(default=10.0, metadata={"doc": "auction price cap"})
    sharding: dict = field(
        default_factory=dict,
        metadata={"doc": "intra-replica mesh axes: dp/fsdp/tp/sp/ep = n"},
    )
    checkpoint_dir: str = field(
        default="", metadata={"doc": "resume checkpoints under this dir; empty = off"}
    )
    checkpoint_every: int = field(
        default=1, metadata={"doc": "checkpoint every N completed rounds"}
    )
    ps_checkpoint_every_rounds: int = field(
        default=1,
        metadata={
            "doc": "durable PS: outer-state checkpoint every N committed "
            "rounds (journal covers the gap; needs checkpoint_dir)"
        },
    )
    max_attempts: int = field(
        default=1,
        metadata={"doc": "re-run a failed job up to N times (elastic recovery)"},
    )
    quorum_fraction: float = field(
        default=0.0,
        metadata={
            "doc": "elastic rounds: aggregate at ceil(f*active) deltas after "
            "the round deadline; 0 = wait for every worker (seed behavior)"
        },
    )
    round_deadline_s: float = field(
        default=30.0,
        metadata={"doc": "elastic rounds: PS wait before quorum aggregation"},
    )
    phi_threshold: float = field(
        default=8.0,
        metadata={"doc": "phi-accrual suspicion threshold (Cassandra-style)"},
    )
    delta_codec: str = field(
        default="none",
        metadata={
            "doc": "outer-round wire codec: none | bf16 | int8 | int4 "
            "(int8/int4 = chunkwise quantization + error feedback)"
        },
    )
    sync_mode: str = field(
        default="blocking",
        metadata={
            "doc": "outer sync: blocking (ship, wait, merge) | overlap "
            "(upload + broadcast hidden behind inner steps) | stream "
            "(overlap + staggered parameter fragments)"
        },
    )
    num_fragments: int = field(
        default=0,
        metadata={
            "doc": "stream mode: parameter fragments per round cycle "
            "(0 = default 4); each fragment syncs every num_fragments rounds"
        },
    )
    input_pipeline: bool = field(
        default=False,
        metadata={
            "doc": "async input pipeline: background slice prefetch + "
            "zero-copy batch assembly + deferred device sync (batch order "
            "and losses stay bit-exact; off = the synchronous loader)"
        },
    )
    prefetch_slices: int = field(
        default=0,
        metadata={
            "doc": "input pipeline: dataset slices fetched ahead / held "
            "per worker (0 = executor default; needs input_pipeline)"
        },
    )
    adaptive_steps: bool = field(
        default=False,
        metadata={
            "doc": "straggler-adaptive inner steps: per-worker step counts "
            "from EWMA round-trip history (off = the reference projection)"
        },
    )
    adaptive_codec: bool = field(
        default=False,
        metadata={
            "doc": "per-link codec selection: slow links degrade to "
            "int8/int4 from the PS's measured-bandwidth table (off = one "
            "job-wide delta_codec)"
        },
    )
    codec_bw_hi_mbps: float = field(
        default=100.0,
        metadata={"doc": "adaptive_codec: links >= this keep the job codec"},
    )
    codec_bw_lo_mbps: float = field(
        default=10.0,
        metadata={"doc": "adaptive_codec: links below this ship int4"},
    )
    metrics_plane: bool = field(
        default=False,
        metadata={
            "doc": "live metrics plane: nodes push periodic MetricsReport "
            "deltas to the scheduler on /hypha-metrics/0.0.1; the scheduler "
            "aggregates, journals metrics-<job>.jsonl and evaluates "
            "slo_rules (off = byte-identical wire)"
        },
    )
    metrics_interval_s: float = field(
        default=1.0,
        metadata={"doc": "metrics plane: seconds between node reports"},
    )
    metrics_dir: str = field(
        default="",
        metadata={
            "doc": "metrics plane: journal directory (empty = the trace "
            "dir when tracing is on, else no journal)"
        },
    )
    slo_rules: list = field(
        default_factory=list,
        metadata={
            "doc": "metrics plane: declarative SLO rules, e.g. "
            "'hypha.serve.request_latency_ms.p99 <= 250', "
            "'round_wall_s <= 30', 'silent_s <= 15' — breaches log "
            "advisories and fire flight events"
        },
    )

    def validate(self) -> None:
        if self.kind not in ("train", "serve"):
            raise ConfigError("job.kind must be 'train' or 'serve'")
        try:
            ModelType(self.model_type)
        except ValueError:
            raise ConfigError(
                f"job.model_type: unknown {self.model_type!r}"
            ) from None
        if self.kind == "serve":
            if not self.serve_name:
                raise ConfigError("job.serve_name is required for serve jobs")
            if self.serve_max_new_tokens < 1:
                raise ConfigError("job.serve_max_new_tokens must be >= 1")
            if self.serve_max_batch < 1:
                raise ConfigError("job.serve_max_batch must be >= 1")
            if self.serve_workers < 1:
                raise ConfigError("job.serve_workers must be >= 1")
            if self.serve_queue_limit < 0:
                raise ConfigError("job.serve_queue_limit must be >= 0")
            if self.serve_block_size < 0:
                raise ConfigError("job.serve_block_size must be >= 0")
            if self.serve_spec_ngram < 0:
                raise ConfigError("job.serve_spec_ngram must be >= 0")
            if self.serve_spec_draft < 0:
                raise ConfigError("job.serve_spec_draft must be >= 0")
            if self.serve_prefix_cache and self.serve_block_size <= 0:
                raise ConfigError(
                    "job.serve_prefix_cache requires serve_block_size > 0 "
                    "(paged mode)"
                )
            if self.serve_spec_ngram > 0 and self.serve_block_size <= 0:
                raise ConfigError(
                    "job.serve_spec_ngram requires serve_block_size > 0 "
                    "(paged mode)"
                )
            if self.serve_ragged and self.serve_block_size <= 0:
                raise ConfigError(
                    "job.serve_ragged requires serve_block_size > 0 "
                    "(paged mode)"
                )
            if self.serve_kv_quant not in ("", "int8"):
                raise ConfigError(
                    "job.serve_kv_quant must be '' or 'int8'"
                )
            if self.serve_kv_quant and self.serve_block_size <= 0:
                raise ConfigError(
                    "job.serve_kv_quant requires serve_block_size > 0 "
                    "(paged mode)"
                )
            if self.serve_spec_layers < 0:
                raise ConfigError("job.serve_spec_layers must be >= 0")
            if self.serve_spec_layers > 0 and self.serve_block_size <= 0:
                raise ConfigError(
                    "job.serve_spec_layers requires serve_block_size > 0 "
                    "(paged mode)"
                )
            if (
                self.serve_fleet_cache or self.serve_kv_migration
            ) and not self.serve_prefix_cache:
                raise ConfigError(
                    "job.serve_fleet_cache / serve_kv_migration require "
                    "serve_prefix_cache (content-addressed blocks)"
                )
            if self.serve_digest_k < 1:
                raise ConfigError("job.serve_digest_k must be >= 1")
            return  # dataset/rounds are train-only concerns
        if not self.dataset:
            raise ConfigError("job.dataset is required")
        if self.max_attempts < 1:
            raise ConfigError("job.max_attempts must be >= 1")
        if self.ps_checkpoint_every_rounds < 1:
            raise ConfigError("job.ps_checkpoint_every_rounds must be >= 1")
        if not 0.0 <= self.quorum_fraction <= 1.0:
            raise ConfigError("job.quorum_fraction must be in [0, 1]")
        from .compress import CODECS

        if self.delta_codec not in CODECS:
            raise ConfigError(
                f"job.delta_codec must be one of {'|'.join(CODECS)}, "
                f"got {self.delta_codec!r}"
            )
        from .stream import SYNC_MODES

        if self.sync_mode not in SYNC_MODES:
            raise ConfigError(
                f"job.sync_mode must be one of {'|'.join(SYNC_MODES)}, "
                f"got {self.sync_mode!r}"
            )
        if self.num_fragments < 0:
            raise ConfigError("job.num_fragments must be >= 0 (0 = default)")
        if self.prefetch_slices < 0:
            raise ConfigError("job.prefetch_slices must be >= 0 (0 = default)")
        if self.prefetch_slices > 0 and not self.input_pipeline:
            raise ConfigError("job.prefetch_slices needs job.input_pipeline")
        if self.adaptive_codec and self.sync_mode != "blocking":
            raise ConfigError(
                "job.adaptive_codec requires sync_mode = blocking"
            )
        if self.adaptive_codec and self.checkpoint_dir:
            raise ConfigError(
                "job.adaptive_codec is not supported with checkpoint_dir yet"
            )
        if self.codec_bw_lo_mbps > self.codec_bw_hi_mbps:
            raise ConfigError(
                "job.codec_bw_lo_mbps must be <= job.codec_bw_hi_mbps"
            )
        if self.metrics_interval_s <= 0:
            raise ConfigError("job.metrics_interval_s must be positive")
        if self.slo_rules:
            from .telemetry.slo import parse_slo_rule

            for rule in self.slo_rules:
                try:
                    parse_slo_rule(str(rule))
                except ValueError as e:
                    raise ConfigError(f"job.slo_rules: {e}") from None
        if self.round_deadline_s < 0:
            raise ConfigError("job.round_deadline_s must be >= 0")
        if self.phi_threshold <= 0:
            raise ConfigError("job.phi_threshold must be positive")
        try:
            ModelType(self.model_type)
        except ValueError:
            raise ConfigError(f"job.model_type: unknown {self.model_type!r}")
        try:
            LRSchedulerKind(self.lr_schedule)
        except ValueError:
            raise ConfigError(f"job.lr_schedule: unknown {self.lr_schedule!r}")

    def to_model_spec(self) -> dict:
        """The model dict shared by train and serve jobs."""
        model: dict[str, Any] = {
            "model_type": ModelType(self.model_type),
            "family": self.model_family,
            "seed": self.model_seed,
        }
        if self.model_preset:
            model["preset"] = self.model_preset
        if self.model_config:
            model["config"] = dict(self.model_config)
        return model

    def worker_resources(self) -> Resources:
        return Resources(
            tpu=self.worker_tpu, cpu=self.worker_cpu, memory=self.worker_memory
        )

    def worker_price(self) -> PriceRange:
        return PriceRange(bid=self.worker_bid, max=self.worker_max_price)

    def to_job(self) -> DiLoCoJob:
        model = self.to_model_spec()
        schedule = None
        if self.lr_schedule != "constant":
            schedule = LRScheduler(
                kind=LRSchedulerKind(self.lr_schedule),
                warmup_steps=self.warmup_steps,
                total_steps=self.total_steps,
            )
        return DiLoCoJob(
            model=model,
            dataset=self.dataset,
            rounds=DiLoCoRounds(
                update_rounds=self.update_rounds,
                avg_samples_between_updates=self.avg_samples_between_updates,
                max_batch_size=self.max_batch_size,
            ),
            inner_optimizer=Adam(lr=self.inner_lr, weight_decay=self.inner_weight_decay),
            outer_optimizer=Nesterov(lr=self.outer_lr, momentum=self.outer_momentum),
            resources=JobResources(
                num_workers=self.num_workers,
                worker=self.worker_resources(),
                parameter_server=Resources(cpu=self.ps_cpu, memory=self.ps_memory),
                worker_price=self.worker_price(),
                parameter_server_price=self.worker_price(),
            ),
            lr_scheduler=schedule,
            sharding=dict(self.sharding) or None,
            checkpoint_dir=self.checkpoint_dir or None,
            checkpoint_every=self.checkpoint_every,
            ps_checkpoint_every_rounds=self.ps_checkpoint_every_rounds,
            delta_codec=self.delta_codec,
            sync_mode=self.sync_mode,
            num_fragments=self.num_fragments,
            input_pipeline=self.input_pipeline,
            prefetch_slices=self.prefetch_slices,
            adaptive_steps=self.adaptive_steps,
            adaptive_codec=self.adaptive_codec,
            codec_bw_hi_mbps=self.codec_bw_hi_mbps,
            codec_bw_lo_mbps=self.codec_bw_lo_mbps,
            metrics_plane=self.metrics_plane,
            metrics_interval_s=self.metrics_interval_s,
            metrics_dir=self.metrics_dir or None,
            slo_rules=list(self.slo_rules),
            ft=(
                FTConfig(
                    quorum_fraction=self.quorum_fraction,
                    round_deadline_s=self.round_deadline_s,
                    phi_threshold=self.phi_threshold,
                )
                if self.quorum_fraction > 0
                else None
            ),
        )


@dataclass
class SchedulerConfig:
    name: str = field(default="scheduler", metadata={"doc": "node name (cert CN)"})
    status_bridge: str = field(
        default="", metadata={"doc": "AIM metrics sink host:port; empty = log only"}
    )
    job: JobSection = field(default_factory=JobSection)
    network: NetworkConfig = field(default_factory=NetworkConfig)
    tls: TLSConfig = field(default_factory=TLSConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)

    def validate(self) -> None:
        self.job.validate()
        self.tls.validate_files()
        self.telemetry.validate()
