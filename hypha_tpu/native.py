"""ctypes bindings for the native (C++) runtime layer, with numpy fallback.

The reference's native layer is its Rust crates; the numerical hot spot is
the parameter server's outer step (SURVEY.md §2.9: candle-core averaging +
Nesterov over mmapped SafeTensors). The C++ equivalents live in
``native/``:

  * ``hypha_ps.cpp``          — flat f32 kernels (weighted sum, Nesterov,
    fused mean+Nesterov);
  * ``hypha_safetensors.cpp`` — mmap'd SafeTensors reader (own JSON header
    parser), writer, and ``ps_outer_step``: the WHOLE outer step over the
    delta files, zero-copy;
  * ``hypha_io.cpp``          — sendfile(2) file→socket fast path for bulk
    tensor serving (the data node's io::copy role, tensor_data.rs:8-16);
  * ``hypha_quant.cpp``       — chunkwise int8/int4 quantization for the
    compressed delta transport (hypha_tpu.compress), bit-exact against
    the numpy fallback there.

Everything is compiled on first use with the system g++ into one shared
library and cached. Environments without a toolchain transparently fall
back to numpy/Python paths — results are identical.
"""

from __future__ import annotations

import ctypes
import logging
import subprocess
from pathlib import Path

import numpy as np

__all__ = [
    "weighted_sum",
    "nesterov_update",
    "fused_mean_nesterov",
    "native_available",
    "ps_outer_step",
    "send_file_fd",
    "SafeTensorsView",
    "quant_chunks",
    "dequant_chunks",
]

log = logging.getLogger("hypha.native")

_REPO = Path(__file__).resolve().parent.parent
_SRCS = [
    _REPO / "native" / "hypha_ps.cpp",
    _REPO / "native" / "hypha_safetensors.cpp",
    _REPO / "native" / "hypha_io.cpp",
    _REPO / "native" / "hypha_quant.cpp",
]
_SO = _REPO / "native" / "build" / "libhypha_native.so"

_lib: ctypes.CDLL | None = None
_tried = False

_F32P = ctypes.POINTER(ctypes.c_float)


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    try:
        newest_src = max(src.stat().st_mtime for src in _SRCS)
        if not _SO.exists() or _SO.stat().st_mtime < newest_src:
            _SO.parent.mkdir(parents=True, exist_ok=True)
            subprocess.run(
                [
                    "g++", "-O3", "-march=native", "-std=c++17", "-shared",
                    "-fPIC", *map(str, _SRCS), "-o", str(_SO),
                ],
                check=True,
                capture_output=True,
                timeout=300,
            )
        lib = ctypes.CDLL(str(_SO))
        lib.weighted_sum_f32.argtypes = [
            ctypes.POINTER(_F32P), _F32P, ctypes.c_int64, _F32P, ctypes.c_int64,
        ]
        lib.nesterov_update_f32.argtypes = [
            _F32P, _F32P, _F32P, ctypes.c_int64, ctypes.c_float, ctypes.c_float,
        ]
        lib.fused_mean_nesterov_f32.argtypes = [
            ctypes.POINTER(_F32P), _F32P, ctypes.c_int64,
            _F32P, _F32P, ctypes.c_int64, ctypes.c_float, ctypes.c_float,
        ]
        lib.st_open.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        lib.st_open.restype = ctypes.c_void_p
        lib.st_close.argtypes = [ctypes.c_void_p]
        lib.st_count.argtypes = [ctypes.c_void_p]
        lib.st_count.restype = ctypes.c_int64
        lib.st_name.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.st_name.restype = ctypes.c_char_p
        lib.st_tensor.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int, ctypes.POINTER(ctypes.c_int),
        ]
        lib.st_tensor.restype = ctypes.c_void_p
        lib.ps_outer_step.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int64, _F32P,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_float, ctypes.c_float, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.ps_outer_step.restype = ctypes.c_int64
        lib.send_file_fd.argtypes = [ctypes.c_int, ctypes.c_char_p]
        lib.send_file_fd.restype = ctypes.c_int64
        _U8P = ctypes.POINTER(ctypes.c_uint8)
        lib.quant_chunks_f32.argtypes = [
            _F32P, ctypes.c_int64, ctypes.c_int64, ctypes.c_int, _U8P, _F32P,
        ]
        lib.quant_chunks_f32.restype = ctypes.c_int64
        lib.dequant_chunks_f32.argtypes = [
            _U8P, _F32P, ctypes.c_int64, ctypes.c_int64, ctypes.c_int, _F32P,
        ]
        lib.dequant_chunks_f32.restype = ctypes.c_int64
        _lib = lib
    except (subprocess.SubprocessError, OSError, FileNotFoundError) as e:
        log.info("native kernels unavailable (%s); using numpy", e)
        _lib = None
    return _lib


def native_available() -> bool:
    return _load() is not None


def _as_f32(a: np.ndarray) -> np.ndarray:
    out = np.ascontiguousarray(a, dtype=np.float32)
    return out


def _ptr(a: np.ndarray) -> "ctypes._Pointer":
    return a.ctypes.data_as(_F32P)


def weighted_sum(srcs: list[np.ndarray], weights: np.ndarray) -> np.ndarray:
    """sum_k w[k] * srcs[k]; pass normalized weights for a weighted mean."""
    srcs = [_as_f32(s).ravel() for s in srcs]
    w = _as_f32(np.asarray(weights)).ravel()
    n = srcs[0].size
    lib = _load()
    if lib is None:
        return sum(wk * s for wk, s in zip(w, srcs)).astype(np.float32)
    dst = np.empty(n, np.float32)
    arr_type = _F32P * len(srcs)
    lib.weighted_sum_f32(
        arr_type(*(_ptr(s) for s in srcs)), _ptr(w), len(srcs), _ptr(dst), n
    )
    return dst


def nesterov_update(
    momentum: np.ndarray, grad: np.ndarray, lr: float, mu: float
) -> tuple[np.ndarray, np.ndarray]:
    """m <- mu*m + g; update <- lr*(mu*m + g). Returns (momentum, update)."""
    m = _as_f32(momentum).ravel().copy()
    g = _as_f32(grad).ravel()
    lib = _load()
    if lib is None:
        m = mu * m + g
        return m, (lr * (mu * m + g)).astype(np.float32)
    upd = np.empty_like(g)
    lib.nesterov_update_f32(_ptr(m), _ptr(g), _ptr(upd), g.size, lr, mu)
    return m, upd


def fused_mean_nesterov(
    srcs: list[np.ndarray],
    weights: np.ndarray,
    momentum: np.ndarray,
    lr: float,
    mu: float,
) -> tuple[np.ndarray, np.ndarray]:
    """Weighted mean of ``srcs`` then Nesterov, one pass.
    Returns (momentum, update)."""
    srcs = [_as_f32(s).ravel() for s in srcs]
    w = _as_f32(np.asarray(weights)).ravel()
    m = _as_f32(momentum).ravel().copy()
    lib = _load()
    if lib is None:
        g = sum(wk * s for wk, s in zip(w, srcs)).astype(np.float32)
        m = mu * m + g
        return m, (lr * (mu * m + g)).astype(np.float32)
    upd = np.empty_like(m)
    arr_type = _F32P * len(srcs)
    lib.fused_mean_nesterov_f32(
        arr_type(*(_ptr(s) for s in srcs)), _ptr(w), len(srcs),
        _ptr(m), _ptr(upd), m.size, lr, mu,
    )
    return m, upd


# ---------------------------------------------------------------------------
# Native SafeTensors + outer step + data-plane IO
# ---------------------------------------------------------------------------

_DTYPES = {
    "F32": np.float32,
    "F64": np.float64,
    "F16": np.float16,
    "I64": np.int64,
    "I32": np.int32,
    "I16": np.int16,
    "I8": np.int8,
    "U8": np.uint8,
    "BOOL": np.bool_,
}


class SafeTensorsView:
    """Zero-copy mmap'd SafeTensors reader over the native parser.

    Tensors come back as numpy views into the mapping (read-only); the
    mapping lives until close(). Raises OSError when the native library is
    unavailable — callers fall back to safetensors.numpy.
    """

    def __init__(self, path: str | Path) -> None:
        lib = _load()
        if lib is None:
            raise OSError("native library unavailable")
        err = ctypes.create_string_buffer(256)
        self._lib = lib
        self._handle = lib.st_open(str(path).encode(), err, len(err))
        if not self._handle:
            raise ValueError(f"st_open({path}): {err.value.decode()}")

    def _live_handle(self):
        # After close() the C layer would dereference NULL -> SIGSEGV;
        # surface a Python error instead.
        if not self._handle:
            raise ValueError("SafeTensorsView is closed")
        return self._handle

    def keys(self) -> list[str]:
        handle = self._live_handle()
        n = self._lib.st_count(handle)
        return [self._lib.st_name(handle, i).decode() for i in range(n)]

    def tensor(self, name: str) -> np.ndarray:
        handle = self._live_handle()
        nbytes = ctypes.c_int64()
        dtype_buf = ctypes.create_string_buffer(16)
        shape = (ctypes.c_int64 * 16)()
        ndim = ctypes.c_int()
        ptr = self._lib.st_tensor(
            handle, name.encode(), ctypes.byref(nbytes),
            dtype_buf, len(dtype_buf), shape, 16, ctypes.byref(ndim),
        )
        if not ptr:
            raise KeyError(name)
        dtype_name = dtype_buf.value.decode()
        if dtype_name == "BF16":
            # ml_dtypes ships with jax; imported lazily so the PS path (pure
            # f32) keeps working in stripped environments.
            import ml_dtypes

            dtype = ml_dtypes.bfloat16
        else:
            dtype = _DTYPES.get(dtype_name)
        if dtype is None:
            raise ValueError(f"unsupported dtype {dtype_name!r} for {name}")
        buf = (ctypes.c_char * nbytes.value).from_address(ptr)
        # The array's base chain ends at `buf`; anchor the view there so a
        # GC'd SafeTensorsView can't munmap pages a live array still reads
        # (explicit close() remains the caller's contract).
        buf._owner = self
        arr = np.frombuffer(buf, dtype=dtype)
        # The mapping is PROT_READ: an in-place write through a writable
        # view would SIGSEGV, not raise. Make numpy enforce it.
        arr.flags.writeable = False
        dims = tuple(shape[i] for i in range(ndim.value))
        return arr.reshape(dims)

    def close(self) -> None:
        if self._handle:
            self._lib.st_close(self._handle)
            self._handle = None

    def __del__(self) -> None:  # leak guard; safe: arrays anchor self via buf
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self) -> "SafeTensorsView":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def ps_outer_step(
    delta_paths: list[str | Path],
    weights: np.ndarray,
    momentum_in: str | Path | None,
    momentum_out: str | Path,
    update_out: str | Path,
    lr: float,
    mu: float,
) -> int | None:
    """The whole DiLoCo outer step in C++ over mmapped delta files.

    Returns total elements processed, or None when the native library is
    unavailable (caller falls back to the Python path). Raises ValueError
    on malformed/mismatched inputs.
    """
    lib = _load()
    if lib is None:
        return None
    paths = [str(p).encode() for p in delta_paths]
    arr = (ctypes.c_char_p * len(paths))(*paths)
    w = _as_f32(np.asarray(weights)).ravel()
    if w.size != len(paths):
        raise ValueError("one weight per delta file required")
    err = ctypes.create_string_buffer(256)
    total = lib.ps_outer_step(
        arr,
        len(paths),
        _ptr(w),
        str(momentum_in).encode() if momentum_in else b"",
        str(momentum_out).encode(),
        str(update_out).encode(),
        lr,
        mu,
        err,
        len(err),
    )
    if total < 0:
        raise ValueError(f"ps_outer_step failed: {err.value.decode()}")
    return int(total)


_QUANT_BITS = {"int8": 8, "int4": 4}


def _u8ptr(a: np.ndarray) -> "ctypes._Pointer":
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def quant_chunks(
    src: np.ndarray, chunk: int, codec: str,
    payload_out: np.ndarray, scales_out: np.ndarray,
) -> bool:
    """Chunkwise quantize ``src`` (contiguous f32) in place into the
    caller's payload/scales buffers. Returns False when the native library
    is unavailable (caller runs the bit-exact numpy spec instead)."""
    lib = _load()
    if lib is None:
        return False
    wrote = lib.quant_chunks_f32(
        _ptr(src), src.size, chunk, _QUANT_BITS[codec],
        _u8ptr(payload_out), _ptr(scales_out),
    )
    if wrote < 0:
        raise ValueError(f"quant_chunks_f32 rejected args (codec {codec})")
    return True


def dequant_chunks(
    payload: np.ndarray, scales: np.ndarray, n: int, chunk: int, codec: str,
    dst: np.ndarray,
) -> bool:
    """Invert :func:`quant_chunks` into ``dst`` (f32, ``n`` elements).
    Returns False when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return False
    got = lib.dequant_chunks_f32(
        _u8ptr(payload), _ptr(scales), n, chunk, _QUANT_BITS[codec], _ptr(dst)
    )
    if got < 0:
        raise ValueError(f"dequant_chunks_f32 rejected args (codec {codec})")
    return True


def send_file_fd(fd: int, path: str | Path) -> int | None:
    """sendfile(2) loop: file -> connected socket fd. Returns bytes sent,
    None if the native library is unavailable. Raises OSError on errno."""
    lib = _load()
    if lib is None:
        return None
    n = lib.send_file_fd(fd, str(path).encode())
    if n < 0:
        import os

        raise OSError(-n, os.strerror(-n), str(path))
    return int(n)
