"""ctypes bindings for the native (C++) tensor kernels, with numpy fallback.

The runtime's numerical hot spot outside JAX is the parameter server's
outer step (SURVEY.md §2.9: the reference's only native math is Rust
candle-core averaging + Nesterov). The C++ source lives in
``native/hypha_ps.cpp``; it is compiled on first use with the system g++
into ``native/build/libhypha_ps.so`` and cached. Environments without a
toolchain transparently fall back to numpy — results are identical, the
C++ path just fuses the passes.
"""

from __future__ import annotations

import ctypes
import logging
import subprocess
from pathlib import Path

import numpy as np

__all__ = ["weighted_sum", "nesterov_update", "fused_mean_nesterov", "native_available"]

log = logging.getLogger("hypha.native")

_REPO = Path(__file__).resolve().parent.parent
_SRC = _REPO / "native" / "hypha_ps.cpp"
_SO = _REPO / "native" / "build" / "libhypha_ps.so"

_lib: ctypes.CDLL | None = None
_tried = False

_F32P = ctypes.POINTER(ctypes.c_float)


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    try:
        if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
            _SO.parent.mkdir(parents=True, exist_ok=True)
            subprocess.run(
                [
                    "g++", "-O3", "-march=native", "-shared", "-fPIC",
                    str(_SRC), "-o", str(_SO),
                ],
                check=True,
                capture_output=True,
                timeout=120,
            )
        lib = ctypes.CDLL(str(_SO))
        lib.weighted_sum_f32.argtypes = [
            ctypes.POINTER(_F32P), _F32P, ctypes.c_int64, _F32P, ctypes.c_int64,
        ]
        lib.nesterov_update_f32.argtypes = [
            _F32P, _F32P, _F32P, ctypes.c_int64, ctypes.c_float, ctypes.c_float,
        ]
        lib.fused_mean_nesterov_f32.argtypes = [
            ctypes.POINTER(_F32P), _F32P, ctypes.c_int64,
            _F32P, _F32P, ctypes.c_int64, ctypes.c_float, ctypes.c_float,
        ]
        _lib = lib
    except (subprocess.SubprocessError, OSError, FileNotFoundError) as e:
        log.info("native kernels unavailable (%s); using numpy", e)
        _lib = None
    return _lib


def native_available() -> bool:
    return _load() is not None


def _as_f32(a: np.ndarray) -> np.ndarray:
    out = np.ascontiguousarray(a, dtype=np.float32)
    return out


def _ptr(a: np.ndarray) -> "ctypes._Pointer":
    return a.ctypes.data_as(_F32P)


def weighted_sum(srcs: list[np.ndarray], weights: np.ndarray) -> np.ndarray:
    """sum_k w[k] * srcs[k]; pass normalized weights for a weighted mean."""
    srcs = [_as_f32(s).ravel() for s in srcs]
    w = _as_f32(np.asarray(weights)).ravel()
    n = srcs[0].size
    lib = _load()
    if lib is None:
        return sum(wk * s for wk, s in zip(w, srcs)).astype(np.float32)
    dst = np.empty(n, np.float32)
    arr_type = _F32P * len(srcs)
    lib.weighted_sum_f32(
        arr_type(*(_ptr(s) for s in srcs)), _ptr(w), len(srcs), _ptr(dst), n
    )
    return dst


def nesterov_update(
    momentum: np.ndarray, grad: np.ndarray, lr: float, mu: float
) -> tuple[np.ndarray, np.ndarray]:
    """m <- mu*m + g; update <- lr*(mu*m + g). Returns (momentum, update)."""
    m = _as_f32(momentum).ravel().copy()
    g = _as_f32(grad).ravel()
    lib = _load()
    if lib is None:
        m = mu * m + g
        return m, (lr * (mu * m + g)).astype(np.float32)
    upd = np.empty_like(g)
    lib.nesterov_update_f32(_ptr(m), _ptr(g), _ptr(upd), g.size, lr, mu)
    return m, upd


def fused_mean_nesterov(
    srcs: list[np.ndarray],
    weights: np.ndarray,
    momentum: np.ndarray,
    lr: float,
    mu: float,
) -> tuple[np.ndarray, np.ndarray]:
    """Weighted mean of ``srcs`` then Nesterov, one pass.
    Returns (momentum, update)."""
    srcs = [_as_f32(s).ravel() for s in srcs]
    w = _as_f32(np.asarray(weights)).ravel()
    m = _as_f32(momentum).ravel().copy()
    lib = _load()
    if lib is None:
        g = sum(wk * s for wk, s in zip(w, srcs)).astype(np.float32)
        m = mu * m + g
        return m, (lr * (mu * m + g)).astype(np.float32)
    upd = np.empty_like(m)
    arr_type = _F32P * len(srcs)
    lib.fused_mean_nesterov_f32(
        arr_type(*(_ptr(s) for s in srcs)), _ptr(w), len(srcs),
        _ptr(m), _ptr(upd), m.size, lr, mu,
    )
    return m, upd
