"""Asyncio task lifecycle helpers: the blessed shapes hypha-lint checks for.

Three recurring needs across the scheduler / worker / network layers, each
previously hand-rolled slightly differently (and slightly wrong) at every
site:

  * :func:`spawn` — create a background task that can NEVER become an
    exception black hole: the handle is retained (optionally in a caller
    set) and a done-callback logs any failure and bumps
    :data:`TASK_FAILURES`, so a dead heartbeat pump or membership push
    surfaces in telemetry the moment it dies instead of at GC time;
  * :func:`reap` — cancel-and-await teardown that absorbs the reaped
    tasks' outcomes (including their ``CancelledError``) while still
    propagating cancellation *of the caller* — the subtlety every
    ``except (CancelledError, Exception): pass`` site got wrong;
  * :func:`wait_quiet` — await something whose outcome you don't care
    about, bounded by an optional timeout, again without eating the
    caller's own cancellation;
  * :func:`retry` — jittered exponential backoff around a transient
    operation (a fabric push across a parameter-server restart, a
    catch-up send to a rejoiner), with a per-attempt timeout and an
    overall deadline so a dead peer fails the caller in bounded time
    instead of parking it forever.  Worker executors must route fabric
    pushes through this (hypha-lint ``naked-stream-push``).

``asyncio.gather(..., return_exceptions=True)`` is the primitive that makes
the cancellation semantics right: child outcomes become return values, but
cancellation delivered to the *waiter* still raises through the await.
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Any, Awaitable, Callable, Coroutine, MutableSet, TypeVar

from .telemetry import Counter

__all__ = [
    "TASK_FAILURES",
    "spawn",
    "reap",
    "wait_quiet",
    "retry",
    "gather_bounded",
]

log = logging.getLogger("hypha.aio")

# Background tasks that died with an unexpected exception (exported as an
# observable gauge wherever a Meter is wired up; tests read .value()).
TASK_FAILURES = Counter("hypha.aio.task_failures")


def spawn(
    coro: Coroutine[Any, Any, Any],
    *,
    name: str | None = None,
    tasks: MutableSet[asyncio.Task] | None = None,
    what: str = "",
    logger: logging.Logger | None = None,
) -> asyncio.Task:
    """``create_task`` with mandatory exception surfacing.

    ``tasks`` (usually the owner's ``self._tasks`` set) keeps a strong
    reference until completion; the done-callback logs non-cancellation
    failures and counts them in :data:`TASK_FAILURES`.
    """
    task = asyncio.create_task(coro, name=name or what or None)
    if tasks is not None:
        tasks.add(task)
        task.add_done_callback(tasks.discard)
    label = what or name or getattr(coro, "__qualname__", "task")
    lg = logger or log

    def _surface(t: asyncio.Task) -> None:
        if t.cancelled():
            return
        exc = t.exception()
        if exc is not None:
            TASK_FAILURES.add(1)
            lg.error("background task %r failed: %r", label, exc)

    task.add_done_callback(_surface)
    return task


async def reap(*tasks: asyncio.Task | None) -> None:
    """Cancel the given tasks and await them to actual completion.

    Outcomes (results, exceptions, their cancellation) are absorbed —
    anything noteworthy was already logged by :func:`spawn`'s callback.
    Cancellation of the *caller* propagates normally, so shutdown paths
    built on ``reap`` stay cancellable.
    """
    live = [t for t in tasks if t is not None]
    for t in live:
        t.cancel()
    live = [t for t in live if not t.done()]
    while live:
        # Re-cancel periodically: py3.10's wait_for can swallow a
        # cancellation that races the inner future completing (the task
        # keeps looping, un-cancelled, and a single .cancel() above would
        # leave this await parked forever — seen with a consumer.next()
        # racing a push at teardown).
        done, pending = await asyncio.wait(live, timeout=1.0)
        for t in pending:
            t.cancel()
        live = list(pending)


async def wait_quiet(
    *aws: Awaitable[Any] | None, timeout: float | None = None
) -> None:
    """Await things whose failure/result is someone else's problem.

    On timeout the awaitables are cancelled (``asyncio.wait_for``
    semantics) and the timeout is swallowed; caller cancellation always
    propagates.
    """
    live = [a for a in aws if a is not None]
    if not live:
        return
    gathered = asyncio.gather(*live, return_exceptions=True)
    if timeout is None:
        await gathered
        return
    try:
        await asyncio.wait_for(gathered, timeout)
    except asyncio.TimeoutError:
        pass


_T = TypeVar("_T")


async def gather_bounded(
    fns: "list[Callable[[], Awaitable[_T]]]", *, limit: int = 8
) -> "list[_T]":
    """Run awaitable FACTORIES concurrently, at most ``limit`` in flight,
    returning results in input order.

    The fleet-scale fan-out primitive (ISSUE 14): a serial
    ``for peer: await`` walk makes every control-plane sweep O(N) round
    trips, while an unbounded gather at N=128 floods the fabric. The
    factories (not coroutines) keep lints and retries simple — nothing is
    created until a slot frees. First failure propagates after every
    sibling is cancelled and awaited (no orphaned in-flight requests).
    """
    if not fns:
        return []
    sem = asyncio.Semaphore(max(int(limit), 1))

    async def run(fn: "Callable[[], Awaitable[_T]]") -> "_T":
        async with sem:
            return await fn()

    tasks = [asyncio.create_task(run(fn)) for fn in fns]
    try:
        return await asyncio.gather(*tasks)
    finally:
        await reap(*(t for t in tasks if not t.done()))


async def retry(
    fn: Callable[[], Awaitable[_T]],
    *,
    attempts: int = 0,
    base_delay: float = 0.25,
    max_delay: float = 10.0,
    attempt_timeout: float | None = None,
    deadline: float | None = None,
    retry_on: tuple[type[BaseException], ...] = (Exception,),
    what: str = "",
    logger: logging.Logger | None = None,
) -> _T:
    """Call ``fn()`` until it succeeds, with jittered exponential backoff.

    The shape every worker-executor fabric push must take (hypha-lint
    ``naked-stream-push``): a parameter-server restart or a transient
    partition then costs a few backed-off re-attempts instead of a lost
    delta and a wedged round.

      * ``attempts``        — total tries; 0 = unbounded (the deadline is
        then the only stop);
      * ``attempt_timeout`` — wall-clock bound per try (``wait_for``
        semantics: the in-flight attempt is cancelled);
      * ``deadline``        — overall seconds budget from the first try;
        when it cannot fit another attempt, the last error re-raises;
      * ``retry_on``        — exception classes worth re-trying.
        ``CancelledError`` always propagates immediately: a cancelled
        caller must never be held hostage by backoff sleeps.

    Each re-attempt bumps ``hypha.ft.retry_attempts`` (telemetry) so an
    outage shows up as a counter spike, not just log spam.
    """
    from .telemetry.ft_metrics import FT_METRICS  # lazy: no import cycle

    loop = asyncio.get_running_loop()
    stop_at = None if deadline is None else loop.time() + deadline
    label = what or getattr(fn, "__qualname__", "operation")
    lg = logger or log
    # A per-attempt timeout is retryable regardless of ``retry_on`` — it is
    # this function's own signal, not the operation's failure mode.
    catchable = tuple(retry_on) + (asyncio.TimeoutError,)
    attempt = 0
    while True:
        attempt += 1
        try:
            if attempt_timeout is None:
                return await fn()
            return await asyncio.wait_for(fn(), attempt_timeout)
        except asyncio.CancelledError:
            raise
        except catchable as e:
            out_of_attempts = attempts > 0 and attempt >= attempts
            delay = min(max_delay, base_delay * (2 ** (attempt - 1)))
            delay *= 0.5 + random.random()  # jitter: 0.5x..1.5x
            out_of_time = (
                stop_at is not None and loop.time() + delay >= stop_at
            )
            if out_of_attempts or out_of_time:
                lg.warning(
                    "retry %r: giving up after %d attempt(s): %s",
                    label, attempt, e,
                )
                raise
            FT_METRICS.retry_attempts.add(1)
            # Flight-recorder breadcrumb: post-mortems of a late round need
            # the retry storm visible next to the chaos/drop events.
            from .telemetry.flight import FLIGHT  # lazy: no import cycle

            FLIGHT.record(
                "retry", what=label, attempt=attempt, error=str(e)[:200],
            )
            lg.info(
                "retry %r: attempt %d failed (%s); next in %.2fs",
                label, attempt, e, delay,
            )
            await asyncio.sleep(delay)
