"""Asyncio task lifecycle helpers: the blessed shapes hypha-lint checks for.

Three recurring needs across the scheduler / worker / network layers, each
previously hand-rolled slightly differently (and slightly wrong) at every
site:

  * :func:`spawn` — create a background task that can NEVER become an
    exception black hole: the handle is retained (optionally in a caller
    set) and a done-callback logs any failure and bumps
    :data:`TASK_FAILURES`, so a dead heartbeat pump or membership push
    surfaces in telemetry the moment it dies instead of at GC time;
  * :func:`reap` — cancel-and-await teardown that absorbs the reaped
    tasks' outcomes (including their ``CancelledError``) while still
    propagating cancellation *of the caller* — the subtlety every
    ``except (CancelledError, Exception): pass`` site got wrong;
  * :func:`wait_quiet` — await something whose outcome you don't care
    about, bounded by an optional timeout, again without eating the
    caller's own cancellation.

``asyncio.gather(..., return_exceptions=True)`` is the primitive that makes
the cancellation semantics right: child outcomes become return values, but
cancellation delivered to the *waiter* still raises through the await.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Awaitable, Coroutine, MutableSet

from .telemetry import Counter

__all__ = ["TASK_FAILURES", "spawn", "reap", "wait_quiet"]

log = logging.getLogger("hypha.aio")

# Background tasks that died with an unexpected exception (exported as an
# observable gauge wherever a Meter is wired up; tests read .value()).
TASK_FAILURES = Counter("hypha.aio.task_failures")


def spawn(
    coro: Coroutine[Any, Any, Any],
    *,
    name: str | None = None,
    tasks: MutableSet[asyncio.Task] | None = None,
    what: str = "",
    logger: logging.Logger | None = None,
) -> asyncio.Task:
    """``create_task`` with mandatory exception surfacing.

    ``tasks`` (usually the owner's ``self._tasks`` set) keeps a strong
    reference until completion; the done-callback logs non-cancellation
    failures and counts them in :data:`TASK_FAILURES`.
    """
    task = asyncio.create_task(coro, name=name or what or None)
    if tasks is not None:
        tasks.add(task)
        task.add_done_callback(tasks.discard)
    label = what or name or getattr(coro, "__qualname__", "task")
    lg = logger or log

    def _surface(t: asyncio.Task) -> None:
        if t.cancelled():
            return
        exc = t.exception()
        if exc is not None:
            TASK_FAILURES.add(1)
            lg.error("background task %r failed: %r", label, exc)

    task.add_done_callback(_surface)
    return task


async def reap(*tasks: asyncio.Task | None) -> None:
    """Cancel the given tasks and await them to actual completion.

    Outcomes (results, exceptions, their cancellation) are absorbed —
    anything noteworthy was already logged by :func:`spawn`'s callback.
    Cancellation of the *caller* propagates normally, so shutdown paths
    built on ``reap`` stay cancellable.
    """
    live = [t for t in tasks if t is not None]
    for t in live:
        t.cancel()
    if live:
        await asyncio.gather(*live, return_exceptions=True)


async def wait_quiet(
    *aws: Awaitable[Any] | None, timeout: float | None = None
) -> None:
    """Await things whose failure/result is someone else's problem.

    On timeout the awaitables are cancelled (``asyncio.wait_for``
    semantics) and the timeout is swallowed; caller cancellation always
    propagates.
    """
    live = [a for a in aws if a is not None]
    if not live:
        return
    gathered = asyncio.gather(*live, return_exceptions=True)
    if timeout is None:
        await gathered
        return
    try:
        await asyncio.wait_for(gathered, timeout)
    except asyncio.TimeoutError:
        pass
