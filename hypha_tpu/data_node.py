"""Data node runtime: serve dataset slices over pull streams.

Reference: crates/data/src/bin/hypha-data.rs:153-209 and
crates/data/src/tensor_data.rs:8-16 — each file in the dataset directory is
one slice (sorted order), the node announces a ``DataRecord{num_slices}``
registry record under the dataset name, and serves concurrent pull streams
whose header names ``DataSlice{dataset, index}``; the payload is the raw
bytes of the slice file.

The reference's index bounds check is off-by-one (``>`` where ``>=`` is
needed, hypha-data.rs:195) — fixed here per SURVEY.md §7 "Known reference
bugs to fix, not replicate".
"""

from __future__ import annotations

import logging
from pathlib import Path

from . import messages
from .health import serve_health
from .messages import DataRecord, DataSlice
from .network.node import Node
from .network.fabric import Transport

__all__ = ["DataNode"]

log = logging.getLogger("hypha.data")


class DataNode:
    """Serves one or more datasets; ``datasets`` maps name -> directory."""

    def __init__(
        self,
        transport: Transport | None,
        datasets: dict[str, str | Path],
        peer_id: str | None = None,
        bootstrap: list[str] | None = None,
        node: Node | None = None,
        **node_kwargs,
    ) -> None:
        # ``node`` injection lets the CLI hand in an mTLS-secured Node
        # (network.secure) instead of building a plain one here.
        self.node = node or Node(
            transport, peer_id=peer_id, bootstrap=bootstrap, **node_kwargs
        )
        self._slices: dict[str, list[Path]] = {}
        for name, directory in datasets.items():
            files = sorted(p for p in Path(directory).iterdir() if p.is_file())
            if not files:
                raise ValueError(f"dataset {name!r}: no slice files in {directory}")
            self._slices[name] = files
        self._health = None
        self._ready = False

    @property
    def peer_id(self) -> str:
        return self.node.peer_id

    def num_slices(self, dataset: str) -> int:
        return len(self._slices[dataset])

    async def start(self, listen: list[str] | None = None) -> None:
        await self.node.start(listen)
        self.node.on_pull(self._serve_slice)
        self._health = serve_health(self.node, lambda: self._ready)
        # Node.start pre-sets the bootstrapped event for self-anchored nodes,
        # so this returns immediately when there are no gateways.
        await self.node.wait_for_bootstrap()
        # Announce one record per dataset (hypha-data.rs:176-185) and mark
        # this peer a provider so schedulers can resolve name -> peer.
        for name, files in self._slices.items():
            await self.node.put_record(
                name, messages.encode(DataRecord(num_slices=len(files)))
            )
            await self.node.provide(name)
        self._ready = True
        log.info(
            "data node %s serving %s",
            self.peer_id,
            {n: len(f) for n, f in self._slices.items()},
        )

    async def _serve_slice(self, peer: str, resource) -> Path:
        """Pull handler: validate the header, hand back the slice file path
        (the Node streams it — the raw ``io::copy`` role, tensor_data.rs:8-16)."""
        if not isinstance(resource, DataSlice):
            raise ValueError(f"unsupported pull resource {type(resource).__name__}")
        files = self._slices.get(resource.dataset)
        if files is None:
            raise ValueError(f"unknown dataset {resource.dataset!r}")
        if not 0 <= resource.index < len(files):
            raise ValueError(
                f"slice index {resource.index} out of range 0..{len(files) - 1}"
            )
        log.debug("serving %s[%d] to %s", resource.dataset, resource.index, peer)
        return files[resource.index]

    async def stop(self) -> None:
        self._ready = False
        if self._health is not None:
            self._health.close()
        await self.node.stop()
