"""Stable-named flat serialization of JAX param trees (SafeTensors).

DiLoCo ships pseudo-gradients between processes as SafeTensors files
(reference: executors/accelerate/.../training.py:131-141 saves Δθ;
crates/worker/src/executor/parameter_server.rs mmaps them by tensor name).
Key compatibility therefore matters: the same param tree must always
flatten to the same names so a worker's Δθ file, the parameter server's
momentum state and the broadcast update all line up tensor-by-tensor.

Names are the tree path entries joined with ``/`` (flax param trees give
``params/blocks_0/attn/c_attn/kernel``-style names, matching how torch
state_dicts name the reference's tensors).
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

import jax
import numpy as np
from safetensors.numpy import load_file, save_file

__all__ = [
    "path_name",
    "flatten_tree",
    "flat_leaf_map",
    "replace_leaves",
    "unflatten_like",
    "save_tree",
    "load_flat",
]


def path_name(path: tuple) -> str:
    """Join a jax key path into a stable '/'-separated name."""
    parts = []
    for entry in path:
        if isinstance(entry, jax.tree_util.DictKey):
            parts.append(str(entry.key))
        elif isinstance(entry, jax.tree_util.SequenceKey):
            parts.append(str(entry.idx))
        elif isinstance(entry, jax.tree_util.GetAttrKey):
            parts.append(str(entry.name))
        elif isinstance(entry, jax.tree_util.FlattenedIndexKey):
            parts.append(str(entry.key))
        else:  # pragma: no cover - future key kinds
            parts.append(str(entry))
    return "/".join(parts)


def flatten_tree(tree: Any) -> dict[str, np.ndarray]:
    """Flatten a pytree of arrays to {stable_name: np.ndarray}."""
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    flat: dict[str, np.ndarray] = {}
    for path, leaf in leaves:
        name = path_name(path)
        if name in flat:
            raise ValueError(f"duplicate tensor name {name!r} in tree")
        flat[name] = np.asarray(leaf)
    return flat


def flat_leaf_map(tree: Any) -> dict[str, Any]:
    """{stable_name: leaf} WITHOUT materializing to numpy.

    The streaming sync path (hypha_tpu.stream) addresses single fragments
    of a device-resident param tree by wire name per round;
    :func:`flatten_tree`'s ``np.asarray`` would device_get the WHOLE tree
    each time. Leaves are aliases — callers copy what they keep.
    """
    flat: dict[str, Any] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        name = path_name(path)
        if name in flat:
            raise ValueError(f"duplicate tensor name {name!r} in tree")
        flat[name] = leaf
    return flat


def replace_leaves(tree: Any, updates: dict[str, Any]) -> Any:
    """A copy of ``tree`` with the named leaves swapped for ``updates``'.

    Unnamed leaves alias the input tree's. Every update name must exist in
    the tree — a leftover name means the caller's fragment map and the
    tree disagree, which must fail loudly.
    """
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    remaining = dict(updates)
    leaves = [
        remaining.pop(path_name(path), leaf) for path, leaf in paths_leaves
    ]
    if remaining:
        raise KeyError(
            f"replace_leaves: names not in tree: {sorted(remaining)}"
        )
    return jax.tree_util.tree_unflatten(treedef, leaves)


def unflatten_like(flat: dict[str, np.ndarray], like: Any) -> Any:
    """Rebuild a tree shaped like ``like`` from a flat name->array dict."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(like)
    leaves = []
    for path, leaf in paths:
        name = path_name(path)
        if name not in flat:
            raise KeyError(f"missing tensor {name!r} (have {len(flat)} tensors)")
        arr = flat[name]
        expected = tuple(np.shape(leaf))
        if tuple(arr.shape) != expected:
            # SafeTensors has no rank-0 tensors; scalars round-trip as (1,).
            if arr.size == 1 and int(np.prod(expected, dtype=np.int64)) == 1:
                arr = arr.reshape(expected)
            else:
                raise ValueError(
                    f"tensor {name!r}: shape {arr.shape} != expected {expected}"
                )
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_tree(path: Path | str, tree: Any) -> Path:
    """Save a param tree (or an already-flat dict) as SafeTensors."""
    if isinstance(tree, dict) and all(
        isinstance(v, np.ndarray) for v in tree.values()
    ):
        flat = dict(tree)
    else:
        flat = flatten_tree(tree)
    # SafeTensors rejects non-contiguous arrays and rank-0 tensors; normalize
    # once here rather than at every call site (scalars restore via
    # unflatten_like's shape-1 tolerance).
    flat = {k: np.ascontiguousarray(np.atleast_1d(v)) for k, v in flat.items()}
    save_file(flat, str(path))
    return Path(path)


def load_flat(path: Path | str) -> dict[str, np.ndarray]:
    return load_file(str(path))
