"""Autoregressive generation: KV-cached prefill + lax.scan decode loop.

The reference ships NO inference path (BASELINE.json config 4 — "Llama-2-7B
inference serving" — is a north-star scenario, not an existing feature);
this is the TPU-native serving primitive: the prompt prefills the cache in
one batched forward (MXU-sized matmuls), then a single compiled
``lax.scan`` emits one token per step against the static-shape cache — no
per-token retracing, no dynamic shapes, greedy or temperature/top-k
sampling inside the scan.

Works with any model module exposing ``decode``/``decode_len`` attrs and a
"cache" variable collection (models.gpt2, models.llama and its
Mistral/Qwen2/Gemma configs, models.mixtral — MoE decode routes DROP-FREE,
so serving is exact regardless of router load; the aux loss is dropped).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp

__all__ = ["generate"]


def _sample(logits: jnp.ndarray, rng, temperature: float, top_k: int | None):
    """logits [B, V] -> token ids [B]."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k is not None:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def generate(
    model: Any,
    params: Any,
    prompt_ids: jnp.ndarray,  # [B, S_prompt] int32
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    top_k: int | None = None,
    rng: jax.Array | None = None,
    eos_token_id: int | None = None,
) -> jnp.ndarray:
    """Generate ``max_new_tokens`` continuations. Returns [B, max_new_tokens].

    ``model`` is a training-mode module instance (e.g. ``GPT2(cfg)``); its
    decode twin is derived here, so the SAME converted/trained params serve
    inference. After ``eos_token_id`` a sequence keeps emitting eos (the
    scan stays static-shape; callers trim).
    """
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    B, S = prompt_ids.shape
    total = S + max_new_tokens
    cfg = model.config
    limit = getattr(cfg, "n_positions", None) or getattr(cfg, "max_seq_len", None)
    if limit is not None and total > limit:
        raise ValueError(f"prompt+new = {total} exceeds the model's {limit} positions")
    rng = rng if rng is not None else jax.random.key(0)
    # The framework's model protocol hands apply() the full variables dict
    # (init's return value); accept a bare param tree too.
    if isinstance(params, dict) and "params" in params:
        base_vars = dict(params)
    else:
        base_vars = {"params": params}

    prefill, decode_steps, cache_skel = _compiled(
        model, B, S, max_new_tokens, temperature, top_k, eos_token_id
    )
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), cache_skel)

    rng, r0 = jax.random.split(rng)
    cache, first = prefill(base_vars, cache, prompt_ids, r0)
    if max_new_tokens == 1:
        return first[:, None]
    rest = decode_steps(base_vars, cache, first, rng)
    return jnp.concatenate([first[:, None], rest.T], axis=1)


@functools.lru_cache(maxsize=64)
def _compiled(model, B, S, max_new_tokens, temperature, top_k, eos_token_id):
    """Jitted (prefill, decode_steps, cache_skeleton) for a serving shape.

    Keyed on the (hashable, frozen) flax module + static shape/sampling
    params, so repeat calls with the SAME (B, S, max_new) shapes reuse the
    compiled executables. Distinct prompt lengths still compile separately
    — a production serving loop should pad prompts to a small set of length
    buckets before calling generate() (prompt-bucket masking inside the
    cache is future work), and the persistent jax compilation cache
    amortizes the rest.
    """
    total = S + max_new_tokens
    dec = dataclasses.replace(model, decode=True, decode_len=total)

    # Cache skeleton without materializing throwaway params: eval_shape
    # traces init abstractly; callers build zeros per leaf.
    shapes = jax.eval_shape(
        lambda: dec.init(jax.random.key(0), jnp.zeros((B, 1), jnp.int32))
    )
    cache_skel = shapes["cache"]

    @jax.jit
    def prefill(params, cache, prompt, rng):
        logits, vars_ = dec.apply(
            {**params, "cache": cache}, prompt, mutable=["cache"]
        )
        if isinstance(logits, tuple):  # MoE models return (logits, aux)
            logits = logits[0]
        tok = _sample(logits[:, -1], rng, temperature, top_k)
        return vars_["cache"], tok

    @jax.jit
    def decode_steps(params, cache, first, rng):
        def step(carry, _):
            cache, tok, rng = carry
            logits, vars_ = dec.apply(
                {**params, "cache": cache}, tok[:, None], mutable=["cache"]
            )
            if isinstance(logits, tuple):
                logits = logits[0]
            rng, sub = jax.random.split(rng)
            nxt = _sample(logits[:, -1], sub, temperature, top_k)
            if eos_token_id is not None:
                nxt = jnp.where(tok == eos_token_id, eos_token_id, nxt)
            return (vars_["cache"], nxt, rng), nxt

        (_, _, _), toks = jax.lax.scan(
            step, (cache, first, rng), None, length=max_new_tokens - 1
        )
        return toks  # [max_new-1, B]

    return prefill, decode_steps, cache_skel
