"""The training executor: the DiLoCo inner loop on JAX, driven by the bridge.

Parity with the reference's accelerate executor
(executors/accelerate/src/hypha/accelerate_executor/training.py:28-147):

  * parse the job spec, open a bridge Session, fetch model artifacts;
  * build model / AdamW / LR schedule / streaming slice dataset;
  * snapshot the round anchor θ₀ (the reference's ``0_global_weights.pt``);
  * loop: jitted train step → per-batch ``Status`` heartbeat → on
    ``ScheduleUpdate{counter}`` run ``counter`` more batches → send
    ``update`` status → save Δθ = θ_t − θ₀ SafeTensors → ship to the
    parameter server (tagged with the round's sample count for the
    weighted mean) → send round metrics → await the broadcast update →
    merge (θ ← θ + update) → ``update-received`` → Continue | Done.

TPU-native differences: the whole inner step is ONE jit-compiled function
(forward+loss+backward+AdamW fused by XLA, bf16 activations on the MXU);
optional intra-replica sharding lays the step out over a device mesh
(dp/fsdp/tp/sp/ep axes) so collectives ride ICI; Δθ extraction and the
merge are jitted tree ops (hypha_tpu.executor.diloco).

Launch (the worker's process executor substitutes the placeholders —
crates/worker/src/executor/process.rs:124-137):

    python -m hypha_tpu.executor.training \
        --socket {SOCKET_PATH} --work-dir {WORK_DIR} --job {JOB_JSON}
"""

from __future__ import annotations

import argparse
import json
import logging
import math
import os
import threading
import time
from pathlib import Path
from typing import Any, Callable, Iterator

import numpy as np

from .. import messages
from ..messages import (
    CODEC_KEY,
    SHARD_KEY,
    TRACEPARENT_KEY,
    FragmentTag,
    JobSpec,
    Loss,
    ModelType,
    Progress,
    ProgressKind,
    ProgressResponse,
    ProgressResponseKind,
    TrainExecutorConfig,
)
from .. import compress
from ..ft.durable import RESYNC_KEY, restart_signal, stale_scheduler_response
from ..ft.rejoin import CATCHUP_KEY
from ..stream import SYNC_MODES, effective_fragments, fragment_due, merge_corrected
from ..stream.partition import partition_names, shard_of
from ..worker.connectors import shard_route
from ..telemetry import trace
from ..telemetry.ft_metrics import (
    DATA_METRICS,
    FT_METRICS,
    HET_METRICS,
    STREAM_METRICS,
)
from .diloco import (
    apply_updates,
    extract_delta,
    merge_update,
    merge_update_f32,
)
from .serialization import flat_leaf_map, flatten_tree, replace_leaves, unflatten_like
from .train import TrainState, build_optimizer, make_train_step

__all__ = ["run_training", "main", "TrainResult"]

log = logging.getLogger("hypha.executor.training")

# Multihost liveness bound: a lost follower process leaves the leader's
# cross-process collectives (and therefore the loss fetch) blocked forever
# — jax.distributed's own heartbeat detection is minutes away and may hard-
# kill the process instead of failing the job. Any collective-bearing phase
# exceeding this raises, so the bridge reports a clean job failure the
# scheduler can re-auction. Overridable for tests / long compiles.
_MH_STEP_TIMEOUT_ENV = "HYPHA_MULTIHOST_STEP_TIMEOUT"
_MH_STEP_TIMEOUT_DEFAULT = 600.0
# The FIRST dispatch of each jitted multihost program compiles on every
# process — minutes at 7B scale — so the liveness bound only tightens once
# a program has run end-to-end at least once.
_MH_COMPILE_GRACE_ENV = "HYPHA_MULTIHOST_COMPILE_GRACE"
_MH_COMPILE_GRACE_DEFAULT = 1800.0


def _with_deadline(fn: Callable[[], Any], timeout: float, what: str):
    """Run ``fn`` in a daemon thread with a wall-clock bound.

    On timeout the worker thread is abandoned (a thread blocked inside a
    collective cannot be cancelled) and the caller raises — the executor
    process is about to exit over the bridge's failure path anyway, and a
    daemon thread cannot keep it alive.
    """
    import threading

    box: dict[str, Any] = {}

    def work() -> None:
        try:
            box["value"] = fn()
        except BaseException as e:  # hypha-lint: disable=swallowed-cancel
            box["error"] = e  # thread-bridge: re-raised on the caller thread

    t = threading.Thread(target=work, daemon=True, name="mh-step")
    t.start()
    t.join(timeout)
    if t.is_alive():
        raise RuntimeError(
            f"multihost {what} did not complete within {timeout:.0f}s — "
            "follower process lost? (job fails instead of hanging; "
            f"tune ${_MH_STEP_TIMEOUT_ENV})"
        )
    if "error" in box:
        raise box["error"]
    return box["value"]


def _mh_done_bounded(mh) -> None:
    """Best-effort OP_DONE: with a follower already dead, the done
    broadcast itself blocks — never let the cleanup path hang the job."""
    try:
        _with_deadline(mh.done, 30.0, "done broadcast")
    except Exception as e:
        log.warning("multihost done broadcast failed: %s", e)

def _non_causal_types():
    from ..models.heads import HEAD_TYPES

    return {
        ModelType.IMAGE_CLASSIFICATION,
        ModelType.SEQUENCE_CLASSIFICATION,
        ModelType.TOKEN_CLASSIFICATION,
    } | HEAD_TYPES


# Streaming sync poll wait (seconds): how long the inner loop blocks on the
# in-flight sync before each batch. 0 (default) = pure overlap — never wait,
# keep stepping. Positive values degrade toward blocking semantics; tests
# use a large value to pin "zero flight drift == blocking bit-exactly".
_STREAM_POLL_WAIT_ENV = "HYPHA_STREAM_POLL_WAIT"


class _RoundTrace:
    """Worker-side round-trace bookkeeping (every method no-ops when
    tracing is off — call sites never branch on config).

    The scheduler's per-round root context arrives on SCHEDULE_UPDATE /
    Continue responses (:class:`~hypha_tpu.messages.ProgressResponse.
    traceparent`); the worker parents its ``inner_steps`` / ``encode`` /
    ``upload`` / ``merge`` spans under it, stamps it into delta push
    headers so the parameter server's spans join the same trace, and
    attaches it to its round-tagged Progress messages.
    """

    def __init__(self, node: str | None) -> None:
        self.node = node
        self.tp: str | None = None  # the round context last handed down
        self.tp_round = -1
        self.inner: "trace.TraceSpan | None" = None
        self.inner_round = -1

    @property
    def on(self) -> bool:
        return trace.active() is not None

    def adopt(self, resp, round_num: int) -> None:
        """Record the context a scheduler response handed down."""
        tp = getattr(resp, "traceparent", None)
        if tp:
            self.tp, self.tp_round = tp, round_num

    def ctx(self, round_num: int) -> str | None:
        """The context for ``round_num`` (None when off / not yet seen)."""
        return self.tp if self.tp_round == round_num else None

    def stamp(self, meta: dict, round_num: int) -> dict:
        """Inject the round context into a push header (no-op when off)."""
        return trace.inject(meta, self.ctx(round_num))

    def batch(self, round_num: int) -> None:
        """First batch of a round opens its ``inner_steps`` span."""
        if not self.on:
            return
        if self.inner is None or self.inner_round != round_num:
            self.close_inner()
            self.inner = trace.begin(
                "inner_steps",
                parent=self.ctx(round_num),
                attrs={"round": round_num},
                node=self.node,
            )
            self.inner_round = round_num

    def close_inner(self) -> None:
        if self.inner is not None:
            trace.finish(self.inner)
            self.inner = None


class _WorkerStream:
    """Worker-side streaming outer sync: at most ONE fragment in flight.

    ``begin`` snapshots the due fragment (θ_s), extracts Δ = θ_s − anchor
    and hands encode → upload → await-broadcast to a daemon thread while
    the inner loop keeps stepping; ``poll``/``finish`` (main thread) apply
    the delayed-update correction when the broadcast lands:

        θ ← θ_l + u          (live params keep the in-flight drift)
        anchor ← θ_s + u     (anchor excludes it → next Δ ships the drift)

    Updates for fragments NOT in flight (broadcasts this worker missed or
    that raced ahead) are absorbed into params AND anchor — leaving
    Δ = θ − anchor untouched, because an outer update is not local
    progress. That rule keeps the worker live across lost broadcasts, the
    failure the blocking path tolerates by merging whatever file arrives
    next.

    Error feedback is per fragment: ErrorFeedback.absorb replaces the
    whole residual tree, so one shared instance would drop every other
    fragment's residual each sync.
    """

    def __init__(
        self, session, cfg, work_dir: Path, sync_mode: str, wire_codec: str,
        rtrace: "_RoundTrace | None" = None,
    ) -> None:
        self.session = session
        self.cfg = cfg
        self.work_dir = Path(work_dir)
        self.codec = wire_codec
        self.rtrace = rtrace
        self.F = effective_fragments(
            sync_mode, int(getattr(cfg, "fragments", 0) or 0)
        )
        self.fragments: list[tuple[str, ...]] | None = None
        self.efs = [
            compress.ErrorFeedback()
            if wire_codec in compress.QUANT_CODECS
            else None
            for _ in range(self.F)
        ]
        self.flight: dict[str, Any] | None = None
        self.poll_wait_s = float(
            os.environ.get(_STREAM_POLL_WAIT_ENV, "0") or 0.0
        )
        # Last PS generation observed on the results stream, PER shard
        # (flight-thread confined): a change means that parameter-server
        # shard restarted and an in-flight delta it owned may have died
        # unjournaled — re-send it. The unsharded PS is shard 0.
        self._gens: dict[int, Any] = {}
        # Sharded parameter service: the placement map this worker routes
        # each fragment's push by (None = single PS, the pre-shard wire).
        shard_map = getattr(cfg, "ps_shards", None)
        if shard_map is not None and not getattr(shard_map, "shards", None):
            shard_map = None
        self.shard_map = shard_map
        self.reduce_via = getattr(cfg, "reduce_via", None)

    @property
    def in_flight(self) -> bool:
        return self.flight is not None

    # ------------------------------------------------------------- begin

    def begin(self, round_num: int, params, anchor, num_samples: float) -> None:
        """Snapshot + extract the due fragment; spawn the flight thread."""
        import jax
        import jax.numpy as jnp

        if self.flight is not None:
            raise RuntimeError(
                "stream sync scheduled while a fragment is still in flight"
            )
        anchor_flat = flat_leaf_map(anchor)
        if self.fragments is None:
            # Deterministic by (name, size) only — the parameter server
            # derives the identical partition from the delta frames.
            self.fragments = partition_names(
                {n: int(leaf.size) for n, leaf in anchor_flat.items()}, self.F
            )
        frag = fragment_due(round_num, self.F)
        owner = (
            shard_of(frag, len(self.shard_map.shards))
            if self.shard_map is not None
            else 0
        )
        names = self.fragments[frag]
        params_flat = flat_leaf_map(params)
        # Deep copy, not an alias: the jitted step donates its input state,
        # so live buffers die on the next inner step.
        snap = {n: jnp.copy(params_flat[n]) for n in names}
        delta = extract_delta(snap, {n: anchor_flat[n] for n in names})
        host_delta = jax.device_get(delta)
        tag = FragmentTag(round=round_num, fragment_id=frag, fragments=self.F)
        flight: dict[str, Any] = {
            "round": round_num,
            "frag": frag,
            "owner": owner,
            "names": names,
            "snap": snap,
            "path": self.work_dir / f"delta-{round_num}-f{frag}.safetensors",
            "box": {"absorbed": []},
            "t0": time.monotonic(),
            "compute_s": 0.0,
            "bytes": 0,
            "samples": float(num_samples),
            # Round-trace context at flight launch, carried into the
            # flight thread's encode/upload spans and push headers.
            "tp": (
                self.rtrace.ctx(round_num)
                if self.rtrace is not None
                else None
            ),
        }
        thread = threading.Thread(
            target=self._flight_main,
            args=(flight, host_delta, tag, float(num_samples)),
            daemon=True,
            name=f"stream-sync-r{round_num}",
        )
        flight["thread"] = thread
        self.flight = flight
        thread.start()

    # ----------------------------------------------------- flight thread

    def _flight_main(
        self, flight: dict, host_delta: dict, tag: FragmentTag, samples: float
    ) -> None:
        box = flight["box"]
        tnode = self.rtrace.node if self.rtrace is not None else None
        try:
            # host_delta is already wire-flat: {stable_name: np.ndarray}.
            with trace.span(
                "encode", parent=flight["tp"],
                attrs={
                    "round": flight["round"], "fragment": flight["frag"],
                    "codec": self.codec,
                },
                node=tnode,
            ):
                compress.write_delta(
                    flight["path"],
                    host_delta,
                    self.codec,
                    ef=self.efs[flight["frag"]],
                    tag=tag.header(),
                )
            nbytes = flight["path"].stat().st_size
            flight["bytes"] = nbytes
            STREAM_METRICS.flight_started(nbytes)
            with trace.span(
                "upload", parent=flight["tp"],
                attrs={
                    "round": flight["round"], "fragment": flight["frag"],
                    "bytes": nbytes,
                },
                node=tnode,
            ):
                self._send_flight(flight, tag, samples)
            box["completion"] = self._await_broadcast(flight)
        except BaseException as e:  # hypha-lint: disable=swallowed-cancel
            box["error"] = e  # thread-bridge: re-raised at finish()
        finally:
            # Success or failure, this thread is done with the wire —
            # release the gauge here so an errored/abandoned flight can
            # never read as mid-upload for the rest of the process.
            STREAM_METRICS.flight_landed(flight["bytes"])

    def _send_flight(
        self, flight: dict, tag: FragmentTag, samples: float
    ) -> None:
        """Ship the flight's wire file — to the single PS, or routed to
        the fragment's owning shard (via the group reducer with ANY
        failover when tree-reduce is on)."""
        meta: dict[str, Any] = {"num_samples": samples, **tag.header()}
        trace.inject(meta, flight.get("tp"))
        if self.shard_map is None:
            self.session.send_resource(
                self.cfg.updates,
                flight["path"].name,
                resource=self.cfg.updates.ref.resource or "updates",
                meta=meta,
            )
            return
        send, owner, res_tag = shard_route(
            self.shard_map, flight["frag"], self.reduce_via
        )
        if len(self.shard_map.shards) > 1:
            meta[SHARD_KEY] = owner
        self.session.send_resource(
            send, flight["path"].name, resource=res_tag, meta=meta
        )

    def _resend(self, flight: dict) -> None:
        """The PS (shard) restarted: our un-acknowledged fragment delta may
        have died with it unjournaled — re-push the wire file (the PS's
        journal dedup makes the copy idempotent when the original DID
        land)."""
        if not flight["path"].is_file():
            return
        tag = FragmentTag(
            round=flight["round"], fragment_id=flight["frag"], fragments=self.F
        )
        log.warning(
            "stream sync: ps restart detected; re-sending round %d fragment %d",
            flight["round"], flight["frag"],
        )
        self._send_flight(flight, tag, flight["samples"])

    def _await_broadcast(self, flight: dict) -> dict:
        """Consume results-stream events until OUR fragment's update lands.

        Other fragments' updates are recorded for the main thread's absorb
        pass; stale rebroadcasts of our fragment are dropped. A LATER
        round of our fragment completes the flight too (our round's
        broadcast was lost — waiting for it would hang the worker where
        blocking mode's merge-whatever-arrives keeps going). A PS
        generation change (or an explicit resync announcement) re-sends
        the in-flight delta — the restart may have lost it.
        """
        with self.session.receive(self.cfg.results) as events:
            for event in events:
                meta = event.get("meta") or {}
                try:
                    shard_id = int(meta.get(SHARD_KEY, 0))
                except (TypeError, ValueError):
                    shard_id = 0
                self._gens[shard_id], resend = restart_signal(
                    meta, self._gens.get(shard_id)
                )
                if resend and shard_id == flight.get("owner", 0):
                    # Only the restarted shard's own in-flight part can
                    # have died unjournaled; re-sending to the healthy
                    # shards would just churn their journals' dedup.
                    self._resend(flight)
                if meta.get(RESYNC_KEY):
                    (self.work_dir / event["path"]).unlink(missing_ok=True)
                    continue
                if meta.get(CATCHUP_KEY):
                    # Catch-ups target rejoiners; their content is folded
                    # into every later broadcast — drop defensively.
                    (self.work_dir / event["path"]).unlink(missing_ok=True)
                    continue
                etag = FragmentTag.from_header(meta)
                try:
                    eround = int(meta.get("round", flight["round"]))
                except (TypeError, ValueError):
                    eround = flight["round"]
                if eround < flight["round"]:
                    # Stale for ANY fragment, ours or not: the worker only
                    # ships round r after merging every round < r, so an
                    # older broadcast (a redelivery, or a round already
                    # folded into this worker's rejoin catch-up) is applied
                    # state — absorbing it would double-apply the update.
                    (self.work_dir / event["path"]).unlink(missing_ok=True)
                    continue
                if etag is not None and etag.fragment_id != flight["frag"]:
                    # A FUTURE round's other fragment (the quorum PS ran
                    # ahead without us): genuinely unseen — absorb.
                    flight["box"]["absorbed"].append(event)
                    continue
                if eround > flight["round"]:
                    log.warning(
                        "stream sync: round %d broadcast lost; completing "
                        "with round %d's", flight["round"], eround,
                    )
                return event
        raise RuntimeError(
            "results stream ended before the fragment's update broadcast"
        )

    # ---------------------------------------------------------- progress

    def poll(self) -> bool:
        """True when the in-flight sync is ready to finish (non-blocking
        unless $HYPHA_STREAM_POLL_WAIT asks to degrade toward blocking)."""
        flight = self.flight
        if flight is None:
            return False
        if self.poll_wait_s > 0:
            flight["thread"].join(self.poll_wait_s)
        return not flight["thread"].is_alive()

    def note_compute(self, seconds: float) -> None:
        """One inner step ran while the sync was in flight (overlap win)."""
        if self.flight is not None:
            self.flight["compute_s"] += seconds

    # ------------------------------------------------------------ finish

    def finish(self, params, anchor):
        """Apply the landed broadcast; returns (params, anchor) trees."""
        flight = self.flight
        assert flight is not None
        self.flight = None
        flight["thread"].join()
        box = flight["box"]
        if "error" in box:
            flight["path"].unlink(missing_ok=True)
            raise box["error"]
        for event in box["absorbed"]:
            params, anchor = self._absorb(event, params, anchor)
        event = box["completion"]
        meta = event.get("meta") or {}
        merge_span = trace.begin(
            "merge",
            parent=meta.get(TRACEPARENT_KEY) or flight.get("tp"),
            attrs={"round": flight["round"], "fragment": flight["frag"]},
            node=self.rtrace.node if self.rtrace is not None else None,
        )
        update_file = self.work_dir / event["path"]
        flat = compress.read_delta(update_file)
        names = flight["names"]
        if set(flat) != set(names):
            raise ValueError(
                f"fragment {flight['frag']} partition mismatch: update "
                f"carries {sorted(flat)}, worker expects {sorted(names)}"
            )
        params_flat = flat_leaf_map(params)
        new_live, new_anchor = merge_corrected(
            {n: params_flat[n] for n in names}, flight["snap"], flat
        )
        params = replace_leaves(params, new_live)
        anchor = replace_leaves(anchor, new_anchor)
        trace.finish(merge_span)
        update_file.unlink(missing_ok=True)
        flight["path"].unlink(missing_ok=True)
        STREAM_METRICS.flight_finished(
            time.monotonic() - flight["t0"], flight["compute_s"]
        )
        return params, anchor

    def _absorb(self, event: dict, params, anchor):
        """θ_q ← θ_q + u AND anchor_q ← anchor_q + u for a fragment not in
        flight: Δ_q = θ_q − anchor_q is unchanged, because an outer update
        is global progress, not this worker's."""
        update_file = self.work_dir / event["path"]
        flat = compress.read_delta(update_file)
        params_flat = flat_leaf_map(params)
        anchor_flat = flat_leaf_map(anchor)
        unknown = set(flat) - set(params_flat)
        if unknown:
            raise ValueError(
                f"broadcast update names unknown tensors: {sorted(unknown)}"
            )
        new_live = merge_update({n: params_flat[n] for n in flat}, flat)
        new_anchor = merge_update({n: anchor_flat[n] for n in flat}, flat)
        update_file.unlink(missing_ok=True)
        return (
            replace_leaves(params, new_live),
            replace_leaves(anchor, new_anchor),
        )

    def abort(self) -> None:
        """Loop is exiting with a sync still out: bounded join, then
        abandon the daemon thread (the bridge teardown severs its SSE)."""
        flight = self.flight
        self.flight = None
        if flight is None:
            return
        flight["thread"].join(5.0)
        if flight["thread"].is_alive():
            log.warning(
                "stream sync round %d abandoned (broadcast never landed)",
                flight["round"],
            )
            return
        flight["path"].unlink(missing_ok=True)


class TrainResult:
    """What the loop did — surfaced for tests and the in-process executor."""

    def __init__(self) -> None:
        self.rounds = 0
        self.batches = 0
        self.losses: list[float] = []

    @property
    def last_loss(self) -> float:
        return self.losses[-1] if self.losses else math.nan


def _build_mesh(sharding: dict | None):
    """Optional intra-replica mesh (TrainExecutorConfig.sharding extension)."""
    if not sharding:
        return None
    import jax

    from ..parallel import create_mesh

    sizes = {a: int(sharding.get(a, 1)) for a in ("dp", "fsdp", "tp", "sp", "ep")}
    total = math.prod(sizes.values())
    if total <= 1:
        return None
    if total > len(jax.devices()):
        log.warning(
            "sharding %s needs %d devices, have %d; running unsharded",
            sharding, total, len(jax.devices()),
        )
        return None
    return create_mesh(sizes)


def _init_model(cfg: TrainExecutorConfig, session, work_dir: Path, first_batch):
    """Build the model and its initial params (fetched weights or seeded)."""
    import jax

    from ..models import Mixtral, build_model
    from ..models.registry import resolve_model_type

    model_spec = dict(cfg.model)
    if cfg.lora:
        # Adapter-only fine-tuning: inject the LoRA fields into the model
        # config (the Llama family's _proj picks them up). The LlamaConfig
        # constructor validates rank/targets; unsupported families have no
        # lora_rank field and fail loudly in their config constructor.
        model_spec["config"] = dict(
            model_spec.get("config", {}),
            lora_rank=int(cfg.lora.get("rank", 8)),
            lora_alpha=float(cfg.lora.get("alpha", 16.0)),
            lora_targets=tuple(cfg.lora.get("targets", ("q_proj", "v_proj"))),
        )
    # On TPU the pluggable-attention families run the pallas flash kernel by
    # default (sequence-parallel jobs swap in the ring kernel instead, via
    # _build_mesh); off-TPU the XLA dense path is faster than interpret mode.
    attn_impl = None
    from ..hw import is_accelerator

    if is_accelerator() and not cfg.sharding:
        from ..ops.flash_attention import flash_attention

        attn_impl = flash_attention
        log.info("attention path: pallas flash kernel (backend=%s)", jax.default_backend())
    else:
        log.info("attention path: XLA dense (backend=%s)", jax.default_backend())

    source = model_spec.get("source")
    if model_spec.get("family") == "hf" and source is not None and not model_spec.get("path"):
        # The hf family loads weights via from_pretrained, so the checkpoint
        # dir must exist BEFORE the model is built (the native families
        # init-then-overwrite below instead).
        fetch = messages.from_json_dict(source) if isinstance(source, dict) else source
        rels = session.fetch(fetch)
        cfg_file = next((r for r in rels if r.endswith("config.json")), None)
        model_spec["path"] = str(
            (work_dir / cfg_file).parent if cfg_file else work_dir
        )
        source = None  # weights are loaded by the builder; skip the overwrite

    model, _mcfg = build_model(model_spec, attn_impl)
    model_type = resolve_model_type(model_spec.get("model_type", ModelType.CAUSAL_LM))
    causal_lm = model_type not in _non_causal_types()
    has_aux = isinstance(model, Mixtral)

    inputs = first_batch["input_ids"] if "input_ids" in first_batch else first_batch["inputs"]
    seed = int(model_spec.get("seed", 0))
    params = model.init(jax.random.key(seed), inputs)

    if source is not None:
        fetch = messages.from_json_dict(source) if isinstance(source, dict) else source
        rels = session.fetch(fetch)
        weight_files = [
            r for r in rels if r.endswith((".safetensors", ".bin", ".pt", ".pth"))
        ]
        if weight_files:
            from ..models.convert import convert_state_dict, load_checkpoint_files

            state = load_checkpoint_files([work_dir / r for r in weight_files])
            target = params
            if cfg.lora:
                # Checkpoints carry the BASE weights only; adapters keep
                # their seed init (B=0 -> exact base behavior at step 0).
                from .lora import merge_lora, split_lora

                adapters_t, target = split_lora(params)
            try:
                # Native flat names (our own checkpoints/exports)…
                loaded = unflatten_like(state, target)
            except KeyError:
                # …or an HF-format state dict for this family.
                family = model_spec.get("family", "gpt2")
                loaded = convert_state_dict(family, state, target)
            params = merge_lora(adapters_t, loaded) if cfg.lora else loaded
            log.info("loaded %d initial tensors from %s", len(state), weight_files)
    return model, params, causal_lm, has_aux


def adopt_schedule(resp: ProgressResponse, countdown: "int | None") -> "int | None":
    """Adopt a SCHEDULE_UPDATE's counter — idempotently.

    A countdown already in progress stands: a restarted scheduler that
    re-adopted this execution mid-round has a tracker that forgot the
    first issue and re-schedules on the next Status, but re-adopting its
    counter would re-run (or skip) inner steps the round already
    accounted. Only a worker with NO active countdown (round start, or
    just merged) takes the counter.
    """
    if resp.kind != ProgressResponseKind.SCHEDULE_UPDATE:
        return countdown
    if countdown is None:
        return resp.counter
    return countdown


def run_training(
    session,
    work_dir: Path | str,
    spec: JobSpec,
    *,
    max_batches: int | None = None,
    should_stop: Callable[[], bool] | None = None,
    trace_node: str | None = None,
) -> TrainResult:
    """Run the DiLoCo inner loop to completion over the given bridge session.

    ``session`` implements the bridge client API (fetch / send_resource /
    send_status / receive — hypha_tpu.executor.bridge_client.Session).
    ``max_batches`` is a safety valve for tests. ``should_stop`` is polled
    between batches — the in-process executor's cooperative cancellation.
    ``trace_node`` labels this worker's round-trace spans (telemetry.trace;
    the in-process executor passes its peer id, subprocess executors label
    via $HYPHA_TRACE_NODE) — ignored while tracing is off.
    """
    import jax
    import jax.numpy as jnp

    work_dir = Path(work_dir)
    cfg = spec.executor.train
    if cfg is None:
        raise ValueError(f"job {spec.job_id} is not a train job")

    from .dataset import stream_batches

    def fetch_slice() -> str:
        t0 = time.monotonic()
        rels = session.fetch(cfg.data)
        path = work_dir / rels[0]
        DATA_METRICS.note_fetch(time.monotonic() - t0)
        return str(path)

    # End-to-end round tracing (telemetry.trace): all no-ops when off.
    # Created before the stream so input_wait spans can join round traces.
    rtrace = _RoundTrace(trace_node)

    def input_span_ctx():
        # The most recent round context handed down by the scheduler —
        # good enough to attribute a mid-round input stall to its round.
        return rtrace.tp, rtrace.node

    model_spec = dict(cfg.model)
    input_names = model_spec.get("input_names")
    preprocessor = None
    if cfg.preprocessor:
        from .preprocess import build_preprocessor

        preprocessor = build_preprocessor(cfg.preprocessor, session, work_dir)
    # Async input pipeline (executor.dataset, ISSUE 15): slice prefetch +
    # zero-copy assembly + the deferred device sync below. None/False (the
    # default) takes the original synchronous path, bit-identically.
    pipeline_on = bool(getattr(cfg, "input_pipeline", None))
    stream = stream_batches(
        fetch_slice, cfg.batch_size, input_names, preprocessor,
        pipeline=pipeline_on,
        prefetch=getattr(cfg, "prefetch_slices", None),
        span_ctx=input_span_ctx,
        unlink_consumed=pipeline_on,
    )

    first_batch = next(stream)
    model, params, causal_lm, has_aux = _init_model(cfg, session, work_dir, first_batch)
    mesh = _build_mesh(cfg.sharding)

    # LoRA jobs train (ship, checkpoint, merge) the ADAPTER tree only; the
    # frozen base rides along as a constant input to every step.
    frozen = None
    if cfg.lora:
        from .lora import split_lora

        adapters, frozen = split_lora(params)
        if not jax.tree_util.tree_leaves(adapters):
            raise ValueError(
                f"job {spec.job_id}: lora={cfg.lora!r} produced no adapters "
                f"(family {dict(cfg.model).get('family')!r})"
            )
        params = adapters

    tx = build_optimizer(cfg.optimizer, cfg.scheduler)
    state = TrainState.create(params, tx)

    # Resume (net-new vs reference): a re-dispatched executor picks up the
    # last completed round's params + optimizer state instead of θ₀.
    ckpt_dir = None
    ckpt_every = 1
    round_offset = 0  # completed rounds restored from a checkpoint
    if cfg.checkpoint and cfg.checkpoint.get("dir"):
        from .checkpoint import load_train_checkpoint, save_train_checkpoint

        ckpt_every = int(cfg.checkpoint.get("every_rounds", 1))
        if ckpt_every > 0:  # <= 0 disables checkpointing
            ckpt_dir = Path(cfg.checkpoint["dir"])
            restored = load_train_checkpoint(ckpt_dir, state.params, state.opt_state)
            if restored is not None:
                r_params, r_opt, r_step, r_round, _extra = restored
                state = state.replace(
                    params=r_params, opt_state=r_opt, step=jnp.int32(r_step)
                )
                round_offset = r_round
                log.info(
                    "resumed from %s: step %d, %d completed rounds",
                    ckpt_dir, r_step, r_round,
                )

    # Multi-process replica (pod-as-one-replica): process 0 — this loop —
    # owns the control plane and broadcasts each collective-bearing action
    # so follower processes (executor.multihost_coord.run_training_follower)
    # mirror the dispatches over the same global mesh. The init broadcast
    # runs BEFORE mesh placement: it device_gets the state, which must
    # still be host/single-device arrays (global arrays spanning another
    # process cannot be fetched locally).
    mh = None
    host_anchor = None
    if jax.process_count() > 1:
        if mesh is None:
            # Fail fast HERE: the follower asserts a mesh exists, and a
            # leader training unsharded while followers expect lockstep
            # dispatches would deadlock on the first step broadcast.
            raise ValueError(
                f"job {spec.job_id}: {jax.process_count()} processes need a "
                f"sharding config spanning all {len(jax.devices())} global "
                f"devices; got {cfg.sharding!r}"
            )
        from .multihost_coord import LeaderCoordination

        mh = LeaderCoordination()
        mh.init(
            json.dumps(messages.to_json_dict(spec)), state, first_batch,
            frozen=frozen,
        )
        # θ₀ on the HOST, captured while state is still host/single-device
        # arrays: cross-process meshes shard params onto devices this
        # process cannot address, so a device anchor would be unreadable at
        # delta time (refreshed each round from the OP_GATHER allgather +
        # the merged update).
        host_anchor = jax.tree.map(np.asarray, jax.device_get(state.params))
        log.info(
            "multihost leader: %d processes, %d global devices",
            jax.process_count(), len(jax.devices()),
        )

    try:
        # From the init broadcast on, ANY leader exit without OP_DONE
        # leaves followers blocked in recv — this guard plus the loop's
        # finally below cover every path.
        loss_kind = cfg.loss or Loss.CROSS_ENTROPY
        from ..models.hf import _DECODER_TYPES

        step_kwargs = dict(
            causal_lm=causal_lm,
            has_aux=has_aux,
            # Models that declare an ``rng`` kwarg (the hf family) train
            # with live dropout, keyed per-step from the job seed — the
            # reference trains its torch models in train() mode
            # (training.py:106-116).
            dropout_seed=int(dict(cfg.model).get("seed", 0)),
            # Seq2seq hf models shift labels into decoder inputs
            # internally, so their logits are already aligned with the
            # labels stream.
            labels_aligned=getattr(model, "model_type", None) in _DECODER_TYPES,
            # Heads-family tasks with structured objectives (CTC,
            # detection, contrastive, span…) carry their own loss.
            loss_override=getattr(model, "custom_loss", None),
        )
        if frozen is not None:
            from .lora import make_lora_train_step

            lora_step = make_lora_train_step(model.apply, loss_kind, **step_kwargs)

            def step(state, batch):
                return lora_step(state, frozen, batch)
        else:
            step = make_train_step(model.apply, loss_kind, **step_kwargs)

        if mesh is not None:
            from jax.sharding import NamedSharding

            from ..parallel import param_sharding
            from ..parallel.sharding import batch_spec

            state = jax.device_put(state, param_sharding(state, mesh))
            if frozen is not None:
                frozen = jax.device_put(frozen, param_sharding(frozen, mesh))
            batch_sharding = NamedSharding(mesh, batch_spec())

            if mh is not None:

                def place(batch):
                    # Multi-controller: build global arrays shard-by-shard
                    # (device_put may refuse shardings spanning devices
                    # this process cannot address). Every process holds the
                    # same host batch — the leader just broadcast it.
                    return {
                        k: jax.make_array_from_callback(
                            np.shape(v), batch_sharding,
                            lambda idx, v=v: np.asarray(v)[idx],
                        )
                        for k, v in batch.items()
                    }
            else:

                def place(batch):
                    return {
                        k: jax.device_put(v, batch_sharding)
                        for k, v in batch.items()
                    }
        else:

            def place(batch):
                return batch

        def snapshot(tree):
            # A deep copy, NOT an alias: the jitted step donates its input
            # state, so aliased buffers would be deleted on the next step.
            return jax.tree.map(jnp.copy, tree)

        # Multihost keeps its anchor on the host (captured at mh.init above,
        # while state was still addressable); single-process keeps the
        # jitted device anchor.
        anchor = None if mh is not None else snapshot(state.params)
    except BaseException:
        if mh is not None:
            _mh_done_bounded(mh)  # followers must never hang on a dead leader
        raise
    result = TrainResult()
    countdown: int | None = None
    round_num = 0
    round_samples = 0
    round_losses: list[float] = []
    # Live metrics plane (telemetry.metrics_plane): reporting jobs attach
    # round-tagged training-quality keys (loss EWMA, delta norm, tokens/s,
    # inner steps) to the METRICS progress they already send per round.
    # Off (the default) leaves the metrics dict — and the wire — exactly
    # as it is today.
    report_quality = bool(getattr(cfg, "report_metrics_s", None))
    _EWMA_BETA = 0.7
    qstate: dict[str, Any] = {
        "ewma": None, "t0": time.monotonic(), "tokens": 0.0, "batches": 0,
    }

    def quality_metrics(mean_loss: float) -> dict:
        """One round's quality keys; resets the per-round accumulators."""
        now = time.monotonic()
        dur = max(now - qstate["t0"], 1e-9)
        ewma = qstate["ewma"]
        if not math.isnan(mean_loss):
            ewma = (
                mean_loss
                if ewma is None
                else _EWMA_BETA * ewma + (1.0 - _EWMA_BETA) * mean_loss
            )
            qstate["ewma"] = ewma
        out = {
            "loss_ewma": float(ewma) if ewma is not None else mean_loss,
            "tokens_per_s": float(qstate["tokens"]) / dur,
            "inner_steps": float(qstate["batches"]),
        }
        qstate.update(t0=now, tokens=0.0, batches=0)
        return out

    def note_quality_batch(batch: Any) -> None:
        qstate["batches"] += 1
        ids = batch.get("input_ids") if isinstance(batch, dict) else None
        qstate["tokens"] += (
            float(np.asarray(ids).size)
            if ids is not None
            else float(cfg.batch_size)
        )

    def delta_norm_of(flat: dict) -> float:
        """L2 norm of the shipped (post-EF) delta — one definition for
        every sync path's quality report."""
        return float(
            np.sqrt(sum(float(np.vdot(v, v)) for v in flat.values()))
        )

    # Last PS generation seen on the results stream (ft.durable): a change
    # mid-wait means the parameter server restarted — the shipped delta may
    # have died with it unjournaled, so the worker re-pushes it.
    ps_generation: Any = None
    # Last SCHEDULER generation adopted from stamped responses
    # (ft.durable DurableScheduler). A response stamped with an OLDER
    # generation is a zombie predecessor's control decision — dropped,
    # never acted on; the live scheduler answers the re-send. Unstamped
    # responses (every job that never restarts its scheduler) skip the
    # gate entirely.
    sched_gen: dict[str, Any] = {"v": None}

    def send_status_gated(progress: Progress) -> ProgressResponse:
        """session.send_status + the scheduler-generation gate."""
        for _attempt in range(64):
            gen = sched_gen["v"]
            if gen is not None and int(gen) >= 2:
                progress.scheduler_generation = int(gen)
            resp = session.send_status(progress)
            new_gen, stale = stale_scheduler_response(resp, sched_gen["v"])
            sched_gen["v"] = new_gen
            if not stale:
                return resp
            FT_METRICS.stale_generation_dropped.add(1)
            log.warning(
                "dropping %s response from stale scheduler generation %s "
                "(adopted %s); re-sending",
                progress.kind.value, getattr(resp, "generation", None),
                sched_gen["v"],
            )
            time.sleep(0.2)
        raise RuntimeError(
            "scheduler kept answering from a stale generation"
        )
    # Outer-round wire codec (hypha_tpu.compress): delta_codec wins, the
    # legacy delta_dtype="bfloat16" maps onto the bf16 codec. Quantized
    # codecs carry an error-feedback residual across rounds so the
    # compressed trajectory tracks the uncompressed one.
    wire_codec = compress.effective_codec(
        getattr(cfg, "delta_codec", "none"), cfg.delta_dtype
    )
    delta_ef = (
        compress.ErrorFeedback() if wire_codec in compress.QUANT_CODECS else None
    )

    def apply_codec_hint(meta: dict) -> None:
        """Per-link codec selection (ft.adaptive): an adaptive parameter
        server stamps the codec it picked for THIS worker's link into the
        broadcast header — switch the next upload to it. The error-
        feedback residual carries across the switch (it is plain f32
        error, codec-independent), so a degrading link keeps tracking the
        uncompressed trajectory; a worker newly switched to a quantized
        codec starts a fresh residual. Static jobs never see the key."""
        nonlocal wire_codec, delta_ef
        hint = meta.get(CODEC_KEY) if isinstance(meta, dict) else None
        if (
            not isinstance(hint, str)
            or hint not in compress.CODECS
            or hint == wire_codec
        ):
            return
        log.info(
            "per-link codec hint: switching upload codec %s -> %s",
            wire_codec, hint,
        )
        HET_METRICS.codec_switches.add(1)
        wire_codec = hint
        if wire_codec in compress.QUANT_CODECS and delta_ef is None:
            delta_ef = compress.ErrorFeedback()
    # Streaming outer sync (hypha_tpu.stream): overlap/stream replace the
    # blocking do_update with a background flight + delayed-update merge.
    # The default stays "blocking" and takes the exact code path below.
    sync_mode = getattr(cfg, "sync_mode", "blocking") or "blocking"
    if sync_mode not in SYNC_MODES:
        raise ValueError(
            f"job {spec.job_id}: sync_mode must be {'|'.join(SYNC_MODES)}, "
            f"got {sync_mode!r}"
        )
    # Sharded parameter service (hypha_tpu.stream placement): the worker
    # routes each part's delta to its owning shard. None = single PS, the
    # pre-shard wire.
    shard_map = getattr(cfg, "ps_shards", None)
    if shard_map is not None and not getattr(shard_map, "shards", None):
        shard_map = None
    if shard_map is not None and sync_mode == "overlap":
        # Overlap's single whole-tree flight has no per-part schedule to
        # route by; sharding composes with pipelining via sync_mode=stream.
        raise ValueError(
            f"job {spec.job_id}: ps_shards requires sync_mode blocking or "
            "stream"
        )
    if shard_map is not None and mh is not None:
        _mh_done_bounded(mh)
        raise ValueError(
            f"job {spec.job_id}: sharded parameter service is not supported "
            "for multihost replicas"
        )
    if pipeline_on and mh is not None:
        # The deferred loss read assumes this process can observe the step
        # asynchronously; multihost lockstep broadcasts cannot.
        _mh_done_bounded(mh)
        raise ValueError(
            f"job {spec.job_id}: input_pipeline is not supported for "
            "multihost replicas"
        )
    stream_state: _WorkerStream | None = None
    if sync_mode != "blocking":
        if mh is not None:
            # Multihost delta extraction is a collective gather the flight
            # thread cannot drive; fail loudly like rejoin does.
            _mh_done_bounded(mh)
            raise ValueError(
                f"job {spec.job_id}: streaming sync is not supported for "
                "multihost replicas"
            )
        stream_state = _WorkerStream(
            session, cfg, work_dir, sync_mode, wire_codec, rtrace=rtrace
        )
        log.info(
            "streaming outer sync: mode=%s fragments=%d", sync_mode,
            stream_state.F,
        )

    if getattr(cfg, "rejoin", False):
        # Elastic rejoin (hypha_tpu.ft.rejoin): this replica was dispatched
        # mid-job. θ₀ above is the seed init every original worker started
        # from; the parameter server owes us one catch-up push carrying
        # Σ updates so far plus the authoritative next round number. Regular
        # round broadcasts racing in first are safe to drop — their content
        # is folded into any later cumulative sum.
        if mh is not None:
            _mh_done_bounded(mh)
            raise ValueError("rejoin is not supported for multihost replicas")
        from ..ft.rejoin import await_catchup

        log.info("rejoin: waiting for the parameter server's catch-up")

        def _drop(event: dict) -> None:
            (work_dir / event["path"]).unlink(missing_ok=True)

        if shard_map is not None and len(shard_map.shards) > 1:
            # One catch-up PER shard: each covers only its own fragments'
            # cumulative Σ (disjoint tensors), and the authoritative next
            # round is the most advanced shard's frontier.
            want = len(shard_map.shards)
            got: dict[int, dict] = {}
            with session.receive(cfg.results) as events:
                while len(got) < want:
                    catchup = await_catchup(events, on_skip=_drop)
                    meta = catchup.get("meta") or {}
                    try:
                        sid = int(meta.get(SHARD_KEY, 0))
                    except (TypeError, ValueError):
                        sid = 0
                    if sid in got:
                        _drop(catchup)
                        continue
                    got[sid] = catchup
            round_num = 0
            epoch = "?"
            merged: dict = {}
            for sid, catchup in sorted(got.items()):
                meta = catchup.get("meta") or {}
                catchup_file = work_dir / catchup["path"]
                # Shards own disjoint tensors, so the per-shard Σs union
                # into one flat map — applied in a SINGLE tree pass below
                # instead of P parameter-sized flatten/rebuild rounds.
                merged.update(compress.read_delta(catchup_file))
                catchup_file.unlink(missing_ok=True)
                round_num = max(round_num, int(meta.get("round", 0)))
                epoch = meta.get("epoch", epoch)
            merged_tensors = len(merged)
            if merged:
                params_flat = flat_leaf_map(state.params)
                # f32 accumulation — the unsharded catch-up's
                # apply_updates discipline (a long Σ cast to bf16 before
                # the add would compound rounding the other path avoids).
                new_live = merge_update_f32(
                    {n: params_flat[n] for n in merged}, merged
                )
                state = state.replace(
                    params=replace_leaves(state.params, new_live)
                )
            anchor = snapshot(state.params)
            log.info(
                "rejoin: caught up to round %d from %d shards (membership "
                "epoch %s, %d tensors)",
                round_num, want, epoch, merged_tensors,
            )
        else:
            with session.receive(cfg.results) as events:
                catchup = await_catchup(events, on_skip=_drop)
            meta = catchup.get("meta") or {}
            catchup_file = work_dir / catchup["path"]
            flat = compress.read_delta(catchup_file)
            if flat:
                update = unflatten_like(flat, state.params)
                state = state.replace(params=apply_updates(state.params, [update]))
            anchor = snapshot(state.params)
            catchup_file.unlink(missing_ok=True)
            round_num = int(meta.get("round", 0))
            log.info(
                "rejoin: caught up to round %d (membership epoch %s, %d tensors)",
                round_num, meta.get("epoch", "?"), len(flat),
            )

    def batches() -> Iterator[Any]:
        yield first_batch
        while True:
            t0 = time.monotonic()
            batch = next(stream, None)
            # Total input wait: host assembly + any slice acquisition that
            # ran inline — the fraction databench asserts the pipeline
            # shrinks (recording only; values and order are untouched).
            DATA_METRICS.note_input_wait(time.monotonic() - t0)
            if batch is None:
                return
            yield batch

    def do_update() -> bool:
        """Ship Δθ, wait for the PS broadcast, merge. True = next round."""
        nonlocal state, anchor, host_anchor, round_num, round_samples
        nonlocal ps_generation
        rtrace.close_inner()
        round_tp = rtrace.ctx(round_num)
        send_status_gated(
            Progress(
                kind=ProgressKind.UPDATE, job_id=spec.job_id,
                traceparent=round_tp,
            )
        )
        enc_span = trace.begin(
            "encode", parent=round_tp,
            attrs={"round": round_num, "codec": wire_codec}, node=rtrace.node,
        )
        host_params = None
        if mh is not None:
            # Collective Δθ: the allgather every process joins (OP_GATHER),
            # then host-side subtraction against the host anchor — param
            # shards on other processes' devices cannot be device_get here.
            host_params = _with_deadline(
                lambda: mh.gather(state.params), mh_bound("gather"),
                "param gather",
            )
            compiled_once["gather"] = True
            host_delta = jax.tree.map(
                lambda p, a: p - a, host_params, host_anchor
            )
        else:
            delta = extract_delta(state.params, anchor)
            host_delta = jax.device_get(delta)
        delta_path = work_dir / f"delta-{round_num}.safetensors"
        # One send-side entry point for every codec (hypha_tpu.compress):
        # int8/int4 ship Q(Δθ + e) as an HQD1 frame and keep
        # e' = (Δθ + e) − Q(Δθ + e) for the next round (quantization error
        # is re-shipped, never dropped); bf16 halves the upload; the PS
        # widens/accumulates in f32 in every case.
        wire_flat = flatten_tree(host_delta)
        if (
            delta_ef is not None
            and wire_codec not in compress.QUANT_CODECS
            and delta_ef.tensors
        ):
            # The link recovered (per-link hint switched quant -> base
            # codec) with a residual still pending: fold it into this
            # upload — EF's promise is that quantization error is
            # re-shipped, never dropped, and an uncompressed wire can
            # carry it exactly.
            wire_flat = delta_ef.compensate(wire_flat)
            delta_ef.reset()
        compress.write_delta(
            delta_path, wire_flat, wire_codec, ef=delta_ef
        )
        trace.finish(enc_span)
        up_span = trace.begin(
            "upload", parent=round_tp,
            attrs={
                "round": round_num, "codec": wire_codec,
                "bytes": delta_path.stat().st_size,
            },
            node=rtrace.node,
        )
        session.send_resource(
            cfg.updates,
            delta_path.name,
            # The Send reference's resource tag routes the stream to the
            # right consumer on the PS node (job-unique, set by the
            # scheduler's orchestrator).
            resource=cfg.updates.ref.resource or "updates",
            # round tags the delta so an elastic parameter server can
            # reject a stale one (arriving after its round aggregated at
            # quorum) instead of folding it into the wrong mean. Traced
            # jobs additionally stamp the round context so the parameter
            # server's spans join the round's trace.
            meta=rtrace.stamp(
                {"num_samples": float(round_samples), "round": round_num},
                round_num,
            ),
        )
        trace.finish(up_span)
        mean_loss = float(np.mean(round_losses)) if round_losses else math.nan
        round_metrics = {"loss": mean_loss, "samples": float(round_samples)}
        if report_quality:
            round_metrics.update(quality_metrics(mean_loss))
            round_metrics["delta_norm"] = delta_norm_of(wire_flat)
        send_status_gated(
            Progress(
                kind=ProgressKind.METRICS,
                job_id=spec.job_id,
                round=round_num,
                metrics=round_metrics,
                traceparent=round_tp,
            )
        )
        with session.receive(cfg.results) as events:
            while True:
                # Not bare next(): a severed bridge ends the SSE stream,
                # and a StopIteration escaping through asyncio.to_thread
                # turns into an unraisable TypeError instead of a clean
                # job failure.
                event = next(events, None)
                if event is None:
                    raise RuntimeError(
                        "results stream ended before the round's update "
                        "broadcast"
                    )
                meta = event.get("meta") or {}
                ps_generation, resend = restart_signal(meta, ps_generation)
                if resend and delta_path.is_file():
                    # PS restart: the shipped delta may have died with it
                    # unjournaled. Re-push — the PS's journal dedup makes
                    # the copy idempotent when it DID land.
                    log.warning(
                        "ps restart detected (generation %s); re-sending "
                        "round %d delta", ps_generation, round_num,
                    )
                    session.send_resource(
                        cfg.updates,
                        delta_path.name,
                        resource=cfg.updates.ref.resource or "updates",
                        meta=rtrace.stamp(
                            {
                                "num_samples": float(round_samples),
                                "round": round_num,
                            },
                            round_num,
                        ),
                    )
                if meta.get(RESYNC_KEY) or meta.get(CATCHUP_KEY):
                    # Resync announcements carry no tensor payload; stray
                    # catch-ups target rejoiners and are folded into every
                    # later broadcast anyway.
                    (work_dir / event["path"]).unlink(missing_ok=True)
                    continue
                try:
                    eround = int(meta.get("round", round_num))
                except (TypeError, ValueError):
                    eround = round_num
                if eround < round_num:
                    # A recovered PS re-broadcasts its last committed round
                    # so un-wedged workers can proceed; this worker already
                    # merged it — absorbing again would double-apply.
                    (work_dir / event["path"]).unlink(missing_ok=True)
                    continue
                break
        apply_codec_hint(meta)
        merge_span = trace.begin(
            "merge",
            # Parent under the broadcast's context when the PS stamped
            # one (the same round trace), else the scheduler's round.
            parent=meta.get(TRACEPARENT_KEY) or round_tp,
            attrs={"round": round_num}, node=rtrace.node,
        )
        update_file = work_dir / event["path"]
        # read_delta sniffs the format: a quantized (HQD1) broadcast
        # dequantizes to f32, a SafeTensors one loads as before.
        flat = compress.read_delta(update_file)
        if mh is not None:
            # followers mirror the merge dispatch; bounded like the step
            # broadcasts — a lost follower must fail the job, not hang it
            _with_deadline(
                lambda: mh.merge(flat), mh_bound("merge"), "merge broadcast"
            )
            compiled_once["merge"] = True
        update = unflatten_like(flat, state.params)
        state = state.replace(params=merge_update(state.params, update))
        if mh is not None:
            # New anchor = merged params, assembled on the host from the
            # round's gathered params + the same update the device merge
            # applied — no second collective needed.
            host_anchor = jax.tree.map(
                lambda p, u: p + np.asarray(u, p.dtype), host_params, update
            )
        else:
            anchor = snapshot(state.params)
        trace.finish(merge_span)
        delta_path.unlink(missing_ok=True)
        # The broadcast update is merged — drop it, or a long job accumulates
        # one full-parameter-sized file per round under work_dir/incoming.
        update_file.unlink(missing_ok=True)
        resp = send_status_gated(
            Progress(
                kind=ProgressKind.UPDATE_RECEIVED, job_id=spec.job_id,
                traceparent=round_tp,
            )
        )
        round_num += 1
        rtrace.adopt(resp, round_num)
        result.rounds = round_num
        round_samples = 0
        round_losses.clear()
        if ckpt_dir is not None and round_num % ckpt_every == 0:
            if mh is not None:
                # Sharded opt_state spans non-addressable devices; a full
                # host gather of params+opt per round is not worth wiring
                # until a job needs it (sharded orbax-style checkpointing
                # is the real fix). Resume still works via the PS momentum
                # checkpoint + re-dispatch from θ of the last round.
                log.warning(
                    "checkpointing skipped: multihost replicas do not yet "
                    "support train-state checkpoints"
                )
            else:
                # Manifest round counts CUMULATIVE completed rounds across
                # resumes, not just this execution's.
                save_train_checkpoint(
                    ckpt_dir,
                    state.params,
                    state.opt_state,
                    int(state.step),
                    round_offset + round_num,
                )
        return resp.kind == ProgressResponseKind.CONTINUE

    # Sharded blocking sync state: the deterministic part partition, one
    # error-feedback residual per part (absorb replaces the whole residual
    # tree, so parts must not share one), and the last seen generation per
    # PS shard.
    shard_ctx: dict[str, Any] = {"parts": None, "efs": None, "gens": {}}

    def _push_part(p: int, path: Path, samples: float) -> None:
        tag = FragmentTag(
            round=round_num, fragment_id=p,
            fragments=len(shard_ctx["parts"]),
        )
        send, owner, res_tag = shard_route(
            shard_map, p, getattr(cfg, "reduce_via", None)
        )
        meta = {"num_samples": samples, "round": round_num, **tag.header()}
        if len(shard_map.shards) > 1:
            meta[SHARD_KEY] = owner
        session.send_resource(
            send, path.name, resource=res_tag,
            meta=rtrace.stamp(meta, round_num),
        )

    def do_update_sharded() -> bool:
        """Blocking sync against the sharded parameter service: split Δθ
        into placement parts, push each part to its owning shard (via the
        group reducer with ANY failover when tree-reduce is on), await
        EVERY part's update broadcast, merge, re-anchor. True = continue.
        """
        nonlocal state, anchor, round_num, round_samples
        assert shard_map is not None
        rtrace.close_inner()
        round_tp = rtrace.ctx(round_num)
        send_status_gated(
            Progress(
                kind=ProgressKind.UPDATE, job_id=spec.job_id,
                traceparent=round_tp,
            )
        )
        enc_span = trace.begin(
            "encode", parent=round_tp,
            attrs={"round": round_num, "codec": wire_codec}, node=rtrace.node,
        )
        delta = extract_delta(state.params, anchor)
        host_delta = jax.device_get(delta)
        wire_flat = flatten_tree(host_delta)
        P = int(shard_map.fragments) or len(shard_map.shards)
        if shard_ctx["parts"] is None:
            # Deterministic by (name, size) only — shards, reducers and
            # rejoiners derive the identical partition with no manifest.
            shard_ctx["parts"] = partition_names(
                {n: int(np.asarray(v).size) for n, v in wire_flat.items()}, P
            )
            shard_ctx["efs"] = [
                compress.ErrorFeedback()
                if wire_codec in compress.QUANT_CODECS
                else None
                for _ in range(P)
            ]
        parts = shard_ctx["parts"]
        samples = float(round_samples)
        trace.finish(enc_span)
        up_span = trace.begin(
            "upload", parent=round_tp,
            attrs={"round": round_num, "codec": wire_codec, "parts": len(parts)},
            node=rtrace.node,
        )
        paths: dict[int, Path] = {}
        for p, names in enumerate(parts):
            tag = FragmentTag(round=round_num, fragment_id=p, fragments=P)
            path = work_dir / f"delta-{round_num}-p{p}.safetensors"
            compress.write_delta(
                path, {n: wire_flat[n] for n in names}, wire_codec,
                ef=shard_ctx["efs"][p], tag=tag.header(),
            )
            paths[p] = path
            _push_part(p, path, samples)
        trace.finish(up_span)
        mean_loss = float(np.mean(round_losses)) if round_losses else math.nan
        round_metrics = {"loss": mean_loss, "samples": samples}
        if report_quality:
            round_metrics.update(quality_metrics(mean_loss))
            round_metrics["delta_norm"] = delta_norm_of(wire_flat)
        send_status_gated(
            Progress(
                kind=ProgressKind.METRICS,
                job_id=spec.job_id,
                round=round_num,
                metrics=round_metrics,
                traceparent=round_tp,
            )
        )
        gens = shard_ctx["gens"]
        got: dict[int, Path] = {}
        with session.receive(cfg.results) as events:
            while len(got) < P:
                event = next(events, None)
                if event is None:
                    raise RuntimeError(
                        "results stream ended before every part's update "
                        "broadcast"
                    )
                meta = event.get("meta") or {}
                try:
                    sid = int(meta.get(SHARD_KEY, 0))
                except (TypeError, ValueError):
                    sid = 0
                gens[sid], resend = restart_signal(meta, gens.get(sid))
                if resend:
                    # That shard restarted: re-send its still-un-acked
                    # parts — the shard's journal dedup absorbs any copy
                    # whose original did land.
                    for p, path in paths.items():
                        if (
                            p in got
                            or not path.is_file()
                            or shard_of(p, len(shard_map.shards)) != sid
                        ):
                            continue
                        log.warning(
                            "ps shard %d restart detected; re-sending "
                            "round %d part %d", sid, round_num, p,
                        )
                        _push_part(p, path, samples)
                if meta.get(RESYNC_KEY) or meta.get(CATCHUP_KEY):
                    (work_dir / event["path"]).unlink(missing_ok=True)
                    continue
                try:
                    eround = int(meta.get("round", round_num))
                except (TypeError, ValueError):
                    eround = round_num
                if eround < round_num:
                    # A recovered shard's re-broadcast of a merged round.
                    (work_dir / event["path"]).unlink(missing_ok=True)
                    continue
                etag = FragmentTag.from_header(meta)
                p = int(etag.fragment_id) if etag is not None else sid
                if p in got or p not in paths:
                    (work_dir / event["path"]).unlink(missing_ok=True)
                    continue
                got[p] = work_dir / event["path"]
        # Merge every part — disjoint tensors, so their flat maps union
        # into ONE combined merge/replace pass (P separate passes would
        # re-flatten and rebuild the whole parameter tree per part) —
        # then re-anchor ONCE (blocking semantics: no drift correction).
        merge_span = trace.begin(
            "merge", parent=round_tp, attrs={"round": round_num},
            node=rtrace.node,
        )
        combined: dict = {}
        for p in sorted(got):
            flat = compress.read_delta(got[p])
            if set(flat) != set(parts[p]):
                raise ValueError(
                    f"part {p} placement mismatch: update carries "
                    f"{len(flat)} tensors, worker expects {len(parts[p])}"
                )
            combined.update(flat)
            got[p].unlink(missing_ok=True)
        params_flat = flat_leaf_map(state.params)
        new_live = merge_update(
            {n: params_flat[n] for n in combined}, combined
        )
        state = state.replace(params=replace_leaves(state.params, new_live))
        anchor = snapshot(state.params)
        trace.finish(merge_span)
        for path in paths.values():
            path.unlink(missing_ok=True)
        resp = send_status_gated(
            Progress(
                kind=ProgressKind.UPDATE_RECEIVED, job_id=spec.job_id,
                traceparent=round_tp,
            )
        )
        round_num += 1
        rtrace.adopt(resp, round_num)
        result.rounds = round_num
        round_samples = 0
        round_losses.clear()
        if ckpt_dir is not None and round_num % ckpt_every == 0:
            save_train_checkpoint(
                ckpt_dir,
                state.params,
                state.opt_state,
                int(state.step),
                round_offset + round_num,
            )
        return resp.kind == ProgressResponseKind.CONTINUE

    def begin_stream_sync() -> None:
        """Ship the due fragment's Δ in the background; keep stepping.

        Round accumulators reset HERE, not at merge time: batches run
        while the sync is in flight belong to the NEXT delta (that is the
        drift the correction preserves), so their samples and losses must
        not be re-reported for this round.
        """
        nonlocal round_samples
        assert stream_state is not None
        rtrace.close_inner()
        round_tp = rtrace.ctx(round_num)
        send_status_gated(
            Progress(
                kind=ProgressKind.UPDATE, job_id=spec.job_id,
                traceparent=round_tp,
            )
        )
        stream_state.begin(round_num, state.params, anchor, round_samples)
        mean_loss = float(np.mean(round_losses)) if round_losses else math.nan
        round_metrics = {"loss": mean_loss, "samples": float(round_samples)}
        if report_quality:
            # No delta norm here: the due fragment's delta belongs to the
            # background flight thread (stream mode).
            round_metrics.update(quality_metrics(mean_loss))
        send_status_gated(
            Progress(
                kind=ProgressKind.METRICS,
                job_id=spec.job_id,
                round=round_num,
                metrics=round_metrics,
                traceparent=round_tp,
            )
        )
        round_samples = 0
        round_losses.clear()

    def finish_stream_sync() -> bool:
        """The broadcast landed: merge with correction. True = continue."""
        nonlocal state, anchor, round_num
        assert stream_state is not None
        new_params, new_anchor = stream_state.finish(state.params, anchor)
        state = state.replace(params=new_params)
        anchor = new_anchor
        resp = send_status_gated(
            Progress(
                kind=ProgressKind.UPDATE_RECEIVED, job_id=spec.job_id,
                traceparent=rtrace.ctx(round_num),
            )
        )
        round_num += 1
        rtrace.adopt(resp, round_num)
        result.rounds = round_num
        if ckpt_dir is not None and round_num % ckpt_every == 0:
            save_train_checkpoint(
                ckpt_dir,
                state.params,
                state.opt_state,
                int(state.step),
                round_offset + round_num,
            )
        return resp.kind == ProgressResponseKind.CONTINUE

    mh_timeout = float(
        os.environ.get(_MH_STEP_TIMEOUT_ENV, _MH_STEP_TIMEOUT_DEFAULT)
    )
    mh_grace = max(
        mh_timeout,
        float(os.environ.get(_MH_COMPILE_GRACE_ENV, _MH_COMPILE_GRACE_DEFAULT)),
    )
    compiled_once = {"step": False, "merge": False, "gather": False}

    def mh_bound(what: str) -> float:
        return mh_timeout if compiled_once[what] else mh_grace

    def run_one(batch):
        """Broadcast + dispatch + host fetch: every phase that can block on
        a dead follower, so the deadline covers all of them."""
        if mh is not None:
            mh.step(batch)  # followers dispatch the same step
        new_state, metrics = step(state, place(batch))
        return new_state, metrics, float(metrics["loss"])

    def run_one_deferred(batch):
        """Device double-buffering (input_pipeline): dispatch the step and
        return WITHOUT forcing the loss — the host thread goes straight on
        to assemble and place batch n+1 while step n computes on device.
        The metrics land in ``pending_metrics``; ``flush_pending_loss``
        reads them one step later (same values, same order)."""
        new_state, metrics = step(state, place(batch))
        return new_state, metrics

    # One-step-deferred loss reads (input_pipeline only; empty otherwise).
    # Flushed before every round-boundary action that reports or resets
    # ``round_losses``, and after the loop — the loss SEQUENCE is
    # bit-identical to the synchronous read, just observed later.
    pending_metrics: list[Any] = []

    def flush_pending_loss() -> None:
        while pending_metrics:
            metrics = pending_metrics.pop(0)
            loss = float(metrics["loss"])
            round_losses.append(loss)
            result.losses.append(loss)

    t0 = time.monotonic()
    try:
        for batch in batches():
            if should_stop is not None and should_stop():
                log.info("cooperative stop requested; ending training loop")
                break
            # Merge a landed broadcast BEFORE the next step: a sync that
            # completed with no intervening batch has zero drift and is
            # bit-identical to blocking mode's merge.
            if stream_state is not None and stream_state.poll():
                if not finish_stream_sync():
                    break
            rtrace.batch(round_num)
            if mh is not None:
                state, metrics, loss = _with_deadline(
                    lambda b=batch: run_one(b), mh_bound("step"), "train step"
                )
                compiled_once["step"] = True
                round_losses.append(loss)
                result.losses.append(loss)
            else:
                overlapping = stream_state is not None and stream_state.in_flight
                if pipeline_on and not overlapping:
                    # Deferred sync: dispatch step n, then read step n-1's
                    # loss (already done on device) — never this step's.
                    # Skipped while a stream flight is up: note_compute's
                    # overlap accounting needs the synchronous read.
                    state, metrics = run_one_deferred(batch)
                    flush_pending_loss()
                    pending_metrics.append(metrics)
                else:
                    bt0 = time.monotonic() if overlapping else 0.0
                    state, metrics, loss = run_one(batch)
                    if overlapping:
                        stream_state.note_compute(time.monotonic() - bt0)
                    flush_pending_loss()  # older deferred losses first
                    round_losses.append(loss)
                    result.losses.append(loss)
            result.batches += 1
            round_samples += cfg.batch_size
            if report_quality:
                note_quality_batch(batch)

            resp = send_status_gated(
                Progress(
                    kind=ProgressKind.STATUS,
                    job_id=spec.job_id,
                    batch_size=cfg.batch_size,
                )
            )
            if resp.kind == ProgressResponseKind.DONE:
                break
            if resp.kind == ProgressResponseKind.SCHEDULE_UPDATE:
                adopted = adopt_schedule(resp, countdown)
                if adopted is not countdown:
                    countdown = adopted
                    rtrace.adopt(resp, round_num)
            if countdown is not None:
                if countdown <= 0:
                    countdown = None
                    # Round boundary: the round's LAST loss may still be
                    # deferred — it must land in round_losses before the
                    # sync reports/reset them.
                    flush_pending_loss()
                    if stream_state is not None:
                        begin_stream_sync()
                    elif shard_map is not None:
                        if not do_update_sharded():
                            break
                    elif not do_update():
                        break
                else:
                    countdown -= 1
            if max_batches is not None and result.batches >= max_batches:
                log.warning("max_batches=%d reached; stopping", max_batches)
                break
        flush_pending_loss()
    finally:
        rtrace.close_inner()
        # Stop the input pipeline's prefetch thread NOW (the generator's
        # finally owns it) instead of at GC — its next fetch would race
        # the bridge teardown. No-op for the synchronous stream.
        try:
            stream.close()
        except Exception:  # never let input teardown mask the real error
            pass
        if stream_state is not None:
            stream_state.abort()
        if mh is not None:
            _mh_done_bounded(mh)  # followers must never hang on a dead leader
    log.info(
        "training done: %d rounds, %d batches, %.1fs, last loss %.4f",
        result.rounds, result.batches, time.monotonic() - t0, result.last_loss,
    )
    return result


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="hypha-training-executor",
        description="hypha-tpu DiLoCo training executor",
    )
    parser.add_argument("--socket", required=True, help="bridge unix socket path")
    parser.add_argument("--work-dir", required=True)
    parser.add_argument("--job", required=True, help="job spec JSON (inline or @file)")
    parser.add_argument("--max-batches", type=int, default=None)
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO, format="%(name)s %(message)s")

    raw = args.job
    if raw.startswith("@"):
        raw = Path(raw[1:]).read_text()
    spec = messages.from_json_dict(json.loads(raw))
    if not isinstance(spec, JobSpec):
        raise SystemExit(f"--job does not decode to a JobSpec: {type(spec)}")

    from .bridge_client import Session

    with Session(args.socket) as session:
        run_training(session, args.work_dir, spec, max_batches=args.max_batches)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
