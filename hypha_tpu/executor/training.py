"""The training executor: the DiLoCo inner loop on JAX, driven by the bridge.

Parity with the reference's accelerate executor
(executors/accelerate/src/hypha/accelerate_executor/training.py:28-147):

  * parse the job spec, open a bridge Session, fetch model artifacts;
  * build model / AdamW / LR schedule / streaming slice dataset;
  * snapshot the round anchor θ₀ (the reference's ``0_global_weights.pt``);
  * loop: jitted train step → per-batch ``Status`` heartbeat → on
    ``ScheduleUpdate{counter}`` run ``counter`` more batches → send
    ``update`` status → save Δθ = θ_t − θ₀ SafeTensors → ship to the
    parameter server (tagged with the round's sample count for the
    weighted mean) → send round metrics → await the broadcast update →
    merge (θ ← θ + update) → ``update-received`` → Continue | Done.

TPU-native differences: the whole inner step is ONE jit-compiled function
(forward+loss+backward+AdamW fused by XLA, bf16 activations on the MXU);
optional intra-replica sharding lays the step out over a device mesh
(dp/fsdp/tp/sp/ep axes) so collectives ride ICI; Δθ extraction and the
merge are jitted tree ops (hypha_tpu.executor.diloco).

Launch (the worker's process executor substitutes the placeholders —
crates/worker/src/executor/process.rs:124-137):

    python -m hypha_tpu.executor.training \
        --socket {SOCKET_PATH} --work-dir {WORK_DIR} --job {JOB_JSON}
"""

from __future__ import annotations

import argparse
import json
import logging
import math
import time
from pathlib import Path
from typing import Any, Callable, Iterator

import numpy as np

from .. import messages
from ..messages import (
    JobSpec,
    Loss,
    ModelType,
    Progress,
    ProgressKind,
    ProgressResponseKind,
    TrainExecutorConfig,
)
from .diloco import extract_delta, merge_update
from .serialization import load_flat, save_tree, unflatten_like
from .train import TrainState, build_optimizer, make_train_step

__all__ = ["run_training", "main", "TrainResult"]

log = logging.getLogger("hypha.executor.training")

def _non_causal_types():
    from ..models.heads import HEAD_TYPES

    return {
        ModelType.IMAGE_CLASSIFICATION,
        ModelType.SEQUENCE_CLASSIFICATION,
        ModelType.TOKEN_CLASSIFICATION,
    } | HEAD_TYPES


class TrainResult:
    """What the loop did — surfaced for tests and the in-process executor."""

    def __init__(self) -> None:
        self.rounds = 0
        self.batches = 0
        self.losses: list[float] = []

    @property
    def last_loss(self) -> float:
        return self.losses[-1] if self.losses else math.nan


def _build_mesh(sharding: dict | None):
    """Optional intra-replica mesh (TrainExecutorConfig.sharding extension)."""
    if not sharding:
        return None
    import jax

    from ..parallel import create_mesh

    sizes = {a: int(sharding.get(a, 1)) for a in ("dp", "fsdp", "tp", "sp", "ep")}
    total = math.prod(sizes.values())
    if total <= 1:
        return None
    if total > len(jax.devices()):
        log.warning(
            "sharding %s needs %d devices, have %d; running unsharded",
            sharding, total, len(jax.devices()),
        )
        return None
    return create_mesh(sizes)


def _init_model(cfg: TrainExecutorConfig, session, work_dir: Path, first_batch):
    """Build the model and its initial params (fetched weights or seeded)."""
    import jax

    from ..models import Mixtral, build_model
    from ..models.registry import resolve_model_type

    model_spec = dict(cfg.model)
    if cfg.lora:
        # Adapter-only fine-tuning: inject the LoRA fields into the model
        # config (the Llama family's _proj picks them up). The LlamaConfig
        # constructor validates rank/targets; unsupported families have no
        # lora_rank field and fail loudly in their config constructor.
        model_spec["config"] = dict(
            model_spec.get("config", {}),
            lora_rank=int(cfg.lora.get("rank", 8)),
            lora_alpha=float(cfg.lora.get("alpha", 16.0)),
            lora_targets=tuple(cfg.lora.get("targets", ("q_proj", "v_proj"))),
        )
    # On TPU the pluggable-attention families run the pallas flash kernel by
    # default (sequence-parallel jobs swap in the ring kernel instead, via
    # _build_mesh); off-TPU the XLA dense path is faster than interpret mode.
    attn_impl = None
    from ..hw import is_accelerator

    if is_accelerator() and not cfg.sharding:
        from ..ops.flash_attention import flash_attention

        attn_impl = flash_attention
        log.info("attention path: pallas flash kernel (backend=%s)", jax.default_backend())
    else:
        log.info("attention path: XLA dense (backend=%s)", jax.default_backend())

    source = model_spec.get("source")
    if model_spec.get("family") == "hf" and source is not None and not model_spec.get("path"):
        # The hf family loads weights via from_pretrained, so the checkpoint
        # dir must exist BEFORE the model is built (the native families
        # init-then-overwrite below instead).
        fetch = messages.from_json_dict(source) if isinstance(source, dict) else source
        rels = session.fetch(fetch)
        cfg_file = next((r for r in rels if r.endswith("config.json")), None)
        model_spec["path"] = str(
            (work_dir / cfg_file).parent if cfg_file else work_dir
        )
        source = None  # weights are loaded by the builder; skip the overwrite

    model, _mcfg = build_model(model_spec, attn_impl)
    model_type = resolve_model_type(model_spec.get("model_type", ModelType.CAUSAL_LM))
    causal_lm = model_type not in _non_causal_types()
    has_aux = isinstance(model, Mixtral)

    inputs = first_batch["input_ids"] if "input_ids" in first_batch else first_batch["inputs"]
    seed = int(model_spec.get("seed", 0))
    params = model.init(jax.random.key(seed), inputs)

    if source is not None:
        fetch = messages.from_json_dict(source) if isinstance(source, dict) else source
        rels = session.fetch(fetch)
        weight_files = [
            r for r in rels if r.endswith((".safetensors", ".bin", ".pt", ".pth"))
        ]
        if weight_files:
            from ..models.convert import convert_state_dict, load_checkpoint_files

            state = load_checkpoint_files([work_dir / r for r in weight_files])
            target = params
            if cfg.lora:
                # Checkpoints carry the BASE weights only; adapters keep
                # their seed init (B=0 -> exact base behavior at step 0).
                from .lora import merge_lora, split_lora

                adapters_t, target = split_lora(params)
            try:
                # Native flat names (our own checkpoints/exports)…
                loaded = unflatten_like(state, target)
            except KeyError:
                # …or an HF-format state dict for this family.
                family = model_spec.get("family", "gpt2")
                loaded = convert_state_dict(family, state, target)
            params = merge_lora(adapters_t, loaded) if cfg.lora else loaded
            log.info("loaded %d initial tensors from %s", len(state), weight_files)
    return model, params, causal_lm, has_aux


def run_training(
    session,
    work_dir: Path | str,
    spec: JobSpec,
    *,
    max_batches: int | None = None,
    should_stop: Callable[[], bool] | None = None,
) -> TrainResult:
    """Run the DiLoCo inner loop to completion over the given bridge session.

    ``session`` implements the bridge client API (fetch / send_resource /
    send_status / receive — hypha_tpu.executor.bridge_client.Session).
    ``max_batches`` is a safety valve for tests. ``should_stop`` is polled
    between batches — the in-process executor's cooperative cancellation.
    """
    import jax
    import jax.numpy as jnp

    work_dir = Path(work_dir)
    cfg = spec.executor.train
    if cfg is None:
        raise ValueError(f"job {spec.job_id} is not a train job")

    from .dataset import stream_batches

    def fetch_slice() -> str:
        rels = session.fetch(cfg.data)
        return str(work_dir / rels[0])

    model_spec = dict(cfg.model)
    input_names = model_spec.get("input_names")
    preprocessor = None
    if cfg.preprocessor:
        from .preprocess import build_preprocessor

        preprocessor = build_preprocessor(cfg.preprocessor, session, work_dir)
    stream = stream_batches(fetch_slice, cfg.batch_size, input_names, preprocessor)

    first_batch = next(stream)
    model, params, causal_lm, has_aux = _init_model(cfg, session, work_dir, first_batch)
    mesh = _build_mesh(cfg.sharding)

    # LoRA jobs train (ship, checkpoint, merge) the ADAPTER tree only; the
    # frozen base rides along as a constant input to every step.
    frozen = None
    if cfg.lora:
        from .lora import split_lora

        adapters, frozen = split_lora(params)
        if not jax.tree_util.tree_leaves(adapters):
            raise ValueError(
                f"job {spec.job_id}: lora={cfg.lora!r} produced no adapters "
                f"(family {dict(cfg.model).get('family')!r})"
            )
        params = adapters

    tx = build_optimizer(cfg.optimizer, cfg.scheduler)
    state = TrainState.create(params, tx)

    # Resume (net-new vs reference): a re-dispatched executor picks up the
    # last completed round's params + optimizer state instead of θ₀.
    ckpt_dir = None
    ckpt_every = 1
    round_offset = 0  # completed rounds restored from a checkpoint
    if cfg.checkpoint and cfg.checkpoint.get("dir"):
        from .checkpoint import load_train_checkpoint, save_train_checkpoint

        ckpt_every = int(cfg.checkpoint.get("every_rounds", 1))
        if ckpt_every > 0:  # <= 0 disables checkpointing
            ckpt_dir = Path(cfg.checkpoint["dir"])
            restored = load_train_checkpoint(ckpt_dir, state.params, state.opt_state)
            if restored is not None:
                r_params, r_opt, r_step, r_round, _extra = restored
                state = state.replace(
                    params=r_params, opt_state=r_opt, step=jnp.int32(r_step)
                )
                round_offset = r_round
                log.info(
                    "resumed from %s: step %d, %d completed rounds",
                    ckpt_dir, r_step, r_round,
                )

    # Multi-process replica (pod-as-one-replica): process 0 — this loop —
    # owns the control plane and broadcasts each collective-bearing action
    # so follower processes (executor.multihost_coord.run_training_follower)
    # mirror the dispatches over the same global mesh. The init broadcast
    # runs BEFORE mesh placement: it device_gets the state, which must
    # still be host/single-device arrays (global arrays spanning another
    # process cannot be fetched locally).
    mh = None
    if jax.process_count() > 1:
        if mesh is None:
            # Fail fast HERE: the follower asserts a mesh exists, and a
            # leader training unsharded while followers expect lockstep
            # dispatches would deadlock on the first step broadcast.
            raise ValueError(
                f"job {spec.job_id}: {jax.process_count()} processes need a "
                f"sharding config spanning all {len(jax.devices())} global "
                f"devices; got {cfg.sharding!r}"
            )
        from .multihost_coord import LeaderCoordination

        mh = LeaderCoordination()
        mh.init(
            json.dumps(messages.to_json_dict(spec)), state, first_batch,
            frozen=frozen,
        )
        log.info(
            "multihost leader: %d processes, %d global devices",
            jax.process_count(), len(jax.devices()),
        )

    try:
        # From the init broadcast on, ANY leader exit without OP_DONE
        # leaves followers blocked in recv — this guard plus the loop's
        # finally below cover every path.
        loss_kind = cfg.loss or Loss.CROSS_ENTROPY
        from ..models.hf import _DECODER_TYPES

        step_kwargs = dict(
            causal_lm=causal_lm,
            has_aux=has_aux,
            # Models that declare an ``rng`` kwarg (the hf family) train
            # with live dropout, keyed per-step from the job seed — the
            # reference trains its torch models in train() mode
            # (training.py:106-116).
            dropout_seed=int(dict(cfg.model).get("seed", 0)),
            # Seq2seq hf models shift labels into decoder inputs
            # internally, so their logits are already aligned with the
            # labels stream.
            labels_aligned=getattr(model, "model_type", None) in _DECODER_TYPES,
            # Heads-family tasks with structured objectives (CTC,
            # detection, contrastive, span…) carry their own loss.
            loss_override=getattr(model, "custom_loss", None),
        )
        if frozen is not None:
            from .lora import make_lora_train_step

            lora_step = make_lora_train_step(model.apply, loss_kind, **step_kwargs)

            def step(state, batch):
                return lora_step(state, frozen, batch)
        else:
            step = make_train_step(model.apply, loss_kind, **step_kwargs)

        if mesh is not None:
            from jax.sharding import NamedSharding

            from ..parallel import param_sharding
            from ..parallel.sharding import batch_spec

            state = jax.device_put(state, param_sharding(state, mesh))
            if frozen is not None:
                frozen = jax.device_put(frozen, param_sharding(frozen, mesh))
            batch_sharding = NamedSharding(mesh, batch_spec())

            if mh is not None:

                def place(batch):
                    # Multi-controller: build global arrays shard-by-shard
                    # (device_put may refuse shardings spanning devices
                    # this process cannot address). Every process holds the
                    # same host batch — the leader just broadcast it.
                    return {
                        k: jax.make_array_from_callback(
                            np.shape(v), batch_sharding,
                            lambda idx, v=v: np.asarray(v)[idx],
                        )
                        for k, v in batch.items()
                    }
            else:

                def place(batch):
                    return {
                        k: jax.device_put(v, batch_sharding)
                        for k, v in batch.items()
                    }
        else:

            def place(batch):
                return batch

        def snapshot(tree):
            # A deep copy, NOT an alias: the jitted step donates its input
            # state, so aliased buffers would be deleted on the next step.
            return jax.tree.map(jnp.copy, tree)

        anchor = snapshot(state.params)  # θ₀: the round anchor
    except BaseException:
        if mh is not None:
            mh.done()  # followers must never hang on a dead leader
        raise
    result = TrainResult()
    countdown: int | None = None
    round_num = 0
    round_samples = 0
    round_losses: list[float] = []

    def batches() -> Iterator[Any]:
        yield first_batch
        yield from stream

    def do_update() -> bool:
        """Ship Δθ, wait for the PS broadcast, merge. True = next round."""
        nonlocal state, anchor, round_num, round_samples
        session.send_status(Progress(kind=ProgressKind.UPDATE, job_id=spec.job_id))
        delta = extract_delta(state.params, anchor)
        delta_path = work_dir / f"delta-{round_num}.safetensors"
        save_tree(delta_path, jax.device_get(delta))
        session.send_resource(
            cfg.updates,
            delta_path.name,
            # The Send reference's resource tag routes the stream to the
            # right consumer on the PS node (job-unique, set by the
            # scheduler's orchestrator).
            resource=cfg.updates.ref.resource or "updates",
            meta={"num_samples": float(round_samples)},
        )
        mean_loss = float(np.mean(round_losses)) if round_losses else math.nan
        session.send_status(
            Progress(
                kind=ProgressKind.METRICS,
                job_id=spec.job_id,
                round=round_num,
                metrics={"loss": mean_loss, "samples": float(round_samples)},
            )
        )
        with session.receive(cfg.results) as events:
            event = next(events)
        update_file = work_dir / event["path"]
        flat = load_flat(update_file)
        if mh is not None:
            mh.merge(flat)  # followers mirror the merge dispatch
        update = unflatten_like(flat, state.params)
        state = state.replace(params=merge_update(state.params, update))
        anchor = snapshot(state.params)
        delta_path.unlink(missing_ok=True)
        # The broadcast update is merged — drop it, or a long job accumulates
        # one full-parameter-sized file per round under work_dir/incoming.
        update_file.unlink(missing_ok=True)
        resp = session.send_status(
            Progress(kind=ProgressKind.UPDATE_RECEIVED, job_id=spec.job_id)
        )
        round_num += 1
        result.rounds = round_num
        round_samples = 0
        round_losses.clear()
        if ckpt_dir is not None and round_num % ckpt_every == 0:
            # Manifest round counts CUMULATIVE completed rounds across
            # resumes, not just this execution's.
            save_train_checkpoint(
                ckpt_dir,
                state.params,
                state.opt_state,
                int(state.step),
                round_offset + round_num,
            )
        return resp.kind == ProgressResponseKind.CONTINUE

    t0 = time.monotonic()
    try:
        for batch in batches():
            if should_stop is not None and should_stop():
                log.info("cooperative stop requested; ending training loop")
                break
            if mh is not None:
                mh.step(batch)  # followers dispatch the same step
            state, metrics = step(state, place(batch))
            loss = float(metrics["loss"])
            round_losses.append(loss)
            result.losses.append(loss)
            result.batches += 1
            round_samples += cfg.batch_size

            resp = session.send_status(
                Progress(
                    kind=ProgressKind.STATUS,
                    job_id=spec.job_id,
                    batch_size=cfg.batch_size,
                )
            )
            if resp.kind == ProgressResponseKind.DONE:
                break
            if resp.kind == ProgressResponseKind.SCHEDULE_UPDATE:
                countdown = resp.counter
            if countdown is not None:
                if countdown <= 0:
                    countdown = None
                    if not do_update():
                        break
                else:
                    countdown -= 1
            if max_batches is not None and result.batches >= max_batches:
                log.warning("max_batches=%d reached; stopping", max_batches)
                break
    finally:
        if mh is not None:
            mh.done()  # followers must never hang on a dead leader
    log.info(
        "training done: %d rounds, %d batches, %.1fs, last loss %.4f",
        result.rounds, result.batches, time.monotonic() - t0, result.last_loss,
    )
    return result


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description="hypha-tpu DiLoCo training executor")
    parser.add_argument("--socket", required=True, help="bridge unix socket path")
    parser.add_argument("--work-dir", required=True)
    parser.add_argument("--job", required=True, help="job spec JSON (inline or @file)")
    parser.add_argument("--max-batches", type=int, default=None)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO, format="%(name)s %(message)s")

    raw = args.job
    if raw.startswith("@"):
        raw = Path(raw[1:]).read_text()
    spec = messages.from_json_dict(json.loads(raw))
    if not isinstance(spec, JobSpec):
        raise SystemExit(f"--job does not decode to a JobSpec: {type(spec)}")

    from .bridge_client import Session

    with Session(args.socket) as session:
        run_training(session, args.work_dir, spec, max_batches=args.max_batches)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
