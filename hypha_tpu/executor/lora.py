"""LoRA fine-tuning: adapter-only training for checkpoint-scale models.

The reference fine-tunes by updating every parameter through torch/
Accelerate (executors/accelerate/.../training.py:106-116) — at 7B that
needs optimizer state and gradients for 6.7B parameters, far beyond one
chip. The TPU-native answer: freeze the (bf16) base weights on device and
train only low-rank adapters (models/llama.py ``lora_rank``): grads and
AdamW moments exist for ~0.06% of the parameters, so a Llama-2-7B
fine-tune step fits a single 16 GB v5e alongside the weights.

The split here is tree surgery, not model surgery: adapter leaves are
identified by their ``_lora_`` name, separated from the frozen base, and
the jitted step differentiates with respect to the adapter tree only —
the base tree is a closed-over constant input, donated nowhere, cast
never.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from ..messages import Loss
from .train import TrainState, make_loss_fn

__all__ = [
    "split_lora",
    "merge_lora",
    "fold_lora",
    "make_lora_train_step",
]


def _is_lora(name: str) -> bool:
    return "_lora_" in name


def split_lora(params: Any) -> tuple[Any, Any]:
    """Partition a param tree into (adapters, frozen_base) by leaf name."""

    def rec(node):
        if not isinstance(node, dict):
            raise TypeError(f"expected nested dict param tree, got {type(node)}")
        train: dict = {}
        frozen: dict = {}
        for key, value in node.items():
            if isinstance(value, dict):
                t, f = rec(value)
                if t:
                    train[key] = t
                if f:
                    frozen[key] = f
            elif _is_lora(key):
                train[key] = value
            else:
                frozen[key] = value
        return train, frozen

    return rec(params)


def merge_lora(adapters: Any, frozen: Any) -> Any:
    """Inverse of :func:`split_lora` (deep union; adapters win on clash)."""

    def rec(a, f):
        if not isinstance(a, dict):
            return a
        out = dict(f) if isinstance(f, dict) else {}
        for key, value in a.items():
            out[key] = rec(value, out.get(key)) if isinstance(value, dict) else value
        return out

    return rec(adapters, frozen) if adapters else frozen


def fold_lora(params: Any, alpha: float, rank: int) -> Any:
    """Fold adapters into their base kernels for adapter-free serving:
    ``W' = W + (α/r)·A@B``. Returns a tree with no ``_lora_`` leaves, loadable
    by a ``lora_rank=0`` model."""
    scale = alpha / rank

    def rec(node):
        if not isinstance(node, dict):
            return node
        out = {}
        for key, value in node.items():
            if _is_lora(key):
                continue
            out[key] = rec(value)
        for key, value in node.items():
            if not key.endswith("_lora_a"):
                continue
            target = key[: -len("_lora_a")]
            b = node[f"{target}_lora_b"]
            kernel = out[target]["kernel"]
            delta = (jnp.asarray(value) @ jnp.asarray(b)) * scale
            out[target] = dict(out[target], kernel=(
                kernel + delta.astype(kernel.dtype)
            ))
        return out

    return rec(params)


def make_lora_train_step(
    apply_fn: Callable,
    loss_kind: Loss = Loss.CROSS_ENTROPY,
    *,
    causal_lm: bool = True,
    has_aux: bool = False,
    donate: bool = True,
    dropout_seed: int | None = None,
    labels_aligned: bool = False,
    loss_override: Callable | None = None,
):
    """Jitted LoRA step: ``step(lora_state, frozen, batch) -> (state, metrics)``.

    ``lora_state`` is a :class:`TrainState` over the adapter tree only;
    ``frozen`` is the full base tree from :func:`split_lora`. Only the
    adapter state is donated — the base buffers survive every step. Loss
    and label-layout semantics are :func:`executor.train.make_loss_fn`'s,
    shared with the full-parameter step, so the two can never diverge.
    """
    base_loss_fn = make_loss_fn(
        apply_fn,
        loss_kind,
        causal_lm=causal_lm,
        has_aux=has_aux,
        dropout_seed=dropout_seed,
        labels_aligned=labels_aligned,
        loss_override=loss_override,
    )

    def loss_fn(adapters, frozen, batch, step_no):
        return base_loss_fn(merge_lora(adapters, frozen), batch, step_no)

    def step(lora_state: TrainState, frozen, batch):
        (total, (loss, aux)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            lora_state.params, frozen, batch, lora_state.step
        )
        new_state = lora_state.apply_gradients(grads)
        metrics = {
            "loss": loss,
            "total_loss": total,
            "aux_loss": aux,
            "grad_norm": optax.global_norm(grads),
        }
        return new_state, metrics

    return jax.jit(step, donate_argnums=(0,) if donate else ())
