"""The inner training loop: optimizer, LR schedules, losses, jitted step.

TPU-native replacement for the reference's accelerate executor hot loop
(executors/accelerate/.../training.py:106-116: zero_grad/forward/backward/
step/scheduler.step): here the whole step is ONE jit-compiled function —
forward, loss, backward, AdamW update and LR schedule fused by XLA — with
params/optimizer state sharded over the replica's mesh
(parallel.sharding) so collectives ride ICI.

LR schedules mirror the reference's Scheduler enum
(crates/messages/src/lib.rs:674-687: constant / cosine-with-warmup /
linear-with-warmup / wsd), implemented as optax schedules.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct

from ..messages import Adam, Loss, LRScheduler, LRSchedulerKind

__all__ = [
    "TrainState",
    "make_lr_schedule",
    "build_optimizer",
    "compute_loss",
    "make_loss_fn",
    "chunked_causal_ce",
    "make_train_step",
]


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    opt_state: Any
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    @classmethod
    def create(cls, params, tx: optax.GradientTransformation) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            tx=tx,
        )

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=new_opt,
        )


def make_lr_schedule(spec: LRScheduler | None, base_lr: float) -> optax.Schedule:
    if spec is None or spec.kind is LRSchedulerKind.CONSTANT:
        return optax.constant_schedule(base_lr)
    warmup = max(0, int(spec.warmup_steps))
    total = max(warmup + 1, int(spec.total_steps))
    if spec.kind is LRSchedulerKind.COSINE_WITH_WARMUP:
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=base_lr,
            warmup_steps=warmup,
            decay_steps=total,
            end_value=0.0,
        )
    if spec.kind is LRSchedulerKind.LINEAR_WITH_WARMUP:
        return optax.join_schedules(
            [
                optax.linear_schedule(0.0, base_lr, warmup),
                optax.linear_schedule(base_lr, 0.0, total - warmup),
            ],
            [warmup],
        )
    if spec.kind is LRSchedulerKind.WSD:
        # warmup -> stable -> decay-to-zero; stable ends at decay_start·total
        decay_start = max(warmup, int(spec.decay_start * total))
        return optax.join_schedules(
            [
                optax.linear_schedule(0.0, base_lr, warmup),
                optax.constant_schedule(base_lr),
                optax.linear_schedule(base_lr, 0.0, max(1, total - decay_start)),
            ],
            [warmup, decay_start],
        )
    raise ValueError(f"unknown LR schedule {spec.kind}")


def build_optimizer(
    adam: Adam,
    schedule_spec: LRScheduler | None = None,
    max_grad_norm: float | None = 1.0,
    *,
    mu_dtype: Any | None = None,
) -> optax.GradientTransformation:
    """AdamW matching the reference's inner optimizer defaults
    (utils.py get_adam: betas (0.9, 0.999), eps 1e-8).

    ``mu_dtype=jnp.bfloat16`` halves the first-moment buffer — at 7B that
    is 13.5 GB off the optimizer footprint across the mesh (the second
    moment stays f32: its magnitudes span too many decades for bf16's 8
    mantissa bits; see MEM7B feasibility table).
    """
    b1, b2 = adam.betas or (0.9, 0.999)
    sched = make_lr_schedule(schedule_spec, adam.lr)
    parts = []
    if max_grad_norm is not None:
        parts.append(optax.clip_by_global_norm(max_grad_norm))
    parts.append(
        optax.adamw(
            learning_rate=sched,
            b1=b1,
            b2=b2,
            eps=adam.epsilon if adam.epsilon is not None else 1e-8,
            weight_decay=adam.weight_decay,
            mu_dtype=mu_dtype,
        )
    )
    return optax.chain(*parts)


def compute_loss(kind: Loss, logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Loss selector (crates/messages/src/lib.rs:662-670). Labels == -100 are
    ignored for classification losses (HF convention the reference relies on)."""
    if kind in (Loss.CROSS_ENTROPY, Loss.NLL):
        # CE as logsumexp − picked-logit: two streaming reductions over the
        # logits instead of materializing the full f32 log-softmax tensor —
        # at LM vocab width that tensor is gigabytes of HBM traffic per step.
        valid = labels != -100
        safe = jnp.where(valid, labels, 0)
        lse = jax.scipy.special.logsumexp(
            logits.astype(jnp.float32), axis=-1
        )
        picked = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        nll = lse - picked.astype(jnp.float32)
        return jnp.sum(nll * valid) / jnp.maximum(valid.sum(), 1)
    if kind is Loss.MSE:
        return jnp.mean((logits.astype(jnp.float32) - labels) ** 2)
    if kind is Loss.MAE:
        return jnp.mean(jnp.abs(logits.astype(jnp.float32) - labels))
    if kind is Loss.BCE_WITH_LOGITS:
        x = logits.astype(jnp.float32)
        return jnp.mean(jnp.maximum(x, 0) - x * labels + jnp.log1p(jnp.exp(-jnp.abs(x))))
    raise ValueError(f"unknown loss {kind}")


def chunked_causal_ce(
    hidden: jnp.ndarray,
    head_w: jnp.ndarray,
    labels: jnp.ndarray,
    *,
    chunk: int = 128,
) -> jnp.ndarray:
    """Streaming CE over sequence chunks — full-width logits NEVER exist.

    ``hidden`` [B, S, D] are final hidden states (already shifted by the
    caller: ``hidden[:, :-1]`` against ``labels = inputs[:, 1:]``),
    ``head_w`` [V, D] the (tied) LM head. Each ``lax.map`` iteration
    projects one sequence chunk to vocab width, reduces it to
    logsumexp − picked, and drops it; ``jax.checkpoint`` makes the
    backward recompute the chunk's logits instead of storing them. Peak
    loss memory falls from O(B·S·V) to O(B·chunk·V) — the [B,S,50257]
    f32 logits tensor is what OOMs the GPT-2 bench at B≥24
    (MFUPROBE_r04.json). Labels == -100 are ignored, matching
    :func:`compute_loss` CE semantics exactly.
    """
    B, S, D = hidden.shape
    pad = (-S) % chunk
    if pad:
        # Ragged tail (the shifted caller pattern makes S odd — e.g.
        # 1023): pad with ignored positions rather than collapsing to one
        # dense chunk, which would resurrect the full logits tensor.
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)), constant_values=-100)
        S += pad
    n = S // chunk
    hs = hidden.reshape(B, n, chunk, D).swapaxes(0, 1)  # [n, B, c, D]
    ls = labels.reshape(B, n, chunk).swapaxes(0, 1)

    @jax.checkpoint
    def one(h, l):
        logits = jnp.einsum(
            "bcd,vd->bcv", h.astype(head_w.dtype), head_w,
            preferred_element_type=jnp.float32,
        )
        valid = l != -100
        safe = jnp.where(valid, l, 0)
        lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
        picked = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        nll = (lse - picked.astype(jnp.float32)) * valid
        return nll.sum(), valid.sum()

    sums, counts = jax.lax.map(lambda args: one(*args), (hs, ls))
    return sums.sum() / jnp.maximum(counts.sum(), 1)


def make_train_step(
    apply_fn: Callable,
    loss_kind: Loss = Loss.CROSS_ENTROPY,
    *,
    causal_lm: bool = True,
    has_aux: bool = False,
    donate: bool = True,
    dropout_seed: int | None = None,
    labels_aligned: bool = False,
    loss_override: Callable | None = None,
):
    """Build the jitted train step.

    ``apply_fn(params, batch_inputs)`` returns logits (or (logits, aux_loss)
    when ``has_aux`` — the MoE router loss). Apply functions may opt into
    richer calling conventions by declaring keyword params (inspected once
    at build time, so the jitted call stays static):

      * ``rng``   — a per-step dropout key (folded from ``dropout_seed`` and
        the step counter), enabling train-mode stochasticity; the reference
        trains its torch models in train() mode (training.py:106-116).
      * ``batch`` — the full batch dict, for models that consume extra
        streams (e.g. seq2seq ``decoder_input_ids``).

    For causal LM the labels are the *target stream* shifted left — the
    decoder stream when the batch carries one, else the inputs; otherwise
    the batch carries explicit ``labels``. ``loss_override(out, batch)``
    (a model's ``custom_loss`` — CTC, detection, contrastive, span …)
    replaces the ``compute_loss`` selector entirely.
    Returns ``step(state, batch) -> (state, metrics)``.
    """
    loss_fn = make_loss_fn(
        apply_fn,
        loss_kind,
        causal_lm=causal_lm,
        has_aux=has_aux,
        dropout_seed=dropout_seed,
        labels_aligned=labels_aligned,
        loss_override=loss_override,
    )

    def step(state: TrainState, batch) -> tuple:
        (total, (loss, aux)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, batch, state.step
        )
        new_state = state.apply_gradients(grads)
        metrics = {
            "loss": loss,
            "total_loss": total,
            "aux_loss": aux,
            "grad_norm": optax.global_norm(grads),
        }
        return new_state, metrics

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_loss_fn(
    apply_fn: Callable,
    loss_kind: Loss = Loss.CROSS_ENTROPY,
    *,
    causal_lm: bool = True,
    has_aux: bool = False,
    dropout_seed: int | None = None,
    labels_aligned: bool = False,
    loss_override: Callable | None = None,
) -> Callable:
    """``loss_fn(params, batch, step_no) -> (total, (loss, aux))`` with the
    full label-layout semantics documented on :func:`make_train_step`.
    Shared by the full-parameter step and the LoRA step (executor.lora), so
    the two paths can never diverge on label shifting or loss selection."""
    import inspect

    try:
        sig = set(inspect.signature(apply_fn).parameters)
    except (TypeError, ValueError):
        sig = set()
    wants_rng = "rng" in sig and dropout_seed is not None
    wants_batch = "batch" in sig

    def loss_fn(params, batch, step_no):
        inputs = batch["input_ids"] if "input_ids" in batch else batch["inputs"]
        kwargs = {}
        if wants_rng:
            kwargs["rng"] = jax.random.fold_in(jax.random.key(dropout_seed), step_no)
        if wants_batch:
            kwargs["batch"] = batch
        out = apply_fn(params, inputs, **kwargs)
        aux = jnp.float32(0)
        if has_aux:
            out, aux = out
        if loss_override is not None:
            loss = loss_override(out, batch)
            return loss + aux, (loss, aux)
        if causal_lm:
            # Teacher forcing over the target stream. Three layouts:
            #   * decoder_input_ids AND labels (HF convention: decoder is
            #     labels shifted right) — out[t] already predicts labels[t],
            #     no further shift;
            #   * decoder stream only — next-token within the decoder;
            #   * otherwise — next-token over labels (== inputs by default).
            dec = batch.get("decoder_input_ids")
            explicit = batch.get("labels")
            if explicit is not None and (dec is not None or labels_aligned):
                # Decoder inputs are labels shifted right (either supplied
                # by the batch or shifted inside the model — the
                # ``labels_aligned`` seq2seq contract): out[t] predicts
                # labels[t] already.
                logits, labels = out, explicit
            elif dec is not None:
                logits, labels = out[:, :-1], dec[:, 1:]
            else:
                target = explicit if explicit is not None else inputs
                logits, labels = out[:, :-1], target[:, 1:]
        else:
            logits = out
            labels = batch["labels"]
        loss = compute_loss(loss_kind, logits, labels)
        return loss + aux, (loss, aux)

    return loss_fn
