"""Checkpoint/resume: persist training state across executor restarts.

The reference has NO system-level checkpointing (SURVEY.md §5: job state
lives in tmp work dirs deleted on job end; scheduler restart loses the
pool — called out as future work in rfc/2025-08-04). This module is the
net-new capability BASELINE.md's preemption config requires:

  * train side — params + optimizer state + round counter, written
    atomically (tmp + rename) every N rounds; an executor re-dispatched
    after preemption resumes from the last completed round instead of
    θ₀;
  * parameter-server side — the PS persists its Nesterov momentum FILE
    into the same checkpoint dir (ps_executor._checkpoint_momentum; the
    reference keeps momentum in a tmp file that dies with the job,
    parameter_server.rs:392-397).

Format: SafeTensors for tensors (stable tree-path names via
executor.serialization) + a JSON manifest — readable by the C++ runtime
and any SafeTensors tool, no pickle.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from pathlib import Path
from typing import Any

import numpy as np

from .serialization import flatten_tree, load_flat, save_tree, unflatten_like

__all__ = [
    "save_train_checkpoint",
    "load_train_checkpoint",
    "latest_manifest",
]

log = logging.getLogger("hypha.executor.checkpoint")

_MANIFEST = "manifest.json"
_PARAMS = "params.safetensors"
_OPT = "opt_state.safetensors"
_LATEST = "LATEST"
_KEEP_VERSIONS = 2


def _atomic_write(path: Path, write_fn) -> None:
    """Write one file via tmp + rename so it is never observed torn."""
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=path.name + ".tmp")
    os.close(fd)
    committed = False
    try:
        write_fn(Path(tmp))
        os.replace(tmp, path)
        committed = True
    except Exception as e:
        log.warning("atomic write of %s failed: %s", path, e)
        raise
    finally:
        # finally (not a broad except) so the tmp file is reclaimed on ANY
        # exit — KeyboardInterrupt and cancellation included — while every
        # exception still propagates unswallowed.
        if not committed:
            Path(tmp).unlink(missing_ok=True)


def save_train_checkpoint(
    directory: str | Path,
    params: Any,
    opt_state: Any,
    step: int,
    round_num: int,
    extra: dict | None = None,
) -> Path:
    """Persist one train checkpoint, atomically as a WHOLE.

    The three files are staged into a fresh version subdir, the subdir is
    renamed into place, and only then does the ``LATEST`` pointer flip —
    so a crash at any instant leaves either the previous complete
    checkpoint or the new complete one, never params from round N+1 paired
    with round-N optimizer state.
    """
    import jax

    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    version = f"v{round_num:08d}-{step}"
    staging = Path(
        tempfile.mkdtemp(dir=directory, prefix=".staging-")
    )
    committed = False
    try:
        save_tree(staging / _PARAMS, jax.device_get(params))
        save_tree(staging / _OPT, jax.device_get(opt_state))
        manifest = {
            "version": 1,
            "step": int(step),
            "round": int(round_num),
            "extra": extra or {},
        }
        (staging / _MANIFEST).write_text(json.dumps(manifest))
        target = directory / version
        if target.exists():  # re-save of the same round: replace wholesale
            _rmtree(target)
        os.replace(staging, target)
        committed = True
    except Exception as e:
        log.warning(
            "checkpoint save to %s (round %d) failed: %s", directory, round_num, e
        )
        raise
    finally:
        # Reclaim the staging dir on any non-commit exit (interrupts too)
        # without a broad except that could swallow them.
        if not committed:
            _rmtree(staging)
    _atomic_write(directory / _LATEST, lambda p: p.write_text(version))
    _prune_versions(directory, keep=_KEEP_VERSIONS)
    log.info("checkpoint saved to %s/%s (round %d)", directory, version, round_num)
    return directory / version


def _rmtree(path: Path) -> None:
    import shutil

    shutil.rmtree(path, ignore_errors=True)


def _prune_versions(directory: Path, keep: int) -> None:
    versions = sorted(
        (p for p in directory.iterdir() if p.is_dir() and p.name.startswith("v")),
        key=lambda p: p.name,
    )
    for old in versions[:-keep]:
        _rmtree(old)


def load_train_checkpoint(
    directory: str | Path, params_template: Any, opt_template: Any
) -> tuple[Any, Any, int, int, dict] | None:
    """Restore (params, opt_state, step, round, extra) or None if absent.

    Templates define tree structure and expected shapes; a checkpoint for
    a different model fails loudly instead of silently mis-restoring.
    """
    directory = Path(directory)
    pointer = directory / _LATEST
    if not pointer.is_file():
        return None
    target = directory / pointer.read_text().strip()
    manifest_path = target / _MANIFEST
    if not manifest_path.is_file():
        raise ValueError(f"checkpoint pointer {pointer} names missing {target}")
    manifest = json.loads(manifest_path.read_text())
    if manifest.get("version") != 1:
        raise ValueError(f"unknown checkpoint version {manifest.get('version')}")
    params = unflatten_like(load_flat(target / _PARAMS), params_template)
    opt_state = unflatten_like(load_flat(target / _OPT), opt_template)
    return (
        params,
        opt_state,
        int(manifest["step"]),
        int(manifest["round"]),
        manifest.get("extra", {}),
    )


def latest_manifest(directory: str | Path) -> dict | None:
    """The LATEST version's manifest, or None (tooling/test helper)."""
    directory = Path(directory)
    pointer = directory / _LATEST
    if not pointer.is_file():
        return None
    manifest = directory / pointer.read_text().strip() / _MANIFEST
    if not manifest.is_file():
        return None
    return json.loads(manifest.read_text())


def opt_state_template_names(opt_state: Any) -> list[str]:
    """Stable names an optimizer state flattens to (debug/test helper)."""
    return sorted(flatten_tree(opt_state))
