"""Preprocessor pipeline: the reference's 5 HF Auto processor kinds, applied
to configured slice keys before batching.

Parity with executors/accelerate/.../utils.py ``get_preprocessor`` (builds
AutoProcessor / AutoFeatureExtractor / AutoImageProcessor / AutoTokenizer /
AutoVideoProcessor from fetched artifacts) and dataset.py:10-41 (pops the
``processor_inputs`` keys from each slice, runs the processor, merges the
outputs back before per-sample iteration).

Job-spec shape (TrainExecutorConfig.preprocessor):

    {"kind": Preprocessor, "source": Fetch, "inputs": ["text"],
     "options": {...forwarded to the processor call...}}

TPU-native note: slices are SafeTensors, so every value is a fixed-shape
numeric array. Text for the tokenizer kind rides as fixed-width uint8
utf-8 rows (trailing NULs stripped) — decoded here, tokenized with
padding="max_length" so batch shapes stay static for XLA.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any, Callable

import numpy as np

from ..messages import Preprocessor

__all__ = ["build_preprocessor", "make_apply"]

log = logging.getLogger("hypha.executor.preprocess")

_AUTO_CLASSES = {
    Preprocessor.TOKENIZER: "AutoTokenizer",
    Preprocessor.IMAGE_PROCESSOR: "AutoImageProcessor",
    Preprocessor.FEATURE_EXTRACTOR: "AutoFeatureExtractor",
    Preprocessor.PROCESSOR: "AutoProcessor",
    Preprocessor.VIDEO_PROCESSOR: "AutoVideoProcessor",
}


def _decode_text_rows(arr: np.ndarray) -> list[str]:
    """[N, W] uint8 utf-8 rows (NUL-padded) → list of N strings."""
    if arr.dtype != np.uint8:
        raise ValueError(f"tokenizer input must be uint8 rows, got {arr.dtype}")
    rows = np.atleast_2d(arr)
    return [bytes(r).rstrip(b"\x00").decode("utf-8", errors="replace") for r in rows]


def load_processor(kind: Preprocessor | str, path: str | Path):
    """Instantiate the HF Auto processor for ``kind`` from a local dir/file."""
    import transformers

    kind = kind if isinstance(kind, Preprocessor) else Preprocessor(kind)
    cls = getattr(transformers, _AUTO_CLASSES[kind])
    return cls.from_pretrained(str(path), local_files_only=True)


def make_apply(
    processor: Any,
    kind: Preprocessor,
    inputs: list[str],
    options: dict[str, Any] | None = None,
) -> Callable[[dict[str, np.ndarray]], dict[str, np.ndarray]]:
    """Wrap an HF processor as the dataset's slice-level hook: pop ``inputs``
    keys, run the processor, merge its arrays back (dataset.py:25-30)."""
    options = dict(options or {})
    if kind is Preprocessor.TOKENIZER:
        options.setdefault("padding", "max_length")
        options.setdefault("truncation", True)
        options.setdefault(
            "max_length", getattr(processor, "model_max_length", 128) or 128
        )
        if options["max_length"] > 4096:  # HF's "unset" sentinel is huge
            options["max_length"] = 128

    def apply(tensors: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
        taken = {k: tensors.pop(k) for k in inputs if k in tensors}
        if not taken:
            return tensors
        if kind is Preprocessor.TOKENIZER:
            texts: list[str] = []
            for v in taken.values():
                texts.extend(_decode_text_rows(v))
            out = processor(texts, return_tensors="np", **options)
        else:
            out = processor(*taken.values(), return_tensors="np", **options)
        processed = {k: np.asarray(v) for k, v in dict(out).items()}
        return {**processed, **tensors}

    return apply


def build_preprocessor(
    spec: dict[str, Any],
    session: Any,
    work_dir: Path,
) -> Callable[[dict[str, np.ndarray]], dict[str, np.ndarray]] | None:
    """Fetch the processor artifacts via the bridge and build the slice hook.

    Returns None when the spec is empty — jobs without a preprocessor stream
    slices untouched (utils.py:38: ``if preprocessor_config``).
    """
    if not spec:
        return None
    from .. import messages

    kind = spec.get("kind", Preprocessor.TOKENIZER)
    kind = kind if isinstance(kind, Preprocessor) else Preprocessor(kind)
    inputs = list(spec.get("inputs") or [])
    if not inputs:
        raise ValueError("preprocessor spec needs 'inputs': slice keys to process")

    path = spec.get("path")
    if not path:
        source = spec.get("source")
        if source is None:
            raise ValueError("preprocessor spec needs 'source' (Fetch) or 'path'")
        fetch = messages.from_json_dict(source) if isinstance(source, dict) else source
        rels = session.fetch(fetch)
        if not rels:
            raise ValueError("preprocessor fetch returned no artifacts")
        first = work_dir / rels[0]
        path = first.parent if len(rels) > 1 or first.is_dir() else first.parent
    processor = load_processor(kind, path)
    log.info("preprocessor: %s from %s on keys %s", kind.value, path, inputs)
    return make_apply(processor, kind, inputs, spec.get("options"))
