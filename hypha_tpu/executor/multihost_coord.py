"""Multi-process (pod) coordination for the DiLoCo training executor.

One replica's mesh can span several ``jax.distributed`` processes
(parallel/multihost.py). In the multi-controller model every process must
dispatch the SAME jit computations in the same order, but only one process
should own the control plane — the bridge session, data fetching, delta
shipping, scheduler heartbeats. This module makes that split:

  * **leader** (process 0): runs the ordinary ``run_training`` loop inside
    the worker runtime; before every collective-bearing action it
    broadcasts an opcode + payload so followers mirror the dispatch.
  * **followers** (process 1..n-1): run :func:`run_training_follower` — a
    compute daemon that needs NO job foreknowledge: the init broadcast
    carries the job spec, initial params/optimizer state, and the first
    batch; afterwards each STEP/MERGE opcode drives one mirrored dispatch.

Transport is ``jax.experimental.multihost_utils.broadcast_one_to_all``
over the jax.distributed runtime itself (no second network stack): a
fixed-shape [op, nbytes] header, then an npz-encoded byte payload. The
reference has no equivalent — its replicas are single torch processes
(NCCL process groups stay inside one executor); pod-as-one-replica is the
TPU-native scale story (SURVEY §2.8, BASELINE north star).
"""

from __future__ import annotations

import io
import json
import logging
from typing import Any

import numpy as np

__all__ = [
    "HostCoordinator",
    "LeaderCoordination",
    "run_training_follower",
    "OP_INIT",
    "OP_STEP",
    "OP_MERGE",
    "OP_GATHER",
    "OP_DONE",
]

log = logging.getLogger("hypha.executor.multihost")

OP_INIT, OP_STEP, OP_MERGE, OP_DONE, OP_GATHER = 0, 1, 2, 3, 4


def _encode(payload: dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **payload)
    return buf.getvalue()


def _decode(data: bytes) -> dict[str, np.ndarray]:
    with np.load(io.BytesIO(data), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


# Upper bound on one broadcast group. A 7B-class OP_INIT carries ~27 GB of
# f32 base + optimizer state; encoding it as ONE npz blob plus the matching
# uint8 broadcast array tripled peak host memory and could OOM hosts whose
# sharded on-device state would have fit. Grouping bounds the transient to
# ~2x this value (encoded bytes + broadcast buffer) regardless of tree size,
# while keeping the barrier count ~payload_bytes/64MB instead of per-tensor.
_CHUNK_BYTES = 64 << 20


def _group_items(
    items: list[tuple[str, np.ndarray]]
) -> list[list[tuple[str, np.ndarray]]]:
    groups: list[list[tuple[str, np.ndarray]]] = []
    cur: list[tuple[str, np.ndarray]] = []
    cur_bytes = 0
    for k, v in items:
        if cur and cur_bytes + v.nbytes > _CHUNK_BYTES:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append((k, v))
        cur_bytes += v.nbytes
    if cur:
        groups.append(cur)
    return groups


class HostCoordinator:
    """Broadcast channel from process 0 to all processes.

    Per message: a fixed-shape header (opcode, group count), then per group
    a length header and the npz-encoded bytes. Groups cap the transient
    host-memory cost of a broadcast at ~2x ``_CHUNK_BYTES`` (or one tensor,
    if a single tensor exceeds it) — the follower's assembled dict is the
    only full-size allocation, and it is the output. Every process must
    call send/recv in lockstep — which is exactly the property the
    executor protocol maintains.
    """

    def __init__(self) -> None:
        import jax

        self.rank = jax.process_index()
        self.n_processes = jax.process_count()

    def send(self, op: int, payload: dict[str, np.ndarray] | None) -> None:
        assert self.rank == 0, "only the leader sends"
        self._exchange(op, payload)

    def recv(self) -> tuple[int, dict[str, np.ndarray] | None]:
        assert self.rank != 0, "the leader does not recv"
        return self._exchange(0, None)

    def _exchange(
        self, op: int, payload: dict[str, np.ndarray] | None
    ) -> tuple[int, dict[str, np.ndarray] | None]:
        from jax.experimental import multihost_utils as mhu

        groups = (
            _group_items([(k, np.asarray(v)) for k, v in payload.items()])
            if (self.rank == 0 and payload)
            else []
        )
        header = np.array([op, len(groups)], np.int64)
        header = np.asarray(mhu.broadcast_one_to_all(header))
        op, n_groups = int(header[0]), int(header[1])
        if n_groups == 0:
            return op, None
        if self.rank == 0:
            for group in groups:
                data = _encode(dict(group))
                mhu.broadcast_one_to_all(np.array([len(data)], np.int64))
                mhu.broadcast_one_to_all(np.frombuffer(data, np.uint8))
            return op, None
        out: dict[str, np.ndarray] = {}
        for _ in range(n_groups):
            hdr = np.asarray(
                mhu.broadcast_one_to_all(np.zeros(1, np.int64))
            )
            buf = np.asarray(
                mhu.broadcast_one_to_all(np.zeros(int(hdr[0]), np.uint8))
            )
            out.update(_decode(buf.tobytes()))
        return op, out


def _flatten_prefixed(prefix: str, tree: Any) -> dict[str, np.ndarray]:
    from .serialization import flatten_tree

    import jax

    return {
        f"{prefix}{k}": np.asarray(v)
        for k, v in flatten_tree(jax.device_get(tree)).items()
    }


def _unflatten_prefixed(prefix: str, payload: dict, like: Any) -> Any:
    from .serialization import unflatten_like

    flat = {
        k[len(prefix):]: v for k, v in payload.items() if k.startswith(prefix)
    }
    return unflatten_like(flat, like)


class LeaderCoordination:
    """The leader-side hooks run_training calls at each protocol point."""

    def __init__(self) -> None:
        self.mh = HostCoordinator()

    def init(self, spec_json: str, state, first_batch: dict, frozen=None) -> None:
        payload = {
            "__spec__": np.frombuffer(spec_json.encode(), np.uint8),
            "__step__": np.asarray(int(state.step), np.int64),
        }
        payload.update(_flatten_prefixed("p/", state.params))
        payload.update(_flatten_prefixed("o/", state.opt_state))
        payload.update({f"b/{k}": np.asarray(v) for k, v in first_batch.items()})
        if frozen is not None:
            # LoRA replica: state.params is the adapter tree only; the
            # frozen base travels once in the init broadcast (followers
            # then hold it as a constant step input).
            payload.update(_flatten_prefixed("f/", frozen))
        self.mh.send(OP_INIT, payload)

    def step(self, batch: dict) -> None:
        self.mh.send(OP_STEP, {f"b/{k}": np.asarray(v) for k, v in batch.items()})

    def merge(self, flat_update: dict[str, np.ndarray]) -> None:
        self.mh.send(OP_MERGE, {f"u/{k}": np.asarray(v) for k, v in flat_update.items()})

    def gather(self, params) -> Any:
        """Collective Δθ support: fetch the FULL param tree to this host.

        With a mesh spanning processes, param shards live on devices the
        leader cannot address, so ``jax.device_get`` cannot produce the
        delta file (caught by the 4-process test — the 2-process mesh
        layout happened to keep fsdp shards process-local). The gather is
        itself a collective, so followers mirror it via OP_GATHER.
        """
        self.mh.send(OP_GATHER, None)
        return _allgather_host(params)

    def done(self) -> None:
        self.mh.send(OP_DONE, None)


def _allgather_host(params):
    from jax.experimental import multihost_utils as mhu

    import jax

    gathered = mhu.process_allgather(params, tiled=True)
    return jax.tree.map(np.asarray, gathered)


def run_training_follower() -> int:
    """Compute daemon for processes 1..n-1 of a multi-process replica.

    Blocks on the init broadcast, mirrors every STEP/MERGE dispatch, and
    returns the number of merges (outer rounds) completed when the leader
    signals DONE.
    """
    import jax
    import jax.numpy as jnp

    from .. import messages
    from ..messages import JobSpec, Loss
    from .diloco import merge_update
    from .train import TrainState, build_optimizer, make_train_step

    mh = HostCoordinator()
    op, payload = mh.recv()
    if op == OP_DONE:
        return 0
    assert op == OP_INIT, f"expected INIT, got opcode {op}"
    assert payload is not None
    spec = messages.from_json_dict(
        json.loads(bytes(payload["__spec__"]).decode())
    )
    assert isinstance(spec, JobSpec)
    cfg = spec.executor.train
    assert cfg is not None

    from ..models import Mixtral, build_model
    from ..models.hf import _DECODER_TYPES
    from ..models.registry import resolve_model_type
    from .training import _build_mesh, _non_causal_types

    first_batch = {
        k[2:]: payload[k] for k in payload if k.startswith("b/")
    }
    model_spec = dict(cfg.model)
    if cfg.lora:
        # Mirror the leader's LoRA config injection (training._init_model)
        # so the follower's param tree has the same adapter leaves.
        model_spec["config"] = dict(
            model_spec.get("config", {}),
            lora_rank=int(cfg.lora.get("rank", 8)),
            lora_alpha=float(cfg.lora.get("alpha", 16.0)),
            lora_targets=tuple(cfg.lora.get("targets", ("q_proj", "v_proj"))),
        )
    model, _ = build_model(model_spec)
    model_type = resolve_model_type(
        model_spec.get("model_type", messages.ModelType.CAUSAL_LM)
    )
    causal_lm = model_type not in _non_causal_types()
    has_aux = isinstance(model, Mixtral)
    inputs = (
        first_batch["input_ids"] if "input_ids" in first_batch
        else first_batch["inputs"]
    )
    params = model.init(jax.random.key(int(model_spec.get("seed", 0))), inputs)
    frozen = None
    if cfg.lora:
        from .lora import split_lora

        adapters_t, frozen_t = split_lora(params)
        frozen = _unflatten_prefixed("f/", payload, frozen_t)
        params = adapters_t
    state = TrainState.create(
        params, build_optimizer(cfg.optimizer, cfg.scheduler)
    )
    state = state.replace(
        params=_unflatten_prefixed("p/", payload, state.params),
        opt_state=_unflatten_prefixed("o/", payload, state.opt_state),
        step=jnp.asarray(int(payload["__step__"]), jnp.int32),
    )

    mesh = _build_mesh(cfg.sharding)
    assert mesh is not None, "a multi-process replica requires a sharding config"
    from jax.sharding import NamedSharding

    from ..parallel import param_sharding
    from ..parallel.sharding import batch_spec

    state = jax.device_put(state, param_sharding(state, mesh))
    if frozen is not None:
        frozen = jax.device_put(frozen, param_sharding(frozen, mesh))
    b_sharding = NamedSharding(mesh, batch_spec())

    def place(batch):
        # make_array_from_callback works identically on every process of a
        # multi-controller mesh (device_put alone may refuse shardings that
        # span non-addressable devices).
        return {
            k: jax.make_array_from_callback(
                v.shape, b_sharding, lambda idx, v=v: v[idx]
            )
            for k, v in batch.items()
        }

    step_kwargs = dict(
        causal_lm=causal_lm,
        has_aux=has_aux,
        dropout_seed=int(model_spec.get("seed", 0)),
        labels_aligned=getattr(model, "model_type", None) in _DECODER_TYPES,
        loss_override=getattr(model, "custom_loss", None),
    )
    if frozen is not None:
        from .lora import make_lora_train_step

        lora_step = make_lora_train_step(
            model.apply, cfg.loss or Loss.CROSS_ENTROPY, **step_kwargs
        )

        def step(state, batch):
            return lora_step(state, frozen, batch)
    else:
        step = make_train_step(
            model.apply, cfg.loss or Loss.CROSS_ENTROPY, **step_kwargs
        )

    # No follower-side anchor: the leader alone computes Δθ (that op has no
    # cross-process collective), so a follower anchor would be dead state
    # inviting divergence if someone ever read it.
    rounds = 0
    while True:
        op, payload = mh.recv()
        if op == OP_DONE:
            log.info("follower %d done after %d rounds", mh.rank, rounds)
            return rounds
        if op == OP_STEP:
            assert payload is not None
            batch = {k[2:]: payload[k] for k in payload if k.startswith("b/")}
            state, _metrics = step(state, place(batch))
        elif op == OP_GATHER:
            # The leader is assembling Δθ on its host; the allgather is a
            # collective every process must join. Result discarded here.
            _allgather_host(state.params)
        elif op == OP_MERGE:
            assert payload is not None
            # The leader computed Δθ locally to ship it; that op has no
            # cross-process collective, so followers need not (and do not)
            # mirror it — only the merge itself runs here.
            update = _unflatten_prefixed("u/", payload, state.params)
            state = state.replace(params=merge_update(state.params, update))
            rounds += 1
        else:
            raise RuntimeError(f"unknown opcode {op}")
