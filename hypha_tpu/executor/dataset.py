"""Streaming dataset: scheduler-assigned SafeTensors slices → batches.

Parity with the reference's ``IterableStreamDataSet`` + ``fetch_data``
(executors/accelerate/.../dataset.py:10-41, utils.py:68-74): an infinite
generator asks the bridge for the next slice path (the scheduler picks the
slice index via its SliceTracker), loads the SafeTensors file, optionally
applies a preprocessor to configured keys, and yields per-sample dicts;
batching stacks ``batch_size`` consecutive samples.

TPU-native difference: batches come out as device-ready stacked numpy
arrays with static shapes (XLA recompiles on shape change, so ragged
tails are dropped — the stream is infinite anyway).
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any, Callable, Iterator

import numpy as np
from safetensors.numpy import load_file

__all__ = ["slice_samples", "batches", "stream_batches"]

log = logging.getLogger("hypha.executor.dataset")


def slice_samples(
    path: Path | str,
    input_names: list[str] | None = None,
    preprocessor: Callable[[dict[str, np.ndarray]], dict[str, np.ndarray]] | None = None,
) -> Iterator[dict[str, np.ndarray]]:
    """Yield per-sample dicts from one SafeTensors slice file
    (dataset.py:10-41: each tensor's leading axis indexes samples)."""
    tensors = load_file(str(path))
    if preprocessor is not None:
        tensors = preprocessor(tensors)
    if input_names:
        tensors = {k: tensors[k] for k in input_names}
    if not tensors:
        return
    counts = {k: v.shape[0] for k, v in tensors.items()}
    n = min(counts.values())
    if len(set(counts.values())) > 1:
        log.warning("slice %s: ragged sample counts %s; using %d", path, counts, n)
    for i in range(n):
        yield {k: v[i] for k, v in tensors.items()}


def batches(
    samples: Iterator[dict[str, np.ndarray]], batch_size: int
) -> Iterator[dict[str, np.ndarray]]:
    """Stack consecutive samples into static-shape batches."""
    buf: list[dict[str, np.ndarray]] = []
    for sample in samples:
        buf.append(sample)
        if len(buf) == batch_size:
            yield {k: np.stack([s[k] for s in buf]) for k in buf[0]}
            buf.clear()


def stream_batches(
    fetch_slice: Callable[[], str],
    batch_size: int,
    input_names: list[str] | None = None,
    preprocessor: Callable | None = None,
) -> Iterator[dict[str, np.ndarray]]:
    """Infinite batch stream: ``fetch_slice()`` blocks until the scheduler
    assigns the next slice and returns its local path (utils.py:68-74
    fetch_data + dataset_wrapper's infinite epoch loop)."""

    def samples() -> Iterator[dict[str, np.ndarray]]:
        while True:
            path = fetch_slice()
            yield from slice_samples(path, input_names, preprocessor)

    return batches(samples(), batch_size)
