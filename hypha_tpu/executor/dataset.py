"""Streaming dataset: scheduler-assigned SafeTensors slices → batches.

Parity with the reference's ``IterableStreamDataSet`` + ``fetch_data``
(executors/accelerate/.../dataset.py:10-41, utils.py:68-74): an infinite
generator asks the bridge for the next slice path (the scheduler picks the
slice index via its SliceTracker), loads the SafeTensors file, optionally
applies a preprocessor to configured keys, and yields per-sample dicts;
batching stacks ``batch_size`` consecutive samples.

TPU-native difference: batches come out as device-ready stacked numpy
arrays with static shapes (XLA recompiles on shape change, so ragged
tails are dropped — the stream is infinite anyway).

Async input pipeline (``pipeline=True``, ISSUE 15): the same infinite
stream, restructured so the hot path never waits on input —

  * **slice prefetch** — a bounded background :class:`SlicePrefetcher`
    thread runs ``fetch_slice()`` (bridge DataRequest + data-node pull +
    disk write) up to ``prefetch`` slices ahead while the current slice
    trains, so a slice exhaustion costs a queue pop instead of a full
    scheduler round-trip plus a network transfer;
  * **zero-copy batch assembly** — slice tensors are ALREADY stacked
    arrays, so :func:`slice_batches` hands out contiguous
    ``v[i*B:(i+1)*B]`` views instead of re-stacking ``B`` per-sample
    dicts per batch, with a carry-over buffer joining the ragged tail of
    one slice to the head of the next (the only batch that pays a copy —
    exactly the batch the legacy path also materialized). Only the
    configured ``input_names`` keys are read from the SafeTensors file
    (when no preprocessor needs the rest).

Both assemblies yield bit-identical batch values in the identical order;
``pipeline=False`` (the default) runs the original per-sample code path
unchanged.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from pathlib import Path
from typing import Any, Callable, Iterator

import numpy as np
from safetensors.numpy import load_file

from ..telemetry import trace
from ..telemetry.ft_metrics import DATA_METRICS

__all__ = [
    "slice_samples",
    "batches",
    "stream_batches",
    "load_slice",
    "slice_batches",
    "SlicePrefetcher",
    "DEFAULT_PREFETCH_SLICES",
]

log = logging.getLogger("hypha.executor.dataset")

# Slices the pipeline fetches ahead when the job doesn't pin a depth: one
# training, one landing, one in flight is the classic double-buffer + 1;
# two held covers a fetch slower than a whole slice's worth of steps
# without ballooning disk footprint.
DEFAULT_PREFETCH_SLICES = 2


def _sample_count(tensors: dict, path: Path | str) -> int:
    """Leading-axis sample count shared by both assemblies: warn + clamp
    on ragged counts, and surface an all-empty slice as an ERROR — the
    legacy path yielded nothing silently, so the infinite stream spun
    re-fetching the same empty slice forever."""
    if not tensors:
        raise ValueError(
            f"slice {path}: no tensors to train on (empty file, or "
            "input_names filtered everything out)"
        )
    counts = {k: int(v.shape[0]) if v.ndim else 0 for k, v in tensors.items()}
    n = min(counts.values())
    if len(set(counts.values())) > 1:
        log.warning("slice %s: ragged sample counts %s; using %d", path, counts, n)
    if n == 0:
        raise ValueError(f"slice {path}: zero samples (counts {counts})")
    return n


def slice_samples(
    path: Path | str,
    input_names: list[str] | None = None,
    preprocessor: Callable[[dict[str, np.ndarray]], dict[str, np.ndarray]] | None = None,
) -> Iterator[dict[str, np.ndarray]]:
    """Yield per-sample dicts from one SafeTensors slice file
    (dataset.py:10-41: each tensor's leading axis indexes samples)."""
    tensors = load_file(str(path))
    if preprocessor is not None:
        tensors = preprocessor(tensors)
    if input_names:
        tensors = {k: tensors[k] for k in input_names}
    n = _sample_count(tensors, path)
    for i in range(n):
        yield {k: v[i] for k, v in tensors.items()}


def load_slice(
    path: Path | str,
    input_names: list[str] | None = None,
    preprocessor: Callable[[dict[str, np.ndarray]], dict[str, np.ndarray]] | None = None,
) -> dict[str, np.ndarray]:
    """One slice as stacked arrays trimmed to the common sample count.

    The zero-copy twin of :func:`slice_samples`: same key filter, same
    preprocessor hook, same ragged-count clamp and same empty-slice
    error — but the arrays stay whole for contiguous-view batching, and
    when only ``input_names`` matter (no preprocessor, which may read
    other keys) only those tensors are deserialized from the file.
    """
    if input_names and preprocessor is None:
        from safetensors import safe_open

        with safe_open(str(path), framework="np") as f:
            missing = [k for k in input_names if k not in f.keys()]
            if missing:
                raise KeyError(
                    f"slice {path}: missing input tensors {missing}"
                )
            tensors = {k: f.get_tensor(k) for k in input_names}
    else:
        tensors = load_file(str(path))
        if preprocessor is not None:
            tensors = preprocessor(tensors)
        if input_names:
            tensors = {k: tensors[k] for k in input_names}
    n = _sample_count(tensors, path)
    return {k: v[:n] for k, v in tensors.items()}


def batches(
    samples: Iterator[dict[str, np.ndarray]], batch_size: int
) -> Iterator[dict[str, np.ndarray]]:
    """Stack consecutive samples into static-shape batches."""
    buf: list[dict[str, np.ndarray]] = []
    for sample in samples:
        buf.append(sample)
        if len(buf) == batch_size:
            yield {k: np.stack([s[k] for s in buf]) for k in buf[0]}
            buf.clear()


def slice_batches(
    slices: Iterator[dict[str, np.ndarray]], batch_size: int
) -> Iterator[dict[str, np.ndarray]]:
    """Zero-copy batches from whole-slice arrays.

    Full batches inside a slice are contiguous ``v[i*B:(i+1)*B]`` views —
    no per-sample re-stacking, no copy. A slice's ragged tail is carried
    over and concatenated with the next slice's head, so batches span
    slice boundaries with the exact values (and order) the per-sample
    path produces; only those boundary batches materialize new arrays,
    which the stacking path did for EVERY batch.
    """
    B = int(batch_size)
    if B <= 0:
        raise ValueError("batch_size must be positive")
    carry: dict[str, np.ndarray] | None = None
    keys: list[str] | None = None
    for tensors in slices:
        if keys is None:
            keys = sorted(tensors)
        elif sorted(tensors) != keys:
            raise ValueError(
                f"slice key mismatch mid-stream: {sorted(tensors)} vs {keys}"
            )
        n = min(int(v.shape[0]) for v in tensors.values())
        start = 0
        if carry is not None:
            have = int(next(iter(carry.values())).shape[0])
            need = B - have
            if n < need:
                carry = {
                    k: np.concatenate([carry[k], tensors[k][:n]])
                    for k in tensors
                }
                continue
            yield {
                k: np.concatenate([carry[k], tensors[k][:need]])
                for k in tensors
            }
            carry = None
            start = need
        full = (n - start) // B
        for i in range(full):
            lo = start + i * B
            yield {k: v[lo : lo + B] for k, v in tensors.items()}
        rem = start + full * B
        if rem < n:
            # Views into the slice arrays: kept alive by this dict until
            # the boundary batch materializes them above.
            carry = {k: v[rem:n] for k, v in tensors.items()}


class SlicePrefetcher:
    """Bounded background slice fetcher: at most ``depth`` fetched-ahead
    slices exist at once (the queue bound throttles the producer), so a
    slice exhaustion on the training thread costs a queue pop while the
    NEXT slice's scheduler round-trip + network pull is already underway.

    Transient fetch failures (a data node mid-restart, a scheduler blip)
    retry with exponential backoff for up to ``retry_deadline_s`` seconds
    before the error surfaces on the consumer — a killed-and-restarted
    data node costs backed-off re-attempts, not a failed job.
    """

    _ERROR = "error"

    def __init__(
        self,
        fetch_slice: Callable[[], str],
        depth: int = DEFAULT_PREFETCH_SLICES,
        retry_deadline_s: float = 60.0,
        retry_base_s: float = 0.25,
    ) -> None:
        self.depth = max(int(depth), 1)
        self._fetch = fetch_slice
        self._retry_deadline_s = float(retry_deadline_s)
        self._retry_base_s = float(retry_base_s)
        self._q: "queue.Queue[tuple[str, Any]]" = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._main, daemon=True, name="slice-prefetch"
        )
        self._thread.start()

    # ----------------------------------------------------- producer thread

    def _main(self) -> None:
        while not self._stop.is_set():
            failed_since: float | None = None
            attempt = 0
            while True:
                try:
                    path = self._fetch()
                    break
                except Exception as e:  # noqa: BLE001 — surfaced below
                    DATA_METRICS.prefetch_errors.add(1)
                    now = time.monotonic()
                    failed_since = failed_since if failed_since is not None else now
                    if (
                        self._stop.is_set()
                        or now - failed_since >= self._retry_deadline_s
                    ):
                        self._q.put((self._ERROR, e))
                        return
                    delay = min(self._retry_base_s * (2.0 ** attempt), 5.0)
                    attempt += 1
                    log.warning(
                        "slice prefetch failed (%s); retrying in %.2fs", e, delay
                    )
                    if self._stop.wait(delay):
                        return
            self._q.put(("path", path))
            DATA_METRICS.note_queue_depth(self._q.qsize())

    # ------------------------------------------------------------ consumer

    def take(self) -> str:
        """Next ready slice path, blocking until the prefetcher lands one
        (the blocked time IS the residual slice-boundary stall)."""
        kind, value = self._q.get()
        DATA_METRICS.note_queue_depth(self._q.qsize())
        if kind == self._ERROR:
            raise RuntimeError(f"slice prefetch failed: {value}") from (
                value if isinstance(value, BaseException) else None
            )
        return value

    def close(self) -> None:
        """Stop fetching; unblock a producer parked on the full queue."""
        self._stop.set()
        while True:
            try:
                self._q.get_nowait()
            except Exception:  # Empty — also robust to interpreter teardown,
                break  # where the generator's GC can outlive module globals
        self._thread.join(timeout=5.0)


def _boundary_wait(acquire: Callable[[], str], span_ctx) -> str:
    """Time (and trace) the training thread's slice acquisition — the
    slice-boundary stall the prefetcher exists to hide. ``span_ctx`` is a
    zero-arg callable returning ``(traceparent, node)`` so the span joins
    the current round's trace (no-op when tracing is off)."""
    parent, node = span_ctx() if span_ctx is not None else (None, None)
    span = trace.begin("input_wait", parent=parent, node=node)
    t0 = time.monotonic()
    try:
        return acquire()
    finally:
        trace.finish(span)
        DATA_METRICS.note_boundary_wait(time.monotonic() - t0)


def stream_batches(
    fetch_slice: Callable[[], str],
    batch_size: int,
    input_names: list[str] | None = None,
    preprocessor: Callable | None = None,
    *,
    pipeline: bool = False,
    prefetch: int | None = None,
    span_ctx: "Callable[[], tuple[Any, Any]] | None" = None,
    unlink_consumed: bool = False,
) -> Iterator[dict[str, np.ndarray]]:
    """Infinite batch stream: ``fetch_slice()`` blocks until the scheduler
    assigns the next slice and returns its local path (utils.py:68-74
    fetch_data + dataset_wrapper's infinite epoch loop).

    ``pipeline=False`` (default) is the original synchronous per-sample
    path, bit-identical batches included; ``pipeline=True`` switches to
    background slice prefetch (``prefetch`` deep) + zero-copy assembly —
    same values, same order.
    """

    if not pipeline:

        def samples() -> Iterator[dict[str, np.ndarray]]:
            while True:
                path = _boundary_wait(fetch_slice, span_ctx)
                yield from slice_samples(path, input_names, preprocessor)

        return batches(samples(), batch_size)

    prefetcher = SlicePrefetcher(
        fetch_slice, depth=prefetch or DEFAULT_PREFETCH_SLICES
    )

    def slices() -> Iterator[dict[str, np.ndarray]]:
        try:
            while True:
                path = _boundary_wait(prefetcher.take, span_ctx)
                arrays = load_slice(path, input_names, preprocessor)
                if unlink_consumed:
                    # Pipelined fetches land under epoch-unique names (a
                    # later epoch must not overwrite a slice still being
                    # read) — drop each one once its arrays are in memory,
                    # or a long job accumulates num_slices files per epoch.
                    Path(path).unlink(missing_ok=True)
                yield arrays
        finally:
            prefetcher.close()

    return slice_batches(slices(), batch_size)
