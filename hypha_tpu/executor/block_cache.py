"""Host-side physical-block allocator with automatic prefix caching.

The paged pool (executor.pool) maps each decode lane's logical KV window
onto physical blocks through a per-lane table (ops.kvcache paged layout).
This module owns the HOST bookkeeping for those blocks: who references
them, which ones hold content worth keeping, and which one to hand out
next. Device memory never moves here — the pool does the (rare) copies.

Design (vLLM's automatic prefix caching, adapted to this allocator):

* **Content addressing.** A full block of ``block_size`` token positions
  is uniquely identified by the *chain hash* of every token id up to and
  including the block (causal attention: a block's K/V depends on its
  whole prefix, not just its own tokens). :func:`chain_hashes` computes
  the per-block chain; the pool registers a block under its hash once
  its K/V are fully written.
* **Refcounts.** ``ref[b]`` counts lane-table references. A cache hit
  maps the same physical block into several tables (ref > 1) — those
  lanes share the prefix K/V without recomputing it.
* **LRU of ref-0 cached blocks.** When the last reference drops, a
  REGISTERED block is parked in an LRU instead of the free list: its
  content stays addressable (a later request with the same prefix
  re-maps it) until allocation pressure evicts it. Unregistered blocks
  (partial tails, never-hashed content) free immediately.
* **Allocation order.** ``alloc`` draws from the free list first, then
  evicts the LRU's oldest block (dropping its hash entry). Only when
  both are empty does the pool fall back to preemption.
* **Weight generations (live weight streaming, PR 16).** Chain hashes
  address token CONTENT, but the cached K/V were computed under specific
  weights — after a hot swap the same prompt bytes hash identically
  while the blocks hold stale activations. Every registration is
  stamped with the allocator's current ``generation``;
  ``bump_generation`` (called by the pool at the swap boundary)
  invalidates LAZILY: live lanes keep their mapped blocks until release
  (refcounts never move at a swap), but a stale-generation block is a
  cache MISS — ``peek``/``lookup`` drop its registration on contact,
  ``release`` sends a stale ref-0 block to the free list instead of the
  LRU, and ``register`` evicts a stale holder so the new-generation
  content can claim the hash.

Every block is therefore in exactly one of three places — the free
list, at least one live lane table (ref > 0), or the ref-0 LRU — and
``check_conservation`` asserts that partition (the block-conservation
property test drives random op sequences against it, swap bumps
included).
"""

from __future__ import annotations

from collections import OrderedDict

from ..telemetry import SERVE_METRICS

__all__ = ["PrefixBlockCache", "chain_hashes"]


def chain_hashes(tokens, block_size: int) -> list:
    """Per-block chain hashes of ``tokens``: entry ``j`` identifies the
    K/V content of full block ``j`` (tokens ``[0, (j+1)*block_size)`` —
    the whole prefix, because causal attention bakes it into the block).
    Only FULL blocks hash; a partial tail has no entry. Deterministic
    within a process (CPython int/tuple hashing is unseeded)."""
    out: list = []
    h = 0
    for j in range(len(tokens) // block_size):
        h = hash((h, tuple(tokens[j * block_size : (j + 1) * block_size])))
        out.append(h)
    return out


class PrefixBlockCache:
    """Physical-block allocator + content-addressed prefix cache.

    Pure host state (no device arrays): the serve thread is the only
    caller, so there is no locking. ``caching=False`` degrades to a plain
    free-list allocator — ``lookup`` never hits, ``register`` is a no-op,
    and released blocks always return to the free list (bit-identical to
    the pre-cache pool)."""

    def __init__(
        self, num_blocks: int, block_size: int, *, caching: bool = False
    ) -> None:
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.caching = bool(caching)
        self._free = list(range(self.num_blocks))
        self._ref = [0] * self.num_blocks
        self._hash_of: dict[int, int] = {}  # block -> content hash
        self._by_hash: dict[int, int] = {}  # content hash -> block
        self._lru: "OrderedDict[int, None]" = OrderedDict()  # ref-0 cached
        self.evictions = 0  # cached blocks recycled under pressure
        # Weight generation the allocator currently admits against; every
        # registered block remembers the generation its K/V were written
        # under, and a mismatch makes it a miss (lazily dropped).
        self.generation = 0
        self._gen_of: dict[int, int] = {}  # block -> generation registered
        self.stale_drops = 0  # stale-generation registrations dropped
        # Fleet cache: per-hash hit tally feeding the bounded ServeLoad
        # digest — registered-but-never-hit chains count 0 so a fresh
        # worker still advertises what it holds (the fleet can't bootstrap
        # off hits that haven't happened yet).
        self._hits: dict[int, int] = {}  # content hash -> lookup hits

    # ----------------------------------------------------------- querying

    def free_count(self) -> int:
        """Allocatable blocks: truly free + evictable (ref-0 cached)."""
        return len(self._free) + len(self._lru)

    def cached_count(self) -> int:
        """Blocks currently registered under a content hash."""
        return len(self._hash_of)

    def shared_count(self) -> int:
        """Blocks mapped into more than one lane table right now."""
        return sum(1 for r in self._ref if r > 1)

    def refcount(self, block: int) -> int:
        return self._ref[block]

    def is_shared(self, block: int) -> bool:
        return self._ref[block] > 1

    def is_registered(self, block: int) -> bool:
        return block in self._hash_of

    def _stale(self, block: int) -> bool:
        """Registered under an older weight generation than current."""
        return (
            block in self._hash_of
            and self._gen_of.get(block, self.generation) != self.generation
        )

    def _drop_stale(self, block: int) -> None:
        """Lazy invalidation on contact: drop a stale block's
        registration; if it was parked ref-0 in the LRU it becomes plain
        free space (nothing can ever hit it again). Live references are
        untouched — the owning lanes finish on the blocks they mapped."""
        self.forget(block)
        self.stale_drops += 1
        if block in self._lru:
            del self._lru[block]
            self._free.append(block)

    def peek(self, hashes: list) -> tuple:
        """Longest cached prefix of ``hashes`` WITHOUT taking references:
        ``(hit_blocks, hits_in_lru)``. ``hits_in_lru`` counts hits that
        currently sit in the LRU — mapping them consumes allocatable
        headroom, so admission must budget for them like fresh blocks.
        Stale-generation entries are misses (and are dropped on
        contact, so peek/lookup agree on the same admission)."""
        hits = in_lru = 0
        if not self.caching:
            return 0, 0
        for h in hashes:
            b = self._by_hash.get(h)
            if b is None:
                break
            if self._stale(b):
                self._drop_stale(b)
                break
            hits += 1
            if b in self._lru:
                in_lru += 1
        return hits, in_lru

    # ---------------------------------------------------------- mutation

    def bump_generation(self) -> None:
        """A weight swap happened: everything registered so far holds K/V
        from the OLD weights. No refcount or table moves here — the
        stale entries fall out lazily as peek/lookup/release touch them,
        so live lanes are never disturbed mid-decode."""
        self.generation += 1

    def lookup(self, hashes: list) -> list:
        """Map the longest cached prefix of ``hashes``: bumps each hit
        block's refcount (un-parking it from the LRU) and returns the
        physical ids in prefix order. The caller writes them into its
        lane table. Stale-generation entries never map — a post-swap
        admission must recompute the prefix under the new weights."""
        out: list = []
        if not self.caching:
            return out
        for h in hashes:
            b = self._by_hash.get(h)
            if b is None:
                break
            if self._stale(b):
                self._drop_stale(b)
                break
            if self._ref[b] == 0:
                del self._lru[b]
            self._ref[b] += 1
            self._hits[h] = self._hits.get(h, 0) + 1
            out.append(b)
        return out

    # -------------------------------------------------------- fleet cache

    def block_for(self, h: int) -> int | None:
        """Physical block registered under ``h`` at the CURRENT weight
        generation, else None (stale holders are dropped on contact, the
        same lazy invalidation peek/lookup apply)."""
        b = self._by_hash.get(h)
        if b is None:
            return None
        if self._stale(b):
            self._drop_stale(b)
            return None
        return b

    def resolve_chain(self, hashes: list) -> list:
        """Physical ids of the longest cached prefix of ``hashes``
        WITHOUT taking references — the BlockPull serving path. The
        serve thread extracts the rows in the same loop iteration, so
        the blocks cannot move under the read."""
        out: list = []
        for h in hashes:
            b = self.block_for(h)
            if b is None:
                break
            out.append(b)
        return out

    def hot_chains(self, k: int) -> list:
        """Bounded digest for ServeLoad piggybacking: the top-``k``
        currently-registered chain hashes by hit count, as
        ``[hash, hits]`` pairs (hottest first). Hashes whose block was
        evicted are pruned from the tally here, so the digest only ever
        advertises chains a puller can actually fetch."""
        if not self.caching or k <= 0:
            return []
        live = {h: self._hits.get(h, 0) for h in self._by_hash}
        self._hits = dict(live)  # prune tallies for evicted content
        top = sorted(live.items(), key=lambda kv: (-kv[1], kv[0]))[:k]
        return [[h, c] for h, c in top]

    def alloc(self) -> int | None:
        """One fresh block with ref=1: free list first, then evict the
        LRU's oldest cached block (its hash entry drops — the content is
        about to be overwritten). None = pool truly exhausted (every
        block is live in some table); the pool preempts then."""
        if self._free:
            b = self._free.pop()
        elif self._lru:
            b, _ = self._lru.popitem(last=False)
            del self._by_hash[self._hash_of.pop(b)]
            self._gen_of.pop(b, None)
            self.evictions += 1
            SERVE_METRICS.cache_evictions.add(1)
        else:
            return None
        self._ref[b] = 1
        return b

    def register(self, block: int, h: int) -> None:
        """Attach content hash ``h`` to ``block`` (its K/V are fully
        written and final) under the CURRENT weight generation.
        Duplicate content — another block already registered under ``h``
        — keeps the original; this block stays unregistered and will
        free normally. Exception: a stale-generation holder is evicted
        first, so post-swap recomputation can re-claim the hash."""
        if not self.caching or block in self._hash_of:
            return
        holder = self._by_hash.get(h)
        if holder is not None:
            if not self._stale(holder):
                return
            self._drop_stale(holder)
        self._hash_of[block] = h
        self._by_hash[h] = block
        self._gen_of[block] = self.generation

    def forget(self, block: int) -> None:
        """Drop ``block``'s registration (an in-place overwrite is about
        to invalidate its cached content; ref==1, so no one else reads
        it). No-op for unregistered blocks."""
        h = self._hash_of.pop(block, None)
        if h is not None:
            del self._by_hash[h]
            self._gen_of.pop(block, None)

    def release(self, block: int) -> None:
        """Drop one table reference. At ref 0, registered blocks park in
        the LRU (their content stays addressable for future hits);
        unregistered blocks go straight back to the free list — as do
        stale-generation registrations, whose content can never be hit
        again (the lane that held them across a swap just finished)."""
        self._ref[block] -= 1
        if self._ref[block] < 0:
            raise AssertionError(f"block {block} released below ref 0")
        if self._ref[block] == 0:
            if self._stale(block):
                # Not yet parked anywhere: forget and fall through to the
                # free list (the LRU would just defer the same drop).
                self.forget(block)
                self.stale_drops += 1
            if block in self._hash_of:
                self._lru[block] = None
            else:
                self._free.append(block)

    # --------------------------------------------------------- invariant

    def check_conservation(self, tables: list) -> None:
        """Assert the block partition against the caller's live lane
        ``tables`` (a list of block-id lists, one per live lane, possibly
        sharing blocks): every physical block is in exactly one of
        {free list, live tables (ref>0), ref-0 LRU}, and every block's
        refcount equals its total table references. Raises
        AssertionError naming the first violation."""
        refs = [0] * self.num_blocks
        for table in tables:
            for b in table:
                refs[b] += 1
        free = set(self._free)
        lru = set(self._lru)
        if len(free) != len(self._free):
            raise AssertionError("duplicate block on the free list")
        if free & lru:
            raise AssertionError(f"blocks in free AND lru: {free & lru}")
        for b in range(self.num_blocks):
            in_table = refs[b] > 0
            places = (b in free) + (b in lru) + in_table
            if places != 1:
                raise AssertionError(
                    f"block {b} in {places} places (free={b in free}, "
                    f"lru={b in lru}, table_refs={refs[b]})"
                )
            if self._ref[b] != refs[b]:
                raise AssertionError(
                    f"block {b} refcount {self._ref[b]} != "
                    f"{refs[b]} table references"
                )
        for h, b in self._by_hash.items():
            if self._hash_of.get(b) != h:
                raise AssertionError(f"hash index desync on block {b}")
        if len(self._by_hash) != len(self._hash_of):
            raise AssertionError("hash maps disagree on cached count")
        if set(self._gen_of) != set(self._hash_of):
            raise AssertionError(
                "generation stamps desync from registrations: "
                f"{sorted(set(self._gen_of) ^ set(self._hash_of))}"
            )
