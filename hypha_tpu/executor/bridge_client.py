"""Executor-side Job-Bridge client: HTTP over the job's unix socket.

Parity with the reference executor's ``api.py::Session``
(executors/accelerate/src/hypha/accelerate_executor/api.py:11-63):
``fetch``, ``send_resource``, ``send_status``, and ``receive`` — an SSE
context manager yielding JSON file pointers as tensors land.
"""

from __future__ import annotations

import json
from contextlib import contextmanager
from typing import Any, Iterator

import httpx

from .. import messages
from ..messages import Fetch, Progress, ProgressResponse, Receive, Send

__all__ = ["Session"]


class Session:
    def __init__(self, socket_path: str, timeout: float = 300.0) -> None:
        self._client = httpx.Client(
            transport=httpx.HTTPTransport(uds=socket_path),
            base_url="http://bridge",
            timeout=timeout,
        )

    def close(self) -> None:
        self._client.close()

    def __enter__(self) -> "Session":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------

    def fetch(self, fetch: Fetch) -> list[str]:
        """Materialize a reference under work_dir/artifacts; returns the
        work-dir-relative paths."""
        r = self._client.post(
            "/resources/fetch", json={"fetch": messages.to_json_dict(fetch)}
        )
        r.raise_for_status()
        return r.json()["paths"]

    def send_resource(
        self,
        send: Send,
        path: str,
        resource: str = "updates",
        meta: dict[str, Any] | None = None,
    ) -> None:
        """Ship a work-dir file to peers (runs in the worker's background).
        ``meta`` rides the stream header (e.g. num_samples for the parameter
        server's sample-weighted mean)."""
        r = self._client.post(
            "/resources/send",
            json={
                "send": messages.to_json_dict(send),
                "path": path,
                "resource": resource,
                "meta": meta or {},
            },
        )
        r.raise_for_status()

    def send_status(self, progress: Progress) -> ProgressResponse:
        """Report progress; returns the scheduler's control decision."""
        r = self._client.post(
            "/status/send", json={"progress": messages.to_json_dict(progress)}
        )
        r.raise_for_status()
        resp = messages.from_json_dict(r.json()["response"])
        if not isinstance(resp, ProgressResponse):
            raise ValueError(f"unexpected status response {resp!r}")
        return resp

    @contextmanager
    def receive(self, receive: Receive) -> Iterator[Iterator[dict[str, Any]]]:
        """SSE stream of ``{path,size,from_peer,resource}`` pointers."""
        with self._client.stream(
            "POST",
            "/resources/receive",
            json={"receive": messages.to_json_dict(receive)},
            timeout=None,
        ) as response:
            response.raise_for_status()

            def events() -> Iterator[dict[str, Any]]:
                for line in response.iter_lines():
                    if line.startswith("data: "):
                        yield json.loads(line[len("data: ") :])

            yield events()
