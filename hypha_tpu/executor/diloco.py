"""DiLoCo delta algebra and the outer Nesterov optimizer, as jitted tree ops.

Reference semantics being reproduced:
  * pseudo-gradient: Δθ = θ_t − θ_0 against the round's anchor snapshot
    (executors/accelerate/.../utils.py:119-124);
  * worker merge: θ_new = θ_old + update — the update already contains
    lr·(μ·m + ḡ), sign convention per utils.py:112-116;
  * outer step (parameter server): m ← μ·m + ḡ;  update = lr·(μ·m + ḡ)
    with ḡ = mean of worker pseudo-gradients
    (crates/worker/src/executor/parameter_server.rs:386-446, verified there
    against torch SGD(nesterov=True) — our golden test does the same);
  * averaging is a single (optionally sample-weighted) mean, fixing the
    reference's pairwise-average mis-weighting TODO (parameter_server.rs:192).

On TPU all of these are jit-compiled pytree ops; across co-located replicas
the mean lowers to an ICI collective (parallel.collectives).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "extract_delta",
    "merge_update",
    "apply_updates",
    "average_deltas",
    "nesterov_init",
    "nesterov_outer_step",
]


@jax.jit
def extract_delta(params, anchor):
    """Pseudo-gradient Δθ = θ_t − θ_0 (both trees same structure)."""
    return jax.tree.map(lambda p, a: (p - a).astype(jnp.float32), params, anchor)


@jax.jit
def merge_update(params, update):
    """θ_new = θ + update, preserving each leaf's dtype."""
    return jax.tree.map(lambda p, u: (p + u.astype(p.dtype)).astype(p.dtype), params, update)


def merge_update_f32(params, update):
    """θ_new = θ + update with the add in f32 before the per-leaf cast.

    :func:`apply_updates`' precision discipline for flat name→leaf maps
    covering any SUBSET of the tree — the sharded rejoin catch-up applies
    per-shard cumulative Σs to disjoint leaf sets, and casting a long Σ
    to bf16 before the add (plain :func:`merge_update`) would diverge
    from the unsharded catch-up's f32 accumulation."""
    return jax.tree.map(
        lambda p, u: (
            jnp.asarray(p, jnp.float32) + jnp.asarray(u, jnp.float32)
        ).astype(p.dtype),
        params, update,
    )


@jax.jit
def _apply_updates(p, us):
    def leaf(x, *ys):
        total = sum(jnp.asarray(y, jnp.float32) for y in ys)
        return (x.astype(jnp.float32) + total).astype(x.dtype)

    return jax.tree.map(leaf, p, *us)


def apply_updates(params, updates: list):
    """Fold several outer updates into θ in one pass: θ ← θ + Σ updates.

    The rejoin catch-up path (hypha_tpu.ft.rejoin): a worker that missed
    rounds k..r−1 applies their updates — or the parameter server's single
    cumulative Σ — in f32 before the per-leaf cast, so a long catch-up does
    not compound per-round rounding in low-precision params.  The jitted
    body lives at module level so repeated same-shape catch-ups hit the
    compilation cache instead of re-tracing a parameter-sized tree op.
    """
    if not updates:
        return params
    return _apply_updates(params, list(updates))


def average_deltas(deltas: list, weights=None):
    """Mean of worker pseudo-gradients; ``weights`` = per-worker sample counts
    for the sample-weighted fix."""
    if not deltas:
        raise ValueError("no deltas")
    if weights is None:
        w = jnp.full((len(deltas),), 1.0 / len(deltas), jnp.float32)
    else:
        w = jnp.asarray(weights, jnp.float32)
        w = w / jnp.maximum(w.sum(), 1e-20)

    def leaf_mean(*leaves):
        stacked = jnp.stack([l.astype(jnp.float32) for l in leaves])
        return jnp.tensordot(w, stacked, axes=1)

    return jax.tree.map(leaf_mean, *deltas)


def nesterov_init(params):
    """Zero momentum buffers shaped like the param tree (f32)."""
    return jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)


@jax.jit
def _nesterov(momentum, mean_delta, lr, mu):
    m_new = jax.tree.map(lambda m, g: mu * m + g.astype(jnp.float32), momentum, mean_delta)
    update = jax.tree.map(lambda m, g: lr * (mu * m + g.astype(jnp.float32)), m_new, mean_delta)
    return m_new, update


def nesterov_outer_step(momentum, mean_delta, lr: float, mu: float):
    """One outer step: returns (new_momentum, update_to_broadcast).

    Matches torch SGD(nesterov=True) on the ascent-direction pseudo-gradient:
    buf ← μ·buf + ḡ; update = lr·(ḡ + μ·buf); θ ← θ + update.
    """
    return _nesterov(momentum, mean_delta, jnp.float32(lr), jnp.float32(mu))
