"""JAX execution layer: the inner training loop, DiLoCo delta algebra and the
outer (aggregate) optimizer — the TPU-native replacement for the reference's
``executors/accelerate`` Python package and the parameter-server executor's
tensor math (SURVEY.md §2.6, §2.9)."""

from .diloco import extract_delta, merge_update, nesterov_init, nesterov_outer_step
from .generate import generate
from .train import (
    TrainState,
    build_optimizer,
    compute_loss,
    make_lr_schedule,
    make_train_step,
)

__all__ = [
    "generate",
    "extract_delta",
    "merge_update",
    "nesterov_init",
    "nesterov_outer_step",
    "TrainState",
    "build_optimizer",
    "compute_loss",
    "make_lr_schedule",
    "make_train_step",
]
