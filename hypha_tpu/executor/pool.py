"""Continuous batching: iteration-level scheduling over a fixed KV-slot pool.

The window batcher (worker.batcher) coalesces SIMULTANEOUS greedy requests
but runs one decode at a time behind a chip lock: a request arriving 1 ms
after a 128-token decode starts waits the entire decode before its bucket
runs, and finished rows hold their batch position to the end (VERDICT r4
weak #4). This module is the industry-standard fix, built TPU-native:

  * a **fixed pool** of ``slots`` KV rows with a static ``max_len`` window
    each — one compiled decode program for the whole lifetime of the job
    (no dynamic shapes, no retracing);
  * the decode loop advances ALL rows one token per step, ``steps_per_call``
    steps per dispatched program (`lax.scan`), returning to the host at
    each chunk boundary;
  * at every boundary, waiting requests are **admitted into free rows**
    (their prompts prefill into a standalone bucket-shaped cache that is
    scattered into the pool), and rows that reached their budget or EOS
    are **released** — a request arriving mid-decode starts within
    ``steps_per_call`` tokens instead of after the in-flight decode;
  * per-row cache indices and left-pad starts (ops.kvcache per-row mode)
    let rows sit at different sequence positions inside one program —
    the pool's whole point.

Greedy only: sampled rows would draw from a shared key and their outputs
would depend on batch composition, breaking seeded reproducibility (the
same policy as worker.batcher, which remains the sampled/fallback path).

**Paged mode** (``block_size > 0``, the vLLM/PagedAttention design): K/V
live in a pool of ``num_blocks`` physical blocks of ``block_size``
positions shared by every decode lane, mapped through per-lane block
tables (``ops.kvcache`` paged layout — the table is cache *data*, so the
one-compiled-program invariant holds). Admission is decided by **free
blocks**, not free rows: a short request holds only the blocks its window
actually needs, so the same KV memory admits several-fold more concurrent
requests than whole-``max_len`` rows. A watermark reserve keeps blocks
back for running requests to grow into; when growth would starve the pool
anyway, the most recently admitted group is **preempted to the queue**
(recompute resume, vLLM's policy — the youngest request carries the least
sunk decode cost) and re-admitted later with its generated tokens folded
into the prompt, reproducing the uncontended token stream exactly.
**Chunked prefill**: prompts prefill ``prefill_chunk`` tokens per
serve-loop iteration *interleaved* with decode chunks, so a long prompt
no longer stalls every in-flight decode for one monolithic prefill
program (bit-equal to monolithic prefill — the chunk attends to the same
keys with the same positions). ``max_queue`` bounds the waiting line:
beyond it ``submit`` fails fast with :class:`PoolBusy` carrying a
retry-after hint instead of queueing unboundedly.

**Automatic prefix caching** (``prefix_cache=True``, paged mode): paged
lanes are laid out right-aligned at position 0 (RoPE positions and the
causal mask are unchanged — token streams stay pinned against the
one-shot path), so a full block's K/V content is a pure function of the
token prefix. Admission chain-hashes the prompt's full blocks
(executor.block_cache), maps the longest cached prefix into the new
lane's table refcounted, and jumps ``r.pos`` past the hit — capped one
token short of the prompt end, so the last token always recomputes (its
logits yield the first generated token; when that write lands in a
still-shared block it copy-on-writes into a fresh one first,
ops.kvcache.copy_blocks). Completed/preempted lanes register their full
blocks back into the cache; refcount-0 blocks park in an LRU that both
allocation and eviction draw from, so a preempted group's resume is a
cache hit (one prefill chunk) instead of a full recompute.

**Speculative decoding** (``spec_ngram > 0``, paged mode): n-gram
prompt-lookup drafting — the most recent earlier occurrence of the
context's final n-gram proposes the tokens that followed it — verified
by the SAME chunked-prefill program (it already scores every position of
a K-token window per dispatch; per-column argmax makes each column's
greedy next-token visible to the host). The accepted prefix plus one
bonus token lands per verify dispatch, so progress is ≥ 1 token always
and up to ``prefill_chunk`` on repetitive text; greedy output is
token-identical by construction (only model-confirmed tokens are ever
emitted). Both features default OFF; off, behavior and program shapes
are exactly the pre-cache pool's.

The reference has no inference path at all (its Executor union is
Train|Aggregate, crates/messages/src/lib.rs:627-631) — this is net-new
capability, benchmarked in SERVBENCH (late-arrival p50 + aggregate tok/s).
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.kvcache import (
    _POOL_LEAVES,
    copy_blocks,
    extract_blocks,
    insert_blocks,
)
from ..telemetry import SERVE_METRICS
from ..telemetry import trace
from ..telemetry.flight import FLIGHT
from .block_cache import PrefixBlockCache, chain_hashes

__all__ = ["DecodePool", "PoolBusy", "supports_pool", "supports_paging"]

log = logging.getLogger("hypha.executor.pool")


class StaleBlockGeneration(RuntimeError):
    """A shipped KV chain was computed under different weights than this
    pool currently serves: chain hashes address token content, not
    weights, so admission rejects the stamp mismatch rather than silently
    serving old-weight KV (the receiving side of hypha-lint's
    ``msg-block-needs-generation`` contract)."""


class PoolBusy(RuntimeError):
    """Backpressure: the pool's waiting line is full. Callers should retry
    after ``retry_after_s`` (surfaced on the wire as
    ``GenerateResponse.retry_after_ms``) instead of piling onto the queue."""

    def __init__(self, retry_after_s: float) -> None:
        super().__init__(
            f"pool queue is full; retry after {retry_after_s:.2f}s"
        )
        self.retry_after_s = retry_after_s


def supports_pool(model: Any) -> bool:
    """Does this model family implement per-row decode? (Llama lineage —
    Llama/Mistral/Qwen2/Gemma configs — and Mixtral share the per-row
    attention; GPT-2's learned-position decode path is scalar-only.)"""
    return hasattr(model, "per_row_decode")


def supports_paging(model: Any) -> bool:
    """Per-row decode AND the paged cache layout fields (kv_blocks)."""
    return supports_pool(model) and hasattr(model, "kv_blocks")


def _bucket(n: int, lo: int = 16) -> int:
    b = lo
    while b < n:
        b <<= 1
    return b


def _set_rowvar(cache, name: str, value):
    """Replace every cache leaf called ``name`` (idx/start vectors)."""

    def repl(path, leaf):
        key = path[-1]
        if getattr(key, "key", None) == name:
            return jnp.broadcast_to(value, leaf.shape).astype(leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(repl, cache)


@dataclass
class _Row:
    group: "_Group"
    lane: int  # which prompt of the group this row serves
    budget: int
    emitted: list = field(default_factory=list)
    done: bool = False


@dataclass
class _Group:
    prompts: list
    n_new: int
    fut: Future
    rows: dict = field(default_factory=dict)  # lane -> slot
    admit_chunk: int = -1
    finish_chunk: int = -1
    t_submit: float = 0.0  # request latency (SERVE_METRICS)
    order: int = -1  # admission sequence; preemption picks the youngest
    # Serve-path tracing (telemetry.trace): the request's ``decode`` span,
    # opened at first admission and finished at resolve — it spans
    # preempt/re-admit cycles, so its duration is the decode latency the
    # caller actually saw. None while tracing is off. ``traceparent`` is
    # the submitting request's context (the router's route span via the
    # worker's serve span) so pool spans join the request's trace.
    trace_span: Any = None
    traceparent: "str | None" = None


@dataclass
class SpeculationState:
    """ONE per-lane speculation state shared by every proposer (n-gram
    prompt-lookup and the model draft): whichever path proposed, the
    verify's accept count feeds the same EWMA and the same cooldown, so
    a lane backs off the *verify dispatch* — not one proposer — when
    drafts keep missing, and a weight swap re-arms both paths at once
    (:meth:`DecodePool._reset_spec_state`). Splitting this per-proposer
    was the bug: the model-draft path inherited a stale n-gram EWMA
    learned under old weights (or vice versa) and sat out verifies the
    new model would have won."""

    # n-gram context + position index: incrementally maintained
    # (O(1) amortized per token instead of an O(len) rescan per
    # iteration). The model draft reads ``ctx`` too when present.
    ctx: Any = None  # list, extended from emitted lazily
    index: Any = None  # tuple[n-gram] -> ascending positions
    indexed: int = 0
    ewma: float = 0.0  # accepted drafts per verify, smoothed
    cooldown: int = 0  # iterations to sit out after low accepts
    primed: bool = False  # ewma initialized (first proposal happened)


@dataclass
class _PRow:
    """One prompt's state in the PAGED pool. Survives preemption: ``prompt``
    and ``emitted`` persist, the lane/window/block state is rebuilt at
    re-admission (recompute resume — the resume prompt is
    ``prompt + emitted``, so greedy continuation reproduces the
    uncontended stream exactly)."""

    group: _Group
    lane: int
    prompt: list  # original token ids (never mutated)
    budget: int
    emitted: list = field(default_factory=list)
    done: bool = False
    # live-lane state, only meaningful while admitted
    slot: int = -1
    window: int = 0  # prefill target: len(prompt + emitted) at admission
    pos: int = 0  # logical write index: prefill progress, then decode
    blocks: list = field(default_factory=list)
    win_tokens: Any = None  # np[window + P] right-aligned resume prompt
    # prefix-cache progress: how many leading blocks are registered in
    # the cache, and the chain hash after them (block_cache.chain_hashes
    # recurrence) — decode extends the chain incrementally.
    hashed: int = 0
    chain_h: int = 0
    # shared speculation state (n-gram AND model draft — see dataclass)
    spec: SpeculationState = field(default_factory=SpeculationState)


# Serve-loop wake sentinel (request_swap/pin_round): drained and dropped —
# it exists only to unblock an idle queue.get so a staged swap applies
# without waiting for the next request to arrive.
_WAKE: Any = object()


class DecodePool:
    """One serving pool: owns the chip from a dedicated thread.

    ``submit`` is thread-safe and returns a concurrent.futures.Future that
    resolves to one token list per prompt (async callers wrap it with
    ``asyncio.wrap_future``). ``close()`` drains nothing: queued and
    in-flight requests fail fast, matching the window batcher's contract.
    """

    def __init__(
        self,
        model: Any,
        params: Any,
        *,
        slots: int = 8,
        max_len: int = 512,
        steps_per_call: int = 8,
        eos_token_id: int | None = None,
        block_size: int = 0,
        num_blocks: int = 0,
        prefill_chunk: int = 0,
        reserve_blocks: int = -1,
        max_queue: int = 0,
        prefix_cache: bool = False,
        spec_ngram: int = 0,
        spec_draft: int = 0,
        ragged: bool = False,
        kv_quant: str = "",
        spec_layers: int = 0,
        draft_model: Any = None,
        draft_params: Any = None,
        fleet_cache: bool = False,
        kv_migration: bool = False,
        digest_k: int = 32,
    ) -> None:
        if not supports_pool(model):
            raise ValueError(
                f"{type(model).__name__} has no per-row decode path"
            )
        self._paged = block_size > 0
        if prefix_cache and not self._paged:
            raise ValueError("prefix_cache requires paged mode (block_size > 0)")
        if spec_ngram > 0 and not self._paged:
            raise ValueError(
                "speculative decoding requires paged mode (block_size > 0)"
            )
        if (ragged or kv_quant) and not self._paged:
            raise ValueError(
                "ragged / kv_quant require paged mode (block_size > 0)"
            )
        if kv_quant not in ("", "int8"):
            raise ValueError(f"unknown kv_quant {kv_quant!r}")
        if (spec_layers > 0 or draft_model is not None) and not self._paged:
            raise ValueError(
                "model-draft speculation requires paged mode (block_size > 0)"
            )
        if spec_layers > 0 and draft_model is not None:
            raise ValueError("spec_layers and draft_model are exclusive")
        if (fleet_cache or kv_migration) and not (
            self._paged and prefix_cache
        ):
            # Both features trade in content-addressed blocks: without the
            # chain-hash registry there is nothing to ship or land on.
            raise ValueError(
                "fleet_cache / kv_migration require paged mode with "
                "prefix_cache=True"
            )
        if draft_model is not None and draft_params is None:
            raise ValueError("draft_model requires draft_params")
        if spec_layers > 0:
            n_layers = getattr(getattr(model, "config", None), "num_layers", 0)
            if not 0 < spec_layers < n_layers:
                raise ValueError(
                    f"spec_layers {spec_layers} must be in (0, "
                    f"{n_layers}) for this model"
                )
        if self._paged:
            if not supports_paging(model):
                raise ValueError(
                    f"{type(model).__name__} has no paged KV cache fields"
                )
            if max_len % block_size != 0:
                raise ValueError(
                    f"max_len {max_len} must be a multiple of block_size "
                    f"{block_size}"
                )
            if prefill_chunk <= 0:
                prefill_chunk = min(max_len, 4 * block_size)
            if max_len % prefill_chunk != 0:
                raise ValueError(
                    f"max_len {max_len} must be a multiple of prefill_chunk "
                    f"{prefill_chunk}"
                )
            if prefill_chunk % block_size != 0:
                # Windows are prefill_chunk-granular and block allocation
                # counts L // block_size — a non-multiple would leave the
                # prompt tail mapped to the garbage block (silently wrong
                # tokens), so refuse the geometry outright.
                raise ValueError(
                    f"prefill_chunk {prefill_chunk} must be a multiple of "
                    f"block_size {block_size}"
                )
            if num_blocks <= 0:
                # Default: the same total KV positions the fixed-slot pool
                # would hold — block admission then wins purely on packing.
                num_blocks = slots * max_len // block_size
        self.block_size = block_size
        self.num_blocks = num_blocks if self._paged else 0
        self.prefill_chunk = prefill_chunk if self._paged else 0
        self.prefix_cache = bool(prefix_cache)
        self.spec_ngram = int(spec_ngram) if self._paged else 0
        self.ragged = bool(ragged) and self._paged
        self.kv_quant = kv_quant if self._paged else ""
        # Model-draft speculation: either an explicit small family member
        # (draft_model/draft_params) or LayerSkip-style self-draft — the
        # first ``spec_layers`` layers of the SERVED params plus the
        # shared embed/norm/head, filtered lazily from the live var tree
        # so weight swaps propagate to the draft for free.
        self.spec_layers = int(spec_layers) if self._paged else 0
        self._draft_params = (
            draft_params if isinstance(draft_params, dict)
            and "params" in draft_params
            else ({"params": draft_params} if draft_params is not None
                  else None)
        )
        if draft_model is not None:
            self._draft_model = draft_model
        elif self.spec_layers > 0:
            self._draft_model = dataclasses.replace(
                model,
                config=dataclasses.replace(
                    model.config, num_layers=self.spec_layers
                ),
            )
        else:
            self._draft_model = None
        self.spec_model = self._draft_model is not None
        # Draft tokens per verify dispatch: the verify window holds the
        # current token + drafts, so at most prefill_chunk - 1 fit.
        if self._paged:
            cap = max(self.prefill_chunk - 1, 0)
            self.spec_draft = min(spec_draft, cap) if spec_draft > 0 else cap
        else:
            self.spec_draft = 0
        # Model-draft forward window: the draft runs cache-less causal
        # forwards over a static [1, W] buffer (context tail + grown
        # draft) — small by design; correctness is the verify's job.
        self._draft_window = min(max_len, 64) if self._paged else 0
        self._draft_fn = None
        self._model = model
        dec_kw = dict(decode=True, decode_len=max_len, per_row_decode=True)
        if self._paged:
            dec_kw.update(kv_blocks=num_blocks, kv_block_size=block_size)
            if self.ragged:
                dec_kw.update(ragged_attention=True)
            if self.kv_quant:
                dec_kw.update(kv_quant=self.kv_quant)
        self._dec = dataclasses.replace(model, **dec_kw)
        if isinstance(params, dict) and "params" in params:
            self._vars = dict(params)
        else:
            self._vars = {"params": params}
        # Live weight streaming (hypha_tpu.serving.weight_stream): a
        # pending hot swap staged by request_swap() from any thread,
        # applied by the SERVE thread at the next chunk boundary —
        # ``self._vars`` is read exactly once per dispatched program on
        # that thread, so one assignment is atomic and no in-flight
        # decode step ever sees mixed-round weights.
        self._swap_lock = threading.Lock()
        self._pending_swap: dict | None = None
        self._param_names: set | None = None  # lazy flat_leaf_map cache
        self._pending_rollback: int | None = None
        self._prev_leaves: tuple | None = None  # (round, leaves) snapshot
        self.weight_round: int | None = None
        self.weight_generation: int | None = None
        self.pinned_round: int | None = None
        self.swaps_applied = 0
        self.swaps_deferred = 0
        self.swaps_rolled_back = 0
        self.slots = slots
        self.max_len = max_len
        self.steps_per_call = steps_per_call
        self.eos_token_id = eos_token_id
        # Watermark: blocks held back from admission so live requests can
        # grow (one block per lane by default). Preemption backstops it.
        if reserve_blocks < 0:
            reserve_blocks = slots
        self.reserve_blocks = reserve_blocks if self._paged else 0
        self.max_queue = max(int(max_queue), 0)

        # Pool cache + current-token vector live on device for the whole
        # job; everything else is host bookkeeping.
        skel = jax.eval_shape(
            lambda: self._dec.init(
                jax.random.key(0), jnp.zeros((slots, 1), jnp.int32)
            )
        )["cache"]
        self._cache = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), skel
        )
        self._tok = jnp.zeros((slots,), jnp.int32)

        self._rows: dict[int, _Row] = {}
        self._free = list(range(slots))
        # Paged host bookkeeping: lanes, the block allocator (+ prefix
        # cache), and the row-variable mirrors pushed to device before
        # every dispatched program.
        self._lane_rows: dict[int, _PRow] = {}
        self._free_lanes = list(range(slots))
        self._alloc = PrefixBlockCache(
            self.num_blocks, max(self.block_size, 1),
            caching=self.prefix_cache,
        )
        if self._paged:
            max_blocks = max_len // block_size
            self._h_idx = np.full((slots,), max_len, np.int32)
            self._h_start = np.zeros((slots,), np.int32)
            self._h_table = np.full(
                (slots, max_blocks), self.num_blocks, np.int32
            )
        # Fleet prefix cache + KV migration (content-addressed block
        # shipping): the digest is refreshed by the serve thread each
        # iteration and read whole (one attribute load) by the heartbeat
        # thread; serve_chain/inject_chain run as serve-thread ops so the
        # allocator's no-locking contract holds.
        self.fleet_cache = bool(fleet_cache)
        self.kv_migration = bool(kv_migration)
        self.digest_k = max(int(digest_k), 1)
        self.fleet_digest: list = []
        self._ops: list = []  # (fn, Future) run on the serve thread
        self._ops_lock = threading.Lock()
        self._migrate_policy = None  # (est_bytes, tokens) -> target | None
        self._migrate_send = None  # (ticket) -> None, any-thread handoff
        self._prefill_rate = 0.0  # tokens/s EWMA (transfer-vs-recompute)
        self._block_bytes = 0  # lazy: wire bytes per shipped block
        self.migrated_out = 0
        self._queue: "queue.Queue[_Group | None]" = queue.Queue()
        self._waiting: list[_Group] = []
        # Guards the closed-check + enqueue in submit() against the serve
        # thread's final drain in _fail_all(): without it, a submit that
        # passed the check could enqueue AFTER the drain and its Future
        # would never resolve.
        self._submit_lock = threading.Lock()
        self._closed = False
        self._backlog = 0  # submitted, not yet admitted (queue-depth gauge)
        self._admit_seq = 0
        self.chunks = 0  # decode programs dispatched (test/bench hook)
        self.prefill_chunks = 0  # paged: chunked-prefill programs dispatched
        self.spec_chunks = 0  # speculation verify dispatches (same program)
        self.preemptions = 0
        self.requests = 0
        self._prefill_cache: dict = {}
        self._insert_cache: dict = {}
        self._chunk_fn = None
        self._prefill_paged_fn = None
        self._sync_fn = None
        self._copy_fn = None
        self._thread = threading.Thread(
            target=self._serve_loop, name="decode-pool", daemon=True
        )
        self._thread.start()

    # ---------------------------------------------------------- load stats

    def free_blocks(self) -> int:
        """Allocatable KV blocks (paged: free list + evictable ref-0
        cached blocks) / free rows (fixed-slot) — the admission headroom
        reported on ServeLoad heartbeats for router balancing."""
        return self._alloc.free_count() if self._paged else len(self._free)

    def queue_depth(self) -> int:
        """Groups submitted but not yet admitted."""
        with self._submit_lock:
            return self._backlog

    def live_rows(self) -> int:
        """Rows currently decoding/prefilling (either mode)."""
        return len(self._rows) + len(self._lane_rows)

    # ------------------------------------- fleet cache / migration plumbing

    def run_op(self, fn) -> Future:
        """Run ``fn()`` on the serve thread at the next chunk boundary
        (thread-safe). The allocator and the device cache are serve-thread
        property — every cross-thread touch (chain serving, block
        injection) funnels through here instead of growing locks."""
        fut: Future = Future()
        with self._ops_lock:
            if self._closed:
                fut.set_exception(RuntimeError("pool is closed"))
                return fut
            self._ops.append((fn, fut))
        self._queue.put(_WAKE)
        return fut

    def _drain_ops(self) -> None:
        while True:
            with self._ops_lock:
                if not self._ops:
                    return
                fn, fut = self._ops.pop(0)
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn())
            except Exception as exc:  # noqa: BLE001 — delivered to caller
                fut.set_exception(exc)

    def serve_chain(self, hashes: list) -> Future:
        """BlockPull serving: resolve the longest cached prefix of
        ``hashes`` and extract its pool rows (every leaf — payload and
        int8 scales — verbatim). Resolves to ``{"hashes", "leaves"}`` or
        None when nothing is cached."""
        return self.run_op(lambda: self._op_serve_chain(list(hashes)))

    def inject_chain(
        self,
        hashes: list,
        leaves: dict,
        weight_round,
        weight_generation,
    ) -> Future:
        """Land shipped blocks (``extract_blocks`` layout, one row-run
        per hash) as registered ref-0 cache entries, so the next
        admission of the same prefix is an ordinary cache hit. Resolves
        to the number of blocks injected; raises
        :class:`StaleBlockGeneration` when the stamp doesn't match the
        weights this pool currently serves."""
        return self.run_op(
            lambda: self._op_inject_chain(
                list(hashes), leaves, weight_round, weight_generation
            )
        )

    def set_migrate_hooks(self, policy, send) -> None:
        """Install the preemption-migration hooks (worker side):
        ``policy(est_bytes, resume_tokens) -> target | None`` picks
        transfer vs recompute; ``send(ticket)`` hands the extracted state
        to the async sender. Both run ON the serve thread and must not
        block."""
        self._migrate_policy = policy
        self._migrate_send = send

    def _block_nbytes(self) -> int:
        """Wire payload bytes one shipped block carries, summed over
        every pool leaf (k/v payload + int8 scale rows)."""
        if self._block_bytes:
            return self._block_bytes
        n = 0

        def visit(path, leaf):
            nonlocal n
            if getattr(path[-1], "key", None) in _POOL_LEAVES:
                n += (
                    self.block_size
                    * int(np.prod(leaf.shape[1:]))
                    * leaf.dtype.itemsize
                )
            return leaf

        jax.tree_util.tree_map_with_path(visit, self._cache)
        self._block_bytes = n
        return n

    def prefill_cost_s(self, tokens: int) -> float | None:
        """Estimated seconds to prefill ``tokens`` locally (measured
        chunked-prefill throughput EWMA); None until the first prefill
        has been timed."""
        rate = self._prefill_rate
        return tokens / rate if rate > 0 else None

    def _op_serve_chain(self, hashes: list) -> dict | None:
        if not (self._paged and self.prefix_cache):
            raise RuntimeError("chain serving requires the prefix cache")
        ids = self._alloc.resolve_chain(hashes)
        if not ids:
            return None
        return {
            "hashes": list(hashes[: len(ids)]),
            "leaves": extract_blocks(self._cache, ids, self.block_size),
        }

    def _op_inject_chain(
        self, hashes: list, leaves: dict, wr, wg
    ) -> int:
        if not (self._paged and self.prefix_cache):
            raise RuntimeError("chain injection requires the prefix cache")
        if (wr, wg) != self.weight_state():
            raise StaleBlockGeneration(
                f"shipped blocks stamped {(wr, wg)}, pool serves "
                f"{self.weight_state()}"
            )
        bs = self.block_size
        n = len(hashes)
        taken: list = []  # (block, hash)
        rows: list = []  # index into the shipped row-runs
        for i, h in enumerate(hashes):
            if self._alloc.block_for(h) is not None:
                continue  # already cached under the serving weights
            if self._lane_rows and self._alloc.free_count() <= max(
                self.reserve_blocks, 0
            ):
                break  # don't starve live lanes to warm the cache
            b = self._alloc.alloc()
            if b is None:
                break
            taken.append((b, h))
            rows.append(i)
        if not taken:
            return 0
        sub = {
            key: a.reshape(n, bs, *a.shape[1:])[rows].reshape(
                len(rows) * bs, *a.shape[1:]
            )
            for key, a in leaves.items()
        }
        self._cache = insert_blocks(
            self._cache, [b for b, _ in taken], sub, bs
        )
        for b, h in taken:
            self._alloc.register(b, h)
            self._alloc.release(b)  # ref 0 + registered -> parks in LRU
        return len(taken)

    # ----------------------------------------------------- weight swapping

    def weight_state(self) -> tuple:
        """The (round, generation) currently serving — None/None until the
        first swap (requests decode on the dispatched params)."""
        with self._swap_lock:
            return self.weight_round, self.weight_generation

    def _norm_swap_key(self, name: str) -> str:
        """Map a wire delta name onto the local param tree. Trainer-side
        names come from the FULL init tree and so carry the ``params/``
        head; the pool holds the inner subtree with unprefixed names.
        Normalizing ONCE at staging time keeps fold, rollback-undo, and
        apply keys in one spelling — a mismatch here would fold the same
        leaf under two dict keys and silently drop one delta at apply.
        Unknown names pass through so the apply-side lookup fails loud.
        """
        if self._param_names is None:
            from .serialization import flat_leaf_map

            self._param_names = set(flat_leaf_map(self._vars["params"]))
        if name in self._param_names:
            return name
        if name.startswith("params/") and name[7:] in self._param_names:
            return name[7:]
        return name

    def request_swap(
        self,
        updates: dict,
        *,
        round_num: int,
        generation: int = 0,
        keep_previous: bool = False,
    ) -> None:
        """Stage round ``round_num``'s outer UPDATE (flat name -> delta
        array) for an atomic flip at the next chunk boundary. Thread-safe;
        callers feed rounds contiguously (WeightStager enforces it). A
        swap staged before the previous one applied FOLDS into it —
        updates are deltas, so replacing would silently skip a round.
        While ``pin_round`` holds serving back, staged rounds keep
        folding (counted as deferred) and apply the moment the pin lifts.
        """
        with self._swap_lock:
            if self._closed:
                return
            pend = self._pending_swap
            if pend is None:
                self._pending_swap = {
                    "updates": {
                        self._norm_swap_key(k): np.asarray(v, np.float32)
                        for k, v in updates.items()
                    },
                    "round": int(round_num),
                    "generation": int(generation),
                    "keep_previous": bool(keep_previous),
                    "staged_at": time.monotonic(),
                }
            else:
                acc = pend["updates"]
                for k, v in updates.items():
                    k = self._norm_swap_key(k)
                    arr = np.asarray(v, np.float32)
                    acc[k] = acc[k] + arr if k in acc else arr
                pend["round"] = int(round_num)
                pend["generation"] = int(generation)
                pend["keep_previous"] = bool(keep_previous)
            if (
                self.pinned_round is not None
                and int(round_num) > self.pinned_round
            ):
                self.swaps_deferred += 1
                SERVE_METRICS.swap_deferred.add(1)
        # Wake an idle serve loop so the flip doesn't wait for traffic.
        self._queue.put(_WAKE)

    def pin_round(self, round_num: int | None) -> None:
        """Rollback knob: pin serving to ``round_num`` — newer staged
        rounds defer (and keep folding) until unpinned (None). Pinning
        the PREVIOUS applied round restores it from the retained
        ``keep_previous`` snapshot at the next chunk boundary."""
        with self._swap_lock:
            self.pinned_round = (
                int(round_num) if round_num is not None else None
            )
            if (
                round_num is not None
                and self._prev_leaves is not None
                and self._prev_leaves[0] == int(round_num)
                and self.weight_round is not None
                and self.weight_round > int(round_num)
            ):
                self._pending_rollback = int(round_num)
        self._queue.put(_WAKE)

    def _reset_spec_state(self) -> None:
        """Per-lane speculation accept statistics were learned under the
        OLD weights: re-arm every lane optimistically instead of letting
        a stale low EWMA park it on plain decode after the model improved
        (tokens are greedy-verified either way — throughput only). The
        state is the SHARED n-gram + model-draft record, so a swap
        re-arms both proposers — a self-draft built from the new weights
        must not inherit an accept rate the old weights earned. The
        context/index caches stay: emitted tokens are facts."""
        for r in self._lane_rows.values():
            if r.spec.primed:
                r.spec.ewma = float(self.spec_draft)
            r.spec.cooldown = 0

    def _apply_swap(self) -> None:
        """Serve-thread only: flip ``self._vars`` to the staged round (or
        roll back to the pinned snapshot) at a chunk-boundary admission
        point. Device-preserving: only the fragment's named leaves move
        (replace_leaves), everything else aliases the live tree."""
        with self._swap_lock:
            pend = self._pending_swap
            rollback, self._pending_rollback = self._pending_rollback, None
            pinned = self.pinned_round
            if pend is not None and (
                pinned is not None and pend["round"] > pinned
            ):
                pend = None  # stays staged; folds until unpinned
            elif pend is not None:
                self._pending_swap = None
        if rollback is not None and self._prev_leaves is not None:
            prev_round, leaves = self._prev_leaves
            if prev_round == rollback:
                from .serialization import flat_leaf_map, replace_leaves

                # Fold the UNDONE delta (current - snapshot) back into the
                # pending accumulator before restoring: updates are
                # deltas, so once the pin lifts the flip must roll FORWARD
                # through the rolled-back round, not skip it (θ_r + u_{r+2}
                # is a model no trainer ever held).
                rolled_from = self.weight_round
                cur = flat_leaf_map(self._vars["params"])
                undo = {
                    name: np.asarray(cur[name], np.float32)
                    - np.asarray(old, np.float32)
                    for name, old in leaves.items()
                }
                with self._swap_lock:
                    pend2 = self._pending_swap
                    if pend2 is None:
                        self._pending_swap = {
                            "updates": undo,
                            "round": rolled_from,
                            "generation": self.weight_generation or 0,
                            "keep_previous": False,
                            "staged_at": time.monotonic(),
                        }
                    else:
                        acc = pend2["updates"]
                        for k, v in undo.items():
                            acc[k] = acc[k] + v if k in acc else v
                self._vars = {
                    **self._vars,
                    "params": replace_leaves(self._vars["params"], leaves),
                }
                self._prev_leaves = None
                with self._swap_lock:
                    self.weight_round = prev_round
                    self.swaps_rolled_back += 1
                self._alloc.bump_generation()
                self._reset_spec_state()
                SERVE_METRICS.swap_rolled_back.add(1)
                SERVE_METRICS.weight_state(
                    prev_round, self.weight_generation or 0
                )
        if pend is None:
            return
        from .serialization import flat_leaf_map, replace_leaves

        flat = flat_leaf_map(self._vars["params"])
        new = {}
        prev = {} if pend["keep_previous"] else None
        for name, u in pend["updates"].items():
            leaf = flat[name]  # KeyError = wire/tree mismatch: fail loud
            if prev is not None:
                prev[name] = leaf
            upd = jnp.asarray(u)
            new[name] = (
                leaf.astype(jnp.float32) + upd.astype(jnp.float32)
            ).astype(leaf.dtype)
        self._vars = {
            **self._vars,
            "params": replace_leaves(self._vars["params"], new),
        }
        if prev is not None:
            self._prev_leaves = (self.weight_round, prev)
        with self._swap_lock:
            self.weight_round = pend["round"]
            self.weight_generation = pend["generation"]
            self.swaps_applied += 1
        # Cached prefix blocks hold K/V computed under the old weights:
        # same token bytes, stale activations. Invalidate lazily — live
        # lanes keep their blocks until release, new admissions never
        # match a stale-generation chain.
        self._alloc.bump_generation()
        self._reset_spec_state()
        SERVE_METRICS.swap_applied.add(1)
        SERVE_METRICS.swap_finished(
            (time.monotonic() - pend["staged_at"]) * 1000.0
        )
        SERVE_METRICS.weight_state(pend["round"], pend["generation"])
        FLIGHT.record(
            "serve.weight_swap",
            round=pend["round"], generation=pend["generation"],
            live_rows=self.live_rows(),
        )

    # ------------------------------------------------------------ public

    def _pwin(self, n: int) -> int:
        """Paged window for an ``n``-token (resume) prompt: the smallest
        multiple of ``prefill_chunk`` that holds it (P-granular, not
        power-of-two — the paged prefill program has ONE shape)."""
        P = self.prefill_chunk
        return max(-(-max(n, 1) // P) * P, P)

    def _paged_reject(self, prompts: list, n_new: int) -> str | None:
        """Why the paged pool can never serve this request (None = fits).

        The window bound reserves ``prefill_chunk`` of slack because a
        preempted request resumes with its generated tokens folded into
        the prompt — the resume window can round up to one more chunk
        than the original (see _admit_paged)."""
        P = self.prefill_chunk
        longest = max(len(p) for p in prompts)
        limit = self._pwin(longest) + n_new + P
        if limit > self.max_len:
            return (
                f"paged window {self._pwin(longest)} + {n_new} new tokens "
                f"+ {P} resume slack exceed the pool window {self.max_len}"
            )
        need = len(prompts) * (-(-limit // self.block_size))
        if need > self.num_blocks:
            return (
                f"request needs up to {need} KV blocks but the pool has "
                f"{self.num_blocks}"
            )
        return None

    def fits(self, prompts: list, n_new: int) -> bool:
        """Would ``submit`` accept this request? Callers with a one-shot
        fallback (worker.continuous.PoolServer) route oversized requests
        there instead of erroring — the window path served any prompt up
        to the model limit, and pooling must not regress that."""
        if not prompts or any(not p for p in prompts):
            return False
        if len(prompts) > self.slots:
            return False
        if self._paged:
            return self._paged_reject(prompts, n_new) is None
        return _bucket(max(len(p) for p in prompts)) + n_new <= self.max_len

    def submit(
        self, prompts: list, n_new: int, traceparent: str | None = None
    ) -> Future:
        """Queue ``prompts`` for continuation; greedy, ``n_new`` tokens each.
        ``traceparent`` (serve-path tracing) parents the group's
        prefill/decode spans under the submitting request's trace."""
        fut: Future = Future()
        if not prompts or any(not p for p in prompts):
            fut.set_exception(ValueError("prompts must be non-empty"))
            return fut
        if len(prompts) > self.slots:
            fut.set_exception(
                ValueError(f"{len(prompts)} prompts exceed {self.slots} slots")
            )
            return fut
        if self._paged:
            reason = self._paged_reject(prompts, n_new)
            if reason is not None:
                fut.set_exception(ValueError(reason))
                return fut
        else:
            too_long = max(len(p) for p in prompts)
            if _bucket(too_long) + n_new > self.max_len:
                fut.set_exception(
                    ValueError(
                        f"prompt bucket {_bucket(too_long)} + {n_new} new "
                        f"tokens exceed the pool window {self.max_len}"
                    )
                )
                return fut
        # closed-check + enqueue as ONE atomic step against _fail_all's
        # drain: either this group lands before the drain (and is failed by
        # it), or the check sees _closed (always set before the drain runs)
        # and errors here — a caller's Future can never hang unresolved.
        with self._submit_lock:
            if self._closed:
                fut.set_exception(RuntimeError("pool is closed"))
                return fut
            if self.max_queue and self._backlog >= self.max_queue:
                # Reject-with-retry-after instead of unbounded queueing:
                # the hint scales with how far over the line we are.
                SERVE_METRICS.rejections.add(1)
                fut.set_exception(
                    PoolBusy(0.05 * (self._backlog - self.max_queue + 1))
                )
                return fut
            self.requests += 1
            self._backlog += 1
            group = _Group(prompts, int(n_new), fut)
            group.traceparent = traceparent
            group.t_submit = time.monotonic()
            self._queue.put(group)
        return fut

    def close(self, wait: bool = True) -> None:
        """Stop serving. ``wait=False`` returns immediately (the serve
        thread fails all in-flight futures as it exits) — the async cancel
        path must not park the worker's event loop behind a mid-chunk
        decode; heartbeats and lease renewals ride that loop."""
        self._closed = True
        self._queue.put(None)
        if wait:
            self._thread.join(timeout=30)

    def _fail_all(self, exc: Exception) -> None:
        """Serve-thread-side sweep: waiting, queued, and in-flight groups.

        Holds the submit lock for the drain: every submit that passed its
        closed-check has already enqueued (the check + put are atomic under
        the same lock), so nothing can slip in behind the sweep."""
        with self._submit_lock:
            while True:
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    break
                if item is not None and item is not _WAKE:
                    self._waiting.append(item)
            self._backlog = 0
        with self._ops_lock:
            ops, self._ops = self._ops, []
        for _fn, fut in ops:
            if not fut.done():
                fut.set_exception(exc)
        for g in self._waiting:
            if not g.fut.done():
                g.fut.set_exception(exc)
        self._waiting.clear()
        for row in self._rows.values():
            if not row.group.fut.done():
                row.group.fut.set_exception(exc)
        self._rows.clear()
        for prow in self._lane_rows.values():
            if not prow.group.fut.done():
                prow.group.fut.set_exception(exc)
        self._lane_rows.clear()

    # --------------------------------------------------------- jit pieces

    def _prefill_fn(self, k: int, L: int):
        fn = self._prefill_cache.get((k, L))
        if fn is not None:
            return fn
        dec = self._dec
        skel = jax.eval_shape(
            lambda: dec.init(jax.random.key(0), jnp.zeros((k, 1), jnp.int32))
        )["cache"]

        def prefill(variables, padded, start):
            cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), skel)
            cache = _set_rowvar(cache, "start", start)
            out = dec.apply(
                {**variables, "cache": cache}, padded, mutable=["cache"]
            )
            logits, vars_ = out
            if isinstance(logits, tuple):  # MoE: (logits, aux)
                logits = logits[0]
            first = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            return vars_["cache"], first

        fn = jax.jit(prefill)
        self._prefill_cache[(k, L)] = fn
        return fn

    def _insert_fn(self, k: int):
        fn = self._insert_cache.get(k)
        if fn is not None:
            return fn

        def insert(pool_cache, new_cache, rows, tok, first):
            merged = jax.tree.map(
                lambda p, n: p.at[rows].set(n[:k]), pool_cache, new_cache
            )
            return merged, tok.at[rows].set(first[:k])

        fn = jax.jit(insert, donate_argnums=(0, 3))
        self._insert_cache[k] = fn
        return fn

    def _chunk(self):
        if self._chunk_fn is not None:
            return self._chunk_fn
        dec = self._dec
        K = self.steps_per_call

        def chunk(variables, cache, tok):
            def step(carry, _):
                cache, tok = carry
                out = dec.apply(
                    {**variables, "cache": cache}, tok[:, None],
                    mutable=["cache"],
                )
                logits, vars_ = out
                if isinstance(logits, tuple):
                    logits = logits[0]
                nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                return (vars_["cache"], nxt), nxt

            (cache, tok), toks = jax.lax.scan(
                step, (cache, tok), None, length=K
            )
            return cache, tok, toks  # toks [K, slots]

        self._chunk_fn = jax.jit(chunk, donate_argnums=(1, 2))
        return self._chunk_fn

    def _sync(self):
        """One compiled setter for the host-owned row variables: idx, start
        and (paged) block table are data the host rewrites before every
        dispatched program."""
        if self._sync_fn is not None:
            return self._sync_fn

        def sync(cache, idx, start, table):
            def repl(path, leaf):
                key = getattr(path[-1], "key", None)
                if key == "idx":
                    return jnp.broadcast_to(idx, leaf.shape).astype(leaf.dtype)
                if key == "start":
                    return jnp.broadcast_to(start, leaf.shape).astype(
                        leaf.dtype
                    )
                if key == "table":
                    return jnp.broadcast_to(table, leaf.shape).astype(
                        leaf.dtype
                    )
                return leaf

            return jax.tree_util.tree_map_with_path(repl, cache)

        self._sync_fn = jax.jit(sync, donate_argnums=(0,))
        return self._sync_fn

    def _prefill_paged(self):
        """The chunked-prefill program: ONE shape ([slots, prefill_chunk])
        for every prompt length — it writes through the pool's block
        tables at each lane's current position, attending to the lane's
        already-prefilled keys. Idle lanes ride along parked at the
        ``max_len`` sentinel (their writes land in the garbage block).

        Returns the PER-COLUMN greedy next token ([slots, chunk]): the
        host reads the column of each lane's last real token (right-
        aligned prompts can end mid-chunk), and speculation reads every
        column — this program scoring K positions per dispatch IS the
        draft-verify step."""
        if self._prefill_paged_fn is not None:
            return self._prefill_paged_fn
        dec = self._dec

        def prefill(variables, cache, toks):
            out = dec.apply(
                {**variables, "cache": cache}, toks, mutable=["cache"]
            )
            logits, vars_ = out
            if isinstance(logits, tuple):  # MoE: (logits, aux)
                logits = logits[0]
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return vars_["cache"], nxt

        self._prefill_paged_fn = jax.jit(prefill, donate_argnums=(1,))
        return self._prefill_paged_fn

    def _copy_block(self):
        """Copy-on-write kernel: duplicate ONE physical block's K/V rows
        (fixed [1] shape — copies are rare, one compile total)."""
        if self._copy_fn is not None:
            return self._copy_fn
        bs = self.block_size

        def copy(cache, src, dst):
            return copy_blocks(cache, src, dst, bs)

        self._copy_fn = jax.jit(copy, donate_argnums=(0,))
        return self._copy_fn

    def _push_rowvars(self) -> None:
        self._cache = self._sync()(
            self._cache,
            jnp.asarray(self._h_idx),
            jnp.asarray(self._h_start),
            jnp.asarray(self._h_table),
        )

    # --------------------------------------------------------- serve loop

    def _serve_loop(self) -> None:
        try:
            while True:
                # Waiting groups count as live work: a preempted group must
                # be re-admitted when the pool drains, not when the NEXT
                # submit happens to wake the loop.
                live = (
                    bool(self._rows)
                    or bool(self._lane_rows)
                    or bool(self._waiting)
                )
                stop = False
                try:
                    item = self._queue.get(block=not live)
                    if item is None:
                        stop = True
                    elif item is not _WAKE:
                        self._waiting.append(item)
                    # drain anything else that queued meanwhile
                    while not stop:
                        try:
                            more = self._queue.get_nowait()
                        except queue.Empty:
                            break
                        if more is None:
                            stop = True
                        elif more is not _WAKE:
                            self._waiting.append(more)
                except queue.Empty:
                    pass
                if stop:
                    self._fail_all(RuntimeError("pool is closed"))
                    return
                # Chunk boundary: between dispatched programs is the one
                # place no decode step is in flight, so a staged weight
                # swap (or rollback) flips here — atomically w.r.t. every
                # program dispatched below.
                self._apply_swap()
                # Cross-thread ops (chain serving / injection) run at the
                # same boundary — after a staged swap flips, so a stamp
                # check inside an op sees the weights the NEXT program
                # will dispatch with.
                self._drain_ops()
                if self._paged:
                    self._step_paged()
                else:
                    self._admit()
                    if self._rows:
                        self._run_chunk()
        except Exception:
            log.exception("decode pool crashed")
            self._closed = True
            self._fail_all(RuntimeError("decode pool crashed"))

    def _admit(self) -> None:
        """Move waiting groups into free rows (FIFO, no overtaking — a big
        request at the head must not starve behind later small ones)."""
        while self._waiting and len(self._free) >= len(self._waiting[0].prompts):
            group = self._waiting.pop(0)
            with self._submit_lock:
                self._backlog -= 1
            self._admit_group(group)

    def _admit_group(self, group: _Group) -> None:
        k = len(group.prompts)
        L = _bucket(max(len(p) for p in group.prompts))
        kb = 1
        while kb < k:
            kb <<= 1
        padded = np.zeros((kb, L), np.int32)
        start = np.full((kb,), L, np.int32)  # dummy rows: empty window
        for i, p in enumerate(group.prompts):
            padded[i, L - len(p):] = p  # left-pad into the window
            start[i] = L - len(p)
        prefill = self._prefill_fn(kb, L)
        with trace.span(
            "prefill", parent=group.traceparent,
            attrs={"rows": k, "window": L},
        ):
            new_cache, first = prefill(
                self._vars, jnp.asarray(padded), jnp.asarray(start)
            )
        if group.trace_span is None:
            group.trace_span = trace.begin(
                "decode", parent=group.traceparent, attrs={"rows": k}
            )
        rows = [self._free.pop() for _ in range(k)]
        insert = self._insert_fn(k)
        self._cache, self._tok = insert(
            self._cache, new_cache, jnp.asarray(rows, jnp.int32),
            self._tok, first,
        )
        first_host = np.asarray(first[:k])
        group.admit_chunk = self.chunks
        for lane, slot in enumerate(rows):
            row = _Row(group, lane, group.n_new)
            row.emitted.append(int(first_host[lane]))
            self._rows[slot] = row
            group.rows[lane] = slot
        self._finish_done_rows()  # n_new == 1 completes at admission

    def _run_chunk(self) -> None:
        chunk = self._chunk()
        self._cache, self._tok, toks = chunk(self._vars, self._cache, self._tok)
        self.chunks += 1
        toks_host = np.asarray(toks)  # [K, slots] — the per-chunk sync
        for slot, row in list(self._rows.items()):
            for t in toks_host[:, slot]:
                if len(row.emitted) >= row.budget:
                    break
                row.emitted.append(int(t))
        self._finish_done_rows()

    def _row_finished(self, row) -> bool:
        """Budget/EOS completion check shared by both modes; pads an EOS
        row's emitted tokens to budget (matching generate())."""
        full = len(row.emitted) >= row.budget
        eos = self.eos_token_id
        saw_eos = eos is not None and eos in row.emitted
        if not (full or saw_eos):
            return False
        if saw_eos:
            cut = row.emitted.index(eos) + 1
            row.emitted = row.emitted[:cut] + [eos] * (row.budget - cut)
        row.done = True
        return True

    def _resolve_group(self, group: _Group) -> None:
        """All rows done: record latency, hand the tokens to the caller.
        One implementation for both modes — the completion contract (and
        its accounting) must not diverge paged vs fixed-slot."""
        group.finish_chunk = self.chunks
        trace.finish(group.trace_span)
        group.trace_span = None
        if group.fut.done():
            return
        if group.t_submit:
            SERVE_METRICS.request_finished(
                (time.monotonic() - group.t_submit) * 1e3
            )
        group.fut.set_result(
            [group.rows[i].emitted for i in range(len(group.prompts))]
        )

    def _finish_done_rows(self) -> None:
        for slot, row in list(self._rows.items()):
            if not self._row_finished(row):
                continue
            del self._rows[slot]
            self._free.append(slot)
            group = row.group
            group.rows[row.lane] = row
            if all(isinstance(r, _Row) and r.done for r in group.rows.values()):
                self._resolve_group(group)

    # ------------------------------------------------------- paged serving

    def _step_paged(self) -> None:
        """One serve-loop iteration in paged mode: admit what fits,
        advance chunked prefills + speculation verifies (one shared
        dispatch), then run one decode chunk for the remaining lanes —
        prefill and decode interleave, so a long prompt costs running
        requests at most one ``prefill_chunk`` program per decode chunk,
        never a monolithic prefill stall."""
        self._admit_paged()
        drafts: dict = {}
        spec: list = []
        speculating = self.spec_ngram > 0 or self.spec_model
        if speculating:
            for r in self._lane_rows.values():
                if r.pos < r.window or r.done:
                    continue
                d = self._propose(r)
                # None = no proposal (decode chunk); [] = zero-draft
                # verify (the budget-edge final token, see _propose).
                if d is not None:
                    spec.append(r)
                    drafts[id(r)] = d
        pre = [r for r in self._lane_rows.values() if r.pos < r.window]
        if pre or spec:
            self._run_prefill_chunk(pre, spec, drafts)
            self._finish_paged()
        specced = {id(r) for r in spec}
        if speculating:
            # A lane that completed prefill THIS step hasn't been seen by
            # the proposal loop yet — hold it out of this step's decode
            # chunk so its first generation step can be a verify (matters
            # at the budget edge: a 2-token request ships entirely as
            # prefill + zero-draft verify, never paying a decode chunk).
            specced |= {id(r) for r in pre}
        dec = [
            r
            for r in self._lane_rows.values()
            if r.pos >= r.window and not r.done and id(r) not in specced
        ]
        if dec:
            self._run_decode_chunk(dec)
            self._finish_paged()
        SERVE_METRICS.pool_state(self.free_blocks(), self.queue_depth())
        if self.prefix_cache:
            SERVE_METRICS.cache_state(
                self._alloc.cached_count(), self._alloc.shared_count()
            )
        if self.fleet_cache:
            # Refreshed here (serve thread), read whole by the heartbeat
            # thread — a single attribute load, no locking needed.
            self.fleet_digest = self._alloc.hot_chains(self.digest_k)

    def _admit_paged(self) -> None:
        """FIFO block-granular admission: the head group is admitted when
        it has lanes AND its uncached prompt-region blocks fit above the
        watermark reserve (held back so live requests can grow). An empty
        pool admits anything that fits the absolute bound — the reserve
        must not park the only customer.

        With the prefix cache on, each lane maps the longest cached
        prefix of its (resume) prompt into its table refcounted and
        prefill starts at the first uncached position — capped one token
        short of the end, so the last prompt token always recomputes (its
        logits are the first generated token)."""
        bs = self.block_size
        while self._waiting:
            group = self._waiting[0]
            if not group.rows:
                for lane, p in enumerate(group.prompts):
                    group.rows[lane] = _PRow(
                        group, lane, list(p), group.n_new
                    )
            live = [r for r in group.rows.values() if not r.done]
            if len(live) > len(self._free_lanes):
                break
            # Budget fresh blocks per lane net of cached-prefix hits;
            # hits parked in the LRU leave the allocatable pool when
            # mapped, so they count like fresh blocks.
            need = 0
            plans = []
            for r in live:
                full = r.prompt + r.emitted  # recompute-resume prompt
                hashes = (
                    chain_hashes(full, bs) if self.prefix_cache else []
                )
                hits, in_lru = self._alloc.peek(hashes)
                lane_blocks = -(-len(full) // bs)
                need += lane_blocks - hits + in_lru
                plans.append((r, full, hashes, lane_blocks))
            free = self._alloc.free_count()
            if free < need:
                break
            if self._lane_rows and free - need < self.reserve_blocks:
                break
            self._waiting.pop(0)
            with self._submit_lock:
                self._backlog -= 1
            self._admit_seq += 1
            group.order = self._admit_seq
            group.admit_chunk = self.chunks
            if group.trace_span is None:
                group.trace_span = trace.begin(
                    "decode", parent=group.traceparent,
                    attrs={"rows": len(live)},
                )
            for r, full, hashes, lane_blocks in plans:
                r.slot = self._free_lanes.pop()
                hit = self._alloc.lookup(hashes)
                fresh = [
                    self._alloc.alloc()
                    for _ in range(lane_blocks - len(hit))
                ]
                if any(b is None for b in fresh):
                    # peek() budgeted every mapped-LRU hit as consumed
                    # headroom, so this cannot happen; fail loudly over
                    # corrupting a table with a None id.
                    raise RuntimeError("paged admission accounting broke")
                r.blocks = hit + fresh
                r.window = len(full)
                r.pos = min(len(hit) * bs, len(full) - 1)
                r.hashed = len(hit)
                r.chain_h = hashes[len(hit) - 1] if hit else 0
                r.win_tokens = np.zeros(
                    (len(full) + self.prefill_chunk,), np.int32
                )
                r.win_tokens[: len(full)] = full
                self._lane_rows[r.slot] = r
                self._h_start[r.slot] = 0
                self._h_table[r.slot, :] = self.num_blocks
                self._h_table[r.slot, : len(r.blocks)] = r.blocks
                if self.prefix_cache:
                    SERVE_METRICS.prefix_hit_blocks.add(len(hit))
                    SERVE_METRICS.prefix_miss_blocks.add(
                        len(hashes) - len(hit)
                    )
            SERVE_METRICS.admissions.add(1)

    def _propose(self, r: _PRow) -> "list | None":
        """Draft tokens for one verify dispatch, or ``None`` for a plain
        decode chunk. The n-gram proposer runs first (free — host-side
        lookup), the model draft backs it up on traffic the prompt can't
        predict; both sit behind ONE cooldown/EWMA gate (``r.spec``), so
        accept-rate backoff is a property of the lane, not the proposer.

        Budget edge: a verify dispatch emits drafts + 1 bonus token, so
        drafts cap one short of the remaining budget. At exactly ONE
        remaining token that cap is zero — but the verify program still
        emits the bonus token, so the final token of every speculating
        row ships as a zero-draft verify (``[]``, one prefill-shaped
        dispatch) instead of dragging the whole pool through a K-step
        decode chunk for one kept token. ``[]`` bypasses the cooldown
        gate (nothing is being speculated) and skips the EWMA update in
        the verifier — it must neither cost a proposal nor count as one.
        Both proposer paths share this boundary by construction: it is
        decided before either runs."""
        remaining = r.budget - len(r.emitted)
        cap = min(self.spec_draft, remaining - 1)
        if remaining == 1 and self.spec_draft > 0:
            return []
        if cap <= 0:
            return None
        if r.spec.cooldown > 0:
            r.spec.cooldown -= 1
            return None
        if not r.spec.primed:
            r.spec.primed = True
            r.spec.ewma = float(self.spec_draft)  # start optimistic
        d = self._propose_ngram(r, cap) if self.spec_ngram > 0 else None
        if d is None and self.spec_model:
            d = self._propose_model(r, cap)
        return d

    def _propose_ngram(self, r: _PRow, cap: int) -> "list | None":
        """Prompt-lookup drafting (n-gram speculation, no draft model):
        find an earlier occurrence of the context's final ``spec_ngram``
        tokens and propose the tokens that followed it — repetitive
        output (templates, code, chat echoes) drafts itself.

        Match policy: the NEAREST occurrence with a full draft window
        after it, else the leftmost (longest continuation) — the
        occurrence adjacent to the tail always matches trivially but has
        almost nothing to copy. Lookup is O(log occurrences) over an
        incrementally maintained position index; lanes whose drafts keep
        missing back off to plain decode chunks (``spec.cooldown``), so
        low-repetition traffic floors at the non-speculative pool."""
        import bisect

        n = self.spec_ngram
        ctx = self._spec_ctx(r)
        if len(ctx) <= n:
            return None
        # Index interior positions only (i <= len-n-1): the tail's own
        # position must not match itself. Positions append in ascending
        # order, so each bucket stays sorted for the bisect below.
        for i in range(r.spec.indexed, len(ctx) - n):
            r.spec.index.setdefault(tuple(ctx[i : i + n]), []).append(i)
        r.spec.indexed = max(r.spec.indexed, len(ctx) - n)
        positions = r.spec.index.get(tuple(ctx[-n:]))
        if not positions:
            return None
        # Largest i with a full window (i + n + cap <= len), else the
        # leftmost occurrence.
        k = bisect.bisect_right(positions, len(ctx) - n - cap) - 1
        best = positions[k] if k >= 0 else positions[0]
        return ctx[best + n : best + n + cap] or None

    def _spec_ctx(self, r: _PRow) -> list:
        """The lane's token context (prompt + emitted), cached and
        extended incrementally — shared by both proposers."""
        if r.spec.ctx is None:
            r.spec.ctx = list(r.prompt)
            r.spec.index = {}
            r.spec.indexed = 0
        base = len(r.prompt)
        if len(r.spec.ctx) - base < len(r.emitted):
            r.spec.ctx.extend(r.emitted[len(r.spec.ctx) - base :])
        return r.spec.ctx

    def _draft_vars(self) -> dict:
        """Variables for the draft forward. Explicit draft params are
        static; the self-draft (``spec_layers``) filters the LIVE served
        tree on every call — host-side dict surgery over aliased device
        arrays, so an applied weight swap reaches the draft at the very
        next proposal with no copy and no staleness window."""
        if self._draft_params is not None:
            return self._draft_params
        keep = {}
        for k, v in self._vars["params"].items():
            if k.startswith("layers_"):
                try:
                    if int(k[7:]) >= self.spec_layers:
                        continue
                except ValueError:
                    pass
            keep[k] = v
        return {"params": keep}

    def _draft_forward(self):
        """Jitted cache-less draft forward: [1, W] tokens -> per-column
        greedy argmax. ONE static shape for the pool's lifetime."""
        if self._draft_fn is not None:
            return self._draft_fn
        dmodel = self._draft_model

        def fwd(variables, toks):
            out = dmodel.apply(variables, toks)
            logits = out[0] if isinstance(out, tuple) else out  # MoE aux
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)

        self._draft_fn = jax.jit(fwd)
        return self._draft_fn

    def _propose_model(self, r: _PRow, cap: int) -> "list | None":
        """Model-draft proposal: grow ``cap`` draft tokens by running the
        draft model's cache-less causal forward over a static [1, W]
        window holding the context tail, appending its greedy next token
        each step. The window truncates long contexts and restarts RoPE
        positions at 0 — that only costs accept rate; every emitted
        token still comes from the verify program, so correctness is
        position-exact regardless of what the draft saw."""
        W = self._draft_window
        cap = min(cap, W - 1)
        if cap <= 0:
            return None
        ctx = self._spec_ctx(r)
        L = max(min(len(ctx), W - cap), 1)
        buf = np.zeros((1, W), np.int32)
        buf[0, :L] = ctx[-L:]
        fwd = self._draft_forward()
        variables = self._draft_vars()
        draft = []
        pos = L
        for _ in range(cap):
            step = fwd(variables, jnp.asarray(buf))
            nxt = int(np.asarray(step)[0, pos - 1])
            draft.append(nxt)
            if pos >= W:
                break
            buf[0, pos] = nxt
            pos += 1
        return draft or None

    def _register_lane(self, r: _PRow) -> None:
        """Register ``r``'s newly FULL blocks in the prefix cache: a
        block's content is final once every one of its positions is
        written with tokens the request actually carries (``r.pos`` is
        the written extent; positions past ``prompt+emitted`` hold
        budget-overrun continuation tokens that nothing hashes)."""
        if not self.prefix_cache:
            return
        bs = self.block_size
        full_len = len(r.prompt) + len(r.emitted)
        nfull = min(min(r.pos, full_len) // bs, len(r.blocks))
        if nfull <= r.hashed:
            return
        full = r.prompt + r.emitted
        h = r.chain_h
        for j in range(r.hashed, nfull):
            h = hash((h, tuple(full[j * bs : (j + 1) * bs])))
            self._alloc.register(r.blocks[j], h)
        r.chain_h = h
        r.hashed = nfull

    def _cow_for_write(self, r: _PRow, pos: int, span: int) -> bool:
        """Make the blocks a write of ``[pos, pos + span)`` will touch
        privately writable: copy-on-write any block still shared with
        another lane (ops.kvcache.copy_blocks), and un-register a
        privately held cached block about to be overwritten. False =
        the pool could not supply a copy target (treated like decode
        exhaustion by the caller)."""
        if not self.prefix_cache:
            return True
        bs = self.block_size
        hi = min(pos + span, len(r.blocks) * bs)
        for bi in range(pos // bs, -(-hi // bs)):
            b = r.blocks[bi]
            if self._alloc.is_shared(b):
                nb = self._alloc.alloc()
                while nb is None:
                    victim = self._pick_victim(exclude=r.group)
                    if victim is None:
                        return False
                    self._preempt(victim)
                    nb = self._alloc.alloc()
                self._cache = self._copy_block()(
                    self._cache,
                    jnp.asarray([b], jnp.int32),
                    jnp.asarray([nb], jnp.int32),
                )
                self._alloc.release(b)
                r.blocks[bi] = nb
                self._h_table[r.slot, bi] = nb
                SERVE_METRICS.cow_copies.add(1)
            elif self._alloc.is_registered(b):
                # Sole owner (ref 1), overwriting in place. The expected
                # such write is the capped-hit recompute of the final
                # prompt token (pos == len(full)-1 inside the terminal
                # hit block): it rewrites byte-identical K/V — the
                # block's chain hash covers that very token — so the
                # registration stays valid and exact-repeat traffic
                # keeps hitting it. Any OTHER overwrite of a registered
                # block would diverge from the hashed content: drop the
                # registration rather than serve a corrupt cache entry.
                full_len = len(r.prompt) + len(r.emitted)
                identical = (
                    pos == full_len - 1
                    and bi == pos // bs
                    and bi < r.hashed
                )
                if not identical:
                    self._alloc.forget(b)
        return True

    def _run_prefill_chunk(
        self, pre: list, spec: list = (), drafts: dict | None = None
    ) -> None:
        """One [slots, prefill_chunk] dispatch serving BOTH chunked
        prefills and speculation verifies: prefilling lanes consume the
        next window slice; speculating lanes consume [current token,
        draft...] and accept the greedy-matched prefix plus one bonus
        token from the per-column argmax."""
        P = self.prefill_chunk
        # Allocation + CoW settle membership first: growing a spec lane
        # (or copying a shared block) can preempt a group that is in
        # these very lists.
        for r in list(spec):
            if r.slot < 0 or r.done:
                continue
            d = drafts[id(r)]
            ok = self._grow(r, target=r.pos + 1 + len(d))
            ok = ok and self._cow_for_write(r, r.pos, 1 + len(d))
            if not ok:
                self._fail_group(r.group, RuntimeError("paged pool exhausted"))
        for r in list(pre):
            if r.slot < 0 or r.done:
                continue
            if not self._cow_for_write(r, r.pos, P):
                self._fail_group(r.group, RuntimeError("paged pool exhausted"))
        pre = [r for r in pre if r.slot >= 0 and not r.done]
        spec = [r for r in spec if r.slot >= 0 and not r.done]
        if not pre and not spec:
            return
        toks = np.zeros((self.slots, P), np.int32)
        self._h_idx[:] = self.max_len  # park every lane in the garbage block
        for r in pre:
            toks[r.slot] = r.win_tokens[r.pos : r.pos + P]
            self._h_idx[r.slot] = r.pos
        for r in spec:
            x = [r.emitted[-1]] + drafts[id(r)]
            toks[r.slot, : len(x)] = x
            self._h_idx[r.slot] = r.pos
        self._push_rowvars()
        # A paged prefill chunk can serve several groups; parent on the
        # first row's request (chunks are FIFO, so it is the oldest).
        t0 = time.monotonic()
        with trace.span(
            "prefill",
            parent=(pre + spec)[0].group.traceparent,
            attrs={"rows": len(pre) + len(spec), "chunk": P,
                   "spec_rows": len(spec)},
        ):
            self._cache, nxt = self._prefill_paged()(
                self._vars, self._cache, jnp.asarray(toks)
            )
        if pre:
            self.prefill_chunks += 1
        if spec:
            self.spec_chunks += 1
        nxt_host = np.asarray(nxt)  # [slots, P] per-column greedy tokens
        if pre:
            # Measured prefill throughput (host sync above closes the
            # dispatch): the recompute side of the transfer-vs-recompute
            # policy. Spec verifies share the program but not the shape
            # of a resume prefill, so only prefill lanes count.
            dt = time.monotonic() - t0
            if dt > 0:
                rate = P * len(pre) / dt
                self._prefill_rate = (
                    rate
                    if self._prefill_rate == 0
                    else 0.7 * self._prefill_rate + 0.3 * rate
                )
        for r in pre:
            base = r.pos
            r.pos = min(r.pos + P, r.window)
            if r.pos >= r.window:
                # The column of the last (resume-)prompt token: its
                # argmax is the first generated token, exactly the
                # monolithic prefill's output.
                r.emitted.append(int(nxt_host[r.slot, r.window - 1 - base]))
            self._register_lane(r)
        for r in spec:
            d = drafts[id(r)]
            row = nxt_host[r.slot]
            a = 0
            while a < len(d) and int(row[a]) == d[a]:
                a += 1
            # d[:a] is greedy-confirmed; row[a] is the model's token
            # after the accepted prefix — the bonus that guarantees >= 1
            # token of progress per verify. Token-identical to plain
            # decode by construction.
            got = d[:a] + [int(row[a])]
            r.emitted.extend(got[: r.budget - len(r.emitted)])
            r.pos += a + 1
            if d:
                SERVE_METRICS.spec_proposed.add(len(d))
                SERVE_METRICS.spec_accepted.add(a)
                # Accept-rate backoff: a verify averaging < 1 accepted
                # draft is worse than a decode chunk in every regime (1
                # token per wide dispatch vs K per chunk). Lanes whose
                # drafts keep missing sit out 8 iterations of plain
                # decode, then retry fresh — incidental repeats in
                # low-repetition traffic cannot pin a lane to the verify
                # path. One EWMA per LANE: n-gram and model drafts feed
                # it alike (SpeculationState). A zero-draft budget-edge
                # verify (d == []) skips this block entirely — it
                # proposed nothing, so it must not count as a hit or a
                # miss.
                r.spec.ewma = 0.5 * r.spec.ewma + 0.5 * a
                if r.spec.ewma < 1.0:
                    r.spec.cooldown = 8
                    r.spec.ewma = float(self.spec_draft)  # optimism on retry
            self._register_lane(r)

    def _grow(self, r: _PRow, target: int | None = None) -> bool:
        """Allocate the blocks the next decode chunk (or speculation
        verify, via ``target``) will write for ``r``, preempting the
        youngest other group when the pool is dry."""
        if target is None:
            remaining = max(r.budget - len(r.emitted), 0)
            target = r.pos + min(self.steps_per_call, remaining)
        need = -(-target // self.block_size)
        while len(r.blocks) < need:
            b = self._alloc.alloc()
            if b is None:
                victim = self._pick_victim(exclude=r.group)
                if victim is None:
                    return False
                self._preempt(victim)
                continue
            self._h_table[r.slot, len(r.blocks)] = b
            r.blocks.append(b)
        return True

    def _pick_victim(self, exclude: _Group) -> _Group | None:
        """The most recently admitted live group (vLLM's preemption order:
        the youngest request has the least sunk decode cost to recompute)."""
        victims: dict[int, _Group] = {}
        for r in self._lane_rows.values():
            if r.group is not exclude:
                victims[id(r.group)] = r.group
        if not victims:
            return None
        return max(victims.values(), key=lambda g: g.order)

    def _release_lane(self, r: _PRow, *, register: bool) -> None:
        """Return ``r``'s lane and blocks to the pool. ``register=True``
        (preemption) hashes its full blocks into the prefix cache first,
        so releasing refcounts parks them in the LRU and the resume
        re-admission becomes a cache hit instead of a full recompute.
        Finished rows pass ``register=False`` — their blocks were already
        registered at the chunk boundaries that filled them (before
        :meth:`_row_finished` EOS-padding rewrote ``emitted``)."""
        if register:
            self._register_lane(r)
        # Tail-first: the LRU evicts oldest-first, and a chain is useless
        # without its head — releasing deepest blocks first means eviction
        # eats cached chains from the END, leaving the surviving prefix
        # still hittable (evicting block 0 first would orphan the rest).
        for b in reversed(r.blocks):
            self._alloc.release(b)
        self._h_table[r.slot, :] = self.num_blocks
        self._h_idx[r.slot] = self.max_len
        self._lane_rows.pop(r.slot, None)
        self._free_lanes.append(r.slot)
        r.slot = -1
        r.blocks = []
        r.pos = 0
        r.window = 0
        r.win_tokens = None
        r.hashed = 0
        r.chain_h = 0
        r.spec = SpeculationState()

    def _preempt(self, group: _Group) -> None:
        """Preemption-to-queue with recompute resume: free the group's
        lanes and blocks, park it at the HEAD of the waiting line; its
        emitted tokens fold into the resume prompt at re-admission, so
        greedy continuation is token-identical to an uncontended run.
        With the prefix cache on, the freed full blocks stay cached, so
        the resume re-prefills only the uncached tail.

        With KV migration on, a single-prompt victim whose link beats
        local recompute ships instead: its computed blocks + cursor +
        emitted tokens leave for the router-named target and the group
        exits this pool's books entirely (the async sender resolves the
        future from the target's MigrateAck, or requeues the group here
        on any failure — exactly this method's recompute path)."""
        if self._try_migrate(group):
            return
        for r in list(group.rows.values()):
            if r.slot < 0 or r.done:
                continue
            self._release_lane(r, register=True)
        self._waiting.insert(0, group)
        with self._submit_lock:
            self._backlog += 1
        self.preemptions += 1
        SERVE_METRICS.preemptions.add(1)
        FLIGHT.record(
            "serve.preempt", rows=len(group.rows), order=group.order,
            emitted=sum(len(r.emitted) for r in group.rows.values()),
        )

    def _try_migrate(self, group: _Group) -> bool:
        """Attempt to ship a preemption victim instead of requeueing it.
        Single-prompt groups only (one lane's state travels as one
        MigrateRequest); multi-prompt groups keep recompute-resume. True
        = the group left this pool's books (sender owns its future)."""
        if not (
            self.kv_migration
            and self._migrate_policy is not None
            and self._migrate_send is not None
            and len(group.prompts) == 1
        ):
            return False
        r = group.rows.get(0)
        if r is None or r.slot < 0 or r.done:
            return False
        bs = self.block_size
        full = r.prompt + r.emitted
        nfull = min(min(r.pos, len(full)) // bs, len(r.blocks))
        if nfull <= 0:
            return False  # nothing computed worth shipping
        try:
            target = self._migrate_policy(
                nfull * self._block_nbytes(), len(full)
            )
        except Exception:  # noqa: BLE001 — policy is a worker hook
            log.exception("migrate policy failed; recompute-resume")
            return False
        if target is None:
            return False  # recompute wins (or no router hint yet)
        hashes = chain_hashes(full, bs)[:nfull]
        leaves = extract_blocks(self._cache, r.blocks[:nfull], bs)
        wr, wg = self.weight_state()
        ticket = {
            "group": group,
            "prompt": list(r.prompt),
            "emitted": list(r.emitted),
            "budget": max(r.budget - len(r.emitted), 0),
            "hashes": hashes,
            "block_size": bs,
            "leaves": leaves,
            "weight_round": wr,
            "weight_generation": wg,
            "target": target,
        }
        self._release_lane(r, register=True)
        self.preemptions += 1
        self.migrated_out += 1
        SERVE_METRICS.preemptions.add(1)
        FLIGHT.record(
            "serve.migrate_out", order=group.order, blocks=nfull,
            emitted=len(ticket["emitted"]),
        )
        try:
            self._migrate_send(ticket)
        except Exception:  # noqa: BLE001 — sender is a worker hook
            log.exception("migrate send failed; recompute-resume")
            self.requeue_migrated(group)
        return True

    def requeue_migrated(self, group: _Group) -> None:
        """Any-thread fallback: a migration attempt failed (target busy,
        stale generation, link died) — hand the group back to the serve
        loop for plain recompute-resume, today's preemption behavior."""
        with self._submit_lock:
            if self._closed:
                if not group.fut.done():
                    group.fut.set_exception(RuntimeError("pool is closed"))
                return
            self._backlog += 1
            self._queue.put(group)

    def complete_migrated(self, group: _Group, tokens: list) -> None:
        """Any-thread completion: the migration target decoded the rest
        of the budget — resolve the original client future with
        ``emitted-before-preempt + remote continuation`` (same latency
        accounting as a locally finished group)."""
        r = group.rows[0]
        r.emitted = list(r.emitted) + [int(t) for t in tokens]
        r.done = True
        trace.finish(group.trace_span)
        group.trace_span = None
        if group.fut.done():
            return
        if group.t_submit:
            SERVE_METRICS.request_finished(
                (time.monotonic() - group.t_submit) * 1e3
            )
        group.fut.set_result([r.emitted])

    def _run_decode_chunk(self, dec: list) -> None:
        K = self.steps_per_call
        for r in list(dec):
            if r.slot < 0 or r.done:  # preempted by an earlier _grow
                continue
            if not self._grow(r):
                # Defensive: fits() bounds every group's worst-case block
                # need, so a sole live group always grows. Fail loudly
                # rather than wedge the serve loop.
                self._fail_group(
                    r.group, RuntimeError("paged pool exhausted")
                )
        live = [r for r in dec if r.slot >= 0 and not r.done]
        for r in list(live):
            # Defensive CoW sweep: decode writes land past the hit
            # boundary by construction, but a shared block in the write
            # range must never be scribbled on.
            if not self._cow_for_write(r, r.pos, K):
                self._fail_group(r.group, RuntimeError("paged pool exhausted"))
        live = [r for r in live if r.slot >= 0 and not r.done]
        if not live:
            return
        tok = np.zeros((self.slots,), np.int32)
        self._h_idx[:] = self.max_len
        for r in live:
            tok[r.slot] = r.emitted[-1]
            self._h_idx[r.slot] = r.pos
        self._push_rowvars()
        chunk = self._chunk()
        self._cache, _, toks = chunk(
            self._vars, self._cache, jnp.asarray(tok)
        )
        self.chunks += 1
        # Occupancy telemetry for THIS dispatch: blocks the kernel
        # actually attended vs blocks the lanes hold vs the dense-gather
        # worst case (every live lane × max_blocks). With ragged off the
        # gather always pays the worst case — the attended/capacity gap
        # is exactly the work ragged attention skips.
        max_blocks = self.max_len // self.block_size
        allocated = sum(len(r.blocks) for r in live)
        capacity = len(live) * max_blocks
        attended = allocated if self.ragged else capacity
        SERVE_METRICS.attention_state(attended, allocated, capacity)
        toks_host = np.asarray(toks)  # [K, slots]
        for r in live:
            for t in toks_host[:, r.slot]:
                if len(r.emitted) >= r.budget:
                    break
                r.emitted.append(int(t))
            r.pos += K
            self._register_lane(r)

    def _fail_group(self, group: _Group, exc: Exception) -> None:
        for r in list(group.rows.values()):
            if r.slot >= 0:
                self._release_lane(r, register=False)
        if not group.fut.done():
            group.fut.set_exception(exc)

    def _finish_paged(self) -> None:
        for slot, r in list(self._lane_rows.items()):
            if r.pos < r.window:
                continue  # still prefilling
            if not self._row_finished(r):
                continue
            self._release_lane(r, register=False)
            group = r.group
            if all(pr.done for pr in group.rows.values()):
                self._resolve_group(group)
