"""Continuous batching: iteration-level scheduling over a fixed KV-slot pool.

The window batcher (worker.batcher) coalesces SIMULTANEOUS greedy requests
but runs one decode at a time behind a chip lock: a request arriving 1 ms
after a 128-token decode starts waits the entire decode before its bucket
runs, and finished rows hold their batch position to the end (VERDICT r4
weak #4). This module is the industry-standard fix, built TPU-native:

  * a **fixed pool** of ``slots`` KV rows with a static ``max_len`` window
    each — one compiled decode program for the whole lifetime of the job
    (no dynamic shapes, no retracing);
  * the decode loop advances ALL rows one token per step, ``steps_per_call``
    steps per dispatched program (`lax.scan`), returning to the host at
    each chunk boundary;
  * at every boundary, waiting requests are **admitted into free rows**
    (their prompts prefill into a standalone bucket-shaped cache that is
    scattered into the pool), and rows that reached their budget or EOS
    are **released** — a request arriving mid-decode starts within
    ``steps_per_call`` tokens instead of after the in-flight decode;
  * per-row cache indices and left-pad starts (ops.kvcache per-row mode)
    let rows sit at different sequence positions inside one program —
    the pool's whole point.

Greedy only: sampled rows would draw from a shared key and their outputs
would depend on batch composition, breaking seeded reproducibility (the
same policy as worker.batcher, which remains the sampled/fallback path).

The reference has no inference path at all (its Executor union is
Train|Aggregate, crates/messages/src/lib.rs:627-631) — this is net-new
capability, benchmarked in SERVBENCH (late-arrival p50 + aggregate tok/s).
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["DecodePool", "supports_pool"]

log = logging.getLogger("hypha.executor.pool")


def supports_pool(model: Any) -> bool:
    """Does this model family implement per-row decode? (Llama lineage —
    Llama/Mistral/Qwen2/Gemma configs — and Mixtral share the per-row
    attention; GPT-2's learned-position decode path is scalar-only.)"""
    return hasattr(model, "per_row_decode")


def _bucket(n: int, lo: int = 16) -> int:
    b = lo
    while b < n:
        b <<= 1
    return b


def _set_rowvar(cache, name: str, value):
    """Replace every cache leaf called ``name`` (idx/start vectors)."""

    def repl(path, leaf):
        key = path[-1]
        if getattr(key, "key", None) == name:
            return jnp.broadcast_to(value, leaf.shape).astype(leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(repl, cache)


@dataclass
class _Row:
    group: "_Group"
    lane: int  # which prompt of the group this row serves
    budget: int
    emitted: list = field(default_factory=list)
    done: bool = False


@dataclass
class _Group:
    prompts: list
    n_new: int
    fut: Future
    rows: dict = field(default_factory=dict)  # lane -> slot
    admit_chunk: int = -1
    finish_chunk: int = -1


class DecodePool:
    """One serving pool: owns the chip from a dedicated thread.

    ``submit`` is thread-safe and returns a concurrent.futures.Future that
    resolves to one token list per prompt (async callers wrap it with
    ``asyncio.wrap_future``). ``close()`` drains nothing: queued and
    in-flight requests fail fast, matching the window batcher's contract.
    """

    def __init__(
        self,
        model: Any,
        params: Any,
        *,
        slots: int = 8,
        max_len: int = 512,
        steps_per_call: int = 8,
        eos_token_id: int | None = None,
    ) -> None:
        if not supports_pool(model):
            raise ValueError(
                f"{type(model).__name__} has no per-row decode path"
            )
        self._model = model
        self._dec = dataclasses.replace(
            model, decode=True, decode_len=max_len, per_row_decode=True
        )
        if isinstance(params, dict) and "params" in params:
            self._vars = dict(params)
        else:
            self._vars = {"params": params}
        self.slots = slots
        self.max_len = max_len
        self.steps_per_call = steps_per_call
        self.eos_token_id = eos_token_id

        # Pool cache + current-token vector live on device for the whole
        # job; everything else is host bookkeeping.
        skel = jax.eval_shape(
            lambda: self._dec.init(
                jax.random.key(0), jnp.zeros((slots, 1), jnp.int32)
            )
        )["cache"]
        self._cache = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), skel
        )
        self._tok = jnp.zeros((slots,), jnp.int32)

        self._rows: dict[int, _Row] = {}
        self._free = list(range(slots))
        self._queue: "queue.Queue[_Group | None]" = queue.Queue()
        self._waiting: list[_Group] = []
        # Guards the closed-check + enqueue in submit() against the serve
        # thread's final drain in _fail_all(): without it, a submit that
        # passed the check could enqueue AFTER the drain and its Future
        # would never resolve.
        self._submit_lock = threading.Lock()
        self._closed = False
        self.chunks = 0  # decode programs dispatched (test/bench hook)
        self.requests = 0
        self._prefill_cache: dict = {}
        self._insert_cache: dict = {}
        self._chunk_fn = None
        self._thread = threading.Thread(
            target=self._serve_loop, name="decode-pool", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------ public

    def fits(self, prompts: list, n_new: int) -> bool:
        """Would ``submit`` accept this request? Callers with a one-shot
        fallback (worker.continuous.PoolServer) route oversized requests
        there instead of erroring — the window path served any prompt up
        to the model limit, and pooling must not regress that."""
        if not prompts or any(not p for p in prompts):
            return False
        if len(prompts) > self.slots:
            return False
        return _bucket(max(len(p) for p in prompts)) + n_new <= self.max_len

    def submit(self, prompts: list, n_new: int) -> Future:
        """Queue ``prompts`` for continuation; greedy, ``n_new`` tokens each."""
        fut: Future = Future()
        if not prompts or any(not p for p in prompts):
            fut.set_exception(ValueError("prompts must be non-empty"))
            return fut
        if len(prompts) > self.slots:
            fut.set_exception(
                ValueError(f"{len(prompts)} prompts exceed {self.slots} slots")
            )
            return fut
        too_long = max(len(p) for p in prompts)
        if _bucket(too_long) + n_new > self.max_len:
            fut.set_exception(
                ValueError(
                    f"prompt bucket {_bucket(too_long)} + {n_new} new tokens "
                    f"exceed the pool window {self.max_len}"
                )
            )
            return fut
        # closed-check + enqueue as ONE atomic step against _fail_all's
        # drain: either this group lands before the drain (and is failed by
        # it), or the check sees _closed (always set before the drain runs)
        # and errors here — a caller's Future can never hang unresolved.
        with self._submit_lock:
            if self._closed:
                fut.set_exception(RuntimeError("pool is closed"))
                return fut
            self.requests += 1
            self._queue.put(_Group(prompts, int(n_new), fut))
        return fut

    def close(self, wait: bool = True) -> None:
        """Stop serving. ``wait=False`` returns immediately (the serve
        thread fails all in-flight futures as it exits) — the async cancel
        path must not park the worker's event loop behind a mid-chunk
        decode; heartbeats and lease renewals ride that loop."""
        self._closed = True
        self._queue.put(None)
        if wait:
            self._thread.join(timeout=30)

    def _fail_all(self, exc: Exception) -> None:
        """Serve-thread-side sweep: waiting, queued, and in-flight groups.

        Holds the submit lock for the drain: every submit that passed its
        closed-check has already enqueued (the check + put are atomic under
        the same lock), so nothing can slip in behind the sweep."""
        with self._submit_lock:
            while True:
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    break
                if item is not None:
                    self._waiting.append(item)
        for g in self._waiting:
            if not g.fut.done():
                g.fut.set_exception(exc)
        self._waiting.clear()
        for row in self._rows.values():
            if not row.group.fut.done():
                row.group.fut.set_exception(exc)
        self._rows.clear()

    # --------------------------------------------------------- jit pieces

    def _prefill_fn(self, k: int, L: int):
        fn = self._prefill_cache.get((k, L))
        if fn is not None:
            return fn
        dec = self._dec
        skel = jax.eval_shape(
            lambda: dec.init(jax.random.key(0), jnp.zeros((k, 1), jnp.int32))
        )["cache"]

        def prefill(variables, padded, start):
            cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), skel)
            cache = _set_rowvar(cache, "start", start)
            out = dec.apply(
                {**variables, "cache": cache}, padded, mutable=["cache"]
            )
            logits, vars_ = out
            if isinstance(logits, tuple):  # MoE: (logits, aux)
                logits = logits[0]
            first = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            return vars_["cache"], first

        fn = jax.jit(prefill)
        self._prefill_cache[(k, L)] = fn
        return fn

    def _insert_fn(self, k: int):
        fn = self._insert_cache.get(k)
        if fn is not None:
            return fn

        def insert(pool_cache, new_cache, rows, tok, first):
            merged = jax.tree.map(
                lambda p, n: p.at[rows].set(n[:k]), pool_cache, new_cache
            )
            return merged, tok.at[rows].set(first[:k])

        fn = jax.jit(insert, donate_argnums=(0, 3))
        self._insert_cache[k] = fn
        return fn

    def _chunk(self):
        if self._chunk_fn is not None:
            return self._chunk_fn
        dec = self._dec
        K = self.steps_per_call

        def chunk(variables, cache, tok):
            def step(carry, _):
                cache, tok = carry
                out = dec.apply(
                    {**variables, "cache": cache}, tok[:, None],
                    mutable=["cache"],
                )
                logits, vars_ = out
                if isinstance(logits, tuple):
                    logits = logits[0]
                nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                return (vars_["cache"], nxt), nxt

            (cache, tok), toks = jax.lax.scan(
                step, (cache, tok), None, length=K
            )
            return cache, tok, toks  # toks [K, slots]

        self._chunk_fn = jax.jit(chunk, donate_argnums=(1, 2))
        return self._chunk_fn

    # --------------------------------------------------------- serve loop

    def _serve_loop(self) -> None:
        try:
            while True:
                live = bool(self._rows)
                stop = False
                try:
                    item = self._queue.get(block=not live)
                    if item is None:
                        stop = True
                    else:
                        self._waiting.append(item)
                    # drain anything else that queued meanwhile
                    while not stop:
                        try:
                            more = self._queue.get_nowait()
                        except queue.Empty:
                            break
                        if more is None:
                            stop = True
                        else:
                            self._waiting.append(more)
                except queue.Empty:
                    pass
                if stop:
                    self._fail_all(RuntimeError("pool is closed"))
                    return
                self._admit()
                if self._rows:
                    self._run_chunk()
        except Exception:
            log.exception("decode pool crashed")
            self._closed = True
            self._fail_all(RuntimeError("decode pool crashed"))

    def _admit(self) -> None:
        """Move waiting groups into free rows (FIFO, no overtaking — a big
        request at the head must not starve behind later small ones)."""
        while self._waiting and len(self._free) >= len(self._waiting[0].prompts):
            group = self._waiting.pop(0)
            self._admit_group(group)

    def _admit_group(self, group: _Group) -> None:
        k = len(group.prompts)
        L = _bucket(max(len(p) for p in group.prompts))
        kb = 1
        while kb < k:
            kb <<= 1
        padded = np.zeros((kb, L), np.int32)
        start = np.full((kb,), L, np.int32)  # dummy rows: empty window
        for i, p in enumerate(group.prompts):
            padded[i, L - len(p):] = p  # left-pad into the window
            start[i] = L - len(p)
        prefill = self._prefill_fn(kb, L)
        new_cache, first = prefill(
            self._vars, jnp.asarray(padded), jnp.asarray(start)
        )
        rows = [self._free.pop() for _ in range(k)]
        insert = self._insert_fn(k)
        self._cache, self._tok = insert(
            self._cache, new_cache, jnp.asarray(rows, jnp.int32),
            self._tok, first,
        )
        first_host = np.asarray(first[:k])
        group.admit_chunk = self.chunks
        for lane, slot in enumerate(rows):
            row = _Row(group, lane, group.n_new)
            row.emitted.append(int(first_host[lane]))
            self._rows[slot] = row
            group.rows[lane] = slot
        self._finish_done_rows()  # n_new == 1 completes at admission

    def _run_chunk(self) -> None:
        chunk = self._chunk()
        self._cache, self._tok, toks = chunk(self._vars, self._cache, self._tok)
        self.chunks += 1
        toks_host = np.asarray(toks)  # [K, slots] — the per-chunk sync
        for slot, row in list(self._rows.items()):
            for t in toks_host[:, slot]:
                if len(row.emitted) >= row.budget:
                    break
                row.emitted.append(int(t))
        self._finish_done_rows()

    def _finish_done_rows(self) -> None:
        eos = self.eos_token_id
        for slot, row in list(self._rows.items()):
            full = len(row.emitted) >= row.budget
            saw_eos = eos is not None and eos in row.emitted
            if not (full or saw_eos):
                continue
            if saw_eos:  # pad to budget with eos, matching generate()
                cut = row.emitted.index(eos) + 1
                row.emitted = row.emitted[:cut] + [eos] * (
                    row.budget - cut
                )
            row.done = True
            del self._rows[slot]
            self._free.append(slot)
            group = row.group
            group.rows[row.lane] = row
            if all(isinstance(r, _Row) and r.done for r in group.rows.values()):
                group.finish_chunk = self.chunks
                if not group.fut.done():
                    group.fut.set_result(
                        [group.rows[i].emitted for i in range(len(group.prompts))]
                    )
