"""GPT-2 decoder (BASELINE configs 1-2: GPT-2-small DiLoCo).

Native flax definition with an HF-compatible architecture (learned position
embeddings, pre-LayerNorm blocks, gelu MLP, tied LM head) so HF ``gpt2``
checkpoints convert 1:1 (hypha_tpu.models.registry). Activations run in a
configurable dtype (bf16 on TPU); layer norms and softmax in f32.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import flax.linen as nn
import jax.numpy as jnp

from ..ops.attention import dot_product_attention

__all__ = ["GPT2", "GPT2Config"]


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    layer_norm_epsilon: float = 1e-5  # HF gpt2 parity
    dtype: str = "bfloat16"
    # Gradient checkpointing: recompute each block in the backward instead
    # of keeping activations — HBM for FLOPs, the standard big-model trade
    # (jax.checkpoint / nn.remat per transformer block).
    remat: bool = False

    @classmethod
    def small(cls) -> "GPT2Config":
        return cls()

    @classmethod
    def tiny(cls) -> "GPT2Config":
        """CI-sized config for CPU tests."""
        return cls(vocab_size=256, n_positions=128, n_embd=64, n_layer=2, n_head=4)


class _Block(nn.Module):
    config: GPT2Config
    attn_impl: Callable | None = None
    decode: bool = False  # KV-cached serving forward (see models/llama.py)
    decode_len: int = 0

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        B, S, E = x.shape
        h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=jnp.float32, name="ln_1")(x).astype(dtype)
        qkv = nn.Dense(3 * E, dtype=dtype, name="c_attn")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        hd = E // cfg.n_head
        q = q.reshape(B, S, cfg.n_head, hd)
        k = k.reshape(B, S, cfg.n_head, hd)
        v = v.reshape(B, S, cfg.n_head, hd)
        if self.decode:
            from ..ops.kvcache import update_kv_cache

            full_k, full_v, offset = update_kv_cache(
                self, k.astype(dtype), v.astype(dtype), self.decode_len
            )
            attn = dot_product_attention(
                q, full_k, full_v, causal=True, q_offset=offset
            )
        else:
            attn = (self.attn_impl or dot_product_attention)(q, k, v, causal=True)
        attn = attn.reshape(B, S, E)
        x = x + nn.Dense(E, dtype=dtype, name="c_proj")(attn)

        h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=jnp.float32, name="ln_2")(x).astype(dtype)
        h = nn.Dense(4 * E, dtype=dtype, name="c_fc")(h)
        h = nn.gelu(h)
        x = x + nn.Dense(E, dtype=dtype, name="mlp_proj")(h)
        return x


class GPT2(nn.Module):
    config: GPT2Config = GPT2Config()
    attn_impl: Callable | None = None  # e.g. the pallas flash kernel
    decode: bool = False  # serving mode: KV-cached autoregressive forward
    decode_len: int = 0
    # with_head=False returns the final hidden states [B, S, E] instead of
    # logits — the fused/chunked-CE training path computes the vocab
    # projection inside the loss so full-width [B, S, V] logits never
    # materialize (executor.train.chunked_causal_ce).
    with_head: bool = True

    @nn.compact
    def __call__(self, input_ids: jnp.ndarray) -> jnp.ndarray:
        """input_ids [B, S] -> logits [B, S, vocab] (f32), or final hidden
        states when ``with_head=False``."""
        import jax

        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        B, S = input_ids.shape
        wte = self.param(
            "wte", nn.initializers.normal(0.02), (cfg.vocab_size, cfg.n_embd), jnp.float32
        )
        wpe = self.param(
            "wpe", nn.initializers.normal(0.01), (cfg.n_positions, cfg.n_embd), jnp.float32
        )
        if self.decode:
            pos = self.variable("cache", "pos", lambda: jnp.zeros((), jnp.int32))
            pe = jax.lax.dynamic_slice(wpe, (pos.value, 0), (S, cfg.n_embd))
            pos.value = pos.value + S
            x = (wte[input_ids] + pe[None]).astype(dtype)
        else:
            x = (wte[input_ids] + wpe[None, :S]).astype(dtype)
        block_cls = nn.remat(_Block) if cfg.remat and not self.decode else _Block
        for i in range(cfg.n_layer):
            x = block_cls(
                cfg, self.attn_impl, self.decode, self.decode_len, name=f"h_{i}"
            )(x)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=jnp.float32, name="ln_f")(x)
        if not self.with_head:
            return x
        # tied LM head: logits against the embedding matrix, f32 for the loss
        return jnp.einsum("bse,ve->bsv", x.astype(jnp.float32), wte)
