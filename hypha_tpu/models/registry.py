"""Model registry: resolve a job's model spec to a flax module.

The reference maps 38 ``ModelType`` variants to HF ``AutoModelFor*`` classes
(executors/accelerate/.../model.py:48-123). Here every variant resolves:
the flagship families (GPT-2, Llama + its Mistral/Qwen2/Gemma descendants,
Mixtral, LeNet) are native JAX definitions; the 14 types with an HF **Flax**
head resolve through the hf fallback family (torch checkpoints convert via
``from_pt``); the remaining torch-only-head types resolve through the
``heads`` family — JAX task heads over Flax backbones (models/heads.py),
mirroring HF's own random-init-the-missing-head fine-tuning behavior.

A model spec is the ``model`` dict of a TrainExecutorConfig:
  {"model_type": ModelType, "family": "gpt2"|"llama"|"mixtral"|"lenet"|"hf",
   "config": {...family config overrides...}, "preset": "tiny"|"small"|...}
"""

from __future__ import annotations

from typing import Any

from ..messages import ModelType
from .gpt2 import GPT2, GPT2Config
from .lenet import LeNet, LeNetConfig
from .llama import Llama, LlamaConfig
from .mixtral import Mixtral, MixtralConfig

__all__ = ["build_model", "resolve_model_type", "FAMILIES"]

_PRESETS = {
    "gpt2": {"tiny": GPT2Config.tiny, "small": GPT2Config.small},
    "llama": {"tiny": LlamaConfig.tiny, "llama2-7b": LlamaConfig.llama2_7b},
    "mixtral": {"tiny": MixtralConfig.tiny, "8x7b": MixtralConfig.mixtral_8x7b},
    "lenet": {"default": LeNetConfig},
}

FAMILIES = {
    "gpt2": (GPT2, GPT2Config),
    "llama": (Llama, LlamaConfig),
    # Llama-architecture descendants HF ships no Flax port for — the
    # reference reaches them via torch AutoModel (model.py:48-123); here
    # they are the native Llama module under family-specific config defaults
    # with converted torch weights (models.convert).
    "mistral": (Llama, LlamaConfig),
    "qwen2": (Llama, LlamaConfig),
    "qwen3": (Llama, LlamaConfig),
    "gemma": (Llama, LlamaConfig),
    "mixtral": (Mixtral, MixtralConfig),
    "lenet": (LeNet, LeNetConfig),
}

# Architecture toggles implied by the family name.
_FAMILY_DEFAULTS: dict[str, dict[str, Any]] = {
    "qwen2": {"attn_bias": True},
    "qwen3": {"qk_norm": True},
    "gemma": {
        "mlp_act": "gelu_tanh",
        "rms_offset": True,
        "embed_scale": True,
        "tie_word_embeddings": True,
    },
}


def resolve_model_type(model_type: ModelType | str) -> ModelType:
    if isinstance(model_type, ModelType):
        return model_type
    return ModelType(model_type)


def _head_types():
    from .heads import HEAD_TYPES

    return HEAD_TYPES


def build_model(spec: dict[str, Any], attn_impl=None):
    """Build (module, config) from a job's model spec."""
    family = spec.get("family")
    if family is None:
        mt = resolve_model_type(spec.get("model_type", ModelType.CAUSAL_LM))
        if mt in _head_types():
            family = "heads"
        else:
            family = {
                ModelType.CAUSAL_LM: "gpt2",
                ModelType.IMAGE_CLASSIFICATION: "lenet",
            }.get(mt, "hf")
    if family == "hf":
        from .hf import build_hf_model

        mt = resolve_model_type(spec.get("model_type", ModelType.CAUSAL_LM))
        return build_hf_model(spec, mt)
    if family == "heads":
        from .heads import build_head_model

        mt = resolve_model_type(spec.get("model_type", ModelType.CAUSAL_LM))
        return build_head_model(spec, mt)
    if family not in FAMILIES:
        raise ValueError(f"unknown model family {family!r}")
    module_cls, config_cls = FAMILIES[family]
    preset = spec.get("preset")
    hf_config = spec.get("hf_config")
    if preset is not None:
        presets = _PRESETS.get(family, {})
        if preset not in presets:
            raise KeyError(
                f"unknown preset {preset!r} for family {family!r} "
                f"(have {sorted(presets) or 'none'})"
            )
        cfg = presets[preset]()
    elif hf_config is not None and hasattr(config_cls, "from_hf"):
        # A fetched checkpoint's config.json fields drive the native config.
        # The family name stands in for a missing model_type so from_hf can
        # derive architecture toggles (gemma/qwen2) even from a bare field
        # dict — otherwise a caller-supplied hf_config without model_type
        # would silently build plain-Llama architecture.
        hf = dict(hf_config)
        hf.setdefault("model_type", family)
        cfg = config_cls.from_hf(hf)
    else:
        cfg = config_cls()
    # Family defaults fill gaps only when NO checkpoint config drove the
    # build — from_hf already derives architecture toggles from the
    # config.json (and may legitimately disagree with the defaults, e.g. an
    # untied-head gemma variant).
    base = {} if hf_config is not None else _FAMILY_DEFAULTS.get(family, {})
    overrides = {**base, **(spec.get("config") or {})}
    if overrides:
        import dataclasses

        cfg = dataclasses.replace(cfg, **overrides)
    if family == "lenet":  # no attention to plug
        return module_cls(cfg), cfg
    return module_cls(cfg, attn_impl), cfg
