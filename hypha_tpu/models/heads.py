"""Task-head family: the ModelTypes HF ships no Flax head for.

The reference resolves every ``ModelType`` through a torch ``AutoModelFor*``
class (executors/accelerate/.../model.py:48-123). Fourteen of them have HF
Flax auto-classes (models/hf.py); the remaining types have torch-only heads.
HF's own behavior when a checkpoint lacks the task head is to random-init it
with a warning and fine-tune — so the TPU-native equivalent is a **JAX task
head over a Flax backbone**: the backbone (ViT / BERT / Wav2Vec2 / CLIP /
Whisper, all with Flax implementations) loads pretrained or from-config, and
a small linen head — randomly initialized, exactly like HF's missing-head
path — maps its features to the task output. Types with no usable Flax
backbone at all (time series, TTS) are native JAX models end to end.

Head designs are TPU-first, not torch-ports:

* dense prediction (segmentation / depth / keypoints / image-to-image) is a
  SETR-style linear decoder over the ViT patch grid + ``jax.image.resize``
  — one big matmul on the MXU instead of a conv-decoder cascade;
* detection is an FCOS-style dense per-patch head (class + box + centerness)
  — anchor-free and jit-static, no Hungarian matching host round-trip;
* zero-shot heads reuse CLIP's joint space (patch/image embeddings against
  text embeddings) the OWL-ViT way;
* layout (document QA) and table (table QA) conditioning are late-fusion
  embedding adds — LayoutLM/TAPAS-style extra embeddings, fused after the
  text backbone because Flax BERT takes token ids only;
* audio heads (frame classification / x-vector) follow Wav2Vec2's heads:
  per-frame linear, and mean+std statistics pooling respectively.

Each built model follows the framework protocol (``init(rng, inputs) ->
params`` / ``apply(params, inputs, rng=, batch=) -> logits``) so the jitted
train step, Δθ shipping, and checkpointing are family-agnostic. Tasks whose
objective is not a plain ``Loss`` variant expose ``custom_loss(out, batch)``
which the train step picks up (executor/train.py).
"""

from __future__ import annotations

import logging
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..messages import ModelType

__all__ = ["build_head_model", "HEAD_TYPES", "HeadedModel"]

log = logging.getLogger("hypha.models.heads")


# --------------------------------------------------------------------------
# Backbones: thin adapters from HF Flax models to feature tensors.
# --------------------------------------------------------------------------

_BACKBONE_DEFAULTS = {
    # modality → (HF model_type, tiny config fields for from-config builds)
    "text": ("bert", dict(vocab_size=1024, hidden_size=64, num_hidden_layers=2,
                          num_attention_heads=4, intermediate_size=128,
                          max_position_embeddings=512)),
    "vision": ("vit", dict(hidden_size=64, num_hidden_layers=2,
                           num_attention_heads=4, intermediate_size=128,
                           image_size=32, patch_size=8, num_channels=3)),
    "audio": ("wav2vec2", dict(hidden_size=64, num_hidden_layers=2,
                               num_attention_heads=4, intermediate_size=128,
                               conv_dim=(32, 32), conv_stride=(4, 4),
                               conv_kernel=(8, 8), num_feat_extract_layers=2,
                               num_conv_pos_embeddings=16,
                               num_conv_pos_embedding_groups=4,
                               # Flax Wav2Vec2 only implements the
                               # stable-layer-norm encoder variant.
                               do_stable_layer_norm=True,
                               feat_extract_norm="layer")),
    "clip": ("clip", dict(
        text_config=dict(vocab_size=1024, hidden_size=64, num_hidden_layers=2,
                         num_attention_heads=4, intermediate_size=128,
                         max_position_embeddings=64),
        vision_config=dict(hidden_size=64, num_hidden_layers=2,
                           num_attention_heads=4, intermediate_size=128,
                           image_size=32, patch_size=8),
        projection_dim=64,
    )),
}


class _Backbone:
    """HF Flax model (FlaxAutoModel / FlaxCLIPModel) → hidden states."""

    def __init__(self, hf_model: Any, modality: str) -> None:
        self.model = hf_model
        self.modality = modality
        self.config = hf_model.config

    @property
    def hidden_size(self) -> int:
        cfg = self.config
        return getattr(cfg, "hidden_size", None) or cfg.text_config.hidden_size

    @property
    def params(self):
        return self.model.params

    def __call__(self, params, inputs, *, rng=None, **kw):
        kwargs: dict[str, Any] = {"params": params}
        if rng is not None:
            kwargs["dropout_rng"] = rng
            kwargs["train"] = True
        if self.modality == "vision":
            kwargs["pixel_values"] = inputs
        elif self.modality == "audio":
            kwargs["input_values"] = inputs
        else:
            kwargs["input_ids"] = inputs
        kwargs.update(kw)
        out = self.model(**kwargs)
        return out.last_hidden_state  # [B, T, H]


def _load_flax_model(cls, spec: dict, make_config, what: str):
    """Shared loader for every heads-family HF Flax model: pretrained from
    ``spec['path']`` (torch checkpoints sniffed and converted), else
    random-init from ``make_config()`` with the job seed; params become jax
    arrays once so the first jitted step pays no per-leaf host transfer."""
    from .hf import _has_flax_weights  # same checkpoint-format sniffing

    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        spec.get("dtype", "float32")
    ]
    path = spec.get("path")
    if path:
        from pathlib import Path

        model = cls.from_pretrained(
            str(path), dtype=dtype,
            from_pt=not _has_flax_weights(Path(path)), local_files_only=True,
        )
        log.info("heads: loaded %s from %s", what, path)
    else:
        seed = int(spec.get("seed", 0))
        config = make_config()
        if hasattr(cls, "from_config"):  # Auto classes build via from_config
            model = cls.from_config(config, dtype=dtype, seed=seed)
        else:
            model = cls(config, dtype=dtype, seed=seed)
        log.info("heads: random-initialized tiny %s", what)
    model.params = jax.tree.map(jnp.asarray, model.params)
    return model


def _build_backbone(spec: dict, modality: str) -> _Backbone:
    """Pretrained from ``spec['path']`` or tiny-config otherwise (tests /
    from-scratch jobs); ``spec['backbone']`` overrides config fields."""
    import transformers

    cls = transformers.FlaxCLIPModel if modality == "clip" else transformers.FlaxAutoModel

    def make_config():
        mt, defaults = _BACKBONE_DEFAULTS[modality]
        fields = {**defaults, **(spec.get("backbone") or {})}
        if modality == "clip":
            return transformers.CLIPConfig(
                text_config=fields["text_config"],
                vision_config=fields["vision_config"],
                projection_dim=fields["projection_dim"],
            )
        return transformers.AutoConfig.for_model(mt, **fields)

    model = _load_flax_model(cls, spec, make_config, f"{modality} backbone")
    return _Backbone(model, modality)


def _patch_grid(cfg) -> tuple[int, int]:
    g = int(cfg.image_size) // int(cfg.patch_size)
    return g, g


# --------------------------------------------------------------------------
# Head modules (linen) — small, MXU-friendly maps from features to outputs.
# --------------------------------------------------------------------------


class PooledHead(nn.Module):
    """mean-pool → Dense: sequence/clip-level classification."""

    num_labels: int

    @nn.compact
    def __call__(self, feats: jnp.ndarray) -> jnp.ndarray:  # [B, T, H]
        return nn.Dense(self.num_labels, name="classifier")(feats.mean(axis=1))


class FrameHead(nn.Module):
    """Per-frame linear: audio frame classification (Wav2Vec2 head shape)."""

    num_labels: int

    @nn.compact
    def __call__(self, feats: jnp.ndarray) -> jnp.ndarray:  # [B, T, H]
        return nn.Dense(self.num_labels, name="classifier")(feats)


class XVectorHead(nn.Module):
    """Statistics pooling (mean ‖ std) → embedding → class logits."""

    num_labels: int
    embed_dim: int = 128

    @nn.compact
    def __call__(self, feats: jnp.ndarray) -> jnp.ndarray:
        mean = feats.mean(axis=1)
        std = jnp.sqrt(feats.var(axis=1) + 1e-7)
        x = jnp.concatenate([mean, std], axis=-1)
        x = nn.relu(nn.Dense(self.embed_dim, name="embedding")(x))
        return nn.Dense(self.num_labels, name="classifier")(x)


class DenseGridHead(nn.Module):
    """SETR-style linear decoder: per-patch Dense → reshape to the patch
    grid → bilinear resize to pixel resolution. One matmul, then a resize —
    the whole decoder stays on the MXU/VPU."""

    out_channels: int
    grid: tuple[int, int]
    out_size: tuple[int, int]

    @nn.compact
    def __call__(self, feats: jnp.ndarray) -> jnp.ndarray:  # [B, 1+P, H]
        gh, gw = self.grid
        patches = feats[:, 1:, :] if feats.shape[1] == gh * gw + 1 else feats
        x = nn.Dense(self.out_channels, name="decoder")(patches)  # [B, P, C]
        x = x.reshape(x.shape[0], gh, gw, self.out_channels)
        return jax.image.resize(
            x, (x.shape[0], *self.out_size, self.out_channels), "bilinear"
        )  # [B, H, W, C]


class DetectionHead(nn.Module):
    """FCOS-style dense head over the patch grid: per-patch class logits
    (num_classes + background at index 0), box ltrb offsets (via softplus,
    in patch units) and centerness. Anchor-free and shape-static — no
    Hungarian matching, so train steps stay one fused XLA program."""

    num_classes: int
    grid: tuple[int, int]

    @nn.compact
    def __call__(self, feats: jnp.ndarray) -> dict[str, jnp.ndarray]:
        gh, gw = self.grid
        patches = feats[:, 1:, :] if feats.shape[1] == gh * gw + 1 else feats
        x = nn.relu(nn.Dense(patches.shape[-1], name="tower")(patches))
        cls = nn.Dense(self.num_classes + 1, name="cls")(x)  # [B, P, C+1]
        ltrb = nn.softplus(nn.Dense(4, name="box")(x))  # [B, P, 4] >= 0
        ctr = nn.Dense(1, name="centerness")(x)[..., 0]  # [B, P]
        return {"cls": cls, "ltrb": ltrb, "centerness": ctr}


class FusionHead(nn.Module):
    """Two-stream fusion (CLIP image ‖ text) → MLP → answer logits (VQA)."""

    num_labels: int
    hidden: int = 256

    @nn.compact
    def __call__(self, img: jnp.ndarray, txt: jnp.ndarray) -> jnp.ndarray:
        x = jnp.concatenate([img, txt, img * txt], axis=-1)
        x = nn.gelu(nn.Dense(self.hidden, name="fuse")(x))
        return nn.Dense(self.num_labels, name="classifier")(x)


class SpanHead(nn.Module):
    """Extractive-QA span head with optional late-fusion side embeddings:
    layout bboxes (LayoutLM-style, buckets 0..1023) or table row/column ids
    (TAPAS-style). Fused after the backbone (Flax BERT takes ids only), then
    one transformer block re-mixes tokens with the side signal."""

    side: str | None = None  # None | "bbox" | "table"
    num_buckets: int = 1024
    table_max: int = 256
    num_heads: int = 4

    @nn.compact
    def __call__(self, feats: jnp.ndarray, batch: Any) -> jnp.ndarray:
        # Side-stream embed params must exist whether or not this call's
        # batch carries the stream (init passes batch=None) — create them
        # unconditionally, feed zeros when the stream is absent.
        h = feats.shape[-1]
        B, T = feats.shape[:2]
        if self.side == "bbox":
            bbox = (batch or {}).get("bbox")
            if bbox is None:
                bbox = jnp.zeros((B, T, 4), jnp.int32)
            emb = nn.Embed(self.num_buckets, h, name="bbox_embed")
            feats = feats + emb(jnp.clip(bbox, 0, self.num_buckets - 1)).sum(axis=2)
        if self.side == "table":
            rows = (batch or {}).get("row_ids")
            cols = (batch or {}).get("column_ids")
            zeros = jnp.zeros((B, T), jnp.int32)
            feats = feats + nn.Embed(self.table_max, h, name="row_embed")(
                jnp.clip(rows if rows is not None else zeros, 0, self.table_max - 1)
            )
            feats = feats + nn.Embed(self.table_max, h, name="col_embed")(
                jnp.clip(cols if cols is not None else zeros, 0, self.table_max - 1)
            )
        attn = nn.SelfAttention(num_heads=self.num_heads, name="mix")(feats)
        feats = nn.LayerNorm(name="mix_norm")(feats + attn)
        return nn.Dense(2, name="qa_outputs")(feats)  # [B, T, 2] start/end


class CellSelectionHead(nn.Module):
    """TAPAS-style: token-level cell-selection logit + aggregation-op
    logits from the [CLS] position."""

    num_agg_ops: int = 4
    table_max: int = 256

    @nn.compact
    def __call__(self, feats: jnp.ndarray, batch: Any) -> dict[str, jnp.ndarray]:
        h = feats.shape[-1]
        zeros = jnp.zeros(feats.shape[:2], jnp.int32)
        rows = (batch or {}).get("row_ids")
        cols = (batch or {}).get("column_ids")
        feats = feats + nn.Embed(self.table_max, h, name="row_embed")(
            jnp.clip(rows if rows is not None else zeros, 0, self.table_max - 1)
        )
        feats = feats + nn.Embed(self.table_max, h, name="col_embed")(
            jnp.clip(cols if cols is not None else zeros, 0, self.table_max - 1)
        )
        select = nn.Dense(1, name="select")(feats)[..., 0]  # [B, T]
        agg = nn.Dense(self.num_agg_ops, name="aggregation")(feats[:, 0, :])
        return {"select": select, "aggregation": agg}


# --------------------------------------------------------------------------
# Native models (no Flax backbone exists for these modalities).
# --------------------------------------------------------------------------


class _EncoderBlock(nn.Module):
    num_heads: int

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        h = x.shape[-1]
        a = nn.SelfAttention(num_heads=self.num_heads, name="attn")(
            nn.LayerNorm(name="ln1")(x)
        )
        x = x + a
        m = nn.Dense(h * 4, name="up")(nn.LayerNorm(name="ln2")(x))
        return x + nn.Dense(h, name="down")(nn.gelu(m))


class TimeSeriesModel(nn.Module):
    """PatchTST-style native forecaster: patchify the context window →
    linear embed → transformer encoder → flatten → linear horizon map.
    The reference reaches time series via torch AutoModel; this is the
    TPU-native counterpart (big batched matmuls, static shapes)."""

    horizon: int = 24
    patch: int = 8
    d_model: int = 128
    layers: int = 2
    heads: int = 4

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:  # [B, T, C]
        B, T, C = x.shape
        P = T // self.patch
        x = x.transpose(0, 2, 1).reshape(B * C, P, self.patch)
        x = nn.Dense(self.d_model, name="patch_embed")(x)
        x = x + self.param(
            "pos", nn.initializers.normal(0.02), (P, self.d_model)
        )
        for i in range(self.layers):
            x = _EncoderBlock(self.heads, name=f"block{i}")(x)
        x = x.reshape(B * C, P * self.d_model)
        y = nn.Dense(self.horizon, name="forecast")(x)  # [B*C, horizon]
        return y.reshape(B, C, self.horizon).transpose(0, 2, 1)  # [B, Hz, C]


class TextToSpectrogramModel(nn.Module):
    """FastSpeech-style non-autoregressive TTS: token embed + encoder →
    fixed-ratio length regulator (upsample) → decoder → mel frames.
    Non-autoregressive on purpose: the whole utterance is one static-shape
    batched matmul pipeline (MXU), not a sequential decode loop."""

    vocab_size: int = 256
    n_mels: int = 80
    upsample: int = 4  # frames per input token
    d_model: int = 128
    layers: int = 2
    heads: int = 4
    waveform_hop: int = 0  # >0: add a conv-transpose vocoder → waveform

    @nn.compact
    def __call__(self, ids: jnp.ndarray) -> jnp.ndarray:  # [B, T] int
        x = nn.Embed(self.vocab_size, self.d_model, name="embed")(ids)
        for i in range(self.layers):
            x = _EncoderBlock(self.heads, name=f"enc{i}")(x)
        # Length regulation: each token expands to ``upsample`` frames.
        x = jnp.repeat(x, self.upsample, axis=1)  # [B, T*r, D]
        for i in range(self.layers):
            x = _EncoderBlock(self.heads, name=f"dec{i}")(x)
        mel = nn.Dense(self.n_mels, name="mel")(x)  # [B, T*r, M]
        if not self.waveform_hop:
            return mel
        w = mel
        hop = self.waveform_hop
        # Two transposed convs: M → hop samples per frame.
        w = nn.ConvTranspose(32, (4,), strides=(hop // 2,), name="up1")(w)
        w = nn.gelu(w)
        w = nn.ConvTranspose(1, (4,), strides=(2,), name="up2")(w)
        return w[..., 0]  # [B, samples]


# --------------------------------------------------------------------------
# Losses for tasks whose objective is not a plain Loss variant.
# --------------------------------------------------------------------------


def _ctc_loss(logits: jnp.ndarray, batch: Any) -> jnp.ndarray:
    """optax CTC over frame logits; paddings from masks or all-valid."""
    import optax

    labels = batch["labels"]
    logit_pad = batch.get("logit_paddings")
    if logit_pad is None:
        logit_pad = jnp.zeros(logits.shape[:2], jnp.float32)
    label_pad = batch.get("label_paddings")
    if label_pad is None:
        label_pad = (labels < 0).astype(jnp.float32)
    safe = jnp.maximum(labels, 0)
    per_seq = optax.ctc_loss(
        logits.astype(jnp.float32), logit_pad, safe, label_pad
    )
    return per_seq.mean()


def _detection_loss(out: dict, batch: Any) -> jnp.ndarray:
    """FCOS-style dense assignment, fully vectorized: each patch center is
    assigned the smallest gt box containing it ([B,P,N] containment mask →
    argmin area); class CE (background where unassigned) + L1 on ltrb +
    centerness BCE on positives."""
    cls, ltrb, ctr = out["cls"], out["ltrb"], out["centerness"]
    B, P, _ = cls.shape
    g = int(P**0.5)
    boxes = batch["boxes"].astype(jnp.float32)  # [B, N, 4] xyxy in [0,1]
    labels = batch["labels"]  # [B, N] int, -100 pads
    valid = (labels != -100)[:, None, :]  # [B, 1, N]

    xs = (jnp.arange(g, dtype=jnp.float32) + 0.5) / g
    cx = jnp.tile(xs, (g,))  # [P] col-major x
    cy = jnp.repeat(xs, g)
    l = cx[None, :, None] - boxes[:, None, :, 0]  # noqa: E741 — ltrb naming
    t = cy[None, :, None] - boxes[:, None, :, 1]
    r = boxes[:, None, :, 2] - cx[None, :, None]
    b = boxes[:, None, :, 3] - cy[None, :, None]
    inside = (l > 0) & (t > 0) & (r > 0) & (b > 0) & valid  # [B, P, N]
    area = (boxes[:, :, 2] - boxes[:, :, 0]) * (boxes[:, :, 3] - boxes[:, :, 1])
    area = jnp.where(inside, area[:, None, :], jnp.inf)
    best = jnp.argmin(area, axis=-1)  # [B, P]
    pos = inside.any(axis=-1)  # [B, P]

    tgt_cls = jnp.where(
        pos, jnp.take_along_axis(labels, best, axis=1) + 1, 0
    )  # background = 0
    logp = jax.nn.log_softmax(cls.astype(jnp.float32), axis=-1)
    cls_loss = -jnp.take_along_axis(logp, tgt_cls[..., None], axis=-1).mean()

    take = lambda x: jnp.take_along_axis(x, best[..., None], axis=2)[..., 0]
    tgt_ltrb = jnp.stack([take(l), take(t), take(r), take(b)], axis=-1) * g
    npos = jnp.maximum(pos.sum(), 1)
    box_loss = (
        jnp.abs(ltrb - tgt_ltrb).sum(-1) * pos
    ).sum() / npos
    lr_min = jnp.minimum(take(l), take(r)) / jnp.maximum(
        jnp.maximum(take(l), take(r)), 1e-6
    )
    tb_min = jnp.minimum(take(t), take(b)) / jnp.maximum(
        jnp.maximum(take(t), take(b)), 1e-6
    )
    tgt_ctr = jnp.sqrt(jnp.clip(lr_min * tb_min, 0.0, 1.0))
    x = ctr.astype(jnp.float32)
    bce = jnp.maximum(x, 0) - x * tgt_ctr + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ctr_loss = (bce * pos).sum() / npos
    return cls_loss + box_loss + ctr_loss


def _contrastive_loss(sim: jnp.ndarray, batch: Any) -> jnp.ndarray:
    """CLIP symmetric InfoNCE over the in-batch [B, B] similarity matrix."""
    del batch
    sim = sim.astype(jnp.float32)
    n = sim.shape[0]
    tgt = jnp.arange(n)
    li = -jnp.take_along_axis(
        jax.nn.log_softmax(sim, axis=-1), tgt[:, None], axis=-1
    ).mean()
    lt = -jnp.take_along_axis(
        jax.nn.log_softmax(sim.T, axis=-1), tgt[:, None], axis=-1
    ).mean()
    return (li + lt) / 2


def _span_loss(logits: jnp.ndarray, batch: Any) -> jnp.ndarray:
    """Start/end CE (the HF QA objective)."""
    start, end = logits[..., 0], logits[..., 1]

    def ce(lg, tgt):
        logp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, tgt[:, None], axis=-1).mean()

    return (ce(start, batch["start_positions"]) + ce(end, batch["end_positions"])) / 2


def _cell_selection_loss(out: dict, batch: Any) -> jnp.ndarray:
    """BCE on cell selection (+ CE on the aggregation op when labeled)."""
    x = out["select"].astype(jnp.float32)
    y = batch["labels"].astype(jnp.float32)  # [B, T] 0/1 cell mask
    mask = (batch["labels"] >= 0).astype(jnp.float32)
    y = jnp.maximum(y, 0.0)
    bce = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
    loss = (bce * mask).sum() / jnp.maximum(mask.sum(), 1)
    agg_labels = batch.get("aggregation_labels")
    if agg_labels is not None:
        logp = jax.nn.log_softmax(out["aggregation"].astype(jnp.float32), -1)
        loss = loss - jnp.take_along_axis(logp, agg_labels[:, None], -1).mean()
    return loss


def _masked_patch_loss(pred: jnp.ndarray, batch: Any) -> jnp.ndarray:
    """SimMIM-style masked-image-modeling: L1 on masked pixels (all pixels
    when the batch carries no mask)."""
    tgt = batch["labels"].astype(jnp.float32)
    err = jnp.abs(pred.astype(jnp.float32) - tgt)
    mask = batch.get("mask")
    if mask is None:
        return err.mean()
    m = mask.astype(jnp.float32)
    while m.ndim < err.ndim:
        m = m[..., None]
    return (err * m).sum() / jnp.maximum(m.sum() * err.shape[-1], 1)


# --------------------------------------------------------------------------
# The family: HeadedModel + per-type builders.
# --------------------------------------------------------------------------


class HeadedModel:
    """Backbone (HF Flax or None) + linen head under the framework protocol.

    ``params`` = {"backbone": <hf tree>, "head": <linen tree>}; gradients
    flow through both (full fine-tuning, matching the reference's torch
    AutoModel training). ``custom_loss`` (when set) replaces the train
    step's ``compute_loss``.
    """

    def __init__(
        self,
        model_type: ModelType,
        head: nn.Module,
        backbone: _Backbone | None = None,
        *,
        head_inputs: str = "feats",  # "feats" | "feats+batch" | "raw"
        custom_loss: Callable | None = None,
        frames_fn: Callable | None = None,  # video: [B,T,C,H,W] → [B·T,...]
    ) -> None:
        self.model_type = model_type
        self.head = head
        self.backbone = backbone
        self.head_inputs = head_inputs
        if custom_loss is not None:
            self.custom_loss = custom_loss
        self.frames_fn = frames_fn
        self.config = backbone.config if backbone else None

    def init(self, rng: Any, inputs: Any) -> Any:
        if self.backbone is None:
            return {"head": self.head.init(rng, inputs)["params"]}
        feats = self._features(self.backbone.params, inputs)
        if self.head_inputs == "feats+batch":
            head = self.head.init(rng, feats, None)["params"]
        else:
            # .get: a paramless head (feature extraction) inits to {}.
            head = self.head.init(rng, feats).get("params", {})
        return {"backbone": self.backbone.params, "head": head}

    def _features(self, bp, inputs, rng=None):
        x = inputs
        if self.frames_fn is not None:
            x, meta = self.frames_fn(x)
            feats = self.backbone(bp, x, rng=rng)
            return meta(feats)
        return self.backbone(bp, x, rng=rng)

    def apply(self, params: Any, inputs: Any, *, rng: Any = None, batch: Any = None):
        if self.backbone is None:
            return self.head.apply({"params": params["head"]}, inputs)
        feats = self._features(params["backbone"], inputs, rng=rng)
        if self.head_inputs == "feats+batch":
            return self.head.apply({"params": params["head"]}, feats, batch)
        return self.head.apply({"params": params["head"]}, feats)


class _CLIPZeroShot:
    """CLIP joint-space models (zero-shot classification / detection / VQA):
    both streams (pixel_values + input_ids) come from the batch."""

    def __init__(self, backbone, mode, num_labels=None, grid=None):
        self.backbone = backbone
        self.mode = mode
        self.model_type = {
            "zs-cls": ModelType.ZERO_SHOT_IMAGE_CLASSIFICATION,
            "zs-det": ModelType.ZERO_SHOT_OBJECT_DETECTION,
            "vqa": ModelType.VISUAL_QUESTION_ANSWERING,
        }[mode]
        self.config = backbone.config
        if mode == "vqa":
            self.head = FusionHead(num_labels or 2)
        elif mode == "zs-det":
            self.head = nn.Dense(4, name="box")  # per-patch boxes
        else:
            self.head = None
        self.grid = grid
        self.custom_loss = {
            "zs-cls": _contrastive_loss,
            "vqa": None,  # plain CE via Loss selector
            "zs-det": _zs_detection_loss,
        }[mode]
        if self.custom_loss is None:
            del self.custom_loss  # fall through to compute_loss

    def _streams(self, params, batch, inputs, rng=None):
        m = self.backbone.model
        kwargs = dict(params=params["backbone"])
        if rng is not None:
            kwargs.update(dropout_rng=rng, train=True)
        pixel = batch.get("pixel_values") if batch else None
        if pixel is None:
            pixel = inputs
        ids = batch.get("input_ids") if batch else None
        if ids is None:
            ids = jnp.zeros((pixel.shape[0], 4), jnp.int32)
        out = m(input_ids=ids, pixel_values=pixel, **kwargs)
        return out

    def init(self, rng, inputs):
        params = {"backbone": self.backbone.params}
        if self.head is not None:
            dim = self.backbone.config.projection_dim
            if self.mode == "vqa":
                dummy = jnp.zeros((1, dim))
                params["head"] = self.head.init(rng, dummy, dummy)["params"]
            else:
                h = self.backbone.config.vision_config.hidden_size
                params["head"] = self.head.init(rng, jnp.zeros((1, 1, h)))["params"]
        return params

    def apply(self, params, inputs, *, rng=None, batch=None):
        out = self._streams(params, batch, inputs, rng=rng)
        if self.mode == "zs-cls":
            return out.logits_per_image  # [B, B] similarity
        if self.mode == "vqa":
            return self.head.apply(
                {"params": params["head"]}, out.image_embeds, out.text_embeds
            )
        # zs-det: per-patch similarity to the text queries + box head over
        # the vision tower's patch tokens (OWL-ViT shape).
        vis = out.vision_model_output.last_hidden_state[:, 1:, :]  # [B,P,H]
        boxes = nn.sigmoid(
            self.head.apply({"params": params["head"]}, vis)
        )  # [B, P, 4] in [0,1] cxcywh
        # Project patches into the joint space with the model's own
        # visual_projection so text queries and patches are comparable.
        proj = params["backbone"]["visual_projection"]["kernel"]
        pe = vis @ proj  # [B, P, D]
        pe = pe / jnp.maximum(jnp.linalg.norm(pe, axis=-1, keepdims=True), 1e-6)
        te = out.text_embeds
        te = te / jnp.maximum(jnp.linalg.norm(te, axis=-1, keepdims=True), 1e-6)
        sim = jnp.einsum("bpd,bd->bp", pe, te)  # [B, P] query match score
        return {"sim": sim, "boxes": boxes}


def _zs_detection_loss(out: dict, batch: Any) -> jnp.ndarray:
    """OWL-ViT-lite: BCE on patch-query match (positives = patches inside
    the query's gt box) + L1 on matched patch boxes (cxcywh)."""
    sim, boxes = out["sim"].astype(jnp.float32), out["boxes"]
    gt = batch["boxes"].astype(jnp.float32)  # [B, 4] xyxy: the query's box
    B, P = sim.shape
    g = int(P**0.5)
    xs = (jnp.arange(g, dtype=jnp.float32) + 0.5) / g
    cx = jnp.tile(xs, (g,))[None, :]  # [1, P]
    cy = jnp.repeat(xs, g)[None, :]
    pos = (
        (cx > gt[:, None, 0]) & (cy > gt[:, None, 1])
        & (cx < gt[:, None, 2]) & (cy < gt[:, None, 3])
    ).astype(jnp.float32)
    bce = jnp.maximum(sim, 0) - sim * pos + jnp.log1p(jnp.exp(-jnp.abs(sim)))
    tgt = jnp.stack(
        [
            (gt[:, 0] + gt[:, 2]) / 2,
            (gt[:, 1] + gt[:, 3]) / 2,
            gt[:, 2] - gt[:, 0],
            gt[:, 3] - gt[:, 1],
        ],
        axis=-1,
    )[:, None, :]
    npos = jnp.maximum(pos.sum(), 1)
    box_l1 = (jnp.abs(boxes - tgt).sum(-1) * pos).sum() / npos
    return bce.mean() + box_l1


class _DirectFlax:
    """Architecture-specific Flax class (no Auto coverage): Wav2Vec2ForCTC,
    BeitForMaskedImageModeling, WhisperForAudioClassification."""

    def __init__(self, model, model_type, input_kw, custom_loss=None):
        self.model = model
        self.model_type = model_type
        self.input_kw = input_kw
        self.config = model.config
        if custom_loss is not None:
            self.custom_loss = custom_loss

    def init(self, rng, inputs):
        del rng, inputs
        return self.model.params

    def apply(self, params, inputs, *, rng=None, batch=None):
        kwargs = {self.input_kw: inputs, "params": params}
        if rng is not None:
            kwargs.update(dropout_rng=rng, train=True)
        out = self.model(**kwargs)
        return out.logits


def _video_frames(clip: jnp.ndarray):
    """[B, T, H, W, C] video → per-frame backbone batch + temporal mean."""
    B, T = clip.shape[0], clip.shape[1]
    flat = clip.reshape(B * T, *clip.shape[2:])

    def pool(feats):  # [B·T, P, H] → [B, T·P→mean over T of CLS/mean]
        f = feats.mean(axis=1).reshape(B, T, -1)  # frame embedding
        return f  # PooledHead mean-pools over T

    return flat, pool


# Builders -----------------------------------------------------------------


def _n_labels(spec) -> int:
    return int(spec.get("num_labels", 2))


def _vision_dense(spec, mt, channels, loss=None, num_labels=None):
    bb = _build_backbone(spec, "vision")
    grid = _patch_grid(bb.config)
    size = (int(bb.config.image_size), int(bb.config.image_size))
    ch = channels if channels is not None else num_labels
    return HeadedModel(
        mt, DenseGridHead(ch, grid, size), bb, custom_loss=loss
    )


def build_head_model(spec: dict[str, Any], model_type: ModelType):
    """Entry point: build (model, config) for a heads-family model spec."""
    mt = model_type
    n = _n_labels(spec)

    if mt in (ModelType.AUDIO_CLASSIFICATION,):
        bb = _build_backbone(spec, "audio")
        return HeadedModel(mt, PooledHead(n), bb), bb.config
    if mt is ModelType.AUDIO_FRAME_CLASSIFICATION:
        bb = _build_backbone(spec, "audio")
        return HeadedModel(mt, FrameHead(n), bb), bb.config
    if mt is ModelType.AUDIO_XVECTOR:
        bb = _build_backbone(spec, "audio")
        return HeadedModel(mt, XVectorHead(n), bb), bb.config
    if mt is ModelType.CTC:
        m = _build_wav2vec2_ctc(spec, n)
        return _DirectFlax(m, mt, "input_values", custom_loss=_ctc_loss), m.config

    if mt is ModelType.VIDEO_CLASSIFICATION:
        bb = _build_backbone(spec, "vision")
        return (
            HeadedModel(mt, PooledHead(n), bb, frames_fn=_video_frames),
            bb.config,
        )
    if mt in (
        ModelType.IMAGE_SEGMENTATION,
        ModelType.SEMANTIC_SEGMENTATION,
        ModelType.INSTANCE_SEGMENTATION,
        ModelType.UNIVERSAL_SEGMENTATION,
    ):
        # Per-pixel class logits (instance/universal collapse to the same
        # dense per-pixel output here — the reference's Mask2Former-class
        # query decoders have no Flax counterpart; honest simplification).
        return _vision_dense(spec, mt, None, num_labels=n), None
    if mt is ModelType.DEPTH_ESTIMATION:
        return _vision_dense(spec, mt, 1), None
    if mt is ModelType.KEYPOINT_DETECTION:
        k = int(spec.get("num_keypoints", 17))
        return _vision_dense(spec, mt, k), None
    if mt is ModelType.IMAGE_TO_IMAGE:
        return _vision_dense(spec, mt, 3), None
    if mt is ModelType.MASK_GENERATION:
        # SAM-class promptable masks → dense per-pixel mask logits
        # (BCE against batch["labels"] masks).
        return _vision_dense(spec, mt, int(spec.get("num_masks", 1))), None
    if mt is ModelType.MASKED_IMAGE_MODELING:
        bb = _build_backbone(spec, "vision")
        size = (int(bb.config.image_size), int(bb.config.image_size))
        model = HeadedModel(
            mt,
            DenseGridHead(3, _patch_grid(bb.config), size),
            bb,
            custom_loss=_masked_patch_loss,
        )
        return model, bb.config
    if mt is ModelType.OBJECT_DETECTION:
        bb = _build_backbone(spec, "vision")
        grid = _patch_grid(bb.config)
        return (
            HeadedModel(
                mt, DetectionHead(n, grid), bb, custom_loss=_detection_loss
            ),
            bb.config,
        )
    if mt is ModelType.IMAGE_FEATURE_EXTRACTION:
        bb = _build_backbone(spec, "vision")
        ident = _Identity()
        return HeadedModel(mt, ident, bb), bb.config

    if mt is ModelType.ZERO_SHOT_IMAGE_CLASSIFICATION:
        bb = _build_backbone(spec, "clip")
        return _CLIPZeroShot(bb, "zs-cls"), bb.config
    if mt is ModelType.ZERO_SHOT_OBJECT_DETECTION:
        bb = _build_backbone(spec, "clip")
        return _CLIPZeroShot(bb, "zs-det"), bb.config
    if mt is ModelType.VISUAL_QUESTION_ANSWERING:
        bb = _build_backbone(spec, "clip")
        return _CLIPZeroShot(bb, "vqa", num_labels=n), bb.config

    if mt is ModelType.DOCUMENT_QUESTION_ANSWERING:
        bb = _build_backbone(spec, "text")
        model = HeadedModel(
            mt,
            SpanHead(side="bbox"),
            bb,
            head_inputs="feats+batch",
            custom_loss=_span_loss,
        )
        return model, bb.config
    if mt is ModelType.TABLE_QUESTION_ANSWERING:
        bb = _build_backbone(spec, "text")
        model = HeadedModel(
            mt,
            CellSelectionHead(),
            bb,
            head_inputs="feats+batch",
            custom_loss=_cell_selection_loss,
        )
        return model, bb.config

    if mt is ModelType.TIME_SERIES_PREDICTION:
        cfg = {k: int(spec[k]) for k in ("horizon", "patch", "d_model", "layers")
               if k in spec}
        m = TimeSeriesModel(**cfg)
        return HeadedModel(mt, m, None), None
    if mt is ModelType.TEXT_TO_SPECTROGRAM:
        m = TextToSpectrogramModel(
            vocab_size=int(spec.get("vocab_size", 256)),
            n_mels=int(spec.get("n_mels", 80)),
        )
        return HeadedModel(mt, m, None), None
    if mt is ModelType.TEXT_TO_WAVEFORM:
        m = TextToSpectrogramModel(
            vocab_size=int(spec.get("vocab_size", 256)),
            n_mels=int(spec.get("n_mels", 80)),
            waveform_hop=int(spec.get("hop", 64)),
        )
        return HeadedModel(mt, m, None), None

    raise NotImplementedError(f"heads family does not cover {mt.value!r}")


class _Identity(nn.Module):
    @nn.compact
    def __call__(self, feats: jnp.ndarray) -> jnp.ndarray:
        return feats


def _build_wav2vec2_ctc(spec: dict, vocab: int):
    import transformers

    def make_config():
        _, defaults = _BACKBONE_DEFAULTS["audio"]
        fields = {**defaults, **(spec.get("backbone") or {}), "vocab_size": vocab}
        return transformers.Wav2Vec2Config(**fields)

    return _load_flax_model(
        transformers.FlaxWav2Vec2ForCTC, spec, make_config, "wav2vec2-ctc"
    )


# Every type this family covers (registry routes these here by default).
HEAD_TYPES = {
    ModelType.AUDIO_CLASSIFICATION,
    ModelType.AUDIO_FRAME_CLASSIFICATION,
    ModelType.AUDIO_XVECTOR,
    ModelType.CTC,
    ModelType.VIDEO_CLASSIFICATION,
    ModelType.IMAGE_SEGMENTATION,
    ModelType.SEMANTIC_SEGMENTATION,
    ModelType.INSTANCE_SEGMENTATION,
    ModelType.UNIVERSAL_SEGMENTATION,
    ModelType.DEPTH_ESTIMATION,
    ModelType.KEYPOINT_DETECTION,
    ModelType.IMAGE_TO_IMAGE,
    ModelType.MASK_GENERATION,
    ModelType.MASKED_IMAGE_MODELING,
    ModelType.OBJECT_DETECTION,
    ModelType.IMAGE_FEATURE_EXTRACTION,
    ModelType.ZERO_SHOT_IMAGE_CLASSIFICATION,
    ModelType.ZERO_SHOT_OBJECT_DETECTION,
    ModelType.VISUAL_QUESTION_ANSWERING,
    ModelType.DOCUMENT_QUESTION_ANSWERING,
    ModelType.TABLE_QUESTION_ANSWERING,
    ModelType.TIME_SERIES_PREDICTION,
    ModelType.TEXT_TO_SPECTROGRAM,
    ModelType.TEXT_TO_WAVEFORM,
}
