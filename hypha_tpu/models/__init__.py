"""Native JAX/flax model families for the BASELINE configs.

The reference loads models through 38 HF ``AutoModelFor*`` classes
(executors/accelerate/.../model.py:48-123). TPU-native equivalents: the
flagship families are defined natively here (static shapes, bf16 activations,
MXU-sized matmuls, sharding-friendly param trees); anything else resolves
through the registry's HF-conversion fallback (hypha_tpu.models.registry).
"""

from .lenet import LeNet, LeNetConfig
from .gpt2 import GPT2, GPT2Config
from .llama import Llama, LlamaConfig
from .mixtral import Mixtral, MixtralConfig
from .registry import build_model, resolve_model_type

__all__ = [
    "LeNet",
    "LeNetConfig",
    "GPT2",
    "GPT2Config",
    "Llama",
    "LlamaConfig",
    "Mixtral",
    "MixtralConfig",
    "build_model",
    "resolve_model_type",
]
