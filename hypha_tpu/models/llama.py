"""Llama-2 family (BASELINE configs 3-4: Llama-2-7B DiLoCo fine-tune and
inference serving).

Native flax definition: RMSNorm, rotary embeddings, SwiGLU MLP,
grouped-query attention. Param tree names are chosen to map 1:1 onto HF
``LlamaForCausalLM`` checkpoints for conversion (registry). Long-context runs
shard the sequence axis and swap the attention core for the ring kernel
(hypha_tpu.ops.ring_attention) — the model takes an ``attn_impl`` hook so the
executor can lower attention onto the mesh without redefining the model.

The same module also hosts the Llama-ARCHITECTURE descendants the reference
reaches through torch AutoModel (model.py:48-123) but HF ships no Flax port
for: **Mistral** (sliding-window attention; otherwise weight-identical) and
**Qwen2** (q/k/v projection biases, optionally tied embeddings) — selected
via config fields, converted via models.convert.
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass
from typing import Callable

import flax.linen as nn
import jax.numpy as jnp


def _accepts_kw(fn: Callable, name: str) -> bool:
    import inspect

    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False
    return name in params or any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
    )

from ..ops.attention import dot_product_attention
from ..ops.rmsnorm import rms_norm
from ..ops.rope import apply_rope, rope_frequencies

__all__ = ["Llama", "LlamaConfig"]


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32_000
    hidden_size: int = 4096
    intermediate_size: int = 11_008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    max_seq_len: int = 4096
    rope_theta: float = 10_000.0
    rms_eps: float = 1e-5
    dtype: str = "bfloat16"
    # Architecture toggles for Llama descendants:
    attn_bias: bool = False  # Qwen2: biases on q/k/v projections
    remat: bool = False  # gradient checkpointing per block (see gpt2.py)
    sliding_window: int | None = None  # Mistral: local attention window
    tie_word_embeddings: bool = False  # Qwen2-small/Gemma: head = embeddings
    head_dim_override: int | None = None  # Gemma: head_dim != hidden/heads
    mlp_act: str = "silu"  # "silu" (Llama) | "gelu_tanh" (Gemma GeGLU)
    rms_offset: bool = False  # Gemma RMSNorm: x * (1 + weight)
    embed_scale: bool = False  # Gemma: embeddings scaled by sqrt(hidden)
    # Qwen3: RMSNorm over each head's q/k vectors before RoPE (replaces
    # qwen2's projection biases as the attention-stability mechanism).
    qk_norm: bool = False
    # LoRA adapters (executor/lora.py): rank 0 = off. Applied as the
    # runtime two-matmul form y = xW + (xA)B·(α/r) — never materializing
    # W+ΔW, so a 7B fine-tune's grads/optimizer touch only the adapters.
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_targets: tuple = ("q_proj", "v_proj")

    _LORA_SUPPORTED = frozenset({"q_proj", "k_proj", "v_proj", "o_proj"})

    def __post_init__(self):
        if self.lora_rank > 0:
            bad = set(self.lora_targets) - self._LORA_SUPPORTED
            if bad or not self.lora_targets:
                # A typo'd target would silently create ZERO adapters and
                # train nothing — fail at construction instead.
                raise ValueError(
                    f"lora_targets {sorted(bad) or '(empty)'} unsupported; "
                    f"choose from {sorted(self._LORA_SUPPORTED)}"
                )

    @classmethod
    def llama2_7b(cls) -> "LlamaConfig":
        return cls()

    @classmethod
    def from_hf(cls, d: dict, **overrides) -> "LlamaConfig":
        """Map an HF ``config.json`` dict (llama / mistral / qwen2) onto the
        native config, so real checkpoint dirs load without hand-mapping."""
        fields = dict(
            vocab_size=d.get("vocab_size", 32_000),
            hidden_size=d.get("hidden_size", 4096),
            intermediate_size=d.get("intermediate_size", 11_008),
            num_layers=d.get("num_hidden_layers", 32),
            num_heads=d.get("num_attention_heads", 32),
            num_kv_heads=d.get(
                "num_key_value_heads", d.get("num_attention_heads", 32)
            ),
            max_seq_len=d.get("max_position_embeddings", 4096),
            rope_theta=d.get("rope_theta", 10_000.0),
            rms_eps=d.get("rms_norm_eps", 1e-5),
            attn_bias=d.get("model_type") == "qwen2",
            qk_norm=d.get("model_type") == "qwen3",
            # Qwen2 configs ship a non-null sliding_window with
            # use_sliding_window=false — honor the switch (absent means
            # enabled, the Mistral convention).
            sliding_window=(
                d.get("sliding_window") if d.get("use_sliding_window", True) else None
            ),
            tie_word_embeddings=d.get("tie_word_embeddings", False),
            # Any Llama-family config may pin an explicit head_dim that
            # differs from hidden/heads (Gemma always; Mistral-NeMo-style
            # checkpoints too).
            head_dim_override=d.get("head_dim"),
        )
        if d.get("model_type") == "gemma":
            fields.update(
                mlp_act="gelu_tanh",
                rms_offset=True,
                embed_scale=True,
                # HF Gemma always ties (the field is often absent from
                # config.json but GemmaForCausalLM ties unconditionally).
                tie_word_embeddings=d.get("tie_word_embeddings", True),
            )
        fields.update(overrides)
        return cls(**fields)

    @classmethod
    def tiny(cls) -> "LlamaConfig":
        """CI-sized config for CPU tests (GQA exercised: 4 q heads, 2 kv)."""
        return cls(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=128,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            max_seq_len=128,
        )

    @property
    def head_dim(self) -> int:
        if self.head_dim_override is not None:
            return self.head_dim_override
        return self.hidden_size // self.num_heads


class _RMSNorm(nn.Module):
    eps: float
    # Gemma convention: weights parameterize the DELTA from identity
    # (effective scale = 1 + weight, zero-init on disk).
    offset: bool = False

    @nn.compact
    def __call__(self, x):
        init = nn.initializers.zeros if self.offset else nn.initializers.ones
        w = self.param("weight", init, (x.shape[-1],), jnp.float32)
        return rms_norm(x, w + 1.0 if self.offset else w, self.eps)


class _Attention(nn.Module):
    config: LlamaConfig
    attn_impl: Callable | None = None
    decode: bool = False  # autoregressive serving: KV cache in the "cache"
    decode_len: int = 0  # static cache capacity (prompt + new tokens)
    # Continuous-batching pool mode: every row carries its own cache index
    # and left-pad start (executor.pool.DecodePool admits/releases rows at
    # token boundaries, so rows sit at different positions).
    per_row_decode: bool = False
    # Paged KV (executor.pool paged mode): kv_blocks > 0 re-layouts the
    # cache as a shared block pool addressed through a per-lane block
    # table (ops.kvcache paged mode). Attention math is unchanged — the
    # cache update hands back the same dense per-lane views.
    kv_blocks: int = 0
    kv_block_size: int = 0
    # Ragged paged attention (ops.paged_attention): skip the dense window
    # gather and attend over occupied blocks only. Default off = the
    # historical dense-gather path, bit-identical.
    ragged_attention: bool = False
    # int8 KV blocks (ops.kvcache kv_quant): "" = full-precision pools.
    kv_quant: str = ""

    def _proj(self, x, features, use_bias, dtype, name):
        """Dense projection, plus the low-rank LoRA path when enabled.

        B starts at zero so a freshly-initialized adapter is an exact
        no-op; the (xA)B form keeps autodiff low-rank — dL/dA, dL/dB
        never touch a [in, out]-shaped buffer.
        """
        cfg = self.config
        y = nn.Dense(features, use_bias=use_bias, dtype=dtype, name=name)(x)
        if cfg.lora_rank > 0 and name in cfg.lora_targets:
            r = cfg.lora_rank
            a = self.param(
                f"{name}_lora_a", nn.initializers.normal(0.02),
                (x.shape[-1], r), jnp.float32,
            )
            b = self.param(
                f"{name}_lora_b", nn.initializers.zeros, (r, features),
                jnp.float32,
            )
            y = y + ((x @ a.astype(dtype)) @ b.astype(dtype)) * (
                cfg.lora_alpha / r
            )
        return y

    @nn.compact
    def __call__(self, x, cos, sin):
        import jax

        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        B, S, E = x.shape
        hd = cfg.head_dim
        bias = cfg.attn_bias
        q = self._proj(x, cfg.num_heads * hd, bias, dtype, "q_proj")
        k = self._proj(x, cfg.num_kv_heads * hd, bias, dtype, "k_proj")
        v = self._proj(x, cfg.num_kv_heads * hd, bias, dtype, "v_proj")
        q = q.reshape(B, S, cfg.num_heads, hd)
        k = k.reshape(B, S, cfg.num_kv_heads, hd)
        v = v.reshape(B, S, cfg.num_kv_heads, hd)
        if cfg.qk_norm:
            # Qwen3 QK-norm: per-head RMSNorm on the last (head_dim) axis,
            # BEFORE RoPE — shared by the training forward and both decode
            # paths, so cached generation matches training exactly.
            qn = self.param("q_norm", nn.initializers.ones, (hd,), jnp.float32)
            kn = self.param("k_norm", nn.initializers.ones, (hd,), jnp.float32)
            q = rms_norm(q, qn, cfg.rms_eps).astype(dtype)
            k = rms_norm(k, kn, cfg.rms_eps).astype(dtype)
        if self.decode:
            # KV-cache decoding (net-new vs the reference, which has no
            # inference path): static-shape cache + q_offset causal masking
            # — everything a lax.scan'd decode loop needs to stay one
            # compiled program. RoPE must see absolute positions, so it
            # runs against the pre-update index (read via a peek variable
            # inside update_kv_cache's offset return).
            from ..ops.kvcache import update_kv_cache

            # RoPE needs absolute positions, i.e. the cache index BEFORE
            # this step's write — the prepare hook runs against it.
            roped = {}

            if self.per_row_decode:
                # Pool rows are left-padded into their window: RoPE runs on
                # LOGICAL positions (cache index minus the row's pad
                # boundary), and attention masks keys below the boundary.
                def _rope_rows(offset, start):
                    logical = jnp.maximum(
                        offset[:, None] - start[:, None] + jnp.arange(S)[None, :],
                        0,
                    )
                    roped["q"] = apply_rope(q, cos, sin, positions=logical)
                    return (
                        apply_rope(k, cos, sin, positions=logical).astype(dtype),
                        v.astype(dtype),
                    )

                ragged = self.ragged_attention and self.kv_blocks > 0
                full_k, full_v, offset, start = update_kv_cache(
                    self, k, v, self.decode_len, prepare=_rope_rows,
                    per_row=True, blocks=self.kv_blocks,
                    block_size=self.kv_block_size,
                    kv_quant=self.kv_quant, ragged=ragged,
                )
                if ragged:
                    # full_k is the raw PagedKV pool view; attention walks
                    # the block table directly (occupancy-proportional).
                    from ..ops.paged_attention import paged_attention

                    attn = paged_attention(
                        roped["q"], full_k, blocks=self.kv_blocks,
                        block_size=self.kv_block_size, q_offset=offset,
                        k_start=start, window=cfg.sliding_window,
                    )
                else:
                    attn = dot_product_attention(
                        roped["q"], full_k, full_v, causal=True,
                        q_offset=offset, window=cfg.sliding_window,
                        k_start=start,
                    )
                attn = attn.reshape(B, S, cfg.num_heads * hd)
                return self._proj(attn, E, False, dtype, "o_proj")

            def _rope_at(offset):
                positions = jnp.broadcast_to(offset + jnp.arange(S), (B, S))
                roped["q"] = apply_rope(q, cos, sin, positions=positions)
                return (
                    apply_rope(k, cos, sin, positions=positions).astype(dtype),
                    v.astype(dtype),
                )

            full_k, full_v, offset = update_kv_cache(
                self, k, v, self.decode_len, prepare=_rope_at
            )
            q = roped["q"]
            # The window applies in decode too (positions are absolute, so
            # the band mask composes with q_offset) — cached generation must
            # match the training forward exactly for Mistral-style configs.
            attn = dot_product_attention(
                q, full_k, full_v, causal=True, q_offset=offset,
                window=cfg.sliding_window,
            )
            attn = attn.reshape(B, S, cfg.num_heads * hd)
            return self._proj(attn, E, False, dtype, "o_proj")
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        window = cfg.sliding_window
        if window is not None and S > window:
            # Mistral local attention: position i sees (i-window, i]. The
            # window threads through attn_impl when the kernel supports it;
            # otherwise the fused-iota dense path runs (the flash/ring
            # kernels don't take a window yet — warn, don't silently alter
            # the objective OR silently drop the installed kernel).
            impl = self.attn_impl or dot_product_attention
            if _accepts_kw(impl, "window"):
                attn = impl(q, k, v, causal=True, window=window)
            else:
                if self.attn_impl is not None:
                    warnings.warn(
                        "sliding_window set but the installed attn_impl "
                        "takes no 'window' kwarg; using the dense windowed "
                        "path instead", stacklevel=2,
                    )
                attn = dot_product_attention(q, k, v, causal=True, window=window)
        else:
            attn = (self.attn_impl or dot_product_attention)(q, k, v, causal=True)
        attn = attn.reshape(B, S, cfg.num_heads * hd)
        return self._proj(attn, E, False, dtype, "o_proj")


class _MLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        gate = nn.Dense(cfg.intermediate_size, use_bias=False, dtype=dtype, name="gate_proj")(x)
        up = nn.Dense(cfg.intermediate_size, use_bias=False, dtype=dtype, name="up_proj")(x)
        if cfg.mlp_act in ("gelu_tanh", "gelu"):  # Gemma GeGLU — HF ships
            # both spellings ("gelu_pytorch_tanh" maps here via from_hf;
            # older configs say "gelu" but GemmaMLP runs the tanh approx).
            act = nn.gelu(gate, approximate=True)
        elif cfg.mlp_act == "silu":
            act = nn.silu(gate)
        else:
            raise ValueError(f"unknown mlp_act {cfg.mlp_act!r} (silu | gelu_tanh)")
        return nn.Dense(x.shape[-1], use_bias=False, dtype=dtype, name="down_proj")(
            act * up
        )


class _Block(nn.Module):
    config: LlamaConfig
    attn_impl: Callable | None = None
    decode: bool = False
    decode_len: int = 0
    per_row_decode: bool = False
    kv_blocks: int = 0
    kv_block_size: int = 0
    ragged_attention: bool = False
    kv_quant: str = ""

    @nn.compact
    def __call__(self, x, cos, sin):
        cfg = self.config
        x = x + _Attention(
            cfg, self.attn_impl, self.decode, self.decode_len,
            self.per_row_decode, self.kv_blocks, self.kv_block_size,
            self.ragged_attention, self.kv_quant,
            name="self_attn"
        )(_RMSNorm(cfg.rms_eps, cfg.rms_offset, name="input_layernorm")(x), cos, sin)
        x = x + _MLP(cfg, name="mlp")(
            _RMSNorm(cfg.rms_eps, cfg.rms_offset, name="post_attention_layernorm")(x)
        )
        return x


class Llama(nn.Module):
    config: LlamaConfig = LlamaConfig()
    attn_impl: Callable | None = None  # e.g. a ring-attention closure
    decode: bool = False  # serving mode: KV-cached autoregressive forward
    decode_len: int = 0
    per_row_decode: bool = False  # continuous-batching pool (executor.pool)
    # Paged KV serving (executor.pool paged mode): block-pool cache layout.
    kv_blocks: int = 0
    kv_block_size: int = 0
    # Ragged paged attention + int8 KV blocks (both default-off: the
    # dense-gather full-precision path, bit-identical to before).
    ragged_attention: bool = False
    kv_quant: str = ""
    # with_head=False returns final hidden states [B, S, E] — the
    # chunked-CE training path (executor.train.chunked_causal_ce) projects
    # to vocab inside the loss so [B, S, 32000] f32 logits never
    # materialize (0.5 GB/chip at B_local=1 S=4096; see gpt2.py). Init
    # with with_head=True so the param tree still carries lm_head.
    with_head: bool = True

    @nn.compact
    def __call__(self, input_ids: jnp.ndarray) -> jnp.ndarray:
        """input_ids [B, S] -> logits [B, S, vocab] (f32), or final hidden
        states when ``with_head=False``."""
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        embed = self.param(
            "embed_tokens",
            nn.initializers.normal(0.02),
            (cfg.vocab_size, cfg.hidden_size),
            jnp.float32,
        )
        x = embed[input_ids].astype(dtype)
        if cfg.embed_scale:  # Gemma: inputs scaled by sqrt(hidden), in dtype
            x = x * jnp.asarray(cfg.hidden_size**0.5, dtype)
        table_len = max(cfg.max_seq_len, self.decode_len)
        cos, sin = rope_frequencies(cfg.head_dim, table_len, cfg.rope_theta)
        block_cls = nn.remat(_Block) if cfg.remat and not self.decode else _Block
        for i in range(cfg.num_layers):
            x = block_cls(
                cfg, self.attn_impl, self.decode, self.decode_len,
                self.per_row_decode, self.kv_blocks, self.kv_block_size,
                self.ragged_attention, self.kv_quant,
                name=f"layers_{i}",
            )(x, cos, sin)
        x = _RMSNorm(cfg.rms_eps, cfg.rms_offset, name="norm")(x)
        if not self.with_head:
            return x
        if cfg.tie_word_embeddings:
            lm_head = embed  # Qwen2-small convention: head shares embeddings
        else:
            lm_head = self.param(
                "lm_head",
                nn.initializers.normal(0.02),
                (cfg.vocab_size, cfg.hidden_size),
                jnp.float32,
            )
        return jnp.einsum("bse,ve->bsv", x.astype(jnp.float32), lm_head)
