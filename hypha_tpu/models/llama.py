"""Llama-2 family (BASELINE configs 3-4: Llama-2-7B DiLoCo fine-tune and
inference serving).

Native flax definition: RMSNorm, rotary embeddings, SwiGLU MLP,
grouped-query attention. Param tree names are chosen to map 1:1 onto HF
``LlamaForCausalLM`` checkpoints for conversion (registry). Long-context runs
shard the sequence axis and swap the attention core for the ring kernel
(hypha_tpu.ops.ring_attention) — the model takes an ``attn_impl`` hook so the
executor can lower attention onto the mesh without redefining the model.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import flax.linen as nn
import jax.numpy as jnp

from ..ops.attention import dot_product_attention
from ..ops.rmsnorm import rms_norm
from ..ops.rope import apply_rope, rope_frequencies

__all__ = ["Llama", "LlamaConfig"]


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32_000
    hidden_size: int = 4096
    intermediate_size: int = 11_008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    max_seq_len: int = 4096
    rope_theta: float = 10_000.0
    rms_eps: float = 1e-5
    dtype: str = "bfloat16"

    @classmethod
    def llama2_7b(cls) -> "LlamaConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "LlamaConfig":
        """CI-sized config for CPU tests (GQA exercised: 4 q heads, 2 kv)."""
        return cls(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=128,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            max_seq_len=128,
        )

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


class _RMSNorm(nn.Module):
    eps: float

    @nn.compact
    def __call__(self, x):
        w = self.param("weight", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        return rms_norm(x, w, self.eps)


class _Attention(nn.Module):
    config: LlamaConfig
    attn_impl: Callable | None = None

    @nn.compact
    def __call__(self, x, cos, sin):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        B, S, E = x.shape
        hd = cfg.head_dim
        q = nn.Dense(cfg.num_heads * hd, use_bias=False, dtype=dtype, name="q_proj")(x)
        k = nn.Dense(cfg.num_kv_heads * hd, use_bias=False, dtype=dtype, name="k_proj")(x)
        v = nn.Dense(cfg.num_kv_heads * hd, use_bias=False, dtype=dtype, name="v_proj")(x)
        q = q.reshape(B, S, cfg.num_heads, hd)
        k = k.reshape(B, S, cfg.num_kv_heads, hd)
        v = v.reshape(B, S, cfg.num_kv_heads, hd)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        attn = (self.attn_impl or dot_product_attention)(q, k, v, causal=True)
        attn = attn.reshape(B, S, cfg.num_heads * hd)
        return nn.Dense(E, use_bias=False, dtype=dtype, name="o_proj")(attn)


class _MLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        gate = nn.Dense(cfg.intermediate_size, use_bias=False, dtype=dtype, name="gate_proj")(x)
        up = nn.Dense(cfg.intermediate_size, use_bias=False, dtype=dtype, name="up_proj")(x)
        return nn.Dense(x.shape[-1], use_bias=False, dtype=dtype, name="down_proj")(
            nn.silu(gate) * up
        )


class _Block(nn.Module):
    config: LlamaConfig
    attn_impl: Callable | None = None

    @nn.compact
    def __call__(self, x, cos, sin):
        cfg = self.config
        x = x + _Attention(cfg, self.attn_impl, name="self_attn")(
            _RMSNorm(cfg.rms_eps, name="input_layernorm")(x), cos, sin
        )
        x = x + _MLP(cfg, name="mlp")(
            _RMSNorm(cfg.rms_eps, name="post_attention_layernorm")(x)
        )
        return x


class Llama(nn.Module):
    config: LlamaConfig = LlamaConfig()
    attn_impl: Callable | None = None  # e.g. a ring-attention closure

    @nn.compact
    def __call__(self, input_ids: jnp.ndarray) -> jnp.ndarray:
        """input_ids [B, S] -> logits [B, S, vocab] (f32)."""
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        embed = self.param(
            "embed_tokens",
            nn.initializers.normal(0.02),
            (cfg.vocab_size, cfg.hidden_size),
            jnp.float32,
        )
        x = embed[input_ids].astype(dtype)
        cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
        for i in range(cfg.num_layers):
            x = _Block(cfg, self.attn_impl, name=f"layers_{i}")(x, cos, sin)
        x = _RMSNorm(cfg.rms_eps, name="norm")(x)
        lm_head = self.param(
            "lm_head",
            nn.initializers.normal(0.02),
            (cfg.vocab_size, cfg.hidden_size),
            jnp.float32,
        )
        return jnp.einsum("bse,ve->bsv", x.astype(jnp.float32), lm_head)
