"""HF checkpoint interop: torch state dicts → native JAX param trees.

The reference loads models through HF ``AutoModelFor*`` classes
(executors/accelerate/.../model.py:48-123) and trains them with torch; the
TPU framework defines the flagship families natively in flax. This module
bridges the two worlds so a user can point a job at an HF checkpoint
(``gpt2``, Llama-format repos) and get the same weights in the native
model — with stable flat names, so Δθ SafeTensors stay key-compatible
through the whole DiLoCo pipeline.

Conventions handled:
  * GPT-2 uses Conv1D ([in, out] — flax kernel orientation, no transpose);
  * Llama/Mixtral use torch Linear ([out, in] — transposed to flax);
  * LayerNorm weight/bias → flax scale/bias;
  * tied LM heads (GPT-2) are dropped, untied heads map through.
"""

from __future__ import annotations

import logging
import re
from pathlib import Path
from typing import Any

import jax
import numpy as np

from ..executor.serialization import unflatten_like

__all__ = [
    "convert_state_dict",
    "convert_checkpoint",
    "load_checkpoint_files",
    "ShardedCheckpoint",
    "HF_CONVERTERS",
]

log = logging.getLogger("hypha.models.convert")


def _gpt2_key(key: str) -> tuple[str, bool] | None:
    """HF gpt2 name -> (our flat name, transpose?) or None to skip."""
    key = key.removeprefix("transformer.")
    if key in ("wte.weight", "wpe.weight"):
        return f"params/{key.removesuffix('.weight')}", False
    if key in ("ln_f.weight", "ln_f.bias"):
        suffix = "scale" if key.endswith("weight") else "bias"
        return f"params/ln_f/{suffix}", False
    if key.startswith("lm_head."):
        return None  # tied to wte
    m = re.fullmatch(r"h\.(\d+)\.(.+)", key)
    if m is None:
        return None
    i, rest = m.group(1), m.group(2)
    table = {
        "ln_1.weight": ("ln_1/scale", False),
        "ln_1.bias": ("ln_1/bias", False),
        "ln_2.weight": ("ln_2/scale", False),
        "ln_2.bias": ("ln_2/bias", False),
        # GPT-2 Conv1D stores [in, out]: flax kernel orientation already.
        "attn.c_attn.weight": ("c_attn/kernel", False),
        "attn.c_attn.bias": ("c_attn/bias", False),
        "attn.c_proj.weight": ("c_proj/kernel", False),
        "attn.c_proj.bias": ("c_proj/bias", False),
        "mlp.c_fc.weight": ("c_fc/kernel", False),
        "mlp.c_fc.bias": ("c_fc/bias", False),
        "mlp.c_proj.weight": ("mlp_proj/kernel", False),
        "mlp.c_proj.bias": ("mlp_proj/bias", False),
    }
    entry = table.get(rest)
    if entry is None:
        if rest.endswith((".attn.bias", "attn.masked_bias")) or rest in (
            "attn.bias",
            "attn.masked_bias",
        ):
            return None  # HF's causal-mask buffers, not weights
        raise KeyError(f"unmapped gpt2 tensor {key!r}")
    name, transpose = entry
    return f"params/h_{i}/{name}", transpose


def _llama_key(key: str) -> tuple[str, bool] | None:
    """HF Llama name -> (our flat name, transpose?) or None to skip."""
    key = key.removeprefix("model.")
    if key == "embed_tokens.weight":
        return "params/embed_tokens", False
    if key == "norm.weight":
        return "params/norm/weight", False
    if key == "lm_head.weight":
        return "params/lm_head", False  # torch Linear [V, E] == our [V, E]
    if key.endswith("rotary_emb.inv_freq"):
        return None  # recomputed
    m = re.fullmatch(r"layers\.(\d+)\.(.+)", key)
    if m is None:
        return None
    i, rest = m.group(1), m.group(2)
    if rest in ("input_layernorm.weight", "post_attention_layernorm.weight"):
        return f"params/layers_{i}/{rest.removesuffix('.weight')}/weight", False
    proj = re.fullmatch(r"(self_attn|mlp)\.(\w+_proj)\.weight", rest)
    if proj is not None:
        # torch Linear stores [out, in]; flax kernels are [in, out].
        return f"params/layers_{i}/{proj.group(1)}/{proj.group(2)}/kernel", True
    raise KeyError(f"unmapped llama tensor {key!r}")


def _qwen2_key(key: str) -> tuple[str, bool] | None:
    """Qwen2 is Llama-architecture plus q/k/v projection biases."""
    m = re.fullmatch(
        r"model\.layers\.(\d+)\.self_attn\.([qkv]_proj)\.bias", key
    )
    if m is not None:
        return f"params/layers_{m.group(1)}/self_attn/{m.group(2)}/bias", False
    return _llama_key(key)


def _qwen3_key(key: str) -> tuple[str, bool] | None:
    """Qwen3 drops qwen2's projection biases and adds per-head QK-norm
    weights (model.layers.N.self_attn.{q,k}_norm.weight, 1-D)."""
    m = re.fullmatch(
        r"model\.layers\.(\d+)\.self_attn\.([qk]_norm)\.weight", key
    )
    if m is not None:
        return f"params/layers_{m.group(1)}/self_attn/{m.group(2)}", False
    return _llama_key(key)


class StackSlot:
    """Mapper result for one slice of a stacked tensor: HF Mixtral stores
    experts as separate ``experts.K.w{1,2,3}`` Linears, the TPU-native
    MoE stores them stacked ``[E, ...]`` so dispatch/combine are single
    batched matmuls on the MXU (models/mixtral.py). The converter buffers
    slices and emits the stack once every index has arrived."""

    __slots__ = ("name", "index", "transpose")

    def __init__(self, name: str, index: int, transpose: bool) -> None:
        self.name = name
        self.index = index
        self.transpose = transpose


def _mixtral_key(key: str):
    """HF Mixtral name -> (our name, transpose) | StackSlot | None."""
    m = re.fullmatch(
        r"model\.layers\.(\d+)\.block_sparse_moe\.experts\.(\d+)\.(w[123])\.weight",
        key,
    )
    if m is not None:
        i, e, w = m.group(1), int(m.group(2)), m.group(3)
        # Mixtral semantics: w1 = gate-proj, w3 = up-proj ([F, D] torch ->
        # transposed [D, F]); w2 = down-proj ([D, F] -> [F, D]).
        name = {"w1": "w_gate", "w3": "w_up", "w2": "w_down"}[w]
        return StackSlot(f"params/layers_{i}/moe/{name}", e, True)
    m = re.fullmatch(
        r"model\.layers\.(\d+)\.block_sparse_moe\.gate\.weight", key
    )
    if m is not None:
        # router Linear [E, D] -> flax kernel [D, E]
        return f"params/layers_{m.group(1)}/moe/gate/kernel", True
    return _llama_key(key)  # attention / norms / embed / head are Llama-shaped


# Mistral checkpoints are weight-identical to Llama (the sliding window is a
# config property, not a tensor); Qwen2 adds attention biases; Gemma uses
# the same tensor names (its offset-RMSNorm/GeGLU/embed-scale differences
# are config, not layout).
HF_CONVERTERS = {
    "gpt2": _gpt2_key,
    "llama": _llama_key,
    "mistral": _llama_key,
    "qwen2": _qwen2_key,
    "qwen3": _qwen3_key,
    "gemma": _llama_key,
    "mixtral": _mixtral_key,
}

# Llama-architecture families whose checkpoints may tie the LM head to the
# embeddings (no lm_head.weight tensor on disk).
_TIED_HEAD_FAMILIES = {"llama", "mistral", "qwen2", "qwen3", "gemma"}


class _Stacker:
    """Accumulates StackSlot slices into ``[E, ...]`` tensors.

    With ``expected`` counts (from a params template) a stack is emitted
    as soon as its last slice arrives — the streaming path then holds at
    most one layer's experts. Without counts, stacks finalize at the end.
    """

    def __init__(self, expected: dict[str, int] | None = None) -> None:
        self._slices: dict[str, dict[int, np.ndarray]] = {}
        self._expected = expected or {}

    def add(self, slot: StackSlot, arr: np.ndarray):
        got = self._slices.setdefault(slot.name, {})
        if slot.index in got:
            raise KeyError(f"duplicate expert slice {slot.index} for {slot.name}")
        got[slot.index] = arr
        want = self._expected.get(slot.name)
        if want is not None and len(got) == want:
            del self._slices[slot.name]
            return slot.name, self._stack(slot.name, got)
        return None

    @staticmethod
    def _stack(name: str, got: dict[int, np.ndarray]) -> np.ndarray:
        if sorted(got) != list(range(len(got))):
            raise KeyError(
                f"{name}: expert indices {sorted(got)} are not contiguous"
            )
        return np.stack([got[i] for i in range(len(got))])

    def finalize(self):
        for name, got in self._slices.items():
            yield name, self._stack(name, got)
        self._slices.clear()


def convert_state_dict(
    family: str, state_dict: dict[str, np.ndarray], params_template: Any
) -> Any:
    """Convert an HF state dict to a param tree shaped like the template.

    Missing tensors (or shape mismatches against the template) fail loudly
    via unflatten_like — a half-converted model must never train silently.
    """
    mapper = HF_CONVERTERS.get(family)
    if mapper is None:
        raise ValueError(
            f"no HF converter for family {family!r} (have {sorted(HF_CONVERTERS)})"
        )
    flat: dict[str, np.ndarray] = {}
    stacker = _Stacker()
    for key, value in state_dict.items():
        mapped = mapper(key)
        if mapped is None:
            continue
        arr = np.asarray(value)
        if isinstance(mapped, StackSlot):
            if mapped.transpose:
                arr = np.ascontiguousarray(arr.T)
            stacker.add(mapped, arr.astype(np.float32, copy=False))
            continue
        name, transpose = mapped
        if transpose:
            arr = np.ascontiguousarray(arr.T)
        flat[name] = arr.astype(np.float32, copy=False)
    for name, arr in stacker.finalize():
        flat[name] = arr
    if (
        family in _TIED_HEAD_FAMILIES
        and "params/lm_head" not in flat
        and "params/embed_tokens" in flat
        and _template_has(params_template, "lm_head")
    ):
        # Tied-embedding checkpoint into an untied template: materialize the
        # head from the embeddings rather than failing or training silently
        # from random head weights.
        log.info("%s: tied checkpoint — materializing lm_head from embeddings", family)
        flat["params/lm_head"] = flat["params/embed_tokens"]
    return unflatten_like(flat, params_template)


def _template_has(template: Any, leaf: str) -> bool:
    params = template.get("params", template) if isinstance(template, dict) else {}
    return isinstance(params, dict) and leaf in params


def _torch_to_np(t) -> np.ndarray:
    """Torch tensor -> numpy, upcasting bf16 (numpy has no bfloat16; the
    converter casts everything to f32 anyway)."""
    import torch

    if t.dtype == torch.bfloat16:
        t = t.float()
    return t.numpy()


class ShardedCheckpoint:
    """Lazy tensor reader over an HF checkpoint — single ``.safetensors``
    file, a directory with one, or a sharded repo with
    ``model.safetensors.index.json`` (the layout every released >2 GB HF
    checkpoint uses; reference loads these through AutoModel which resolves
    the same index, executors/accelerate/.../model.py:48-123).

    Tensors are read one at a time (native mmap when available, lazy
    ``safe_open`` slices otherwise), so peak host memory is one tensor —
    a 7B checkpoint converts on a host with a few GB of RAM.
    """

    def __init__(self, path: str | Path) -> None:
        path = Path(path)
        self._weight_map: dict[str, Path]  # tensor name -> shard file
        if path.is_dir():
            index = sorted(path.glob("*.safetensors.index.json"))
            if index:
                import json

                meta = json.loads(index[0].read_text())
                self._weight_map = {
                    k: path / v for k, v in meta["weight_map"].items()
                }
            else:
                shards = sorted(path.glob("*.safetensors"))
                if not shards:
                    raise FileNotFoundError(
                        f"no .safetensors or index.json under {path}"
                    )
                self._weight_map = {}
                for shard in shards:
                    for name in self._shard_keys(shard):
                        self._weight_map[name] = shard
        elif path.name.endswith(".index.json"):
            import json

            meta = json.loads(path.read_text())
            self._weight_map = {
                k: path.parent / v for k, v in meta["weight_map"].items()
            }
        else:
            self._weight_map = {name: path for name in self._shard_keys(path)}
        self._open: dict[Path, Any] = {}  # shard -> reader, opened lazily

    @staticmethod
    def _shard_keys(shard: Path) -> list[str]:
        from ..native import SafeTensorsView

        try:
            with SafeTensorsView(shard) as view:
                return view.keys()
        except (OSError, ValueError):
            import safetensors

            with safetensors.safe_open(str(shard), framework="numpy") as f:
                return list(f.keys())

    def keys(self) -> list[str]:
        return list(self._weight_map)

    def _reader(self, shard: Path):
        reader = self._open.get(shard)
        if reader is None:
            from ..native import SafeTensorsView

            try:
                reader = SafeTensorsView(shard)
            except (OSError, ValueError):
                import safetensors

                # torch framework: the one loader that reads every dtype a
                # real repo ships (bf16 included) lazily.
                reader = safetensors.safe_open(str(shard), framework="torch")
            self._open[shard] = reader
        return reader

    def tensor(self, name: str) -> np.ndarray:
        shard = self._weight_map.get(name)
        if shard is None:
            raise KeyError(name)
        reader = self._reader(shard)
        if hasattr(reader, "tensor"):
            return reader.tensor(name)  # native mmap view
        return _torch_to_np(reader.get_tensor(name))

    def close(self) -> None:
        for reader in self._open.values():
            if hasattr(reader, "close"):
                reader.close()
        self._open.clear()

    def __enter__(self) -> "ShardedCheckpoint":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def convert_checkpoint(
    family: str,
    path: str | Path,
    params_template: Any,
    *,
    dtype: Any = None,
    put: Any = None,
) -> Any:
    """Streaming HF→native conversion for checkpoints of any size.

    Unlike :func:`convert_state_dict` (which wants the whole state dict in
    host memory), this walks the checkpoint tensor-by-tensor: read → map
    name → transpose → cast to ``dtype`` → hand to ``put`` (e.g.
    ``jax.device_put``) → drop the host copy. A Llama-2-7B in bf16 streams
    onto a 16 GB chip without ever holding more than one tensor on host.

    ``put``: optional ``(flat_name, np.ndarray) -> leaf`` placed into the
    result tree (default: keep the numpy array).
    """
    mapper = HF_CONVERTERS.get(family)
    if mapper is None:
        raise ValueError(
            f"no HF converter for family {family!r} (have {sorted(HF_CONVERTERS)})"
        )
    flat: dict[str, Any] = {}
    # Expected expert counts per stacked tensor, from the template's
    # leading dims — lets the stacker emit (and free) each stack as soon
    # as its layer's last expert streams in.
    expected: dict[str, int] = {}
    for keypath, leaf in jax.tree_util.tree_flatten_with_path(params_template)[0]:
        name = "/".join(str(getattr(k, "key", k)) for k in keypath)
        if name.rsplit("/", 1)[-1] in ("w_gate", "w_up", "w_down"):
            expected[name] = int(leaf.shape[0])
    stacker = _Stacker(expected)

    with ShardedCheckpoint(path) as ckpt:
        target = np.dtype(dtype) if dtype is not None else np.float32

        def _read(hf_key: str, transpose: bool) -> np.ndarray:
            arr = np.asarray(ckpt.tensor(hf_key))
            if transpose:
                arr = arr.T
            # One OWNED contiguous host copy in the target dtype — never a
            # view: the shard mmap is unmapped when the checkpoint closes,
            # and ascontiguousarray would alias it for already-contiguous
            # same-dtype tensors.
            return np.array(arr, dtype=target, order="C")

        def _load_one(hf_key: str, name: str, transpose: bool) -> None:
            arr = _read(hf_key, transpose)
            flat[name] = put(name, arr) if put is not None else arr

        hf_keys: dict[str, tuple[str, bool]] = {}
        for hf_key in ckpt.keys():
            mapped = mapper(hf_key)
            if mapped is None:
                continue
            if isinstance(mapped, StackSlot):
                done = stacker.add(mapped, _read(hf_key, mapped.transpose))
                if done is not None:
                    sname, stacked = done
                    flat[sname] = put(sname, stacked) if put is not None else stacked
                continue
            name, transpose = mapped
            hf_keys[name] = (hf_key, transpose)
            _load_one(hf_key, name, transpose)
        for sname, stacked in stacker.finalize():
            flat[sname] = put(sname, stacked) if put is not None else stacked
        if (
            family in _TIED_HEAD_FAMILIES
            and "params/lm_head" not in flat
            and "params/embed_tokens" in hf_keys
            and _template_has(params_template, "lm_head")
        ):
            log.info(
                "%s: tied checkpoint — materializing lm_head from embeddings",
                family,
            )
            _load_one(hf_keys["params/embed_tokens"][0], "params/lm_head", False)
    return unflatten_like(flat, params_template)


def load_checkpoint_files(paths: list[str | Path]) -> dict[str, np.ndarray]:
    """Load tensors from HF checkpoint files (.safetensors preferred,
    torch .bin supported) into one numpy state dict.

    Real Llama-format repos store bf16, which safetensors' numpy loader
    rejects — those fall back to the torch loader and upcast.
    """
    state: dict[str, np.ndarray] = {}
    for path in paths:
        path = Path(path)
        if path.suffix == ".safetensors":
            try:
                from safetensors.numpy import load_file

                state.update(load_file(str(path)))
            except (TypeError, ValueError, RuntimeError):
                from safetensors.torch import load_file as load_torch

                state.update(
                    {k: _torch_to_np(v) for k, v in load_torch(str(path)).items()}
                )
        elif path.suffix in (".bin", ".pt", ".pth"):
            import torch

            loaded = torch.load(path, map_location="cpu", weights_only=True)
            state.update(
                {
                    k: _torch_to_np(v)
                    for k, v in loaded.items()
                    if hasattr(v, "numpy")
                }
            )
        else:
            log.debug("skipping non-checkpoint artifact %s", path)
    return state
