"""HF-transformers fallback family: non-native ModelTypes as Flax modules.

The reference resolves all 38 ``ModelType`` variants through HF
``AutoModelFor*`` torch classes (executors/accelerate/.../model.py:48-123).
The TPU-native equivalent resolves them through the **Flax** auto classes —
native JAX modules that jit/shard like any other model here — wrapped in the
framework's model protocol (``init(rng, inputs) -> params`` /
``apply(params, inputs) -> logits``) so the jitted train step, Δθ
extraction, and checkpointing are family-agnostic.

Torch-only checkpoints convert on load (``from_pt=True``); ModelTypes HF
ships no Flax head for raise a clear error naming the type — the reference's
torch breadth on those heads has no JAX counterpart to wrap.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any

import jax
import numpy as np

from ..messages import ModelType

__all__ = ["HFFlaxModel", "build_hf_model", "FLAX_AUTO_CLASSES"]

log = logging.getLogger("hypha.models.hf")

# ModelType → transformers Flax auto-class name. Only types with a Flax
# implementation appear; the rest raise in build_hf_model.
FLAX_AUTO_CLASSES: dict[ModelType, str] = {
    ModelType.CAUSAL_LM: "FlaxAutoModelForCausalLM",
    ModelType.MASKED_LM: "FlaxAutoModelForMaskedLM",
    ModelType.SEQ2SEQ_LM: "FlaxAutoModelForSeq2SeqLM",
    ModelType.SEQUENCE_CLASSIFICATION: "FlaxAutoModelForSequenceClassification",
    ModelType.TOKEN_CLASSIFICATION: "FlaxAutoModelForTokenClassification",
    ModelType.QUESTION_ANSWERING: "FlaxAutoModelForQuestionAnswering",
    ModelType.MULTIPLE_CHOICE: "FlaxAutoModelForMultipleChoice",
    ModelType.NEXT_SENTENCE_PREDICTION: "FlaxAutoModelForNextSentencePrediction",
    ModelType.IMAGE_CLASSIFICATION: "FlaxAutoModelForImageClassification",
    ModelType.VISION2SEQ: "FlaxAutoModelForVision2Seq",
    ModelType.IMAGE_TEXT_TO_TEXT: "FlaxAutoModelForVision2Seq",
    ModelType.SPEECH_SEQ2SEQ: "FlaxAutoModelForSpeechSeq2Seq",
    ModelType.PRETRAINING: "FlaxAutoModelForPreTraining",
    ModelType.FEATURE_EXTRACTION: "FlaxAutoModel",
}

_PIXEL_TYPES = {
    ModelType.IMAGE_CLASSIFICATION,
    ModelType.VISION2SEQ,
    ModelType.IMAGE_TEXT_TO_TEXT,
}
_DECODER_TYPES = {ModelType.SEQ2SEQ_LM, ModelType.SPEECH_SEQ2SEQ}


class HFFlaxModel:
    """Adapter: HF Flax model → the framework's (init, apply) protocol."""

    def __init__(self, flax_model: Any, model_type: ModelType) -> None:
        self._model = flax_model
        self.model_type = model_type
        if model_type in _PIXEL_TYPES:
            self.input_kw = "pixel_values"
        elif model_type is ModelType.SPEECH_SEQ2SEQ:
            self.input_kw = "input_features"
        else:
            self.input_kw = "input_ids"

    @property
    def config(self) -> Any:
        return self._model.config

    def init(self, rng: Any, inputs: Any) -> Any:
        """Return the (already materialized) param tree; rng/inputs are part
        of the protocol signature but from_pretrained/from_config own the
        actual initialization."""
        del rng, inputs
        return self._model.params

    def apply(self, params: Any, inputs: Any, *, rng: Any = None, batch: Any = None) -> Any:
        """Forward pass. ``rng`` (supplied by the train step) switches the
        model into train mode with live dropout — matching the reference's
        torch train() mode (training.py:106-116); without it the pass is
        deterministic (eval). ``batch`` provides extra streams: seq2seq
        types take real ``decoder_input_ids`` from it (fallbacks: labels,
        then the encoder stream)."""
        kwargs: dict[str, Any] = {self.input_kw: inputs}
        if self.model_type in _DECODER_TYPES:
            dec = None
            if batch is not None:
                dec = batch.get("decoder_input_ids")
                if dec is None and batch.get("labels") is not None:
                    # HF shift_tokens_right: labels become decoder inputs by
                    # prepending the start token; -100 ignore-sentinels must
                    # NOT reach the embedding table (negative indices wrap).
                    import jax.numpy as jnp

                    labels = batch["labels"]
                    cfg = self._model.config
                    pad = getattr(cfg, "pad_token_id", None)
                    start = getattr(cfg, "decoder_start_token_id", None)
                    if start is None:
                        start = pad if pad is not None else 0
                    if pad is None:
                        pad = 0
                    shifted = jnp.concatenate(
                        [jnp.full_like(labels[:, :1], start), labels[:, :-1]],
                        axis=1,
                    )
                    dec = jnp.where(shifted == -100, pad, shifted)
            kwargs["decoder_input_ids"] = dec if dec is not None else inputs
        if rng is not None:
            kwargs["dropout_rng"] = rng
            kwargs["train"] = True
        else:
            kwargs["train"] = False
        out = self._model(params=params, **kwargs)
        for attr in ("logits", "prediction_logits", "last_hidden_state"):
            if hasattr(out, attr):
                return getattr(out, attr)
        return out[0]


def _has_flax_weights(path: Path) -> bool:
    return any(path.glob("*.msgpack")) or any(path.glob("flax_model*.bin"))


def build_hf_model(
    spec: dict[str, Any], model_type: ModelType
) -> tuple[HFFlaxModel, Any]:
    """Build from a job's model spec: ``path`` (a fetched HF checkpoint dir
    with config.json [+ weights]) loads pretrained; ``hf_config`` (a dict of
    HF config fields incl. ``model_type``) random-inits from config."""
    try:
        import transformers
    except Exception as e:  # pragma: no cover — transformers is baked in
        raise RuntimeError("transformers unavailable for the hf family") from e

    cls_name = FLAX_AUTO_CLASSES.get(model_type)
    if cls_name is None:
        supported = ", ".join(sorted(t.value for t in FLAX_AUTO_CLASSES))
        raise NotImplementedError(
            f"ModelType {model_type.value!r} has no HF Flax head; "
            f"hf-family types: {supported}"
        )
    auto_cls = getattr(transformers, cls_name)

    dtype = spec.get("dtype", "float32")
    jdtype = {"float32": jax.numpy.float32, "bfloat16": jax.numpy.bfloat16}[dtype]
    path = spec.get("path")
    if path:
        path = Path(path)
        from_pt = not _has_flax_weights(path)
        model = auto_cls.from_pretrained(
            str(path), dtype=jdtype, from_pt=from_pt, local_files_only=True
        )
        log.info(
            "hf: loaded %s from %s (%s weights)",
            cls_name, path, "torch-converted" if from_pt else "flax",
        )
    else:
        hf_config = spec.get("hf_config")
        if not hf_config:
            raise ValueError(
                "hf family needs model.path (fetched checkpoint dir) or "
                "model.hf_config ({'model_type': ..., ...} HF config fields)"
            )
        config = transformers.AutoConfig.for_model(**dict(hf_config))
        model = auto_cls.from_config(config, seed=int(spec.get("seed", 0)), dtype=jdtype)
        log.info("hf: random-initialized %s (%s)", cls_name, config.model_type)
    # numpy params → jax arrays once, so the first jitted step doesn't pay
    # a per-leaf host transfer inside tracing.
    model.params = jax.tree.map(jax.numpy.asarray, model.params)
    return HFFlaxModel(model, model_type), model.config


def hf_state_dict(model: HFFlaxModel) -> dict[str, np.ndarray]:
    """Flatten params with '/'-joined names for SafeTensors export."""
    flat = {}

    def walk(prefix: str, tree: Any) -> None:
        if isinstance(tree, dict):
            for k, v in tree.items():
                walk(f"{prefix}/{k}" if prefix else k, v)
        else:
            flat[prefix] = np.asarray(tree)

    walk("", model._model.params)
    return flat
