"""LeNet-5 — the reference's default training job is LeNet/MNIST
(crates/scheduler/src/scheduler_config.rs:79-102)."""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["LeNet", "LeNetConfig"]


@dataclass(frozen=True)
class LeNetConfig:
    num_classes: int = 10
    dtype: str = "float32"


class LeNet(nn.Module):
    config: LeNetConfig = LeNetConfig()

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        # x: [B, 28, 28, 1]
        dtype = jnp.dtype(self.config.dtype)
        x = x.astype(dtype)
        x = nn.Conv(6, (5, 5), padding="SAME", dtype=dtype, name="conv1")(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), padding="VALID", dtype=dtype, name="conv2")(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120, dtype=dtype, name="fc1")(x))
        x = nn.relu(nn.Dense(84, dtype=dtype, name="fc2")(x))
        return nn.Dense(self.config.num_classes, dtype=dtype, name="head")(x)
