"""Mixtral-style sparse MoE decoder (BASELINE config 5: Mixtral-8x7B
8-replica DiLoCo).

The reference can only load Mixtral as a plain HF causal-LM inside one
Accelerate process (executors/accelerate/.../model.py:54-55) — no expert
parallelism. TPU-native design here: experts live in stacked parameter
tensors with a leading expert axis, tokens are dispatched with static-shape
one-hot capacity routing (einsum dispatch/combine — the standard TPU MoE
formulation: everything is a large batched matmul on the MXU, no dynamic
shapes), and the expert axis shards over the mesh's ``ep`` dimension so XLA
lowers dispatch/combine to all-to-alls over ICI.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from .llama import LlamaConfig, _Attention, _RMSNorm

__all__ = ["Mixtral", "MixtralConfig"]


@dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32_000
    hidden_size: int = 4096
    intermediate_size: int = 14_336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    num_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    max_seq_len: int = 4096
    rope_theta: float = 1_000_000.0
    rms_eps: float = 1e-5
    router_aux_coef: float = 0.02
    dtype: str = "bfloat16"
    remat: bool = False  # gradient checkpointing per block (see gpt2.py)
    # Drop-free TRAINING (serving decode is always dropless): every token
    # reaches its top-k experts at E/K x the expert FLOPs — reachable from
    # job specs via {"config": {"dropless": true}}, so the capacity-vs-
    # dropless fidelity tradeoff (MOE_r05.json) is an operator choice, not
    # a code edit.
    dropless: bool = False

    @classmethod
    def mixtral_8x7b(cls) -> "MixtralConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "MixtralConfig":
        return cls(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=128,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            num_experts=4,
            experts_per_token=2,
            max_seq_len=128,
        )

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def as_llama(self) -> LlamaConfig:
        """Attention sublayer config (Mixtral reuses the Llama attention)."""
        return LlamaConfig(
            vocab_size=self.vocab_size,
            hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            num_layers=self.num_layers,
            num_heads=self.num_heads,
            num_kv_heads=self.num_kv_heads,
            max_seq_len=self.max_seq_len,
            rope_theta=self.rope_theta,
            rms_eps=self.rms_eps,
            dtype=self.dtype,
        )


class MoELayer(nn.Module):
    """Top-k routed expert MLP with static capacity dispatch.

    Returns (output, aux_loss) where aux_loss is the standard load-balancing
    loss (mean fraction-routed × mean router-prob per expert × num_experts).
    """

    config: MixtralConfig
    # Drop-free routing: every token reaches its top-k experts, no capacity
    # truncation — the SERVING semantics (decode mode uses it so cached
    # generation is exact for any router load), at E/K x the expert FLOPs.
    # Training keeps the capacity path (static shapes, bounded expert work).
    dropless: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> tuple:
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        B, S, D = x.shape
        E, K = cfg.num_experts, cfg.experts_per_token
        C = max(1, math.ceil(S * K * cfg.capacity_factor / E))  # per-expert capacity

        router = nn.Dense(E, use_bias=False, dtype=jnp.float32, name="gate")
        logits = router(x.astype(jnp.float32))  # [B, S, E]
        probs = jax.nn.softmax(logits, axis=-1)

        # top-k selection; renormalize the kept weights (Mixtral semantics)
        top_w, top_idx = jax.lax.top_k(probs, K)  # [B, S, K]
        top_w = top_w / jnp.maximum(top_w.sum(-1, keepdims=True), 1e-9)

        if self.dropless:
            w_gate = self.param(
                "w_gate", nn.initializers.normal(0.02),
                (E, D, cfg.intermediate_size), jnp.float32,
            )
            w_up = self.param(
                "w_up", nn.initializers.normal(0.02),
                (E, D, cfg.intermediate_size), jnp.float32,
            )
            w_down = self.param(
                "w_down", nn.initializers.normal(0.02),
                (E, cfg.intermediate_size, D), jnp.float32,
            )
            # Every expert sees every token; combine weights zero out the
            # non-selected ones. Exact regardless of router load.
            h = nn.silu(jnp.einsum("bsd,edf->ebsf", x, w_gate.astype(dtype)))
            h = h * jnp.einsum("bsd,edf->ebsf", x, w_up.astype(dtype))
            out_all = jnp.einsum("ebsf,efd->ebsd", h, w_down.astype(dtype))
            onehot = jax.nn.one_hot(top_idx, E, dtype=jnp.float32)  # [B,S,K,E]
            combine_e = jnp.einsum("bsk,bske->bse", top_w, onehot).astype(dtype)
            out = jnp.einsum("bse,ebsd->bsd", combine_e, out_all)
            frac_routed = jnp.mean(onehot.sum(2), axis=(0, 1))
            mean_prob = jnp.mean(probs, axis=(0, 1))
            aux = cfg.router_aux_coef * E * jnp.sum(frac_routed * mean_prob)
            return out, aux

        # position-in-expert via cumulative count over the sequence; tokens
        # beyond capacity are dropped (static shapes — TPU-friendly)
        onehot = jax.nn.one_hot(top_idx, E, dtype=jnp.float32)  # [B, S, K, E]
        pos = jnp.cumsum(onehot.reshape(B, S * K, E), axis=1).reshape(B, S, K, E) - onehot
        keep = (pos < C) * onehot  # [B, S, K, E]
        # Observability for the capacity-routing fidelity question
        # (MOE_r05): fraction of (token, expert-slot) assignments dropped
        # this step. Recorded only when callers apply with
        # mutable=["intermediates"] — zero cost in the jitted train step.
        self.sow(
            "intermediates", "drop_frac",
            1.0 - keep.sum() / jnp.maximum(onehot.sum(), 1.0),
        )
        pos_cap = jax.nn.one_hot(pos.astype(jnp.int32), C, dtype=jnp.float32)  # [B, S, K, E, C]
        dispatch = jnp.einsum("bske,bskec->bsec", keep, pos_cap)  # [B, S, E, C]
        combine = jnp.einsum("bsk,bske,bskec->bsec", top_w, keep, pos_cap)

        # dispatch -> [B, E, C, D] expert batches; single stacked matmuls
        expert_in = jnp.einsum("bsec,bsd->becd", dispatch.astype(dtype), x)
        w_gate = self.param(
            "w_gate", nn.initializers.normal(0.02), (E, D, cfg.intermediate_size), jnp.float32
        )
        w_up = self.param(
            "w_up", nn.initializers.normal(0.02), (E, D, cfg.intermediate_size), jnp.float32
        )
        w_down = self.param(
            "w_down", nn.initializers.normal(0.02), (E, cfg.intermediate_size, D), jnp.float32
        )
        h = nn.silu(jnp.einsum("becd,edf->becf", expert_in, w_gate.astype(dtype)))
        h = h * jnp.einsum("becd,edf->becf", expert_in, w_up.astype(dtype))
        expert_out = jnp.einsum("becf,efd->becd", h, w_down.astype(dtype))
        out = jnp.einsum("bsec,becd->bsd", combine.astype(dtype), expert_out)

        # load-balancing auxiliary loss
        frac_routed = jnp.mean(keep.sum(2), axis=(0, 1))  # [E]
        mean_prob = jnp.mean(probs, axis=(0, 1))  # [E]
        aux = cfg.router_aux_coef * E * jnp.sum(frac_routed * mean_prob)
        return out, aux


class _MoEBlock(nn.Module):
    config: MixtralConfig
    attn_impl: Callable | None = None
    decode: bool = False  # KV-cached serving (the shared llama attention)
    decode_len: int = 0
    dropless: bool = False  # drop-free MoE routing (see MoELayer)
    per_row_decode: bool = False  # continuous-batching pool (executor.pool)
    kv_blocks: int = 0  # paged KV serving (executor.pool paged mode)
    kv_block_size: int = 0
    ragged_attention: bool = False  # occupancy-proportional paged attention
    kv_quant: str = ""  # int8 KV blocks ("" = full precision)

    @nn.compact
    def __call__(self, x, cos, sin):
        cfg = self.config
        lcfg = cfg.as_llama()
        x = x + _Attention(
            lcfg, self.attn_impl, self.decode, self.decode_len,
            self.per_row_decode, self.kv_blocks, self.kv_block_size,
            self.ragged_attention, self.kv_quant,
            name="self_attn"
        )(_RMSNorm(cfg.rms_eps, name="input_layernorm")(x), cos, sin)
        moe_out, aux = MoELayer(
            cfg,
            dropless=self.decode or self.dropless or cfg.dropless,
            name="moe",
        )(
            _RMSNorm(cfg.rms_eps, name="post_attention_layernorm")(x)
        )
        return x + moe_out, aux


class Mixtral(nn.Module):
    config: MixtralConfig = MixtralConfig()
    attn_impl: Callable | None = None
    decode: bool = False  # serving mode: KV-cached autoregressive forward
    decode_len: int = 0
    dropless: bool = False  # drop-free routing in the plain forward too
    per_row_decode: bool = False  # continuous-batching pool (executor.pool)
    kv_blocks: int = 0  # paged KV serving (executor.pool paged mode)
    kv_block_size: int = 0
    ragged_attention: bool = False  # occupancy-proportional paged attention
    kv_quant: str = ""  # int8 KV blocks ("" = full precision)
    # with_head=False returns (hidden [B, S, E], aux) for the chunked-CE
    # training path (see llama.py / gpt2.py).
    with_head: bool = True

    @nn.compact
    def __call__(self, input_ids: jnp.ndarray) -> tuple:
        """input_ids [B, S] -> (logits [B, S, vocab] f32, aux_loss scalar),
        or (hidden, aux) when ``with_head=False``."""
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        from ..ops.rope import rope_frequencies

        embed = self.param(
            "embed_tokens",
            nn.initializers.normal(0.02),
            (cfg.vocab_size, cfg.hidden_size),
            jnp.float32,
        )
        x = embed[input_ids].astype(dtype)
        table_len = max(cfg.max_seq_len, self.decode_len)
        cos, sin = rope_frequencies(cfg.head_dim, table_len, cfg.rope_theta)
        aux_total = 0.0
        block_cls = (
            nn.remat(_MoEBlock) if cfg.remat and not self.decode else _MoEBlock
        )
        for i in range(cfg.num_layers):
            x, aux = block_cls(
                cfg, self.attn_impl, self.decode, self.decode_len,
                self.dropless, self.per_row_decode, self.kv_blocks,
                self.kv_block_size, self.ragged_attention, self.kv_quant,
                name=f"layers_{i}",
            )(x, cos, sin)
            aux_total = aux_total + aux
        x = _RMSNorm(cfg.rms_eps, name="norm")(x)
        if not self.with_head:
            return x, aux_total
        lm_head = self.param(
            "lm_head",
            nn.initializers.normal(0.02),
            (cfg.vocab_size, cfg.hidden_size),
            jnp.float32,
        )
        return jnp.einsum("bse,ve->bsv", x.astype(jnp.float32), lm_head), aux_total
