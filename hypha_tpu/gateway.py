"""Gateway runtime: the network anchor.

The gateway is the bootstrap/registry/relay node — it serves the record and
provider registry (the reference's Kademlia in ``Mode::Server``,
crates/gateway/src/network.rs:152), relays peer address books so nodes can
find each other, answers health probes, and runs no compute
(reference: crates/gateway — SURVEY.md §2.1 #8).

In this framework the registry service itself lives in
:class:`~hypha_tpu.network.node.Node` (``registry_server=True``); this module
is the thin runtime composing it with health serving and lifecycle, the role
of ``hypha-gateway.rs``'s ``run()``.
"""

from __future__ import annotations

import logging

from .health import serve_health
from .network.node import Node
from .network.fabric import Transport

__all__ = ["Gateway"]

log = logging.getLogger("hypha.gateway")


class Gateway:
    """Composes a registry-server Node with health serving."""

    def __init__(
        self,
        transport: Transport | None,
        peer_id: str | None = None,
        node: Node | None = None,
        **node_kwargs,
    ) -> None:
        # ``node`` injection: the CLI passes an mTLS-secured registry Node.
        self.node = node or Node(
            transport, peer_id=peer_id, registry_server=True, **node_kwargs
        )
        self._health = None
        self._running = False

    @property
    def peer_id(self) -> str:
        return self.node.peer_id

    async def start(self, listen: list[str] | None = None) -> None:
        await self.node.start(listen)
        # Gateway readiness = listening; it has no upstream bootstrap.
        self._running = True
        self._health = serve_health(self.node, lambda: self._running)
        log.info("gateway %s listening on %s", self.peer_id, self.node.listen_addrs)

    async def stop(self) -> None:
        self._running = False
        if self._health is not None:
            self._health.close()
        await self.node.stop()
