"""``python -m hypha_tpu`` — the node CLI (see hypha_tpu.cli)."""

from .cli import main

raise SystemExit(main())
