"""Checkpoint/resume tests (net-new vs reference — SURVEY.md §5 records the
reference has none; BASELINE preemption configs require it)."""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hypha_tpu.executor.checkpoint import (
    latest_manifest,
    load_train_checkpoint,
    save_train_checkpoint,
)
from hypha_tpu.executor.train import TrainState, build_optimizer
from hypha_tpu.messages import Adam


def make_state(seed=0):
    from hypha_tpu.models import GPT2, GPT2Config

    cfg = GPT2Config(vocab_size=16, n_positions=8, n_embd=8, n_layer=1, n_head=2)
    model = GPT2(cfg)
    params = model.init(jax.random.key(seed), np.zeros((1, 8), np.int32))
    return model, TrainState.create(params, build_optimizer(Adam(lr=1e-3)))


def test_train_checkpoint_round_trip(tmp_path):
    model, state = make_state()
    # advance the optimizer so opt_state has non-trivial moments
    grads = jax.tree.map(jnp.ones_like, state.params)
    state = state.apply_gradients(grads)
    save_train_checkpoint(
        tmp_path / "ck", state.params, state.opt_state, int(state.step), 3,
        extra={"note": "x"},
    )
    _, fresh = make_state(seed=1)
    restored = load_train_checkpoint(tmp_path / "ck", fresh.params, fresh.opt_state)
    assert restored is not None
    r_params, r_opt, r_step, r_round, extra = restored
    assert r_step == 1 and r_round == 3 and extra == {"note": "x"}
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(r_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(state.opt_state), jax.tree.leaves(r_opt)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_load_absent_checkpoint_returns_none(tmp_path):
    _, state = make_state()
    assert load_train_checkpoint(tmp_path / "nope", state.params, state.opt_state) is None


def test_checkpoint_shape_mismatch_fails_loudly(tmp_path):
    _, state = make_state()
    save_train_checkpoint(
        tmp_path / "ck", state.params, state.opt_state, 0, 0
    )
    from hypha_tpu.models import GPT2, GPT2Config

    other = GPT2(GPT2Config(vocab_size=32, n_positions=8, n_embd=8, n_layer=1, n_head=2))
    other_params = other.init(jax.random.key(0), np.zeros((1, 8), np.int32))
    other_state = TrainState.create(other_params, build_optimizer(Adam()))
    with pytest.raises((ValueError, KeyError)):
        load_train_checkpoint(tmp_path / "ck", other_state.params, other_state.opt_state)


def test_ps_momentum_checkpoint_copy(tmp_path):
    """The PS copies its momentum file into the checkpoint dir atomically
    (ps_executor._checkpoint_momentum) and restores it on restart."""
    from safetensors.numpy import load_file, save_file

    from hypha_tpu.worker.ps_executor import ParameterServerExecutor

    m = {"a/w": np.arange(4, dtype=np.float32), "b": np.ones(2, np.float32)}
    momentum_file = tmp_path / "momentum.safetensors"
    save_file(m, str(momentum_file))
    ckpt = tmp_path / "ckpt"
    ParameterServerExecutor._checkpoint_momentum(momentum_file, ckpt)
    got = load_file(str(ckpt / "momentum.safetensors"))
    np.testing.assert_array_equal(got["a/w"], m["a/w"])
    assert not [p for p in ckpt.iterdir() if p.name.startswith(".momentum")]
    # absent momentum file is a no-op
    ParameterServerExecutor._checkpoint_momentum(tmp_path / "nope", ckpt)


def test_versioned_save_updates_pointer_and_prunes(tmp_path):
    _, state = make_state()
    d = tmp_path / "ck"
    save_train_checkpoint(d, state.params, state.opt_state, 1, 1)
    assert latest_manifest(d)["round"] == 1
    save_train_checkpoint(d, state.params, state.opt_state, 2, 2)
    save_train_checkpoint(d, state.params, state.opt_state, 3, 3)
    assert latest_manifest(d)["round"] == 3
    versions = [p.name for p in d.iterdir() if p.is_dir() and p.name.startswith("v")]
    assert len(versions) == 2  # pruned to the last two complete checkpoints
    # no stray staging/tmp entries
    assert not [p for p in d.iterdir() if p.name.startswith(".staging")]
    # a torn LATEST (pointing at a removed version) fails loudly
    (d / "LATEST").write_text("v99999999-9")
    with pytest.raises(ValueError, match="names missing"):
        load_train_checkpoint(d, state.params, state.opt_state)


@pytest.mark.slow
def test_job_resumes_from_checkpoint(tmp_path):
    """Two successive jobs sharing a checkpoint dir: the second starts from
    the first's weights (step counter keeps growing; resume logged)."""
    import asyncio
    import dataclasses

    from tests.test_e2e import diloco_job, start_cluster

    async def main():
        from hypha_tpu.scheduler.orchestrator import Orchestrator

        hub, gw, data, workers, sched = await start_cluster(tmp_path)
        orch = Orchestrator(sched)
        job = diloco_job(rounds=1)
        job.checkpoint_dir = str(tmp_path / "ckpt")

        async def read_manifests(done) -> dict:
            # Workers write their checkpoint just AFTER the scheduler sees
            # completion (the save follows UpdateReceived in the executor
            # thread) — poll until the expected content appears.
            found = {}
            for _ in range(100):
                found = {}
                for sub in (tmp_path / "ckpt").glob("*"):
                    m = latest_manifest(sub)
                    if m is not None:
                        found[sub.name] = m
                if done(found):
                    return found
                await asyncio.sleep(0.1)
            return found

        def both(found):
            return {"w0", "w1"} <= set(found)

        try:
            await orch.run(job, auction_timeout=1.5)
            manifests_1 = await read_manifests(both)
            await asyncio.sleep(11)  # let the 10 s train leases lapse
            await orch.run(job, auction_timeout=1.5)
            manifests_2 = await read_manifests(
                lambda found: both(found)
                and all(
                    found[w]["step"] != manifests_1[w]["step"] for w in ("w0", "w1")
                )
            )
        finally:
            for w in workers:
                await w.stop()
            await data.stop()
            await sched.stop()
            await gw.stop()
        return manifests_1, manifests_2

    m1, m2 = asyncio.run(asyncio.wait_for(main(), 240))
    assert {"w0", "w1"} <= set(m1)
    for w in ("w0", "w1"):
        assert m2[w]["step"] > m1[w]["step"], (w, m1[w], m2[w])
    # PS momentum persisted
    assert (tmp_path / "ckpt" / "ps" / "momentum.safetensors").exists()
