"""PKI + mTLS tests.

Reference roles covered: certutil hierarchy generation
(crates/certutil/src/main.rs), PEM loading (crates/network/src/cert.rs),
PeerID = cert-key-hash identity, mTLS handshake enforcement and CRL
rejection (rfc/2025-05-30_mtls.md).
"""

from __future__ import annotations

import asyncio
import ssl

import pytest

# The PKI layer is built on the `cryptography` package; environments
# without it (the jax_graft CI image) must skip cleanly instead of erroring
# at collection — hypha_tpu.certs imports it at module scope.
pytest.importorskip(
    "cryptography",
    reason="hypha_tpu.certs requires the 'cryptography' package",
)

from hypha_tpu import certs, certutil
from hypha_tpu.messages import PROTOCOL_HEALTH, HealthRequest, HealthResponse
from hypha_tpu.network.secure import secure_node


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=30))


@pytest.fixture(scope="module")
def pki(tmp_path_factory):
    """root -> org -> {alice, bob, mallory-from-other-org} via the CLI."""
    out = tmp_path_factory.mktemp("pki")
    assert certutil.main(["root", "--out", str(out)]) == 0
    assert certutil.main(["org", "--out", str(out), "--name", "org-a"]) == 0
    for name in ("alice", "bob", "eve"):
        assert (
            certutil.main(["node", "--out", str(out), "--org", "org-a", "--name", name])
            == 0
        )
    # a parallel, untrusted hierarchy for mallory
    other = tmp_path_factory.mktemp("pki-other")
    assert certutil.main(["root", "--out", str(other)]) == 0
    assert certutil.main(["org", "--out", str(other), "--name", "org-x"]) == 0
    assert (
        certutil.main(
            ["node", "--out", str(other), "--org", "org-x", "--name", "mallory"]
        )
        == 0
    )
    return out, other


def _node(out, name, **kw):
    return secure_node(
        out / f"{name}.crt", out / f"{name}.key", out / "trust.crt", **kw
    )


def test_peer_id_is_cert_key_hash(pki):
    out, _ = pki
    pid = certs.peer_id_from_cert_pem((out / "alice.crt").read_bytes())
    assert pid.startswith("12H") and len(pid) == 43
    # deterministic
    assert pid == certs.peer_id_from_cert_pem((out / "alice.crt").read_bytes())
    # distinct keys -> distinct ids
    assert pid != certs.peer_id_from_cert_pem((out / "bob.crt").read_bytes())


def test_loaders(pki):
    out, _ = pki
    chain = certs.load_certs_from_pem(out / "alice.crt")
    assert len(chain) == 2  # node + org CA
    key = certs.load_private_key_from_pem(out / "alice.key")
    assert key is not None


def test_mtls_rpc_roundtrip(pki):
    out, _ = pki

    async def main():
        alice = _node(out, "alice")
        bob = _node(out, "bob")
        await alice.start(listen=["127.0.0.1:0"])
        await bob.start(listen=["127.0.0.1:0"])

        async def health(peer, msg):
            # the caller's identity is certificate-derived
            assert peer == alice.peer_id
            return HealthResponse(healthy=True)

        bob.on(PROTOCOL_HEALTH, HealthRequest).respond_with(health)
        peer = await alice.dial(bob.listen_addrs[0])
        assert peer == bob.peer_id
        resp = await alice.request(bob.peer_id, PROTOCOL_HEALTH, HealthRequest())
        assert resp.healthy
        await alice.stop(); await bob.stop()

    run(main())


def test_untrusted_hierarchy_rejected(pki):
    out, other = pki

    async def main():
        alice = _node(out, "alice")
        mallory = _node(other, "mallory")
        await alice.start(listen=["127.0.0.1:0"])
        await mallory.start(listen=["127.0.0.1:0"])
        with pytest.raises((ssl.SSLError, ConnectionError, OSError)):
            await mallory.dial(alice.listen_addrs[0])
        await alice.stop(); await mallory.stop()

    run(main())


def test_identity_spoof_rejected(pki):
    """A trusted peer claiming another's peer id in the handshake is cut off
    (PeerID must match the TLS certificate)."""
    out, _ = pki

    async def main():
        alice = _node(out, "alice")
        bob = _node(out, "bob")
        eve = _node(out, "eve")
        await alice.start(listen=["127.0.0.1:0"])
        await bob.start(listen=["127.0.0.1:0"])
        await eve.start(listen=["127.0.0.1:0"])

        async def health(peer, msg):
            return HealthResponse(healthy=True)

        bob.on(PROTOCOL_HEALTH, HealthRequest).respond_with(health)

        # eve lies about being alice in the handshake 'from' field
        eve.peer_id = alice.peer_id
        from hypha_tpu.network import RequestError

        eve.add_peer_addr(bob.peer_id, bob.listen_addrs[0])
        with pytest.raises(RequestError):
            await eve.request(bob.peer_id, PROTOCOL_HEALTH, HealthRequest())

        # client-side check: alice dials an address she believes is bob's,
        # but eve answers -> certificate mismatch aborts
        honest_eve = _node(out, "eve")
        await honest_eve.start(listen=["127.0.0.1:0"])
        alice.add_peer_addr(bob.peer_id, honest_eve.listen_addrs[0])
        with pytest.raises(RequestError):
            await alice.request(bob.peer_id, PROTOCOL_HEALTH, HealthRequest())
        for n in (alice, bob, eve, honest_eve):
            await n.stop()

    run(main())


def test_crl_reissue_keeps_prior_revocations(pki):
    """Revoking B after A must keep A revoked (CRL serials merge)."""
    out, _ = pki
    assert certutil.main(
        ["revoke", "--out", str(out), "--org", "org-a", "--cert", str(out / "alice.crt")]
    ) == 0
    assert certutil.main(
        ["revoke", "--out", str(out), "--org", "org-a", "--cert", str(out / "bob.crt")]
    ) == 0
    crls = certs.load_crls_from_pem(out / "org-a.crl")
    serials = {rc.serial_number for crl in crls for rc in crl}
    from cryptography import x509 as _x509

    a = _x509.load_pem_x509_certificate((out / "alice.crt").read_bytes())
    b = _x509.load_pem_x509_certificate((out / "bob.crt").read_bytes())
    assert {a.serial_number, b.serial_number} <= serials
    # reset the CRL so later tests in this module see a clean slate
    (out / "org-a.crl").unlink()


def test_crl_revocation(pki, tmp_path):
    out, _ = pki
    # revoke eve via the CLI, then build nodes that load the CRL
    assert (
        certutil.main(
            [
                "revoke",
                "--out",
                str(out),
                "--org",
                "org-a",
                "--cert",
                str(out / "eve.crt"),
            ]
        )
        == 0
    )
    crl = out / "org-a.crl"

    async def main():
        alice = _node(out, "alice", crl_file=crl)
        eve = _node(out, "eve")
        await alice.start(listen=["127.0.0.1:0"])
        await eve.start(listen=["127.0.0.1:0"])
        # TLS 1.3: the server rejects the revoked client cert after the
        # client's handshake completes, so the client sees either an SSL
        # alert or an immediate EOF (FrameError) on first read.
        from hypha_tpu.network import FrameError

        with pytest.raises((ssl.SSLError, ConnectionError, OSError, FrameError)):
            await eve.dial(alice.listen_addrs[0])
        # bob (not revoked) still connects fine against the same CRL config
        bob = _node(out, "bob", crl_file=crl)
        await bob.start(listen=["127.0.0.1:0"])
        assert await bob.dial(alice.listen_addrs[0]) == alice.peer_id
        for n in (alice, eve, bob):
            await n.stop()

    run(main())
