"""Job bridge + connectors + process executor tests.

Reference roles: crates/worker/src/executor/bridge.rs (UDS HTTP API, path
safety, SSE receive), connector/mod.rs (fetch/send/receive/pull routing),
executor/process.rs (spawn/substitute/supervise/cancel).
"""

from __future__ import annotations

import asyncio
import sys
import textwrap
from pathlib import Path

import pytest

from hypha_tpu.executor.bridge_client import Session
from hypha_tpu.messages import (
    PROTOCOL_API,
    PROTOCOL_PROGRESS,
    Ack,
    DataRequest,
    DataResponse,
    DataSlice,
    Fetch,
    JobSpec,
    Executor,
    TrainExecutorConfig,
    Adam,
    Progress,
    ProgressKind,
    ProgressResponse,
    ProgressResponseKind,
    Receive,
    Reference,
    Send,
)
from hypha_tpu.network import MemoryTransport, Node
from hypha_tpu.worker.bridge import Bridge, BridgeError, safe_rel
from hypha_tpu.worker.connectors import Connector
from hypha_tpu.worker.process_executor import ProcessExecutor


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=60))


async def _pair():
    hub = MemoryTransport()
    worker = Node(hub.shared(), peer_id="worker")
    sched = Node(hub.shared(), peer_id="sched")
    await worker.start(); await sched.start()
    worker.add_peer_addr("sched", sched.listen_addrs[0])
    sched.add_peer_addr("worker", worker.listen_addrs[0])
    return hub, worker, sched


def test_safe_rel_rejects_escape(tmp_path):
    assert safe_rel(tmp_path, "artifacts/model.bin") == tmp_path / "artifacts/model.bin"
    with pytest.raises(BridgeError):
        safe_rel(tmp_path, "/etc/passwd")
    with pytest.raises(BridgeError):
        safe_rel(tmp_path, "../../secrets")


def test_bridge_fetch_file_uri_and_status(tmp_path):
    async def main():
        hub, worker, sched = await _pair()
        src = tmp_path / "model.safetensors"
        src.write_bytes(b"weights" * 100)

        # scheduler answers progress with SCHEDULE_UPDATE{3}
        async def on_progress(peer, progress):
            assert progress.kind == ProgressKind.STATUS
            assert progress.job_id == "j1"
            return ProgressResponse(
                kind=ProgressResponseKind.SCHEDULE_UPDATE, counter=3
            )

        sched.on(PROTOCOL_PROGRESS, Progress).respond_with(on_progress)

        work = tmp_path / "work"
        bridge = Bridge(worker, work, "j1", "sched")
        sock = await bridge.start()

        def client_ops():
            with Session(str(sock)) as s:
                paths = s.fetch(Fetch(Reference.from_uri(src.as_uri())))
                assert paths == ["artifacts/model.safetensors"]
                assert (work / paths[0]).read_bytes() == src.read_bytes()
                resp = s.send_status(
                    Progress(kind=ProgressKind.STATUS, batch_size=8)
                )
                assert resp.kind == ProgressResponseKind.SCHEDULE_UPDATE
                assert resp.counter == 3

        await asyncio.to_thread(client_ops)
        await bridge.stop()
        await worker.stop(); await sched.stop()

    run(main())


def test_bridge_send_and_receive_roundtrip(tmp_path):
    """worker A sends its delta; worker B receives it via SSE pointers,
    with a disallowed sender filtered out."""

    async def main():
        hub = MemoryTransport()
        a = Node(hub.shared(), peer_id="a")
        b = Node(hub.shared(), peer_id="b")
        eve = Node(hub.shared(), peer_id="eve")
        for n in (a, b, eve):
            await n.start()
        for x in (a, b, eve):
            for y in (a, b, eve):
                if x is not y:
                    x.add_peer_addr(y.peer_id, y.listen_addrs[0])

        work_a, work_b = tmp_path / "wa", tmp_path / "wb"
        bridge_a = Bridge(a, work_a, "j", "sched")
        bridge_b = Bridge(b, work_b, "j", "sched")
        sock_a = await bridge_a.start()
        sock_b = await bridge_b.start()
        (work_a / "delta.st").parent.mkdir(parents=True, exist_ok=True)
        (work_a / "delta.st").write_bytes(b"D" * 12345)

        received = []

        def receiver():
            with Session(str(sock_b)) as s:
                ref = Reference.from_peers(["a"], "updates")
                with s.receive(Receive(ref)) as events:
                    for ev in events:
                        received.append(ev)
                        return

        recv_task = asyncio.create_task(asyncio.to_thread(receiver))
        await asyncio.sleep(0.2)
        # eve pushes first — must be dropped (not from an allowed peer)
        await eve.push("b", {"resource": "updates", "name": "evil"}, b"x" * 10)

        def sender():
            with Session(str(sock_a)) as s:
                ref = Reference.from_peers(["b"], "updates")
                s.send_resource(Send(ref), "delta.st", "updates")

        await asyncio.to_thread(sender)
        await asyncio.wait_for(recv_task, 10)
        assert len(received) == 1
        ev = received[0]
        assert ev["from_peer"] == "a" and ev["size"] == 12345
        assert (work_b / ev["path"]).stat().st_size == 12345
        await bridge_a.stop(); await bridge_b.stop()
        for n in (a, b, eve):
            await n.stop()

    run(main())


def test_connector_slice_fetch_via_scheduler(tmp_path):
    async def main():
        hub = MemoryTransport()
        worker = Node(hub.shared(), peer_id="worker")
        sched = Node(hub.shared(), peer_id="sched")
        data = Node(hub.shared(), peer_id="data")
        for n in (worker, sched, data):
            await n.start()
        for x in (worker, sched, data):
            for y in (worker, sched, data):
                if x is not y:
                    x.add_peer_addr(y.peer_id, y.listen_addrs[0])

        # scheduler assigns slice 2 from "data"; data node serves it
        async def on_data(peer, req):
            assert req.dataset == "mnist" and peer == "worker"
            return DataResponse(data_provider="data", index=2)

        sched.on(PROTOCOL_API, DataRequest).respond_with(on_data)

        async def serve(peer, res):
            assert res == DataSlice(dataset="mnist", index=2)
            return b"S2" * 500

        data.on_pull(serve)

        conn = Connector(worker, "sched")
        ref = Reference.from_scheduler("sched", "mnist")
        paths = await conn.fetch(Fetch(ref), tmp_path / "slices")
        assert len(paths) == 1 and paths[0].read_bytes() == b"S2" * 500
        for n in (worker, sched, data):
            await n.stop()

    run(main())


EXECUTOR_SCRIPT = textwrap.dedent(
    """
    import json, os, sys
    sys.path.insert(0, {repo!r})
    from hypha_tpu.executor.bridge_client import Session
    from hypha_tpu.messages import Progress, ProgressKind, ProgressResponseKind

    job = json.loads(os.environ["JOB_JSON"])
    assert job["_t"] == "JobSpec", job
    with Session(os.environ["SOCKET_PATH"]) as s:
        resp = s.send_status(Progress(kind=ProgressKind.STATUS, batch_size=4))
        assert resp.kind == ProgressResponseKind.CONTINUE, resp
    print("executor done")
    """
)


def _train_spec(job_id="pj"):
    uri = Reference.from_uri("file:///dev/null")
    peers = Reference.from_peers(["ps"], "updates")
    return JobSpec(
        job_id=job_id,
        executor=Executor(
            kind="train",
            name="diloco-jax",
            train=TrainExecutorConfig(
                model={"model_type": "causal-lm"},
                data=Fetch(uri),
                updates=Send(peers),
                results=Receive(peers),
                optimizer=Adam(lr=1e-3),
                batch_size=4,
            ),
        ),
    )


def test_process_executor_runs_and_completes(tmp_path):
    async def main():
        hub, worker, sched = await _pair()

        async def on_progress(peer, progress):
            return ProgressResponse(kind=ProgressResponseKind.CONTINUE)

        sched.on(PROTOCOL_PROGRESS, Progress).respond_with(on_progress)

        script = tmp_path / "exec.py"
        script.write_text(EXECUTOR_SCRIPT.format(repo=str(Path.cwd())))
        pe = ProcessExecutor(
            node=worker,
            cmd=sys.executable,
            args=[str(script)],
            work_root=tmp_path,
        )
        execution = await pe.execute("pj", _train_spec(), "sched")
        status = await asyncio.wait_for(execution.wait(), 30)
        assert status.state == "completed", status
        await worker.stop(); await sched.stop()

    run(main())


def test_process_executor_cancel_sigterm(tmp_path):
    async def main():
        hub, worker, sched = await _pair()
        script = tmp_path / "sleep.py"
        script.write_text("import time; time.sleep(300)\n")
        pe = ProcessExecutor(
            node=worker, cmd=sys.executable, args=[str(script)], work_root=tmp_path
        )
        execution = await pe.execute("cj", _train_spec("cj"), "sched")
        await asyncio.sleep(0.3)
        await execution.cancel()
        status = await asyncio.wait_for(execution.wait(), 10)
        assert status.state == "cancelled"
        await worker.stop(); await sched.stop()

    run(main())
